"""Static analyzer (sentio_tpu/analysis) self-tests + the tier-1 gate.

Three layers: seeded-violation fixtures must each produce EXACTLY their
expected finding (the analyzer works), the baseline ratchet must fail new
findings while passing baselined ones (the gate semantics work), and the
committed baseline must hold over the real source tree (the repo is clean
— this test IS ``sentio lint`` in CI).
"""

from pathlib import Path

from sentio_tpu.analysis.findings import Finding, diff_baseline, load_baseline
from sentio_tpu.analysis.runner import DEFAULT_BASELINE, lint_paths, run_gate

FIXTURES = Path(__file__).parent / "fixtures" / "lint"


def _findings(name: str) -> list[Finding]:
    return lint_paths([FIXTURES / name])


class TestSeededFixtures:
    def test_retrace_fixture_exact_findings(self):
        got = [(f.rule, f.line) for f in _findings("retrace_bad.py")]
        assert got == [
            ("retrace-unbounded-static", 17),
            ("retrace-traced-branch", 22),
            ("retrace-traced-cast", 29),
            ("retrace-host-state", 39),
        ]

    def test_lock_fixture_exact_finding(self):
        got = _findings("locks_bad.py")
        assert [(f.rule, f.line) for f in got] == [("lock-discipline", 15)]
        # the finding names both the attribute and the missing lock
        assert "_items" in got[0].message and "_lock" in got[0].message

    def test_replica_fixture_exact_findings(self):
        """Cross-replica routing state (multi-replica tier) mutated without
        its lock: both the unlocked increment and the unlocked read fire."""
        got = _findings("replica_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("lock-discipline", 16),
            ("lock-discipline", 17),
        ]
        assert "_routed" in got[0].message and "_lock" in got[0].message

    def test_supervisor_fixture_exact_findings(self):
        """Replica-supervisor health state (failure domains) mutated
        without its mutex: the unlocked transition write and the unlocked
        read both fire — the regression that would let the router race a
        quarantine."""
        got = _findings("supervisor_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("lock-discipline", 18),
            ("lock-discipline", 19),
        ]
        assert "_health" in got[0].message and "_mutex" in got[0].message

    def test_watchdog_fixture_exact_findings(self):
        """Unbounded blocking calls (the hang class the pump watchdog
        detects in production): the no-timeout thread join fires anywhere;
        the bare Event.wait / Queue.get fire inside supervisor-named code;
        the timeout-carrying and str.join/dict.get calls produce nothing."""
        got = _findings("watchdog_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("join-no-timeout", 23),
            ("supervisor-blocking-wait", 27),
            ("supervisor-blocking-wait", 28),
        ]
        assert "timeout" in got[0].message
        assert "watchdog" in got[1].message

    def test_phase_timer_fixture_exact_findings(self):
        """Phase-timer regions (tick-phase attribution, infra/phases.py)
        entered while an annotated lock is held: the nested form, the
        combined with-items form, and the `_locked`-contract form all
        fire; the timer-outside-lock ordering and the lock-free region
        produce nothing."""
        got = _findings("phase_timer_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("phase-timer-under-lock", 17),
            ("phase-timer-under-lock", 23),
            ("phase-timer-under-lock", 28),
        ]
        assert "dedicated phase" in got[0].message

    def test_fork_fixture_exact_findings(self):
        """Fork-flavored process creation (JAX-after-fork deadlocks): the
        direct syscalls, the fork/forkserver context selections, and the
        default-start-method worker constructions all fire; the spawn
        context, the annotated vetted site, and the unrelated dict.get
        produce nothing."""
        got = _findings("fork_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("no-fork", 12),
            ("no-fork", 14),
            ("no-fork", 18),
            ("no-fork", 19),
            ("no-fork", 24),
            ("no-fork", 25),
        ]
        assert "fork" in got[0].message and "spawn" in got[0].message

    def test_socket_fixture_exact_findings(self):
        """Deadline-free network blocking (the hang class the multi-host
        worker tier's partition watchdog exists to detect): the bare
        socket construction, the timeout-less create_connection, and the
        zero-timeout recv loop all fire; the settimeout-wired scopes, the
        timeout= dial, and the non-socket transport.recv() loop produce
        nothing."""
        got = _findings("socket_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("socket-no-timeout", 11),
            ("socket-no-timeout", 17),
            ("socket-no-timeout", 23),
        ]
        assert "settimeout" in got[0].message
        assert "timeout=" in got[1].message
        assert "recv loop" in got[2].message

    def test_clock_fixture_exact_finding(self):
        got = _findings("clock_bad.py")
        assert [(f.rule, f.line) for f in got] == [("wall-clock-duration", 6)]
        # the annotated stamp() call produced nothing

    def test_swallow_fixture_exact_finding(self):
        got = _findings("swallow_bad.py")
        assert [(f.rule, f.line) for f in got] == [("baseexception-swallow", 7)]
        # the cleanup-and-reraise handler produced nothing

    def test_telemetry_fixture_exact_findings(self):
        """Request-derived metric label values (the series-cardinality
        explosion the fleet telemetry merge would ship from every worker):
        the tenant-labeled shed counter, the request-id gauge key, the
        prompt-keyed merge dict, and the user-id f-string all fire; the
        typed-enum reason, the capped tenant-fairness pair, the
        deque-bounded flight record_tick, the allow-marked site, and the
        non-telemetry call produce nothing."""
        got = _findings("telemetry_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("telemetry-unbounded-labels", 9),
            ("telemetry-unbounded-labels", 14),
            ("telemetry-unbounded-labels", 18),
            ("telemetry-unbounded-labels", 23),
        ]
        assert "cardinality" in got[0].message
        assert "'rid'" in got[1].message

    def test_races_fixture_exact_findings(self):
        """Thread-role model + cross-thread race rule: the unnamed spawn
        and the unregistered name both fire; an unannotated attr written
        from two roles fires once at its first write; a thread-owned attr
        accessed from a foreign role fires at the foreign access. The
        registered spawns, the owner-role write, and the mutex-annotated
        attr produce nothing."""
        got = _findings("races_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("thread-role", 20),
            ("thread-role", 21),
            ("cross-thread-race", 26),
            ("cross-thread-race", 33),
        ]
        assert "without name=" in got[0].message
        assert "mystery-helper" in got[1].message
        assert "pump, telemetry" in got[2].message
        assert "engine-thread" in got[3].message
        assert "telemetry" in got[3].message

    def test_lockorder_fixture_exact_findings(self):
        """Lock-order graph: the lexical a->b/b->a inversion fires on both
        closing edges, the one-level call-propagated c->a/a->c inversion
        fires on both call sites, and the lexical re-acquisition fires as
        a self-deadlock. The consistently-ordered pair produces nothing."""
        got = _findings("lockorder_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("lock-order-inversion", 15),
            ("lock-order-inversion", 20),
            ("lock-order-inversion", 25),
            ("lock-order-inversion", 29),
            ("lock-order-inversion", 37),
        ]
        assert "pick one global order" in got[0].message
        assert "Router._c" in got[2].message  # call-propagated edge
        assert "re-acquires" in got[4].message  # lexical self-deadlock

    def test_failures_fixture_exact_findings(self):
        """Failure-surface rules: the codec-incompatible subclass, the
        untyped raise reaching the pump boundary, the typed catch
        re-raised untyped, the silent broad swallow, and the frame kind
        dispatched on only one transport each fire exactly once; the
        two-sided frame kind and the registered spawn produce nothing."""
        got = _findings("failures_bad.py")
        assert [(f.rule, f.line) for f in got] == [
            ("codec-roundtrip", 18),
            ("untyped-boundary-escape", 25),
            ("typed-error-untyped-rethrow", 41),
            ("broad-except-swallow", 46),
            ("frame-kind-unhandled", 58),
        ]
        assert "degrades to RuntimeError" in got[0].message
        assert "requires extra positional arguments" in got[0].message
        assert "Pump._pump_loop (pump thread)" in got[1].message
        assert "retry_after_s" in got[2].message
        assert "noqa: BLE001" in got[3].message
        assert "socket receive path" in got[4].message

    def test_clean_fixture_is_clean(self):
        assert _findings("clean.py") == []


class TestBaselineRatchet:
    F1 = Finding(rule="r", path="a.py", line=3, message="m", context="x = 1")
    F2 = Finding(rule="r", path="a.py", line=9, message="m", context="y = 2")

    def test_new_finding_fails(self):
        new, matched, stale = diff_baseline(
            [self.F1, self.F2],
            [self.F1.to_json()],
        )
        assert new == [self.F2]
        assert matched == [self.F1]
        assert stale == []

    def test_baselined_finding_passes(self):
        new, matched, stale = diff_baseline(
            [self.F1], [self.F1.to_json(), self.F2.to_json()]
        )
        assert new == []
        assert matched == [self.F1]
        # the fixed F2 entry reports stale so the baseline only shrinks
        assert len(stale) == 1 and stale[0]["context"] == "y = 2"

    def test_line_moves_do_not_break_matching(self):
        moved = Finding(rule="r", path="a.py", line=100, message="m",
                        context="x = 1")
        new, matched, _ = diff_baseline([moved], [self.F1.to_json()])
        assert new == [] and matched == [moved]

    def test_multiplicity(self):
        # two identical findings need two baseline entries
        new, matched, _ = diff_baseline(
            [self.F1, self.F1], [self.F1.to_json()]
        )
        assert len(matched) == 1 and len(new) == 1

    def test_inline_allow_suppresses(self, tmp_path):
        bad = tmp_path / "allow.py"
        bad.write_text(
            "import time\n\n"
            "def f(t0):\n"
            "    return time.time() - t0  # lint: allow(wall-clock-duration)\n"
        )
        assert lint_paths([bad]) == []


class TestRepoGate:
    def test_baseline_committed(self):
        assert DEFAULT_BASELINE.exists()
        entries = load_baseline(DEFAULT_BASELINE)
        assert isinstance(entries, list)

    def test_sentio_tpu_gate_green(self):
        """The committed gate: the analyzer over the real tree must produce
        no findings beyond the committed baseline, and no stale entries."""
        result = run_gate()
        assert result.ok, "NEW findings (fix or baseline):\n" + "\n".join(
            f.render() for f in result.new
        )
        assert not result.stale, (
            "stale baseline entries (finding fixed — shrink the baseline "
            "with `sentio lint --update-baseline`):\n"
            + "\n".join(str(e) for e in result.stale)
        )

    def test_repo_lock_graph_acyclic(self):
        """The real tree's static lock-order digraph must stay a DAG — a
        cycle is a deadlock two threads can walk into from opposite ends,
        and the committed baseline deliberately holds no inversion
        entries."""
        from sentio_tpu.analysis.lockorder import build_lock_graph
        from sentio_tpu.analysis.runner import PACKAGE_ROOT, parse_paths
        from sentio_tpu.analysis.threads import build_program

        files, errs = parse_paths([PACKAGE_ROOT])
        assert errs == []
        graph = build_lock_graph(build_program(files))
        assert graph.cycles() == []
        # the graph is not vacuously empty: the serving tier's known
        # cross-class acquisitions are present
        assert graph.locks, "lock graph lost every node"
        edges = {(e.src_lock, e.dst_lock) for e in graph.edges}
        assert ("PagedGenerationService._mutex", "FlightRecorder._lock") in edges

    def test_full_tree_lint_wall_time(self):
        """Perf guard: the whole-program pass (call graph + role BFS +
        lock digraph over every package file, on top of the 8 per-file
        rules) must stay interactive — it runs in CI on every commit and
        inside `sentio check`. Budget is ~5x the measured cost so only a
        complexity regression (quadratic resolver, unbounded BFS) trips
        it, not machine noise."""
        import time

        t0 = time.perf_counter()
        result = run_gate()
        elapsed = time.perf_counter() - t0
        assert result.findings is not None
        assert elapsed < 15.0, f"full-tree lint took {elapsed:.1f}s"

    def test_baseline_entries_justified(self):
        """Triage discipline: every committed baseline entry must say WHY
        it is acceptable — an unjustified entry is a finding someone
        snoozed, not one someone triaged."""
        for e in load_baseline(DEFAULT_BASELINE):
            assert e.get("why", "").strip(), (
                f"baseline entry for {e['path']} [{e['rule']}] carries no "
                f"'why' justification"
            )

    def test_repo_frame_channels_complete(self):
        """The annotated frame channels over the real tree: both RPC
        directions and both handshake directions exist, and every kind
        either side can emit has a dispatcher branch (the gate being green
        proves emit ⊆ dispatch; this pins the channel inventory so
        deleting an annotation can't silently vacate the contract)."""
        from sentio_tpu.analysis.failures import build_failure_graph
        from sentio_tpu.analysis.runner import PACKAGE_ROOT, parse_paths
        from sentio_tpu.analysis.threads import build_program

        files, _errs = parse_paths([PACKAGE_ROOT])
        graph = build_failure_graph(build_program(files))
        chans = graph["channels"]
        assert set(chans) == {
            "worker-to-router", "router-to-worker",
            "handshake-to-accepter", "handshake-to-dialer",
        }
        assert set(chans["worker-to-router"]["emits"]) == {
            "ready", "status", "ok", "err", "tok", "end", "telemetry",
            "pong", "deregister",
        }
        assert "generate" in chans["router-to-worker"]["emits"]
        assert "__shutdown__" in chans["router-to-worker"]["emits"]
        assert list(chans["handshake-to-accepter"]["emits"]) == ["hello"]
        # serving boundaries include the HTTP handlers and the worker RPC
        # dispatcher; the only typed-escape left is the sanitizer's
        # deliberate loud crash (baselined)
        kinds = {b["kind"] for b in graph["boundaries"]}
        assert "http handler" in kinds
        assert "worker RPC recv loop" in kinds

    def test_guarded_annotations_present(self):
        """The lock checker only has power if the annotations exist: the
        serving/telemetry classes must declare their guarded state."""
        import ast

        from sentio_tpu.analysis.findings import SourceFile
        from sentio_tpu.analysis.locks import collect_guarded

        repo = Path(__file__).resolve().parents[1]
        expectations = [
            ("sentio_tpu/runtime/service.py", "PagedGenerationService",
             "_inbox"),
            ("sentio_tpu/runtime/replica.py", "TenantFairQueue", "_tenants"),
            # replica failure domains: the supervisor's per-replica health
            # machine is submitter-and-supervisor shared state
            ("sentio_tpu/runtime/replica.py", "ReplicaSet", "_health"),
            ("sentio_tpu/infra/flight.py", "FlightRecorder", "_records"),
            ("sentio_tpu/infra/metrics.py", "InMemoryMetrics", "histograms"),
        ]
        for rel, cls, attr in expectations:
            p = repo / rel
            src = SourceFile(path=p, rel=rel, text=p.read_text())
            guarded = collect_guarded(ast.parse(src.text), src)
            assert cls in guarded, f"{rel}: {cls} lost its annotations"
            assert attr in guarded[cls].guarded, (
                f"{rel}: {cls}.{attr} lost its guarded-by annotation"
            )


class TestCli:
    def test_cli_lint_green(self, capsys):
        from sentio_tpu.cli import main

        assert main(["lint"]) == 0
        out = capsys.readouterr().out
        assert "0 new" in out

    def test_cli_lint_fails_on_fixture(self, capsys):
        from sentio_tpu.cli import main

        rc = main(["lint", str(FIXTURES / "clock_bad.py")])
        assert rc == 1
        assert "wall-clock-duration" in capsys.readouterr().out

    def test_cli_update_baseline_refuses_partial_tree(self, capsys):
        # a subset lint must not rewrite the full-tree baseline (the file
        # is legitimately empty since the top_k fix, so compare contents)
        from sentio_tpu.cli import main

        before = Path(DEFAULT_BASELINE).read_text()
        rc = main(["lint", str(FIXTURES / "clean.py"), "--update-baseline"])
        assert rc == 2
        assert "full-tree" in capsys.readouterr().err
        assert Path(DEFAULT_BASELINE).read_text() == before, \
            "baseline was rewritten"

    def test_cli_lint_json(self, capsys):
        import json

        from sentio_tpu.cli import main

        assert main(["lint", "--json", str(FIXTURES / "swallow_bad.py")]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["ok"] is False
        assert payload["new"][0]["rule"] == "baseexception-swallow"
        # the schema names every rule that ran, including the
        # whole-program concurrency rules
        assert "thread-role" in payload["rules"]
        assert "cross-thread-race" in payload["rules"]
        assert "lock-order-inversion" in payload["rules"]

    def test_cli_lock_graph_fixture_cycles(self, capsys):
        import json

        from sentio_tpu.cli import main

        rc = main(["lint", "--lock-graph",
                   str(FIXTURES / "lockorder_bad.py")])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["cycles"]
        assert "Router._a" in payload["locks"]
        vias = {e["via"] for e in payload["edges"]}
        assert vias == {"nested", "call"}

    def test_cli_lock_graph_repo_acyclic(self, capsys):
        import json

        from sentio_tpu.cli import main

        assert main(["lint", "--lock-graph"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["cycles"] == []

    def test_cli_failures_flag_scopes_rules(self, capsys):
        """--failures restricts the gate to the failure-surface rules: the
        fixture's five failure findings fail it, but a fixture whose only
        violations belong to other rules passes clean."""
        from sentio_tpu.cli import main

        assert main(["lint", "--failures",
                     str(FIXTURES / "failures_bad.py")]) == 1
        out = capsys.readouterr().out
        assert "codec-roundtrip" in out
        assert "frame-kind-unhandled" in out
        assert main(["lint", "--failures",
                     str(FIXTURES / "clock_bad.py")]) == 0

    def test_cli_failures_refuses_update_baseline(self, capsys):
        from sentio_tpu.cli import main

        before = Path(DEFAULT_BASELINE).read_text()
        rc = main(["lint", "--failures", "--update-baseline"])
        assert rc == 2
        assert Path(DEFAULT_BASELINE).read_text() == before

    def test_cli_sarif_output(self, capsys, tmp_path):
        import json

        from sentio_tpu.cli import main

        out_path = tmp_path / "out.sarif"
        rc = main(["lint", str(FIXTURES / "failures_bad.py"),
                   "--sarif", str(out_path)])
        assert rc == 1  # gate semantics unchanged by the export
        log = json.loads(out_path.read_text())
        assert log["version"] == "2.1.0"
        run = log["runs"][0]
        assert run["tool"]["driver"]["name"] == "sentio-lint"
        results = run["results"]
        assert {r["level"] for r in results} == {"error"}
        assert {r["ruleId"] for r in results} == {
            "codec-roundtrip", "untyped-boundary-escape",
            "typed-error-untyped-rethrow", "broad-except-swallow",
            "frame-kind-unhandled",
        }
        fp = results[0]["partialFingerprints"]["sentioLintKey/v1"]
        assert fp.count("|") == 2  # rule|path|context baseline key

    def test_cli_sarif_baselined_are_notes(self, tmp_path):
        import json

        from sentio_tpu.cli import main

        out_path = tmp_path / "repo.sarif"
        assert main(["lint", "--sarif", str(out_path)]) == 0
        results = json.loads(out_path.read_text())["runs"][0]["results"]
        assert results, "repo baseline produced no SARIF results"
        assert {r["level"] for r in results} == {"note"}
        # the baselined justification travels in the message
        assert any("[baselined:" in r["message"]["text"] for r in results)

    def test_cli_boundary_graph(self, capsys):
        import json

        from sentio_tpu.cli import main

        assert main(["lint", "--boundary-graph"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert "ServiceOverloaded" in payload["typed"]
        assert "GraphError" in payload["typed"]  # typed as of this pass
        assert len(payload["channels"]) == 4
