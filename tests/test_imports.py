"""Import-coverage smoke gate (tier-1): every module under sentio_tpu/ must
import cleanly on the CPU platform.

The reference enforced `--cov-fail-under=80`; pytest-cov is not in this
image and installs are forbidden, so this restores the intent at the
cheapest level that still catches whole-module rot: a module that cannot
even import (missing dep, syntax error, eager device init, bad top-level
config access) fails CI here instead of silently shipping dead code that
only a ``/chat`` in production would have exercised.
"""

from __future__ import annotations

import importlib
from pathlib import Path

import sentio_tpu

PACKAGE_ROOT = Path(sentio_tpu.__file__).parent


def _module_names():
    for path in sorted(PACKAGE_ROOT.rglob("*.py")):
        rel = path.relative_to(PACKAGE_ROOT.parent)
        parts = list(rel.with_suffix("").parts)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        name = ".".join(parts)
        # runnable entry points execute main() at import time by design
        if name.endswith("__main__"):
            continue
        yield name


def test_every_module_imports():
    names = list(_module_names())
    assert len(names) > 40, f"suspiciously few modules found: {names}"
    failures = []
    for name in names:
        try:
            importlib.import_module(name)
        except Exception as exc:  # noqa: BLE001 — report all, not first
            failures.append(f"{name}: {type(exc).__name__}: {exc}")
    assert not failures, "modules failed to import:\n" + "\n".join(failures)
