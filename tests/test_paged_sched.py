"""Admission scheduling + serving telemetry (runtime/paged.py).

Round-5 scheduler work: skip-ahead admission with a starvation bound,
backlog-scaled tick sizes, TTFT measurement, and prefix hit/miss counters.
The reference serves one request per HTTP call
(/root/reference/src/api/handlers/chat.py:148) and has no scheduler at all;
these tests pin the contract of ours.
"""

import pytest

from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.runtime.paged import ContinuousBatchingEngine

pytestmark = pytest.mark.slow


def make_engine(**kw):
    kw.setdefault("model_config", LlamaConfig.tiny())
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_pages_per_seq", 8)
    kw.setdefault("ignore_eos", True)  # deterministic request lifetimes
    return ContinuousBatchingEngine(**kw)


BIG = "x" * 100   # ~101 tokens -> 8 pages with max_new=24
SMALL = "hi there"  # ~9 tokens -> 1 page


class TestSkipAhead:
    def test_small_request_jumps_blocked_head(self):
        # 12 usable pages; A takes 8, leaving 4 — B (needs 8) blocks at the
        # head while C (1 page) must still admit into the idle slot. B's
        # prompt is DISTINCT from A's: an identical prompt would match A's
        # radix-cached span and rightly admit suffix-only instead of
        # blocking (A pins its span, so eviction can't help B either)
        eng = make_engine(num_pages=13)
        eng.submit(BIG, max_new_tokens=24)
        eng.step()
        assert sum(s.active for s in eng.slots) == 1
        rid_b = eng.submit("z" * 100, max_new_tokens=24)
        # max_new > one tick's sub-steps so C is still live when we assert
        eng.submit(SMALL, max_new_tokens=24)
        eng.step()
        assert sum(s.active for s in eng.slots) == 2
        assert [r.request_id for r in eng._queue] == [rid_b]
        assert eng.stats()["head_skips"] == 1

    def test_starvation_bound_reverts_to_fifo(self):
        eng = make_engine(num_pages=13)
        eng.head_skip_bound = 2
        eng.submit("y" * 60, max_new_tokens=200)  # hog: 8 pages, decodes long
        eng.step()
        rid_b = eng.submit(BIG, max_new_tokens=24)  # needs 8 > 4 free
        smalls = [eng.submit(SMALL, max_new_tokens=2) for _ in range(4)]
        eng.step()  # admits one small past the head (skip 1)
        eng.step()  # retires it, admits the next (skip 2)
        eng.step()
        eng.step()
        # bound reached: the remaining smalls may NOT jump the head anymore
        assert eng._head_skips == 2
        queued = [r.request_id for r in eng._queue]
        assert queued[0] == rid_b
        assert set(queued[1:]) == set(smalls[2:])
        # and a slot idles by design — FIFO fairness beats utilization now
        assert sum(s.active for s in eng.slots) == 1

    def test_head_admission_resets_skip_count(self):
        eng = make_engine(num_pages=13)
        eng.submit(BIG, max_new_tokens=24)
        eng.step()
        # distinct big prompt: must NOT match A's cached span (see above);
        # once A retires, its unpinned cached pages evict to admit B
        eng.submit("z" * 100, max_new_tokens=24)
        eng.submit(SMALL, max_new_tokens=2)
        eng.step()
        assert eng._head_skips == 1
        # drain everything; the blocked head admits once pages free up
        while eng.has_work:
            eng.step()
        assert eng._head_skips == 0


class TestBacklogScaledTicks:
    def test_deep_backlog_shrinks_tick(self):
        eng = make_engine(num_pages=33, steps_per_tick=8, max_tick_steps=32)
        for _ in range(10):
            eng.submit(SMALL, max_new_tokens=16)
        before = eng.total_sub_steps
        eng.step()  # 2 admit, 8 wait -> waiting//slots=4, capped -> steps=2
        assert eng.total_sub_steps - before == 2

    def test_idle_queue_runs_max_tick(self):
        eng = make_engine(num_pages=33, steps_per_tick=8, max_tick_steps=32)
        eng.submit(SMALL, max_new_tokens=20)
        before = eng.total_sub_steps
        eng.step()  # queue empties at admission -> waiting=0 -> big tick
        assert eng.total_sub_steps - before == 32

    def test_moderate_backlog_uses_steps_per_tick(self):
        eng = make_engine(num_pages=33, steps_per_tick=8, max_tick_steps=32)
        for _ in range(3):
            eng.submit(SMALL, max_new_tokens=16)
        before = eng.total_sub_steps
        eng.step()  # 2 admit, 1 waits -> shrink 1 -> steps=8
        assert eng.total_sub_steps - before == 8


class TestTtft:
    def test_ttft_recorded_per_request(self):
        eng = make_engine(num_pages=33)
        results = eng.run_all([SMALL, "another prompt", "third"], max_new_tokens=4)
        assert len(results) == 3
        stats = eng.stats()
        assert stats["ttft_count"] == 3
        assert stats["ttft_p50_ms"] >= 0.0
        assert stats["ttft_p95_ms"] >= stats["ttft_p50_ms"]


class TestPrefixTelemetryAndGuard:
    HEADER = "You are a concise assistant. Cite sources. "  # >1 page of tokens

    def test_hit_and_miss_counters(self):
        eng = make_engine(num_pages=33)
        assert eng.warm_prefix(self.HEADER) > 0
        eng.run_all([self.HEADER + "question one?", "unrelated prompt"],
                    max_new_tokens=2)
        stats = eng.stats()
        assert stats["prefix_hits"] == 1
        assert stats["prefix_misses"] == 1
        assert stats["prefix_hit_tokens"] > 0
        assert stats["prefix_hit_token_ratio"] > 0.0

    def test_warm_while_active_is_safe(self):
        # unlike the old register_prefix, warming the radix cache never
        # frees pages a live table references — legal while slots decode
        eng = make_engine(num_pages=33)
        eng.submit(SMALL, max_new_tokens=32)
        eng.step()
        assert any(s.active for s in eng.slots)
        assert eng.warm_prefix(self.HEADER) > 0
        while eng.has_work:
            eng.step()
        # second warm of the same text is an idempotent no-op
        pages_before = eng._radix.pages_held
        assert eng.warm_prefix(self.HEADER) > 0
        assert eng._radix.pages_held == pages_before

    def test_cache_disabled_has_no_radix(self):
        eng = make_engine(num_pages=33, prefix_cache=False)
        assert eng.warm_prefix(self.HEADER) == 0
        eng.run_all([self.HEADER + "question one?"], max_new_tokens=2)
        stats = eng.stats()
        assert "prefix_hit_tokens" not in stats
        assert eng._radix is None


class TestSustainedLoadOccupancy:
    """Round-5 scheduler targets (VERDICT r4 #2): under sustained load at
    concurrency 8, decode slots must stay busy and the latency tail must
    stay bounded. Thresholds are relaxed from the measured values
    (steady 7.67/8, p95/p50 2.17 on an idle host) to survive CI noise."""

    def test_occupancy_and_tail_under_burst(self):
        import threading
        import time as _t

        from sentio_tpu.runtime.service import PagedGenerationService

        eng = make_engine(max_slots=8, num_pages=1 + 64, steps_per_tick=8,
                          max_tick_steps=32, pipeline_depth=2)
        svc = PagedGenerationService(eng)
        trace = []
        orig = eng.step

        def traced():
            out = orig()
            trace.append(eng.last_tick_active)
            return out

        eng.step = traced
        lat = []

        def worker(i):
            t0 = _t.perf_counter()
            svc.generate(f"req {i} " + "pad " * (i % 5),
                         max_new_tokens=16 + (i * 7) % 48)
            lat.append((_t.perf_counter() - t0) * 1e3)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(40)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        svc.close()
        assert len(lat) == 40
        # steady-state window: skip the cold first tick and the drain tail
        steady = trace[1 : max(int(len(trace) * 0.7), 2)]
        avg = sum(steady) / len(steady)
        assert avg >= 5.0, f"steady occupancy {avg:.2f}/8 — slots are idling"
        lat.sort()
        p50 = lat[len(lat) // 2]
        p95 = lat[int(len(lat) * 0.95)]
        assert p95 <= 4.0 * p50, f"tail blown: p95 {p95:.0f}ms vs p50 {p50:.0f}ms"
        stats = svc.stats()
        assert stats["ttft_count"] == 40
