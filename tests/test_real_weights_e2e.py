"""Real-weights end-to-end: HF checkpoint → `cli convert` → engine →
/chat pipeline → verifier parsing REAL model-emitted JSON.

Round-1 gap (VERDICT item 5): the conversion/loading machinery existed but
no converted checkpoint ever served a request, and the verifier's JSON-audit
contract (reference src/core/llm/answer_verifier.py:67-86) had never met a
model that can emit JSON. There are no pretrained weights in this image
(zero egress), so this test MAKES one: a tiny Llama is trained in-process
to emit a fixed JSON verdict after any prompt (char-level HF tokenizer),
exported to a genuine HuggingFace checkpoint directory, imported back
through the real `cli convert` path, and served through the full
retrieve→generate→verify pipeline on the paged decode path. The verifier
must return verdict="pass" — which it can ONLY produce by successfully
parsing JSON the model actually sampled (every failure path yields "warn").

~1 min of training at CPU-test scale; module-scoped so it runs once.
"""

from __future__ import annotations

import json
import string
import time

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import optax  # noqa: E402

from sentio_tpu.config import (  # noqa: E402
    EmbedderConfig,
    GeneratorConfig,
    RerankConfig,
    Settings,
)
from sentio_tpu.models.llama import LlamaConfig, init_llama, llama_forward  # noqa: E402

pytestmark = pytest.mark.slow

VERDICT_JSON = '{"verdict": "pass", "citations_ok": true, "notes": []}'
TRAIN_SEQ = 208


@pytest.fixture(scope="module")
def char_tokenizer_dir(tmp_path_factory):
    """A genuine HF tokenizer (char-level WordLevel + Fuse decoder) built
    fully offline — round-trips arbitrary ASCII including JSON punctuation."""
    from tokenizers import Regex, Tokenizer, decoders, models, pre_tokenizers

    chars = sorted(set(string.ascii_letters + string.digits + string.punctuation + " "))
    vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
    for c in chars:
        vocab[c] = len(vocab)
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Split(Regex("."), behavior="isolated")
    tok.decoder = decoders.Fuse()
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, pad_token="<pad>", bos_token="<s>",
        eos_token="</s>", unk_token="<unk>",
    )
    d = tmp_path_factory.mktemp("char_tok")
    fast.save_pretrained(d)
    return str(d)


@pytest.fixture(scope="module")
def trained(char_tokenizer_dir):
    """Tiny Llama trained so greedy decode emits VERDICT_JSON after any
    prompt (mixed English/random-char prefixes, loss on the JSON suffix)."""
    import jax.numpy as jnp

    from sentio_tpu.models.tokenizer import HFTokenizer

    ht = HFTokenizer(char_tokenizer_dir)
    cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        mlp_dim=128, max_len=256, rope_theta=10_000.0, dtype="float32",
    )
    params = init_llama(jax.random.PRNGKey(0), cfg)
    target = ht.encode(VERDICT_JSON) + [ht.eos_id]
    rng = np.random.default_rng(0)
    chars = sorted(set(string.ascii_letters + string.digits + string.punctuation + " "))
    printable = [ht.encode(c)[0] for c in chars]
    english = (
        "You are an auditor. Verify the answer against the numbered sources. "
        "Reply with strict JSON only. Question: what is a systolic array? "
        "Answer: it multiplies matrices. Sources: [1] tpu docs (score 0.9). "
        "The quick brown fox jumps over the lazy dog. Context follows."
    )
    eng_ids = ht.encode(english)

    def make_batch(n):
        ids = np.full((n, TRAIN_SEQ), ht.pad_id, np.int32)
        attn = np.zeros((n, TRAIN_SEQ), bool)
        lw = np.zeros((n, TRAIN_SEQ), np.float32)
        for i in range(n):
            plen = int(rng.integers(4, TRAIN_SEQ - len(target) - 2))
            if rng.random() < 0.5:
                start = int(rng.integers(0, max(len(eng_ids) - plen, 1)))
                prompt = eng_ids[start : start + plen]
            else:
                prompt = list(rng.choice(printable, size=plen))
            row = [ht.bos_id] + list(prompt) + target
            ids[i, : len(row)] = row
            attn[i, : len(row)] = True
            lw[i, 1 + len(prompt) : len(row)] = 1.0
        return jnp.asarray(ids), jnp.asarray(attn), jnp.asarray(lw)

    tx = optax.adamw(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, ids, attn, lw):
        def loss_fn(p):
            logits, _ = llama_forward(p, cfg, ids[:, :-1], pad_mask=attn[:, :-1])
            tgt = ids[:, 1:]
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, tgt[:, :, None], axis=-1)[..., 0]
            w = lw[:, 1:]
            return (nll * w).sum() / jnp.maximum(w.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    t0 = time.time()
    loss = None
    for _ in range(500):
        ids, attn, lw = make_batch(12)
        params, opt, loss = step(params, opt, ids, attn, lw)
    assert float(loss) < 0.05, f"training failed to converge: loss={float(loss)}"
    params = jax.tree.map(lambda a: np.asarray(a), params)
    return params, cfg, ht, round(time.time() - t0, 1)


@pytest.fixture(scope="module")
def hf_checkpoint_dir(trained, tmp_path_factory):
    """Export the trained params into a REAL HuggingFace checkpoint
    directory (the exact inverse of models/convert.py's mapping), so the
    production `cli convert` import path is exercised on it."""
    params, cfg, _, _ = trained
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.dim,
        intermediate_size=cfg.mlp_dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_len,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.norm_eps,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    model = transformers.LlamaForCausalLM(hf_cfg)
    sd = {
        "model.embed_tokens.weight": params["embed_tokens"]["embedding"],
        "lm_head.weight": params["lm_head"]["kernel"].T,
        "model.norm.weight": params["final_norm"]["scale"],
    }
    for i in range(cfg.n_layers):
        lp = params[f"layers_{i}"]
        p = f"model.layers.{i}"
        sd[f"{p}.input_layernorm.weight"] = lp["attn_norm"]["scale"]
        sd[f"{p}.post_attention_layernorm.weight"] = lp["mlp_norm"]["scale"]
        for ours, theirs in (
            ("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj"), ("wo", "o_proj"),
        ):
            sd[f"{p}.self_attn.{theirs}.weight"] = lp["attn"][ours]["kernel"].T
        for ours, theirs in (("w_gate", "gate_proj"), ("w_up", "up_proj"), ("w_down", "down_proj")):
            sd[f"{p}.mlp.{theirs}.weight"] = lp["mlp"][ours]["kernel"].T
    missing, unexpected = model.load_state_dict(
        {k: torch.tensor(np.asarray(v, np.float32)) for k, v in sd.items()}, strict=False
    )
    # only non-persistent rotary buffers may be absent
    assert not unexpected, unexpected
    assert all("rotary" in k or "inv_freq" in k for k in missing), missing
    d = tmp_path_factory.mktemp("hf_ckpt")
    model.save_pretrained(d)
    return str(d)


@pytest.fixture(scope="module")
def converted_ckpt(hf_checkpoint_dir, tmp_path_factory):
    """Run the production CLI conversion on the HF directory."""
    from sentio_tpu.cli import main

    dst = str(tmp_path_factory.mktemp("converted") / "llama_ckpt")
    rc = main(["convert", "llama", hf_checkpoint_dir, dst, "--dtype", "float32"])
    assert rc == 0
    return dst


def _pipeline_settings(converted_ckpt, char_tokenizer_dir) -> Settings:
    return Settings(
        embedder=EmbedderConfig(provider="hash", dim=32),
        generator=GeneratorConfig(
            provider="tpu",
            checkpoint_path=converted_ckpt,
            tokenizer_path=char_tokenizer_dir,
            use_verifier=True,
            verifier_max_tokens=64,
            max_new_tokens=64,
            max_prompt_tokens=152,
            mode="fast",  # greedy — deterministic
            use_paged_decode=True,
            kv_page_size=16,
            kv_max_pages_per_seq=10,  # prompt cap 152 + 56 gen < trained 208
            max_batch_size=4,
        ),
        rerank=RerankConfig(enabled=False),
    )


class TestConvertedCheckpointServing:
    def test_chat_pipeline_verifier_parses_real_json(
        self, converted_ckpt, char_tokenizer_dir
    ):
        """Full pipeline on converted real weights, paged decode path: the
        verifier's verdict can only be 'pass' if it parsed JSON the model
        actually generated (every failure path in ops/verifier.py degrades
        to 'warn')."""
        from sentio_tpu.serve.dependencies import DependencyContainer

        settings = _pipeline_settings(converted_ckpt, char_tokenizer_dir)
        container = DependencyContainer(settings=settings)
        try:
            container.ingestor.ingest_document(
                "TPUs multiply matrices using a systolic array called the MXU."
            )
            result = container.chat_handler.process_chat_request_sync(
                question="What multiplies matrices on a TPU?"
            )
            assert result["metadata"]["degraded"] is False
            evaluation = result["metadata"].get("evaluation")
            assert evaluation, f"no verifier evaluation in {result['metadata']}"
            assert evaluation["verdict"] == "pass", evaluation
            assert evaluation["citations_ok"] is True
            # the generation itself came from the converted weights: the
            # model was trained to answer with the verdict JSON string
            assert "verdict" in result["answer"]
            # and it ran through the paged continuous-batching service
            stats = container.generation_service.stats()
            assert stats["completed"] >= 2  # generate + verify calls
        finally:
            container.cleanup()

    def test_loaded_config_roundtrips(self, converted_ckpt, trained):
        from sentio_tpu.runtime.weights import load_model

        _, cfg, _, _ = trained
        params, loaded_cfg, _ = load_model(converted_ckpt, expect_family="llama")
        assert loaded_cfg.dim == cfg.dim
        assert loaded_cfg.vocab_size == cfg.vocab_size
        assert loaded_cfg.n_kv_heads == cfg.n_kv_heads
        assert params["embed_tokens"]["embedding"].shape == (cfg.vocab_size, cfg.dim)

    def test_greedy_json_from_converted_weights_direct(
        self, converted_ckpt, char_tokenizer_dir
    ):
        """Engine-level check without the pipeline: converted weights +
        converted tokenizer produce parseable JSON for unseen prompts."""
        from sentio_tpu.runtime.engine import GeneratorEngine

        engine = GeneratorEngine(
            config=GeneratorConfig(
                provider="tpu", checkpoint_path=converted_ckpt,
                tokenizer_path=char_tokenizer_dir, max_new_tokens=64,
                max_prompt_tokens=152, mode="fast",
            ),
        )
        out = engine.generate(
            ["Audit the answer against the sources; reply with JSON only."],
            temperature=0.0,
        )[0]
        span = out.text[out.text.index("{") : out.text.rindex("}") + 1]
        parsed = json.loads(span)
        assert parsed["verdict"] == "pass"
        assert parsed["citations_ok"] is True
