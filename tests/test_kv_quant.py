"""int8 KV page quantization (runtime/paged.py): numeric fidelity of the
quantize/dequantize pair, attention parity against bf16 pages, engine
end-to-end behavior, and the halved-footprint claim."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.runtime.paged import (
    ContinuousBatchingEngine,
    _gather_pages,
    _layer_pages,
    _page_write,
    _paged_attn_xla,
    dequantize_kv,
    init_pool,
    quantize_kv,
)

pytestmark = pytest.mark.slow


class TestQuantPair:
    def test_roundtrip_error_small(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 16, 8, 64)), jnp.float32)
        q, s = quantize_kv(x)
        back = dequantize_kv(q, s, jnp.float32)
        rel = float(jnp.abs(back - x).max() / jnp.abs(x).max())
        assert rel < 0.01  # absmax int8: <= 1/254 of the vector range

    def test_zero_vectors_stay_zero(self):
        q, s = quantize_kv(jnp.zeros((3, 8)))
        assert float(jnp.abs(dequantize_kv(q, s, jnp.float32)).max()) == 0.0

    def test_int8_pool_halves_kv_bytes(self):
        cfg = LlamaConfig.tiny()
        bf16 = init_pool(cfg, num_pages=33, page_size=16)
        i8 = init_pool(cfg, num_pages=33, page_size=16, quantized=True)
        bf16_bytes = bf16.k.nbytes
        i8_bytes = i8.k["q"].nbytes + i8.k["s"].nbytes
        assert i8_bytes < 0.6 * bf16_bytes  # int8 + f16 scales (2/D overhead)


class TestAttentionParity:
    def test_paged_attn_matches_bf16_pages(self):
        """Decode attention over int8 pages must track the bf16-page result
        within quantization noise."""
        rng = np.random.default_rng(1)
        cfg = LlamaConfig.tiny()
        pool16 = init_pool(cfg, num_pages=17, page_size=16)
        pool8 = init_pool(cfg, num_pages=17, page_size=16, quantized=True)

        b, nb = 2, 4
        table = jnp.asarray(rng.choice(np.arange(1, 17), (b, nb), replace=False),
                            jnp.int32)
        lens = jnp.asarray([30, 55], jnp.int32)

        k16, v16, k8, v8 = pool16.k, pool16.v, pool8.k, pool8.v
        # fill the referenced pages via the write helper (layer 0 suffices)
        for row in range(b):
            for pos in range(int(lens[row]) + 1):
                pid = table[row, pos // 16][None]
                off = jnp.asarray([pos % 16])
                kv = jnp.asarray(rng.standard_normal((1, cfg.n_kv_heads, cfg.head_dim)),
                                 jnp.bfloat16)
                vv = jnp.asarray(rng.standard_normal((1, cfg.n_kv_heads, cfg.head_dim)),
                                 jnp.bfloat16)
                k16 = _page_write(k16, 0, pid, off, kv)
                v16 = _page_write(v16, 0, pid, off, vv)
                k8 = _page_write(k8, 0, pid, off, kv)
                v8 = _page_write(v8, 0, pid, off, vv)

        q = jnp.asarray(rng.standard_normal((b, 1, cfg.n_heads, cfg.head_dim)),
                        jnp.bfloat16)
        n_rep = cfg.n_heads // cfg.n_kv_heads
        out16 = _paged_attn_xla(q, _layer_pages(k16, 0), _layer_pages(v16, 0),
                                table, lens, n_rep)
        out8 = _paged_attn_xla(q, _layer_pages(k8, 0), _layer_pages(v8, 0),
                               table, lens, n_rep)
        diff = float(jnp.abs(out16.astype(jnp.float32) - out8.astype(jnp.float32)).max())
        assert diff < 0.05, diff

    def test_gather_dequantizes(self):
        cfg = LlamaConfig.tiny()
        pool8 = init_pool(cfg, num_pages=5, page_size=16, quantized=True)
        val = jnp.full((1, cfg.n_kv_heads, cfg.head_dim), 0.5, jnp.bfloat16)
        k8 = _page_write(pool8.k, 0, jnp.asarray([2]), jnp.asarray([3]), val)
        table = jnp.asarray([[2]], jnp.int32)
        dense = _gather_pages(_layer_pages(k8, 0), table, jnp.bfloat16)
        got = float(dense[0, 3, 0, 0])
        assert abs(got - 0.5) < 0.01


class TestEngineWithInt8KV:
    def test_generates_and_is_deterministic(self):
        cfg = LlamaConfig.tiny()
        eng = ContinuousBatchingEngine(
            model_config=cfg, max_slots=4, page_size=16, max_pages_per_seq=8,
            steps_per_tick=4, kv_quant="int8",
        )
        prompts = ["int8 pages", "second request"]
        a = eng.run_all(prompts, max_new_tokens=8, temperature=0.0)
        b = ContinuousBatchingEngine(
            model_config=cfg, max_slots=4, page_size=16, max_pages_per_seq=8,
            steps_per_tick=4, kv_quant="int8",
        ).run_all(prompts, max_new_tokens=8, temperature=0.0)
        assert [r.tokens for r in a] == [r.tokens for r in b]
        # a random-init model may greedy-sample EOS immediately (0 tokens);
        # determinism above is the real assertion — just require valid ends
        assert all(r.finish_reason in ("stop", "length") for r in a)

    def test_tracks_bf16_pool_closely(self):
        """Greedy tokens from int8 pages usually match bf16 pages on a tiny
        model; require agreement on the first emitted token per row (the
        least noise-accumulated position)."""
        cfg = LlamaConfig.tiny()
        prompts = ["compare the pools", "on two rows"]
        i8 = ContinuousBatchingEngine(
            model_config=cfg, max_slots=4, page_size=16, max_pages_per_seq=8,
            steps_per_tick=4, kv_quant="int8",
        ).run_all(prompts, max_new_tokens=6, temperature=0.0)
        bf = ContinuousBatchingEngine(
            model_config=cfg, max_slots=4, page_size=16, max_pages_per_seq=8,
            steps_per_tick=4,
        ).run_all(prompts, max_new_tokens=6, temperature=0.0)
        for a, b in zip(i8, bf):
            assert a.tokens[0] == b.tokens[0]

    def test_reset_preserves_quantization(self):
        cfg = LlamaConfig.tiny()
        eng = ContinuousBatchingEngine(
            model_config=cfg, max_slots=2, page_size=16, max_pages_per_seq=4,
            kv_quant="int8",
        )
        eng.reset()
        assert eng.pool.quantized
        assert isinstance(eng.pool.k, dict)

    def test_mesh_sharded_int8_pool(self):
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import build_mesh

        cfg = LlamaConfig.tiny()
        mesh = build_mesh(MeshConfig(dp_size=4, tp_size=2))
        pool = init_pool(cfg, num_pages=9, page_size=16, mesh=mesh,
                         quantized=True)
        # kv-head dim sharded over tp for both payload and scales
        assert pool.k["q"].sharding.spec[3] == "tp"
        assert pool.k["s"].sharding.spec[3] == "tp"

        eng = ContinuousBatchingEngine(
            model_config=cfg, mesh=mesh, max_slots=4, page_size=16,
            max_pages_per_seq=8, steps_per_tick=4, kv_quant="int8",
        )
        out = eng.run_all(["mesh int8"], max_new_tokens=6, temperature=0.0)
        assert out[0].finish_reason in ("stop", "length")

    def test_rejects_unknown_quant(self):
        with pytest.raises(ValueError, match="kv_quant"):
            ContinuousBatchingEngine(
                model_config=LlamaConfig.tiny(), kv_quant="fp4"
            )


class TestInt8PallasPath:
    """kv_quant=int8 no longer forces the XLA gather-dequant fallback: the
    Pallas kernel has a quantization-native variant, and with use_pallas
    the engine selects it (interpret mode on CPU)."""

    def test_engine_selects_pallas_impl_with_int8(self):
        eng = ContinuousBatchingEngine(
            model_config=LlamaConfig.tiny(), max_slots=2, page_size=16,
            max_pages_per_seq=4, kv_quant="int8", use_pallas=True,
        )
        assert eng._attn_impl is not None, (
            "int8 must not force the XLA fallback anymore")

    def test_pallas_and_xla_int8_paths_token_exact(self):
        """Both paths read the SAME int8+scale page values; greedy decode
        must be token-identical between them."""
        cfg = LlamaConfig.tiny()
        prompts = ["int8 kernel path", "second row of pages"]
        kw = dict(model_config=cfg, max_slots=2, page_size=16,
                  max_pages_per_seq=4, steps_per_tick=4, kv_quant="int8")
        pallas = ContinuousBatchingEngine(use_pallas=True, **kw)
        xla = ContinuousBatchingEngine(use_pallas=False, **kw)
        a = pallas.run_all(prompts, max_new_tokens=8, temperature=0.0)
        b = xla.run_all(prompts, max_new_tokens=8, temperature=0.0)
        assert [r.tokens for r in a] == [r.tokens for r in b]

    def test_fused_top_k_sampling_deterministic(self):
        """Per-request top_k rides the fused tick as traced data: same
        seed + same k → identical streams; the emission is valid."""
        cfg = LlamaConfig.tiny()

        def run():
            eng = ContinuousBatchingEngine(
                model_config=cfg, max_slots=2, page_size=16,
                max_pages_per_seq=4, steps_per_tick=4, kv_quant="int8",
            )
            rid = eng.submit("sampled int8", max_new_tokens=6,
                             temperature=0.8, top_k=4)
            done = {}
            while eng.has_work:
                for r in eng.step():
                    done[r.request_id] = r
            return done[rid]

        a, b = run(), run()
        assert a.tokens == b.tokens
        assert a.finish_reason in ("stop", "length")
