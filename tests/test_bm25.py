import numpy as np

from sentio_tpu.models.document import Document
from sentio_tpu.ops.bm25 import BM25Index, BM25Params, default_tokenizer


def test_tokenizer_lowercases_and_splits():
    assert default_tokenizer("Hello, World! 42") == ["hello", "world", "42"]


def test_exact_term_match_ranks_first(docs):
    index = BM25Index().build(docs)
    results = index.retrieve("systolic array matrix", top_k=3)
    assert results
    assert results[0].id == "d2"
    assert results[0].metadata["score"] > 0


def test_scores_match_naive_okapi(docs):
    """Vectorized CSR scoring must equal a straightforward per-doc loop."""
    params = BM25Params(k1=1.2, b=0.6)
    index = BM25Index(params=params).build(docs)
    query = "quick fox dog"
    fast = index.scores(query)

    # naive implementation
    tokenized = [default_tokenizer(d.content) for d in docs]
    n = len(docs)
    avgdl = sum(len(t) for t in tokenized) / n
    naive = np.zeros(n)
    for tok in default_tokenizer(query):
        df = sum(1 for t in tokenized if tok in t)
        if df == 0:
            continue
        idf = max(np.log(1 + (n - df + 0.5) / (df + 0.5)), 0.0)
        for di, toks in enumerate(tokenized):
            tf = toks.count(tok)
            if tf == 0:
                continue
            denom = tf + params.k1 * (1 - params.b + params.b * len(toks) / avgdl)
            naive[di] += idf * tf * (params.k1 + 1) / denom
    np.testing.assert_allclose(fast, naive, rtol=1e-5)


def test_unknown_terms_score_zero(docs):
    index = BM25Index().build(docs)
    assert index.search("zzzxqwv nonexistent", top_k=5) == []


def test_repeated_query_terms_accumulate(docs):
    index = BM25Index().build(docs)
    single = index.scores("fox")
    double = index.scores("fox fox")
    np.testing.assert_allclose(double, single * 2, rtol=1e-5)


def test_bm25_plus_delta_boosts_matches(docs):
    okapi = BM25Index(BM25Params()).build(docs)
    plus = BM25Index(BM25Params(variant="plus")).build(docs)
    q = "fox"
    s_ok, s_plus = okapi.scores(q), plus.scores(q)
    matched = s_ok > 0
    assert (s_plus[matched] > s_ok[matched]).all()
    assert (s_plus[~matched] == 0).all()


def test_save_load_roundtrip(tmp_path, docs):
    index = BM25Index().build(docs)
    index.save(tmp_path / "bm25")
    loaded = BM25Index.load(tmp_path / "bm25")
    q = "retrieval language models"
    np.testing.assert_allclose(loaded.scores(q), index.scores(q), rtol=1e-6)
    orig = [(d.id, d.metadata["score"]) for d in index.retrieve(q, 5)]
    new = [(d.id, d.metadata["score"]) for d in loaded.retrieve(q, 5)]
    assert orig == new


def test_empty_corpus():
    index = BM25Index().build([])
    assert index.search("anything") == []
    assert index.scores("anything").shape == (0,)


def test_load_with_custom_tokenizer_guard(tmp_path, docs):
    def shouty(text):
        return text.upper().split()

    index = BM25Index(tokenizer=shouty).build(docs)
    index.save(tmp_path / "custom")
    import pytest

    with pytest.raises(ValueError, match="custom tokenizer"):
        BM25Index.load(tmp_path / "custom")
    loaded = BM25Index.load(tmp_path / "custom", tokenizer=shouty)
    np.testing.assert_allclose(loaded.scores("quick FOX"), index.scores("quick FOX"))
