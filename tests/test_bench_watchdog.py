"""bench.py backend watchdog: the round-end artifact depends on this logic
choosing correctly between the live chip, a wedged tunnel, and a silently
degraded plugin."""

import os
import subprocess
import sys
from unittest import mock

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _completed(stdout: str, rc: int = 0):
    return subprocess.CompletedProcess(args=[], returncode=rc, stdout=stdout,
                                       stderr="boom" if rc else "")


class TestEnsureLiveBackend:
    def test_cpu_pinned_runs_skip_probe(self):
        with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "cpu"}, clear=False):
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)
            with mock.patch.object(subprocess, "run") as run:
                assert bench.ensure_live_backend() == ""
                run.assert_not_called()

    def test_healthy_accelerator_probe_passes(self):
        env = {"PALLAS_AXON_POOL_IPS": "10.0.0.1"}
        with mock.patch.dict(os.environ, env, clear=False):
            with mock.patch.object(subprocess, "run",
                                   return_value=_completed("tpu\n")):
                assert bench.ensure_live_backend() == ""

    def test_silent_cpu_fallback_is_flagged(self):
        """Plugin expected but the probe child initialized host CPU — must be
        marked, or phase C would publish CPU numbers as device numbers."""
        # sentinel platform: only the watchdog's OWN write can restore
        # "cpu", so the assertion observes the function, not the conftest
        env = {"PALLAS_AXON_POOL_IPS": "10.0.0.1", "JAX_PLATFORMS": "axon"}
        with mock.patch.dict(os.environ, env, clear=False):
            with mock.patch.object(subprocess, "run",
                                   return_value=_completed("cpu\n")):
                reason = bench.ensure_live_backend()
            assert os.environ.get("JAX_PLATFORMS") == "cpu"
            assert "PALLAS_AXON_POOL_IPS" not in os.environ
        assert "cpu" in reason

    def test_hung_probe_is_flagged(self):
        env = {"PALLAS_AXON_POOL_IPS": "10.0.0.1"}
        with mock.patch.dict(os.environ, env, clear=False):
            with mock.patch.object(
                subprocess, "run",
                side_effect=subprocess.TimeoutExpired(cmd="probe", timeout=1),
            ):
                reason = bench.ensure_live_backend(probe_timeout_s=1)
        assert "hung" in reason

    def test_crashed_probe_is_flagged(self):
        env = {"PALLAS_AXON_POOL_IPS": "10.0.0.1"}
        with mock.patch.dict(os.environ, env, clear=False):
            with mock.patch.object(subprocess, "run",
                                   return_value=_completed("", rc=1)):
                reason = bench.ensure_live_backend()
        assert "rc=1" in reason
