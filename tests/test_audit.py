"""Compile-manifest audit (analysis/audit) self-tests + the tier-1 gate.

Four layers, mirroring test_lint.py's structure:

* the REAL audit over the committed manifest must be green (this test IS
  ``sentio audit`` in CI — one report is built per module and shared);
* seeded regressions (an extra compile variant, a dropped donation, HBM
  growth, sharding drift) must each fail the diff / exit non-zero;
* the donation contract: every declared ``donate_argnums`` leaf of the
  decode/prefill-scatter/spec families must be aliased by lowering — the
  artifact-level proof of paged.py's "updated in place, never copied";
* the registry + compile fence: cache growth is counted per family, and an
  armed fence turns a post-warmup compile into CompileFenceError.
"""

from __future__ import annotations

import copy
import json

import numpy as np
import pytest

from sentio_tpu.analysis.audit import fence
from sentio_tpu.analysis.audit.manifest import (
    DEFAULT_MANIFEST,
    diff_manifest,
    load_manifest,
)
from sentio_tpu.analysis.audit.registry import jit_family


@pytest.fixture(scope="module")
def audit_result():
    from sentio_tpu.analysis.audit.runner import run_audit

    return run_audit()


@pytest.fixture(scope="module")
def manifest():
    man = load_manifest(DEFAULT_MANIFEST)
    assert man is not None, "analysis/compile_manifest.json missing"
    return man


DONATING_FAMILIES = (
    "paged.step_n",
    "paged.prefill_scatter",
    "paged.prior_prefill_scatter",
    "paged.draft_prefill",
    "paged_spec.spec_tick",
    # kv_quant="int8": the same families lowered over the {"q","s"} pool —
    # FOUR donated leaves per pool pair (payload + scales, k and v) must
    # all alias or the quantized pool silently starts copying per tick
    "paged.step_n@int8",
    "paged.prefill_scatter@int8",
    "paged.prior_prefill_scatter@int8",
)


class TestCommittedManifestGate:
    def test_audit_green_vs_committed_manifest(self, audit_result):
        assert audit_result.ok, (
            "compile audit regressions:\n"
            + "\n".join(str(r) for r in audit_result.diff.regressions)
        )
        # the ratchet should also be tight: no stale entries committed
        assert audit_result.diff.stale == []

    def test_every_registered_family_audited(self, audit_result):
        from sentio_tpu.analysis.audit.registry import families

        audited = set(audit_result.report["families"])
        assert set(families()) <= audited

    def test_variant_spaces_are_nontrivial(self, audit_result):
        fams = audit_result.report["families"]
        assert len(fams) >= 10
        assert sum(f["variant_count"] for f in fams.values()) >= 40
        # the tick ladder and the prior-table pow2 buckets must be visible
        assert any("steps=" in k for k in fams["paged.step_n"]["variants"])
        assert any("pnb=" in k
                   for k in fams["paged.prior_prefill_scatter"]["variants"])

    def test_quantized_families_audited_and_bounded(self, audit_result):
        """kv_quant=int8 lowers through its own manifest entries with the
        same declared tick ladder — the quantized compile space is bounded
        by exactly the helpers the bf16 space is."""
        fams = audit_result.report["families"]
        for name in ("paged.step_n@int8", "paged.prefill_scatter@int8",
                     "paged.prior_prefill_scatter@int8"):
            assert name in fams, name
            assert fams[name]["variant_count"] > 0
        assert set(fams["paged.step_n@int8"]["variants"]) \
            == set(fams["paged.step_n"]["variants"])

    def test_logprob_plumbing_adds_no_variant_axes(self, audit_result,
                                                   manifest):
        """The confidence gate's logprob accumulators ride the sampling
        dispatches as TRACED [S] data (runtime/sampling.py returning the
        chosen token's logprob, step_n's carried sum/min/count): the
        variant axes of every sampling family must stay exactly the
        declared bucket sets — steps ladder for step_n, width x rows
        (x pnb x do_sample) for the prefill scatters — in BOTH the fresh
        report and the committed manifest. A logprob knob that became a
        static arg would show up here as a new axis name."""
        allowed = {
            "paged.step_n": {"steps"},
            "paged.step_n@int8": {"steps"},
            "paged.prefill_scatter": {"width", "rows"},
            "paged.prefill_scatter@int8": {"width", "rows"},
            "paged.prior_prefill_scatter": {"width", "rows", "pnb",
                                            "do_sample"},
            "paged.prior_prefill_scatter@int8": {"width", "rows", "pnb",
                                                 "do_sample"},
            "paged.merge_admitted": {"rows"},
        }
        for source, where in ((audit_result.report, "report"),
                              (manifest, "manifest")):
            fams = source["families"]
            for name, axes in allowed.items():
                for vkey in fams[name]["variants"]:
                    seen = {part.split("=", 1)[0]
                            for part in vkey.split("|")}
                    assert seen <= axes, (
                        f"{where}: {name} variant {vkey!r} carries an axis "
                        f"outside the declared set {sorted(axes)}")
            # step_n's ladder must be the 3-4 rung set, not a fresh
            # program per logprob state
            assert fams["paged.step_n"]["variant_count"] <= 4

    def test_logprob_plumbing_drops_no_donated_pool_leaf(self, audit_result):
        """Growing step_n/prefill_scatter's outputs (packed logprob state,
        first-token logprobs) must not break donation: every declared
        donated pool leaf still aliases an output — bf16 (2 leaves per
        pool pair) and int8 ({'q','s'} pytree: 4 leaves) both."""
        fams = audit_result.report["families"]
        expect_leaves = {
            "paged.step_n": 2, "paged.prefill_scatter": 2,
            "paged.prior_prefill_scatter": 2,
            "paged.step_n@int8": 4, "paged.prefill_scatter@int8": 4,
            "paged.prior_prefill_scatter@int8": 4,
        }
        for name, leaves in expect_leaves.items():
            for vkey, variant in fams[name]["variants"].items():
                assert variant["donated_leaves"] == leaves, (name, vkey, variant)
                assert variant["aliased"] >= leaves, (
                    f"{name}[{vkey}] aliases {variant['aliased']} of "
                    f"{leaves} donated pool leaves — the logprob output "
                    f"change broke in-place pool updates")

    def test_quantized_pool_footprint_at_most_0_6x(self, audit_result,
                                                   manifest):
        """The footprint claim, gated twice: the fresh report AND the
        committed manifest must both show the int8 pool at <= 0.6x the
        bf16 pool's static HBM bytes (>= 40% saved) at serving head_dim."""
        for source, where in ((audit_result.report, "report"),
                              (manifest, "manifest")):
            pools = source.get("pools")
            assert pools, f"{where} has no pools section"
            assert pools["int8_pool_bytes"] <= 0.6 * pools["bf16_pool_bytes"], (
                where, pools)


class TestSeededRegressions:
    def test_extra_bucket_fails(self, audit_result, manifest):
        report = copy.deepcopy(audit_result.report)
        variants = report["families"]["paged.step_n"]["variants"]
        variants["steps=1024"] = dict(next(iter(variants.values())))
        diff = diff_manifest(report, manifest)
        assert not diff.ok
        assert any(r["kind"] == "new-variant" and "steps=1024" in r["where"]
                   for r in diff.regressions)

    def test_dropped_donation_fails(self, audit_result, manifest):
        report = copy.deepcopy(audit_result.report)
        variants = report["families"]["paged.prefill_scatter"]["variants"]
        key = next(iter(variants))
        variants[key]["aliased"] -= 1
        diff = diff_manifest(report, manifest)
        assert any(r["kind"] == "donation-dropped" for r in diff.regressions)

    def test_hbm_growth_fails(self, audit_result, manifest):
        report = copy.deepcopy(audit_result.report)
        variants = report["families"]["paged.step_n"]["variants"]
        key = next(iter(variants))
        variants[key]["arg_bytes"] += 1 << 20
        diff = diff_manifest(report, manifest)
        assert any(r["kind"] == "hbm-growth" for r in diff.regressions)

    def test_sharding_drift_fails(self, audit_result, manifest):
        report = copy.deepcopy(audit_result.report)
        state = report["sharding"]["state"]
        key = next(k for k, v in state.items() if "tp" in v)
        state[key] = "PartitionSpec()"  # silently replicated weight
        diff = diff_manifest(report, manifest)
        assert any(r["kind"] == "sharding-drift" and key in r["where"]
                   for r in diff.regressions)

    def test_new_jit_family_without_spec_fails(self, audit_result):
        from sentio_tpu.analysis.audit import registry
        from sentio_tpu.analysis.audit.runner import _check_coverage
        from sentio_tpu.analysis.audit.manifest import AuditDiff

        @jit_family("test.rogue_family")
        def rogue(x):
            return x + 1

        try:
            diff = AuditDiff()
            _check_coverage(audit_result.report, diff)
            assert any(r["kind"] == "family-unaudited"
                       and r["where"] == "test.rogue_family"
                       for r in diff.regressions)
        finally:
            registry._REGISTRY.pop("test.rogue_family", None)

    def test_seeded_regression_exits_nonzero(self, audit_result, tmp_path,
                                             monkeypatch, capsys):
        """CLI contract: a manifest missing a now-declared variant makes
        ``sentio audit`` exit 1 (the report itself is reused — only the
        gate runs)."""
        import sentio_tpu.analysis.audit.runner as runner_mod
        from sentio_tpu.cli import main as cli_main

        tampered = copy.deepcopy(audit_result.report)
        victim = tampered["families"]["paged.step_n"]["variants"]
        victim.pop(next(iter(victim)))
        path = tmp_path / "manifest.json"
        path.write_text(json.dumps(tampered))
        monkeypatch.setattr(runner_mod, "run_audit",
                            lambda manifest_path=None, include_mesh=True:
                            runner_mod.AuditResult(
                                report=audit_result.report,
                                diff=diff_manifest(audit_result.report,
                                                   load_manifest(path)),
                            ))
        rc = cli_main(["audit", "--manifest", str(path), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1 and not out["ok"]
        assert any(r["kind"] == "new-variant" for r in out["regressions"])


class TestDonationAliasing:
    def test_all_declared_donations_alias(self, audit_result):
        """Regression guard for the in-place pool contract: every donated
        leaf of every decode/scatter variant must be aliased by XLA. A
        future edit that reorders outputs or drifts a dtype breaks the
        alias silently at runtime — and loudly here."""
        for name in DONATING_FAMILIES:
            fam = audit_result.report["families"][name]
            assert fam["donate_argnums"], name
            for key, variant in fam["variants"].items():
                assert variant["donated_leaves"] > 0, (name, key)
                assert variant["aliased"] == variant["donated_leaves"], (
                    f"{name}[{key}]: {variant['aliased']} of "
                    f"{variant['donated_leaves']} donated leaves aliased"
                )

    def test_dropped_donation_detected_by_lowering(self):
        """A donated arg that cannot alias (not returned) lowers with zero
        aliasing — the signal the manifest gate rides on."""
        from sentio_tpu.analysis.audit.lowering import audit_variant

        @jit_family("test.bad_donor", donate_argnums=(0,), register=False)
        def bad_donor(pool, x):
            return x * 2.0  # pool never returned -> donation unusable

        import jax

        entry = audit_variant(
            bad_donor, (0,),
            (jax.ShapeDtypeStruct((8, 4), np.float32),
             jax.ShapeDtypeStruct((4,), np.float32)),
            {},
        )
        assert entry["donated_leaves"] == 1
        assert entry["aliased"] == 0


class TestCompileFence:
    @pytest.fixture(autouse=True)
    def _clean_fence(self):
        fence.reset()
        yield
        fence.reset()

    def test_family_counts_cache_growth(self):
        @jit_family("test.counting", register=False)
        def fn(x):
            return x + 1

        base = fence.compiles_total()
        fn(np.ones(3, np.float32))
        assert fence.compiles_total() == base + 1
        fn(np.zeros(3, np.float32))  # same shape: cached, no compile
        assert fence.compiles_total() == base + 1
        fn(np.ones(5, np.float32))  # new shape: one more variant
        assert fence.compiles_total() == base + 2
        events = fence.drain_events()
        assert [e["family"] for e in events] == ["test.counting"] * 2
        assert "float32[5]" in events[-1]["signature"]

    def test_armed_fence_raises_with_family_and_signature(self):
        @jit_family("test.fenced", register=False)
        def fn(x):
            return x * 2

        fn(np.ones(3, np.float32))  # warmup
        fence.arm()
        fn(np.ones((3,), np.float32))  # warm shape: fine
        with pytest.raises(fence.CompileFenceError) as exc:
            fn(np.ones(7, np.float32))
        assert exc.value.family == "test.fenced"
        assert "float32[7]" in exc.value.signature
        fence.disarm()
        fn(np.ones(9, np.float32))  # disarmed: counted, not fatal

    def test_lowering_never_feeds_the_counters(self):
        import jax

        @jit_family("test.aot", register=False)
        def fn(x):
            return x + 1

        base = fence.compiles_total()
        fn.lower(jax.ShapeDtypeStruct((4,), np.float32))
        assert fence.compiles_total() == base

    def test_instance_scoped_exemption_for_supervised_rebuild(self):
        """A replica rebuild's fresh engine warms under an ARMED fence via
        instance-scoped exemption: the exempt FamilyFn's cold compiles are
        counted but not fatal, while a sibling (non-exempt) instance still
        trips the fence throughout — steady-state recompiles stay loud."""

        @jit_family("test.rebuilt", register=False)
        def rebuilt(x):
            return x + 1

        @jit_family("test.sibling", register=False)
        def sibling(x):
            return x - 1

        sibling(np.ones(3, np.float32))  # warmed before arming
        fence.arm()
        base = fence.compiles_total()
        rebuilt.fence_exempt = True
        rebuilt(np.ones(4, np.float32))  # cold compile: exempt, counted
        assert fence.compiles_total() == base + 1
        with pytest.raises(fence.CompileFenceError):
            sibling(np.ones(8, np.float32))  # sibling recompile: still fatal
        rebuilt.fence_exempt = False  # warmup over: exemption lifted
        with pytest.raises(fence.CompileFenceError):
            rebuilt(np.ones(16, np.float32))
        fence.disarm()

    def test_engine_set_fence_exempt_toggles_family_instances(self):
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        engine = ContinuousBatchingEngine(
            max_slots=2, page_size=8, max_pages_per_seq=4,
        )
        fns = [getattr(engine, attr) for attr in engine.FAMILY_ATTRS
               if getattr(engine, attr, None) is not None]
        assert fns, "engine exposes no family instances"
        assert all(fn.fence_exempt is False for fn in fns)
        engine.set_fence_exempt(True)
        assert all(fn.fence_exempt is True for fn in fns)
        engine.set_fence_exempt(False)
        assert all(fn.fence_exempt is False for fn in fns)


class TestServingTelemetry:
    def test_ticks_carry_compile_counts_and_fence_survives_warm_traffic(self):
        """One tiny service burst: warmup compiles, the fence arms, warm
        traffic decodes without tripping it, and flight-recorder ticks
        carry the per-tick xla_compiles attribution."""
        from sentio_tpu.analysis.audit.specs import _paged_engine
        from sentio_tpu.infra.flight import FlightRecorder, set_flight_recorder
        from sentio_tpu.runtime.service import PagedGenerationService

        fence.reset()
        recorder = FlightRecorder()
        set_flight_recorder(recorder)
        service = PagedGenerationService(_paged_engine(prefill_chunk=None))
        try:
            stats = service.warmup(max_new_tokens=2)
            assert stats["prompts"] > 0
            assert stats["xla_compiles"] > 0  # cold engine really compiled
            fence.arm()
            out = service.generate("warm again", max_new_tokens=2)
            assert out.finish_reason in ("stop", "length")
            ticks = recorder.timeline()
            assert ticks and all("xla_compiles" in t for t in ticks)
            # compile events are attributed to the tick that paid for them
            compiled_ticks = [t for t in ticks if t["xla_compiles"]]
            assert compiled_ticks
            assert any("family" in e
                       for t in compiled_ticks
                       for e in t.get("compile_events", []))
            # the armed window itself stayed compile-free
            armed_ticks = ticks[-1]
            assert armed_ticks["xla_compiles"] == 0
        finally:
            fence.reset()
            service.close()
            set_flight_recorder(None)

    def test_metrics_counter_increments(self):
        from sentio_tpu.infra.metrics import MetricsCollector, get_metrics, set_metrics

        fence.reset()
        set_metrics(MetricsCollector())
        try:
            fence.note_compile("test.metrics", "(float32[1])", 2)
            snap = get_metrics().export_json()
            key = "xla_compiles('test.metrics',)"
            assert snap["counters"].get(key) == 2.0
        finally:
            fence.reset()
            set_metrics(None)
