"""Quantization-native paged attention (kernels/paged_attention.py).

Tier-1 parity matrix for the int8 Pallas kernel in interpret mode: the
kernel must agree with the XLA gather-dequant path almost exactly (both
read the SAME int8+scale values — only the fold order differs) and with
the bf16-page reference within quantization tolerance, across GQA
ratios, ragged row lengths, and partial last pages. Plus the fused-
sampling compile telemetry: a decode tick is ONE ``paged.step_n``
dispatch — changing per-request top_k/temperature after warmup must not
compile anything new, and no sampling-only jit family may exist.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.kernels.paged_attention import (
    paged_attention,
    paged_attention_quant,
)
from sentio_tpu.runtime.paged import _paged_attn_xla, quantize_kv


def _quant_pool(rng, num_pages, page, hkv, d):
    k = jnp.asarray(rng.standard_normal((num_pages, page, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((num_pages, page, hkv, d)), jnp.float32)
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    return k, v, kq, ks, vq, vs


class TestInt8KernelParity:
    @pytest.mark.parametrize(
        "h,hkv",
        [(4, 1), (4, 2), (4, 4)],
        ids=["gqa4:1", "gqa4:2", "mha4:4"],
    )
    def test_matches_gather_dequant_across_gqa(self, h, hkv):
        """Same int8 values in, near-identical attention out: the in-register
        (q·K)·s fold vs the dense dequant-then-attend gather."""
        rng = np.random.default_rng(0)
        b, d, page, nb, num_pages = 3, 16, 8, 4, 13
        _k, _v, kq, ks, vq, vs = _quant_pool(rng, num_pages, page, hkv, d)
        q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
        table = jnp.asarray(
            [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]], jnp.int32)
        # ragged: mid-first-page, mid-window (partial page 3), full window
        lens = jnp.asarray([5, 17, 30], jnp.int32)

        ref = _paged_attn_xla(
            q[:, None], {"q": kq, "s": ks}, {"q": vq, "s": vs},
            table, lens, h // hkv,
        )[:, 0]
        got = paged_attention_quant(
            q, kq, ks, vq, vs, table, lens, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_tracks_bf16_kernel_within_quant_tolerance(self):
        rng = np.random.default_rng(1)
        b, h, hkv, d, page, nb, num_pages = 2, 4, 2, 32, 8, 4, 9
        k, v, kq, ks, vq, vs = _quant_pool(rng, num_pages, page, hkv, d)
        q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
        table = jnp.asarray(
            rng.choice(np.arange(1, num_pages), (b, nb), replace=False),
            jnp.int32)
        lens = jnp.asarray([13, 27], jnp.int32)

        ref = paged_attention(q, k, v, table, lens, interpret=True)
        got = paged_attention_quant(
            q, kq, ks, vq, vs, table, lens, interpret=True)
        diff = float(jnp.abs(got - ref).max())
        assert diff < 0.05, diff  # absmax int8: ~1e-2 worst-case here

    def test_partial_last_page_masks_garbage(self):
        """Positions past ``lens`` on the current page must not leak: poison
        the tail of the last page and require an unchanged result."""
        rng = np.random.default_rng(2)
        b, h, hkv, d, page, num_pages = 1, 2, 1, 16, 8, 5
        k, v, kq, ks, vq, vs = _quant_pool(rng, num_pages, page, hkv, d)
        q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
        table = jnp.asarray([[2, 3]], jnp.int32)
        lens = jnp.asarray([10], jnp.int32)  # 3rd token of page 3

        clean = paged_attention_quant(
            q, kq, ks, vq, vs, table, lens, interpret=True)
        kq2 = kq.at[3, 4:].set(127)
        ks2 = ks.at[3, 4:].set(100.0)
        vq2 = vq.at[3, 4:].set(127)
        vs2 = vs.at[3, 4:].set(100.0)
        poisoned = paged_attention_quant(
            q, kq2, ks2, vq2, vs2, table, lens, interpret=True)
        np.testing.assert_array_equal(np.asarray(clean), np.asarray(poisoned))

    def test_single_row_single_page(self):
        """Smallest shape: one row, length inside the first page."""
        rng = np.random.default_rng(3)
        b, h, hkv, d, page, num_pages = 1, 2, 2, 16, 8, 3
        k, v, kq, ks, vq, vs = _quant_pool(rng, num_pages, page, hkv, d)
        q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
        table = jnp.asarray([[1]], jnp.int32)
        lens = jnp.asarray([0], jnp.int32)  # only the freshly written token

        ref = _paged_attn_xla(
            q[:, None], {"q": kq, "s": ks}, {"q": vq, "s": vs},
            table, lens, h // hkv,
        )[:, 0]
        got = paged_attention_quant(
            q, kq, ks, vq, vs, table, lens, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5, rtol=2e-5)


class TestFusedSamplingTelemetry:
    def test_tick_is_one_family_and_sampling_params_never_recompile(self):
        """Compile telemetry proof that sampling lives INSIDE the decode
        dispatch: after a warmup generation, submissions with different
        temperature / top_k values reuse the compiled ``paged.step_n``
        variants verbatim (traced sampling params — zero cache growth), and
        every compile event ever seen belongs to a ``paged.*`` family (no
        separate logits-then-sample dispatch exists to compile)."""
        from sentio_tpu.analysis.audit import fence
        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        fence.reset()
        try:
            eng = ContinuousBatchingEngine(
                model_config=LlamaConfig.tiny(), max_slots=2, page_size=16,
                max_pages_per_seq=4, steps_per_tick=4,
            )
            eng.run_all(["warm the tick"], max_new_tokens=6, temperature=0.0)
            # second generation: admission now merges into DEVICE-carried
            # decode state (the first merged into host-mirror seeds), which
            # is its own compiled variant — warm it like service.warmup does
            eng.run_all(["warm the tick"], max_new_tokens=6, temperature=0.0)
            events = fence.drain_events()
            assert events, "cold engine must have compiled something"
            assert all(e["family"].startswith("paged.") for e in events), (
                [e["family"] for e in events])

            # same shapes, different sampling params: the armed fence turns
            # any recompile into an error — none may happen
            fence.arm()
            try:
                out = eng.run_all(
                    ["warm the tick"], max_new_tokens=6, temperature=0.9)
                assert out[0].finish_reason in ("stop", "length")
                rid = eng.submit("warm the tick", max_new_tokens=6,
                                 temperature=0.7, top_k=5)
                done = {}
                while eng.has_work:
                    for r in eng.step():
                        done[r.request_id] = r
                assert done[rid].finish_reason in ("stop", "length")
            finally:
                fence.disarm()
            assert fence.drain_events() == []
        finally:
            fence.reset()

    def test_spec_engine_rejects_top_k(self):
        from sentio_tpu.analysis.audit.specs import _paged_engine

        eng = _paged_engine(draft=True)
        with pytest.raises(ValueError, match="speculation"):
            eng.submit("draft pool", max_new_tokens=2, top_k=3)

    def test_stream_rejects_top_k_at_call_time(self):
        """generate_stream is lazily executed; the top_k/speculation
        rejection must still fire at CALL time (before an SSE handler
        could commit its 200), not at first iteration."""
        from sentio_tpu.analysis.audit.specs import _paged_engine
        from sentio_tpu.runtime.service import PagedGenerationService

        svc = PagedGenerationService(_paged_engine(draft=True))
        try:
            with pytest.raises(ValueError, match="speculation"):
                svc.generate_stream("spec stream", max_new_tokens=2, top_k=3)
            with pytest.raises(ValueError, match="speculation"):
                svc.generate("spec call", max_new_tokens=2, top_k=3)
        finally:
            svc.close()
