"""Runtime sanitizer (SENTIO_SANITIZE=1) — the dynamic half of sentio lint.

Verifies the five checks the sanitizer provides: lock ownership recording
on annotated locks, the single-driver-thread contract on engine entry
points (a cross-thread engine call raises), per-tick engine invariants (an
injected page leak and an injected radix refcount leak are both caught on
the next tick, not at pool exhaustion later), runtime lock-order tracking
(the first acquisition reversing an observed order raises before taking
the lock), and Eraser-style lockset enforcement on ``guard_locksets``
classes (a second thread writing a guarded attribute without the lock
empties the candidate lockset and raises).
"""

import threading

import pytest

from sentio_tpu.analysis.sanitizer import (
    OwnedLock,
    SanitizerError,
    _reset_lock_order,
    assert_held,
    check_engine_invariants,
    enabled,
    guard_locksets,
    held_lock_names,
    make_lock,
)

# conftest enables SENTIO_SANITIZE=1 for this module; every engine below is
# constructed with the sanitizer armed


def _engine(**kw):
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine

    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 4)
    kw.setdefault("steps_per_tick", 4)
    return ContinuousBatchingEngine(**kw)


PROMPT = "a reasonably long prompt that spans multiple cache pages easily"


class TestLockOwnership:
    def test_make_lock_returns_owned_lock(self):
        assert enabled()
        lock = make_lock("test")
        assert isinstance(lock, OwnedLock)

    def test_assert_held_raises_when_not_held(self):
        lock = make_lock("test")
        with pytest.raises(SanitizerError, match="not held"):
            assert_held(lock)

    def test_assert_held_passes_inside_with(self):
        lock = make_lock("test")
        with lock:
            assert_held(lock)
        with pytest.raises(SanitizerError):
            assert_held(lock)

    def test_plain_lock_no_ops(self, monkeypatch):
        monkeypatch.delenv("SENTIO_SANITIZE")
        lock = make_lock("test")
        assert not isinstance(lock, OwnedLock)
        assert_held(lock)  # no-op, never raises

    def test_held_by_other_thread_raises(self):
        lock = make_lock("test")
        lock.acquire()
        err: list = []

        def other():
            try:
                assert_held(lock)
            except SanitizerError as exc:
                err.append(exc)

        t = threading.Thread(target=other)
        t.start()
        t.join()
        lock.release()
        assert err, "assert_held must reject a non-owner thread"


class TestThreadGuard:
    def test_cross_thread_step_raises(self):
        eng = _engine()
        eng.submit(PROMPT, max_new_tokens=4)  # binds this thread as driver
        caught: list = []

        def intruder():
            try:
                eng.step()
            except SanitizerError as exc:
                caught.append(exc)

        t = threading.Thread(target=intruder, name="intruder")
        t.start()
        t.join()
        assert caught, "cross-thread engine.step must raise under sanitize"
        assert "single-threaded" in str(caught[0])
        # the rightful driver still works
        while eng.has_work:
            eng.step()

    def test_cross_thread_submit_raises(self):
        eng = _engine()
        eng.step()  # bind
        caught: list = []

        def intruder():
            try:
                eng.submit("hi", max_new_tokens=2)
            except SanitizerError as exc:
                caught.append(exc)

        t = threading.Thread(target=intruder)
        t.start()
        t.join()
        assert caught

    def test_ownership_migrates_from_dead_thread(self):
        eng = _engine()

        def first_driver():
            eng.submit(PROMPT, max_new_tokens=2)

        t = threading.Thread(target=first_driver)
        t.start()
        t.join()
        # the binding thread is dead: the next driver inherits cleanly
        while eng.has_work:
            eng.step()


class TestCrossReplicaOwnership:
    """Multi-replica tier: pump-thread ownership is PER REPLICA — each
    replica's pump owns only its own engine, and a thread that legitimately
    drives replica 0 is still an intruder on replica 1."""

    def test_cross_replica_mutation_raises(self):
        e0 = _engine()
        e1 = _engine()
        ready = threading.Event()
        release = threading.Event()

        def replica_one_pump():
            e1.submit("replica one work", max_new_tokens=2)  # binds e1
            ready.set()
            release.wait(timeout=60)

        t = threading.Thread(target=replica_one_pump, name="r1-pump")
        t.start()
        ready.wait(timeout=60)
        # this thread legitimately drives replica 0...
        e0.submit("replica zero work", max_new_tokens=2)
        caught: list = []
        try:
            # ...but replica 1 is owned by its own (live) pump: a
            # cross-replica mutation must raise, not silently interleave
            try:
                e1.step()
            except SanitizerError as exc:
                caught.append(exc)
        finally:
            release.set()
            t.join(timeout=60)
        assert caught, "cross-replica engine.step must raise under sanitize"
        assert "single-threaded" in str(caught[0])
        # replica 0 was never poisoned: its rightful driver finishes
        while e0.has_work:
            e0.step()
        # replica 1's owner died: ownership migrates and IT finishes too
        while e1.has_work:
            e1.step()

    def test_replica_set_names_guards_per_replica(self):
        from sentio_tpu.runtime.replica import ReplicaSet
        from sentio_tpu.runtime.service import PagedGenerationService

        e0 = _engine()
        e1 = _engine()
        rs = ReplicaSet([PagedGenerationService(e0),
                         PagedGenerationService(e1)])
        try:
            assert "[r0]" in e0._san.name and "[r1]" in e1._san.name
        finally:
            rs.close()


class TestEngineInvariants:
    # the conservation/refcount checks are representation-blind, but the
    # quantized dict pool must ride through the same per-tick verification
    # — every injected-corruption scenario runs at both pool reprs
    @pytest.mark.parametrize("kv_quant", ["none", "int8"])
    def test_clean_run_passes(self, kv_quant):
        eng = _engine(kv_quant=kv_quant)
        results = eng.run_all([PROMPT, "short one"], max_new_tokens=6)
        assert len(results) == 2
        check_engine_invariants(eng)  # idle state is also conserved

    @pytest.mark.parametrize("kv_quant", ["none", "int8"])
    def test_injected_page_leak_caught(self, kv_quant):
        eng = _engine(kv_quant=kv_quant)
        eng.run_all([PROMPT], max_new_tokens=4)
        # simulate a lost page: it vanishes from the free list without any
        # owner — the very next tick must fail loudly
        leaked = eng.allocator._free.pop()
        assert leaked > 0
        eng.submit("short one", max_new_tokens=2)
        with pytest.raises(SanitizerError, match="leaked"):
            while eng.has_work:
                eng.step()

    def test_injected_double_own_caught(self):
        eng = _engine()
        eng.run_all([PROMPT], max_new_tokens=4)
        # a double-free: the free list gains a second copy of a page id
        # (inserted at the head — allocation pops the tail, so the duplicate
        # survives to the next tick's check instead of being immediately
        # handed out and retired away)
        eng.allocator._free.insert(0, eng.allocator._free[0])
        with pytest.raises(SanitizerError, match="duplicates"):
            eng.submit("short one", max_new_tokens=2)
            while eng.has_work:
                eng.step()

    @pytest.mark.parametrize("kv_quant", ["none", "int8"])
    def test_injected_refcount_leak_caught(self, kv_quant):
        eng = _engine(kv_quant=kv_quant)
        eng.run_all([PROMPT], max_new_tokens=4)
        radix = eng._radix
        assert radix is not None and not radix.empty
        # a pin with no live slot behind it (the bug class: a retire path
        # that forgets unlock) — caught on the next tick
        node = next(iter(radix.root.children.values()))
        radix.lock(node)
        eng.submit("short one", max_new_tokens=2)
        with pytest.raises(SanitizerError, match="refcount"):
            while eng.has_work:
                eng.step()

    def test_disabled_engine_skips_checks(self, monkeypatch):
        monkeypatch.delenv("SENTIO_SANITIZE")
        eng = _engine()
        assert eng._san is None
        eng.run_all([PROMPT], max_new_tokens=2)
        # injected corruption goes UNnoticed without the sanitizer — the
        # checks are genuinely opt-in
        eng.allocator._free.pop()
        eng.submit("short one", max_new_tokens=2)
        while eng.has_work:
            eng.step()


class TestQuantPoolRepr:
    """The sanitizer's pool-representation half: the ``{"q","s"}`` dict
    pool is held to per-tick metadata invariants (int8 payload, f16 scales
    mirroring the payload shape), so a refactor that silently densifies or
    drops the scale tree fails the tick that did it."""

    def test_clean_int8_tick_passes(self):
        eng = _engine(kv_quant="int8")
        eng.run_all([PROMPT], max_new_tokens=4)
        check_engine_invariants(eng)

    def test_densified_pool_caught(self):
        eng = _engine(kv_quant="int8")
        eng.run_all([PROMPT], max_new_tokens=2)
        eng.pool.k = eng.pool.k["q"]  # the dense-copy regression
        with pytest.raises(SanitizerError, match="pytree"):
            check_engine_invariants(eng)

    def test_scale_dtype_drift_caught(self):
        import jax.numpy as jnp

        eng = _engine(kv_quant="int8")
        eng.run_all([PROMPT], max_new_tokens=2)
        eng.pool.k = dict(eng.pool.k)
        eng.pool.k["s"] = eng.pool.k["s"].astype(jnp.float32)
        with pytest.raises(SanitizerError, match="dtypes"):
            check_engine_invariants(eng)

    def test_scale_shape_mismatch_caught(self):
        eng = _engine(kv_quant="int8")
        eng.run_all([PROMPT], max_new_tokens=2)
        eng.pool.v = dict(eng.pool.v)
        eng.pool.v["s"] = eng.pool.v["s"][:, :-1]
        with pytest.raises(SanitizerError, match="scale shape"):
            check_engine_invariants(eng)

    def test_dict_pool_on_unquantized_engine_caught(self):
        eng = _engine()
        eng.run_all([PROMPT], max_new_tokens=2)
        from sentio_tpu.runtime.paged import quantize_kv

        q, s = quantize_kv(eng.pool.k)
        eng.pool.k = {"q": q, "s": s}
        with pytest.raises(SanitizerError, match="unquantized"):
            check_engine_invariants(eng)


class TestLockOrderRuntime:
    """Per-thread acquisition stacks + the global order-edge set: the
    dynamic twin of the static ``lock-order-inversion`` rule."""

    def test_inversion_raises_and_leaves_nothing_held(self):
        _reset_lock_order()
        a, b = make_lock("tsan-A"), make_lock("tsan-B")
        with a:
            with b:
                pass  # establishes A -> B
        with b:
            with pytest.raises(SanitizerError, match="inversion"):
                with a:
                    pass
            # the check runs BEFORE the underlying acquire: the raise
            # left the reversed lock untaken, so nothing is wedged
            assert not a.locked()
        assert held_lock_names() == frozenset()

    def test_inversion_caught_across_threads(self):
        _reset_lock_order()
        a, b = make_lock("tsan-X"), make_lock("tsan-Y")

        def establishes():
            with a:
                with b:
                    pass

        t = threading.Thread(target=establishes, name="edge-setter")
        t.start()
        t.join()
        # the edge set is process-global: THIS thread's reversal trips it
        with b:
            with pytest.raises(SanitizerError, match="pick one global order"):
                with a:
                    pass

    def test_consistent_order_never_raises(self):
        _reset_lock_order()
        a, b = make_lock("tsan-C"), make_lock("tsan-D")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert held_lock_names() == frozenset()

    def test_reentrant_blocking_acquire_raises(self):
        lock = make_lock("tsan-E")
        with lock:
            with pytest.raises(SanitizerError, match="self-deadlock"):
                lock.acquire()

    def test_same_name_nesting_is_not_an_inversion(self):
        _reset_lock_order()
        # two instances sharing one class-qualified name: order between
        # them is an instance hierarchy, which name-granular edges cannot
        # judge — both nestings must pass (mirrors the static rule)
        a1, a2 = make_lock("tsan-F"), make_lock("tsan-F")
        with a1:
            with a2:
                pass
        with a2:
            with a1:
                pass


@guard_locksets
class _Seeded:
    """Lockset-checker fixture: one annotated counter, one locked and one
    unlocked write path."""

    def __init__(self):
        self._mu = make_lock("_Seeded._mu")
        self._count = 0  # guarded-by: _mu

    def locked_bump(self):
        with self._mu:
            self._count += 1

    def unlocked_bump(self):
        self._count += 1


class TestLocksets:
    def test_cross_thread_unlocked_mutation_raises(self):
        s = _Seeded()
        s.unlocked_bump()  # first thread: exclusive phase, anything goes
        caught: list = []

        def second_thread():
            try:
                s.unlocked_bump()
            except SanitizerError as exc:
                caught.append(exc)

        t = threading.Thread(target=second_thread, name="racer")
        t.start()
        t.join()
        assert caught, "second-thread unlocked write must empty the lockset"
        assert "_Seeded._count" in str(caught[0])
        assert "_mu" in str(caught[0])

    def test_lockset_empties_on_late_unlocked_write(self):
        # disciplined shared phase first (candidates = {_mu}), then the
        # owning thread itself regresses to an unlocked write: the
        # intersection with its empty held set raises — the checker is
        # not a second-thread-only tripwire
        s = _Seeded()
        t = threading.Thread(target=s.locked_bump, name="sharer")
        t.start()
        t.join()
        s.locked_bump()
        with pytest.raises(SanitizerError, match="candidate lockset"):
            s.unlocked_bump()

    def test_locked_discipline_never_raises(self):
        s = _Seeded()
        threads = [
            threading.Thread(target=s.locked_bump, name=f"bumper-{i}")
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        s.locked_bump()
        assert s._count == 5

    def test_disabled_construction_is_unarmed(self, monkeypatch):
        monkeypatch.delenv("SENTIO_SANITIZE")
        s = _Seeded()
        assert "_san_lockset_state" not in s.__dict__
        # unlocked cross-thread writes go unnoticed: genuinely opt-in
        t = threading.Thread(target=s.unlocked_bump)
        s.unlocked_bump()
        t.start()
        t.join()
        assert s._count == 2

    def test_serving_classes_are_armed(self):
        """The chaos-drill-facing classes carry the decorator and parse
        their own annotations into a non-empty spec."""
        from sentio_tpu.infra.flight import FlightRecorder
        from sentio_tpu.infra.metrics import InMemoryMetrics

        fr = FlightRecorder()
        assert "_san_lockset_state" in fr.__dict__
        assert "_tick_seq" in fr.__dict__["_san_lockset_state"].spec
        m = InMemoryMetrics()
        assert "counters" in m.__dict__["_san_lockset_state"].spec


class TestServiceUnderSanitizer:
    def test_pump_handoff_and_locks(self):
        """The serving pump rebinding engine ownership + OwnedLock on
        _mutex: a full generate round trip under the sanitizer."""
        from sentio_tpu.runtime.service import PagedGenerationService

        eng = _engine()
        svc = PagedGenerationService(eng)
        assert isinstance(svc._mutex, OwnedLock)
        out = svc.generate(PROMPT, max_new_tokens=4)
        assert out.finish_reason in ("stop", "length")
        # pump bursts rebind: a second generation after the first pump died
        out2 = svc.generate("another prompt entirely", max_new_tokens=4)
        assert out2.finish_reason in ("stop", "length")
        svc.close()
