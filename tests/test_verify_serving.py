"""ISSUE 11 tier-1 acceptance: confidence-gated async verification through
the REAL serve stack (tiny paged TPU engine on CPU).

Lives outside test_serve.py on purpose: that module is slow-marked, and the
acceptance criteria — zero verify-decode admissions for a confident request,
first token before the trailing verify verdict for a low-confidence stream —
must gate tier-1."""

from sentio_tpu.config import GeneratorConfig
from test_serve import fast_settings, run, seed, with_client


class TestConfidenceGatedVerify:
    """ISSUE 11 acceptance: with VERIFY_MODE=gated, a confident request
    completes with ZERO verify-decode admissions (flight + WFQ counters),
    and a low-confidence streamed request delivers its first token before
    the verify verdict while the trailing ``verify`` SSE event still
    arrives after [DONE]."""

    @staticmethod
    def _paged_settings(threshold: float):
        return fast_settings(
            generator=GeneratorConfig(
                provider="tpu", model_preset="tiny", use_verifier=True,
                verify_mode="gated", verify_confidence_threshold=threshold,
                max_new_tokens=8, verifier_max_tokens=4, mode="fast",
                use_paged_decode=True, kv_page_size=16,
                kv_max_pages_per_seq=8, max_batch_size=4,
            ),
        )

    def test_confident_request_skips_verify_with_zero_admissions(self):
        # threshold 0.0: any scored confidence clears the gate, so the
        # skip path is deterministic — the assertion is that NO verify
        # decode ever reaches the engine or the fair queue
        settings = self._paged_settings(threshold=0.0)

        async def body(client, container):
            await seed(client, ["paged decode gating document"])
            resp = await client.post("/chat", json={
                "question": "what about gating?", "thread_id": "gatedskip1",
            })
            assert resp.status == 200, await resp.text()
            data = await resp.json()
            evaluation = data["metadata"].get("evaluation")
            assert evaluation and evaluation["verdict"] == "skipped_confident", data
            assert evaluation["confidence"] >= 0.0
            assert "verify_pending" not in data["metadata"]

            # flight counters: exactly ONE engine admission (the generate
            # decode) — the verify node never admitted
            flight = await (await client.get("/debug/flight/gatedskip1")).json()
            assert len(flight["engine"]["admissions"]) == 1, flight["engine"]
            assert flight["verify"]["outcome"] == "skipped_confident"
            assert flight["verify"]["mode"] == "gated"

            # WFQ counters: one admission charged to the shared tenant —
            # a verify decode would have charged a second
            service = container.generation_service
            if hasattr(service, "tenants"):
                per = service.tenants.stats()["per_tenant"]
                assert sum(t["admitted"] for t in per.values()) == 1, per

            # the gate's outcome is a first-class metric
            prom = await (await client.get("/metrics")).text()
            assert ('sentio_tpu_verify_total{mode="gated",'
                    'outcome="skipped_confident"}') in prom

        run(with_client(settings, body))

    def test_low_confidence_stream_gets_trailing_verify_event(self):
        # threshold > 1.0 is unreachable: every request takes the async
        # path — answer tokens and [DONE] first, the audit verdict as a
        # trailing `verify` event on the still-open connection
        settings = self._paged_settings(threshold=1.1)

        async def body(client, container):
            await seed(client, ["trailing verdict streaming document"])
            resp = await client.post("/chat", json={
                "question": "what about trailing verdicts?", "stream": True,
            })
            assert resp.status == 200
            import json as _json

            events = []
            for line in (await resp.read()).decode().splitlines():
                if line.startswith("data:"):
                    data = line[5:].strip()
                    if data == "[DONE]":
                        events.append(("done", None))
                    else:
                        events.append(next(iter(_json.loads(data).items())))
            kinds = [k for k, _ in events]
            assert "token" in kinds and "done" in kinds, kinds
            assert "verify" in kinds, (
                f"trailing verify event missing: {kinds}")
            first_token = kinds.index("token")
            done_at = kinds.index("done")
            verify_at = kinds.index("verify")
            # first token precedes the verdict; the verdict trails [DONE]
            assert first_token < done_at < verify_at, kinds
            verdict = dict(events[verify_at][1])
            assert verdict["verdict"] in ("pass", "warn", "fail")
            # the gate scored the answer (paged logprobs flowed) but it
            # stayed below the unreachable threshold
            assert verdict.get("confidence") is not None
            assert verdict["confidence"] < 1.1

        run(with_client(settings, body))
