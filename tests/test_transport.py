"""Frame-codec property/fuzz suite for the worker socket transport
(runtime/transport.py): malformed input — truncated, oversized,
corrupt-pickle, wrong-version, random garbage — must raise TYPED transport
errors or drop the connection, never hang a reader and never crash the
router; well-formed frames round-trip exactly with their incarnation
epoch. Also pins the auth/version handshake and the network fault shapes
(drop / half-open partition) the chaos drills arm."""

import pickle
import random
import socket
import struct
import threading
import time

import pytest

from sentio_tpu.infra import faults
from sentio_tpu.runtime.replica import WorkerRegistry
from sentio_tpu.runtime.transport import (
    _HEADER,
    _MAGIC,
    PROTOCOL_VERSION,
    FrameProtocolError,
    FrameTooLarge,
    PipeTransport,
    SocketTransport,
    TransportClosed,
    dial,
    send_hello,
)


def _pair(**kw):
    """Connected (transport, raw peer socket) over a local socketpair."""
    a, b = socket.socketpair()
    return SocketTransport(a, **kw), b


def _tpair(**kw):
    """Two transports over a local socketpair."""
    a, b = socket.socketpair()
    return SocketTransport(a, **kw), SocketTransport(b, **kw)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class TestFrameCodec:
    def test_roundtrip_property(self):
        """Well-formed frames of assorted shapes/sizes round-trip exactly,
        carrying the sender's epoch."""
        tx, rx = _tpair()
        tx.epoch = 7
        rng = random.Random(0)
        payloads = [
            (0, "ok", None),
            (1, "tok", ("piece", [1, 2, 3])),
            (2, "status", {"backlog": 0, "nested": {"x": [None, 1.5]}}),
            (3, "blob", bytes(rng.randrange(256) for _ in range(70_000))),
            (4, "text", "ü" * 5000),
        ]
        for frame in payloads:  # interleaved: a socketpair buffer is small
            tx.send(frame)
            got, epoch = rx.recv(timeout_s=5)
            assert got == frame
            assert epoch == 7
        tx.close(), rx.close()

    def test_recv_timeout_returns_none_not_hang(self):
        tx, rx = _tpair()
        t0 = time.perf_counter()
        assert rx.recv(timeout_s=0.3) is None
        assert time.perf_counter() - t0 < 2.0
        tx.close(), rx.close()

    def test_truncated_header_never_hangs_the_reader(self):
        """A partial frame header followed by silence must raise typed
        within the frame timeout — not block forever."""
        t, peer = _pair(frame_timeout_s=0.5)
        peer.sendall(b"SN")  # 2 of 13 header bytes, then silence
        t0 = time.perf_counter()
        with pytest.raises(TransportClosed):
            t.recv(timeout_s=5)
        assert time.perf_counter() - t0 < 5.0
        t.close(), peer.close()

    def test_truncated_payload_never_hangs_the_reader(self):
        t, peer = _pair(frame_timeout_s=0.5)
        payload = pickle.dumps((1, "ok", "x" * 100))
        header = _HEADER.pack(_MAGIC, PROTOCOL_VERSION, 0, len(payload))
        peer.sendall(header + payload[: len(payload) // 2])  # then silence
        with pytest.raises(TransportClosed):
            t.recv(timeout_s=5)
        t.close(), peer.close()

    def test_oversized_frame_typed_on_both_sides(self):
        t, peer = _pair(max_frame_bytes=1024)
        # sender refuses before any byte hits the wire
        with pytest.raises(FrameTooLarge):
            t.send((1, "blob", b"x" * 4096))
        # receiver refuses before buffering the payload
        header = _HEADER.pack(_MAGIC, PROTOCOL_VERSION, 0, 1 << 20)
        peer.sendall(header)
        with pytest.raises(FrameTooLarge):
            t.recv(timeout_s=5)
        t.close(), peer.close()

    def test_corrupt_pickle_is_typed_not_a_crash(self):
        t, peer = _pair()
        junk = b"\x80\x05garbage-not-a-pickle"
        header = _HEADER.pack(_MAGIC, PROTOCOL_VERSION, 0, len(junk))
        peer.sendall(header + junk)
        with pytest.raises(FrameProtocolError):
            t.recv(timeout_s=5)
        t.close(), peer.close()

    def test_wrong_magic_and_wrong_version_are_typed(self):
        for magic, version in ((b"HTTP", PROTOCOL_VERSION),
                               (_MAGIC, PROTOCOL_VERSION + 9)):
            t, peer = _pair()
            payload = pickle.dumps((0, "ok", None))
            peer.sendall(struct.pack("!4sBII", magic, version, 0,
                                     len(payload)) + payload)
            with pytest.raises(FrameProtocolError):
                t.recv(timeout_s=5)
            t.close(), peer.close()

    def test_random_garbage_fuzz_always_typed_never_hung(self):
        """Random byte soup: every outcome is a typed transport error (or
        a clean idle timeout), bounded in time — the reader thread can
        never be wedged and the process never sees an untyped crash."""
        rng = random.Random(1234)
        for trial in range(12):
            t, peer = _pair(frame_timeout_s=0.3, max_frame_bytes=1 << 16)
            blob = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(1, 200)))
            peer.sendall(blob)
            peer.close()  # EOF after the garbage
            t0 = time.perf_counter()
            try:
                while True:
                    if t.recv(timeout_s=1.0) is None:
                        break
            except (TransportClosed, FrameProtocolError, FrameTooLarge):
                pass  # typed: exactly the contract
            assert time.perf_counter() - t0 < 10.0, f"trial {trial} hung"
            t.close()

    def test_peer_close_is_transport_closed(self):
        t, peer = _pair()
        peer.close()
        with pytest.raises(TransportClosed):
            t.recv(timeout_s=5)
        t.close()

    def test_broken_write_bounded_by_frame_timeout(self):
        """A peer that stops READING (half-open partition, send
        direction): once the kernel buffer fills, send() must raise typed
        within the frame timeout instead of blocking forever — the
        broken-write liveness signal."""
        a, b = socket.socketpair()
        # tiny buffers so the fill happens fast
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        t = SocketTransport(a, frame_timeout_s=0.5)
        big = (1, "blob", b"x" * 65_536)
        t0 = time.perf_counter()
        with pytest.raises(TransportClosed):
            for _ in range(64):  # nobody reads b: must fail, bounded
                t.send(big)
        assert time.perf_counter() - t0 < 30.0
        t.close(), b.close()

    def test_pipe_transport_parity(self):
        """PipeTransport speaks the same (frame, epoch) surface."""
        import multiprocessing

        c1, c2 = multiprocessing.Pipe()
        tx, rx = PipeTransport(c1, epoch=3), PipeTransport(c2, epoch=3)
        assert rx.recv(timeout_s=0.1) is None
        tx.send((5, "ok", {"a": 1}))
        assert rx.recv(timeout_s=5) == ((5, "ok", {"a": 1}), 3)
        tx.close()
        with pytest.raises(TransportClosed):
            rx.recv(timeout_s=5)
        rx.close()


class TestNetworkFaults:
    def test_drop_next_n_frames(self):
        """faults drop=True, times=N at the recv point loses exactly the
        next N frames — the 'lossy link' chaos shape."""
        tx, rx = _tpair()
        rx.fault_scope = "r0"
        with faults.inject("transport.recv.r0", drop=True, times=2) as rule:
            for i in range(4):
                tx.send((i, "tok", i))
            got = [rx.recv(timeout_s=5)[0][0] for _ in range(2)]
            assert got == [2, 3]  # frames 0 and 1 were dropped
            assert rule.fired == 2
        tx.close(), rx.close()

    def test_send_side_drop(self):
        tx, rx = _tpair()
        tx.fault_scope = "w"
        with faults.inject("transport.send.w", drop=True, times=1):
            tx.send((0, "tok", "lost"))
            tx.send((1, "tok", "kept"))
        assert rx.recv(timeout_s=5)[0] == (1, "tok", "kept")
        tx.close(), rx.close()

    def test_half_open_partition_reads_stall_writes_succeed(self):
        """The partition shape the chaos drill arms: a stall at the recv
        point wedges the reader while the same transport's sends keep
        landing on the peer."""
        a, b = _tpair()
        a.fault_scope = "r1"
        release = threading.Event()
        got = {}

        def reader():
            got["frame"] = a.recv(timeout_s=30)

        with faults.inject("transport.recv.r1", stall_event=release,
                           stall_s=30.0, times=1):
            b.send((1, "tok", "wedged"))
            th = threading.Thread(target=reader, daemon=True)
            th.start()
            time.sleep(0.3)
            assert th.is_alive(), "reader should be stalled (partitioned)"
            # writes from the partitioned side still succeed — half-open
            a.send((2, "ok", "write side alive"))
            assert b.recv(timeout_s=5)[0] == (2, "ok", "write side alive")
            release.set()
            th.join(timeout=5)
            assert not th.is_alive()
            assert got["frame"][0] == (1, "tok", "wedged")
        a.close(), b.close()


class TestHandshake:
    def test_registration_grants_monotonic_epochs(self):
        reg = WorkerRegistry("secret", slots=2)
        try:
            t1 = dial(reg.address)
            ack1 = send_hello(t1, "secret", 1, 42)
            rt1, hello, e1 = reg.await_registration(1, 5.0)
            assert ack1["epoch"] == e1 == 1 and hello["pid"] == 42
            t2 = dial(reg.address)
            ack2 = send_hello(t2, "secret", 1, 43)
            rt2, _h, e2 = reg.await_registration(1, 5.0)
            assert ack2["epoch"] == e2 == 2
            assert reg.current_epoch(1) == 2
            # the superseded connection's frames are fenced by epoch
            assert rt2.epoch == 2 and rt1.epoch == 1
            for t in (t1, t2, rt1, rt2):
                t.close()
        finally:
            reg.close()

    def test_bad_token_and_bad_version_rejected(self):
        reg = WorkerRegistry("secret", slots=1)
        try:
            t = dial(reg.address)
            with pytest.raises(FrameProtocolError, match="token"):
                send_hello(t, "WRONG", 0, 1)
            t.close()
            t2 = dial(reg.address)
            t2.send((0, "hello", {"token": "secret", "slot": 0,
                                  "proto": PROTOCOL_VERSION + 1, "pid": 1}))
            got = t2.recv(timeout_s=5)
            assert got is not None and got[0][1] == "hello_reject"
            assert "protocol" in got[0][2]["reason"]
            t2.close()
            stats = reg.stats()
            assert stats["rejections"] == 2
            assert stats["registrations"] == 0
        finally:
            reg.close()

    def test_hostile_hello_payloads_never_crash_the_acceptor(self):
        """Review regression: a hello whose token is non-ASCII (raises
        TypeError from hmac.compare_digest on str input) or whose proto
        is a non-numeric value must be a clean typed rejection, not an
        untyped crash that kills the accept loop and leaks the socket."""
        reg = WorkerRegistry("secret", slots=1)
        try:
            for payload in (
                {"token": "sécrét-ünicode", "slot": 0,
                 "proto": PROTOCOL_VERSION, "pid": 1},
                {"token": "secret", "slot": 0, "proto": "banana", "pid": 1},
                {"token": None, "slot": 0, "proto": PROTOCOL_VERSION,
                 "pid": 1},
            ):
                t = dial(reg.address)
                t.send((0, "hello", payload))
                got = t.recv(timeout_s=5)
                assert got is not None and got[0][1] == "hello_reject", got
                t.close()
            # the registry is still serving: a good hello registers fine
            t = dial(reg.address)
            send_hello(t, "secret", 0, 7)
            rt, _h, epoch = reg.await_registration(0, 5.0)
            assert epoch == 1
            t.close(), rt.close()
        finally:
            reg.close()

    def test_supersede_keeps_highest_epoch(self):
        """Review regression: racing registrations supersede by EPOCH,
        not arrival order — the live (highest-epoch) connection must
        survive the drain no matter which handshake thread ran last."""
        reg = WorkerRegistry("secret", slots=1)
        try:
            t1 = dial(reg.address)
            send_hello(t1, "secret", 0, 1)
            t2 = dial(reg.address)
            send_hello(t2, "secret", 0, 2)
            # both queued (no claim between them): the claimant must get
            # the HIGHEST epoch and the stale one must be closed
            deadline = time.perf_counter() + 5
            while reg.current_epoch(0) < 2 and time.perf_counter() < deadline:
                time.sleep(0.02)
            rt, hello, epoch = reg.await_registration(0, 5.0)
            assert epoch == 2 and hello["pid"] == 2
            t1.close(), t2.close(), rt.close()
        finally:
            reg.close()

    def test_unknown_slot_rejected(self):
        reg = WorkerRegistry("secret", slots=1)
        try:
            t = dial(reg.address)
            with pytest.raises(FrameProtocolError, match="slot"):
                send_hello(t, "secret", 5, 1)
            t.close()
        finally:
            reg.close()

    def test_await_registration_timeout_is_typed(self):
        from sentio_tpu.infra.exceptions import ReplicaUnavailable

        reg = WorkerRegistry("secret", slots=1)
        try:
            with pytest.raises(ReplicaUnavailable):
                reg.await_registration(0, timeout_s=0.3)
        finally:
            reg.close()

    def test_stale_frame_counting(self):
        reg = WorkerRegistry("secret", slots=1)
        try:
            assert reg.stale_frames(0) == 0
            reg.note_stale_frame(0)
            reg.note_stale_frame(0)
            assert reg.stale_frames(0) == 2
            assert reg.stats()["stale_frames"] == [2]
        finally:
            reg.close()
