"""Process-mode replica parity (ISSUE 13): the ``ProcessReplica`` shim over
a spawned worker process must present the same typed surface as the
in-process ``PagedGenerationService`` it wraps — same tokens (seeded random
init is re-derived identically in the worker), same typed sheds and
deadline errors, same mid-stream failure semantics, and a teardown that
REAPS the worker (no orphan processes, asserted via ``active_children``).

ISSUE 15 adds the SOCKET transport parity half: the same worker behind
length-prefixed TCP frames (runtime/transport.py) must be token-IDENTICAL
to the pipe transport from the same seed, cross every typed error intact,
and run the SIGKILL → typed quarantine → re-registration (higher
incarnation epoch) → serving-again lifecycle the pipe mode's respawn
drill pins.

Workers here run tiny seeded-random llama engines (no checkpoint), so the
suite exercises the RPC/liveness machinery, not model quality."""

import dataclasses
import multiprocessing
import threading
import time

import pytest

from sentio_tpu.infra.exceptions import (
    DeadlineExceededError,
    ReplicaUnavailable,
    ServiceOverloaded,
)
from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.models.tokenizer import ByteTokenizer
from sentio_tpu.runtime.replica import WorkerRegistry
from sentio_tpu.runtime.worker import ProcessReplica, WorkerSpec

CFG = LlamaConfig.tiny()
ENGINE_KW = dict(max_slots=2, page_size=8, max_pages_per_seq=4,
                 steps_per_tick=2, num_pages=65)


def _spec(**service_kwargs) -> WorkerSpec:
    return WorkerSpec(factory_kwargs=dict(
        model_config=dataclasses.asdict(CFG),
        engine_kwargs=dict(ENGINE_KW),
        service_kwargs=service_kwargs,
    ))


def _tokenizer() -> ByteTokenizer:
    return ByteTokenizer(CFG.vocab_size)


@pytest.fixture(scope="module")
def worker():
    # ONE worker for the module: each spawn pays a fresh interpreter + jax
    # init + first-tick compiles
    pr = ProcessReplica(_spec(retry_budget=1), _tokenizer(), replica_id=0,
                        build_timeout_s=300.0)
    yield pr
    pr.close()


@pytest.fixture(scope="module")
def socket_worker():
    """ONE socket-transport worker (+ its registry) for the module: each
    spawn pays a fresh interpreter + jax init + first-tick compiles. The
    small max_queue makes the typed-shed drill cheap; parity tests run
    serially and never queue."""
    registry = WorkerRegistry("drill-token", slots=1)
    spec = _spec(retry_budget=1, max_queue=2)
    spec = dataclasses.replace(spec, auth_token="drill-token",
                               status_interval_s=0.05)
    pr = ProcessReplica(spec, _tokenizer(), replica_id=0,
                        build_timeout_s=300.0, transport_mode="socket",
                        registry=registry, partition_timeout_s=2.0,
                        ping_interval_s=0.2)
    yield pr, registry
    pr.close()
    registry.close()


class TestSocketParity:
    """ISSUE 15 acceptance: N=1 socket-transport parity — token-IDENTICAL
    output vs the pipe transport from the same seed, typed errors crossing
    the TCP boundary intact, and the SIGKILL → re-registration lifecycle
    (LAST test: it consumes the module worker)."""

    def test_generate_and_stream_token_parity_with_pipe_transport(
            self, worker, socket_worker):
        """The SAME request through the pipe worker and the socket worker
        must produce IDENTICAL tokens and text: the transport seam carries
        frames, never semantics."""
        sock, _registry = socket_worker
        prompt = "transport parity probe prompt"
        via_pipe = worker.generate(prompt, max_new_tokens=6,
                                   temperature=0.0, timeout_s=120)
        via_sock = sock.generate(prompt, max_new_tokens=6,
                                 temperature=0.0, timeout_s=120)
        assert list(via_sock.tokens) == list(via_pipe.tokens)
        assert via_sock.text == via_pipe.text
        assert via_sock.finish_reason == via_pipe.finish_reason
        # streaming: same pieces reassembled, same stats surface
        pipe_stats: dict = {}
        sock_stats: dict = {}
        pipe_text = "".join(worker.generate_stream(
            prompt, max_new_tokens=6, temperature=0.0, timeout_s=120,
            stats_out=pipe_stats))
        sock_text = "".join(sock.generate_stream(
            prompt, max_new_tokens=6, temperature=0.0, timeout_s=120,
            stats_out=sock_stats))
        assert sock_text == pipe_text
        assert sock_stats.get("tokens") == pipe_stats.get("tokens")
        assert sock.epoch == 1  # first incarnation
        stats = sock.stats()
        assert stats["transport"] == "socket"
        assert stats["incarnation"] == 1
        assert stats["stale_frames"] == 0

    def test_typed_deadline_error_crosses_the_socket(self, socket_worker):
        sock, _registry = socket_worker
        with pytest.raises(DeadlineExceededError):
            sock.generate("expired before submit", max_new_tokens=2,
                          deadline_ts=time.perf_counter() - 0.5,
                          timeout_s=30)

    def test_typed_shed_crosses_the_socket(self, socket_worker):
        """Wedge the worker's pump (in-worker stall fault over the RPC
        surface), oversubscribe the tiny queue: admissions beyond the
        bound shed typed ServiceOverloaded (429 + Retry-After) across the
        TCP boundary; the admitted requests complete once the stall
        lifts."""
        sock, _registry = socket_worker
        sock.inject_fault("paged.step", stall_s=2.5, times=1)
        outcomes: dict = {}

        def call(i):
            try:
                outcomes[i] = sock.generate(f"shed probe {i}",
                                            max_new_tokens=2,
                                            temperature=0.0, timeout_s=120)
            except Exception as exc:  # noqa: BLE001 — typed or bust
                outcomes[i] = exc
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)
        sock.reset_faults()
        sheds = [o for o in outcomes.values()
                 if isinstance(o, ServiceOverloaded)]
        served = [o for o in outcomes.values() if not isinstance(o, Exception)]
        assert sheds, f"no typed shed crossed the boundary: {outcomes}"
        assert all(s.status in (429, 503) for s in sheds)
        assert served, "the admitted requests never completed"
        untyped = [o for o in outcomes.values()
                   if isinstance(o, Exception)
                   and not isinstance(o, (ServiceOverloaded,
                                          ReplicaUnavailable,
                                          DeadlineExceededError))]
        assert untyped == []

    def test_typed_midstream_error_crosses_the_socket(self, socket_worker):
        sock, _registry = socket_worker
        sock.inject_fault("paged.step", delay_s=0.1)
        it = sock.generate_stream("midstream failure over tcp prompt",
                                  max_new_tokens=200, temperature=0.0,
                                  timeout_s=120)
        assert next(it)  # tokens flowed before the fault arms
        sock.inject_fault("paged.step", error=RuntimeError("boom"), times=1)
        with pytest.raises(ReplicaUnavailable):
            for _ in it:
                pass
        sock.reset_faults()
        ok = sock.generate("post failure sanity", max_new_tokens=3,
                           temperature=0.0, timeout_s=120)
        assert ok.finish_reason in ("stop", "length")

    def test_fleet_telemetry_merges_with_replica_labels(self, socket_worker):
        """ISSUE 16 acceptance (socket half): a worker-served request's
        engine truth is reachable from the router — unsolicited telemetry
        frames merge into the router collector under ``{replica}`` labels
        at the worker's incarnation epoch, the ping loop's pongs feed the
        ClockSync estimator, and ``fetch_flight`` returns the request's
        record with per-tick phase conservation intact."""
        from sentio_tpu.infra.metrics import (MetricsCollector, get_metrics,
                                              set_metrics)

        sock, registry = socket_worker
        old_collector = get_metrics()
        fresh = MetricsCollector()
        set_metrics(fresh)
        try:
            r = sock.generate("socket telemetry probe prompt",
                              max_new_tokens=4, temperature=0.0,
                              timeout_s=120, request_id="tel-sock-1")
            assert r.finish_reason in ("stop", "length")
            # the 1 Hz frame lands and merges at THIS incarnation's epoch
            # (the pipe-parity worker also ships as replica 0 at epoch 0;
            # the fence keeps the highest epoch's truth)
            deadline = time.monotonic() + 15
            while (time.monotonic() < deadline
                   and fresh.worker_telemetry_epoch(0) != sock.epoch):
                time.sleep(0.05)
            assert fresh.worker_telemetry_epoch(0) == sock.epoch
            assert fresh.memory.gauges["worker_telemetry_age('0',)"] == 0.0
            text = fresh.export_prometheus().decode()
            for family in ("sentio_tpu_worker_tick_phase_seconds_total",
                           "sentio_tpu_worker_tick_phase_ticks_total"):
                lines = [ln for ln in text.splitlines()
                         if ln.startswith(family + "{")]
                assert lines and all('replica="0"' in ln for ln in lines), \
                    f"{family} missing its replica label on /metrics"
        finally:
            set_metrics(old_collector)
        # pongs (stamped pings every 0.2s) → same-host offset near zero
        est = sock.clock_sync()
        assert est is not None and est["samples"] >= 1
        assert abs(est["offset_s"]) < 0.5
        reply = sock.fetch_flight(request_id="tel-sock-1")
        assert reply["epoch"] == sock.epoch
        rec = reply["record"]
        assert rec is not None and rec["request_id"] == "tel-sock-1"
        assert rec["engine"].get("t_submit_s") is not None
        assert rec["ticks"], "engine tick window must cross the socket"
        for tick in rec["ticks"]:
            if tick.get("phase_ms") and tick.get("pump_ms") is not None:
                assert sum(tick["phase_ms"].values()) == pytest.approx(
                    tick["pump_ms"], rel=0.05, abs=0.5)
        stats = sock.stats()
        assert stats.get("telemetry_age_s") is not None
        assert "clock_offset_s" in stats and "clock_uncertainty_s" in stats

    def test_sigkill_typed_then_reregisters_at_higher_epoch(
            self, socket_worker):
        """LAST (kills the module worker) — ISSUE 15 acceptance: a real
        SIGKILL under socket transport runs the same typed lifecycle as
        pipe-mode respawn, except recovery is RE-REGISTRATION: the fresh
        worker dials the registry and joins at a HIGHER incarnation
        epoch."""
        sock, registry = socket_worker
        old_pid, old_epoch = sock.pid, sock.epoch
        sock.inject_fault("paged.step", delay_s=0.2)  # keep it in flight
        outcome: dict = {}

        def call():
            try:
                outcome["r"] = sock.generate(
                    "inflight kill over tcp", max_new_tokens=100,
                    temperature=0.0, timeout_s=60)
            except Exception as exc:  # noqa: BLE001 — typed or bust
                outcome["r"] = exc

        t = threading.Thread(target=call)
        t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and sock.backlog() < 1:
            time.sleep(0.01)
        assert sock.backlog() >= 1, "request never reached the worker"
        sock.kill()  # real SIGKILL — no handlers, no unwinding
        t.join(timeout=30)
        assert not t.is_alive(), "caller hung across the worker SIGKILL"
        assert isinstance(outcome["r"], ReplicaUnavailable), outcome
        assert sock.broken
        fresh = sock.respawn()
        try:
            assert fresh.pid != old_pid, "respawn reused the corpse's pid?"
            assert fresh.epoch > old_epoch, "re-registration must bump epoch"
            assert registry.current_epoch(0) == fresh.epoch
            fresh_pid = fresh.pid
            ok = fresh.generate("re-registered worker serves",
                                max_new_tokens=3, temperature=0.0,
                                timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
        finally:
            fresh.close()
        # zero orphans from THIS drill (the pipe-parity module worker is
        # still legitimately alive for the next test class)
        alive = [p.pid for p in multiprocessing.active_children()]
        assert old_pid not in alive, "SIGKILLed corpse never reaped"
        assert fresh_pid not in alive, "re-registered worker leaked"


class TestProcessParity:
    def test_generate_token_parity_with_in_process_engine(self, worker):
        """Same tiny config, same seed, temperature 0: the worker's tokens
        must be IDENTICAL to an in-process engine's — the worker re-derives
        the seeded random init, so any drift means the RPC shim changed the
        request or the worker built a different engine."""
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        r = worker.generate("parity probe prompt", max_new_tokens=6,
                            temperature=0.0, timeout_s=120)
        assert r.finish_reason in ("stop", "length")
        assert r.replica_id == 0
        eng = ContinuousBatchingEngine(model_config=CFG, **ENGINE_KW)
        local = eng.run_all(["parity probe prompt"], max_new_tokens=6)[0]
        assert list(r.tokens) == list(local.tokens)
        assert r.text == local.text

    def test_stream_parity_and_stats_out(self, worker):
        """Streaming crosses the boundary as incremental token frames; the
        reassembled text matches the blocking path's, and the stats_out
        contract (logprob accumulators filled before exhaustion) holds."""
        prompt = "stream parity probe prompt"
        blocking = worker.generate(prompt, max_new_tokens=6,
                                   temperature=0.0, timeout_s=120)
        stats_out: dict = {}
        text = "".join(worker.generate_stream(
            prompt, max_new_tokens=6, temperature=0.0, timeout_s=120,
            stats_out=stats_out,
        ))
        assert text == blocking.text
        assert stats_out.get("replica_id") == 0
        assert stats_out.get("tokens") == len(blocking.tokens)

    def test_routing_probes_and_admission_check(self, worker):
        """The read-side probe surface ReplicaSet routes on: peek_prefix
        sees the radix pages the parity prompts left behind, the status
        frames feed backlog/heartbeat, and check_admission round-trips."""
        worker.generate("routing probe session head prompt",
                        max_new_tokens=2, temperature=0.0, timeout_s=120)
        toks = _tokenizer().encode("routing probe session head prompt",
                                   add_bos=True)
        assert worker.engine.peek_prefix(list(toks)) > 0
        assert worker.engine.peek_prefix([499, 498, 497]) == 0
        worker.check_admission()  # no raise = admittable
        # backlog/heartbeat are served from the worker's pushed status
        # frames (0.1s cadence): give the post-generate frame a beat to
        # land rather than asserting against a stale snapshot
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and worker.backlog() != 0:
            time.sleep(0.02)
        assert worker.backlog() == 0
        assert worker.heartbeat_age() is None  # idle replica: nothing stale
        duty = worker.duty_cycle()
        assert set(duty) == {"host", "device", "idle"}
        stats = worker.stats()
        assert stats["replica"] == 0
        assert stats["completed"] >= 1
        assert set(stats["duty_cycle"]) == {"host", "device", "idle"}

    def test_expired_deadline_is_typed_across_the_boundary(self, worker):
        """Absolute router-clock deadlines cross as remaining seconds and
        shed with the same typed error thread mode raises."""
        with pytest.raises(DeadlineExceededError):
            worker.generate("expired before submit", max_new_tokens=2,
                            deadline_ts=time.perf_counter() - 0.5,
                            timeout_s=30)

    def test_midstream_failure_is_typed(self, worker):
        """A decode tick failure while a stream has delivered tokens is the
        non-resumable case: the worker's typed ReplicaUnavailable must
        cross the process boundary as the same exception type, surfaced
        from the router-side iterator."""
        worker.inject_fault("paged.step", delay_s=0.1)
        it = worker.generate_stream("midstream failure probe prompt",
                                    max_new_tokens=200, temperature=0.0,
                                    timeout_s=120)
        first = next(it)
        assert first  # tokens flowed before the fault arms
        worker.inject_fault("paged.step", error=RuntimeError("boom"),
                            times=1)
        with pytest.raises(ReplicaUnavailable):
            for _ in it:
                pass
        worker.reset_faults()
        # the worker CONTAINED the crash (engine reset): it still serves
        ok = worker.generate("post failure sanity", max_new_tokens=3,
                             temperature=0.0, timeout_s=120)
        assert ok.finish_reason in ("stop", "length")
        assert worker.stats()["tick_failures"] >= 1

    def test_admission_shed_drain_close_no_orphans(self):
        """A max_queue=0 worker sheds typed 429 without touching decode;
        drain closes it and close() REAPS the process — active_children
        must not know it afterwards."""
        pr = ProcessReplica(_spec(max_queue=0), _tokenizer(), replica_id=7,
                            build_timeout_s=300.0)
        pid = pr.pid
        try:
            with pytest.raises(ServiceOverloaded) as exc_info:
                pr.generate("cannot even queue", max_new_tokens=2,
                            timeout_s=30)
            assert exc_info.value.status == 429
            with pytest.raises(ServiceOverloaded):
                pr.check_admission()
            out = pr.drain(deadline_s=5.0)
            assert out["drained"] is True
        finally:
            pr.close()
        assert pr.closed
        with pytest.raises(ReplicaUnavailable):
            pr.generate("after drain-close", max_new_tokens=2, timeout_s=10)
        assert pid not in [p.pid for p in multiprocessing.active_children()]

    def test_fetch_flight_and_stitch_over_the_pipe(self, worker):
        """ISSUE 16 acceptance (pipe half): the worker's flight record —
        engine section + tick window with conserved phases — comes back
        on demand over the PIPE transport (no ping loop there: the
        fetch's echoed transmit stamp is the clock source), and the
        ``/debug/flight`` stitch helper splices it into a router record as
        ``engine_window: "stitched"``."""
        rid = "tel-pipe-1"
        r = worker.generate("pipe flight stitch probe prompt",
                            max_new_tokens=4, temperature=0.0,
                            timeout_s=120, request_id=rid)
        assert r.finish_reason in ("stop", "length")
        reply = worker.fetch_flight(request_id=rid)
        rec = reply["record"]
        assert rec is not None and rec["request_id"] == rid
        assert rec["engine"].get("t_submit_s") is not None
        assert rec["ticks"], "engine tick window must cross the pipe"
        for tick in rec["ticks"]:
            if tick.get("phase_ms") and tick.get("pump_ms") is not None:
                assert sum(tick["phase_ms"].values()) == pytest.approx(
                    tick["pump_ms"], rel=0.05, abs=0.5)
        # the echoed t_tx made the fetch double as a clock sample
        assert reply["clock"] is not None
        assert worker.clock_sync()["samples"] >= 1
        # full-window fetch (sentio trace --fleet's shape)
        full = worker.fetch_flight()
        assert full["ticks"] and isinstance(full["records"], list)
        # end-to-end stitch: real RPC, real clock shift, real record
        pytest.importorskip("aiohttp")
        from sentio_tpu.infra.flight import get_flight_recorder
        from sentio_tpu.serve.app import _stitch_flight_record

        shift, bound = worker.flight_shift_s(
            get_flight_recorder().origin())
        assert bound is not None

        class _Members:
            _services = [worker]

        class _Container:
            @staticmethod
            def peek(name):
                return _Members()

        router_record = {"request_id": rid, "t_start_s": 1.0,
                         "engine": {"queue_depth": 0}}
        out = _stitch_flight_record(_Container(), rid, router_record)
        assert out["engine_window"] == "stitched"
        assert out["engine_replica"] == 0
        assert out["engine"]["queue_depth"] == 0  # router fields kept
        assert out["engine"].get("t_submit_s") is not None
        assert out["ticks"] and "replicas_unavailable" not in out
        for tick in out["ticks"]:
            if tick.get("phase_ms") and tick.get("pump_ms") is not None:
                assert sum(tick["phase_ms"].values()) == pytest.approx(
                    tick["pump_ms"], rel=0.05, abs=0.5)

    def test_sigkill_fails_inflight_typed_then_respawns(self, worker):
        """LAST (kills the module worker): a real SIGKILL mid-request fails
        the blocked caller with the typed death error, latches ``broken``
        for the supervisor, and ``respawn()`` brings a fresh worker from
        the same spec that serves again."""
        worker.inject_fault("paged.step", delay_s=0.2)  # keep it in flight
        outcome: dict = {}

        def call():
            try:
                outcome["r"] = worker.generate(
                    "inflight kill probe", max_new_tokens=100,
                    temperature=0.0, timeout_s=60,
                )
            except Exception as exc:  # noqa: BLE001 — typed or bust
                outcome["r"] = exc

        t = threading.Thread(target=call)
        t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and worker.backlog() < 1:
            time.sleep(0.01)
        assert worker.backlog() >= 1, "request never reached the worker"
        worker.kill()  # real SIGKILL — no handlers, no unwinding
        t.join(timeout=30)
        assert not t.is_alive(), "caller hung across the worker SIGKILL"
        assert isinstance(outcome["r"], ReplicaUnavailable), outcome
        assert worker.broken
        fresh = worker.respawn()
        try:
            ok = fresh.generate("respawned worker serves", max_new_tokens=3,
                                temperature=0.0, timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
        finally:
            fresh.close()
        assert multiprocessing.active_children() == []
