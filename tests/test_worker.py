"""Process-mode replica parity (ISSUE 13): the ``ProcessReplica`` shim over
a spawned worker process must present the same typed surface as the
in-process ``PagedGenerationService`` it wraps — same tokens (seeded random
init is re-derived identically in the worker), same typed sheds and
deadline errors, same mid-stream failure semantics, and a teardown that
REAPS the worker (no orphan processes, asserted via ``active_children``).

Workers here run tiny seeded-random llama engines (no checkpoint), so the
suite exercises the RPC/liveness machinery, not model quality."""

import dataclasses
import multiprocessing
import threading
import time

import pytest

from sentio_tpu.infra.exceptions import (
    DeadlineExceededError,
    ReplicaUnavailable,
    ServiceOverloaded,
)
from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.models.tokenizer import ByteTokenizer
from sentio_tpu.runtime.worker import ProcessReplica, WorkerSpec

CFG = LlamaConfig.tiny()
ENGINE_KW = dict(max_slots=2, page_size=8, max_pages_per_seq=4,
                 steps_per_tick=2, num_pages=65)


def _spec(**service_kwargs) -> WorkerSpec:
    return WorkerSpec(factory_kwargs=dict(
        model_config=dataclasses.asdict(CFG),
        engine_kwargs=dict(ENGINE_KW),
        service_kwargs=service_kwargs,
    ))


def _tokenizer() -> ByteTokenizer:
    return ByteTokenizer(CFG.vocab_size)


@pytest.fixture(scope="module")
def worker():
    # ONE worker for the module: each spawn pays a fresh interpreter + jax
    # init + first-tick compiles
    pr = ProcessReplica(_spec(retry_budget=1), _tokenizer(), replica_id=0,
                        build_timeout_s=300.0)
    yield pr
    pr.close()


class TestProcessParity:
    def test_generate_token_parity_with_in_process_engine(self, worker):
        """Same tiny config, same seed, temperature 0: the worker's tokens
        must be IDENTICAL to an in-process engine's — the worker re-derives
        the seeded random init, so any drift means the RPC shim changed the
        request or the worker built a different engine."""
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        r = worker.generate("parity probe prompt", max_new_tokens=6,
                            temperature=0.0, timeout_s=120)
        assert r.finish_reason in ("stop", "length")
        assert r.replica_id == 0
        eng = ContinuousBatchingEngine(model_config=CFG, **ENGINE_KW)
        local = eng.run_all(["parity probe prompt"], max_new_tokens=6)[0]
        assert list(r.tokens) == list(local.tokens)
        assert r.text == local.text

    def test_stream_parity_and_stats_out(self, worker):
        """Streaming crosses the boundary as incremental token frames; the
        reassembled text matches the blocking path's, and the stats_out
        contract (logprob accumulators filled before exhaustion) holds."""
        prompt = "stream parity probe prompt"
        blocking = worker.generate(prompt, max_new_tokens=6,
                                   temperature=0.0, timeout_s=120)
        stats_out: dict = {}
        text = "".join(worker.generate_stream(
            prompt, max_new_tokens=6, temperature=0.0, timeout_s=120,
            stats_out=stats_out,
        ))
        assert text == blocking.text
        assert stats_out.get("replica_id") == 0
        assert stats_out.get("tokens") == len(blocking.tokens)

    def test_routing_probes_and_admission_check(self, worker):
        """The read-side probe surface ReplicaSet routes on: peek_prefix
        sees the radix pages the parity prompts left behind, the status
        frames feed backlog/heartbeat, and check_admission round-trips."""
        worker.generate("routing probe session head prompt",
                        max_new_tokens=2, temperature=0.0, timeout_s=120)
        toks = _tokenizer().encode("routing probe session head prompt",
                                   add_bos=True)
        assert worker.engine.peek_prefix(list(toks)) > 0
        assert worker.engine.peek_prefix([499, 498, 497]) == 0
        worker.check_admission()  # no raise = admittable
        # backlog/heartbeat are served from the worker's pushed status
        # frames (0.1s cadence): give the post-generate frame a beat to
        # land rather than asserting against a stale snapshot
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and worker.backlog() != 0:
            time.sleep(0.02)
        assert worker.backlog() == 0
        assert worker.heartbeat_age() is None  # idle replica: nothing stale
        duty = worker.duty_cycle()
        assert set(duty) == {"host", "device", "idle"}
        stats = worker.stats()
        assert stats["replica"] == 0
        assert stats["completed"] >= 1
        assert set(stats["duty_cycle"]) == {"host", "device", "idle"}

    def test_expired_deadline_is_typed_across_the_boundary(self, worker):
        """Absolute router-clock deadlines cross as remaining seconds and
        shed with the same typed error thread mode raises."""
        with pytest.raises(DeadlineExceededError):
            worker.generate("expired before submit", max_new_tokens=2,
                            deadline_ts=time.perf_counter() - 0.5,
                            timeout_s=30)

    def test_midstream_failure_is_typed(self, worker):
        """A decode tick failure while a stream has delivered tokens is the
        non-resumable case: the worker's typed ReplicaUnavailable must
        cross the process boundary as the same exception type, surfaced
        from the router-side iterator."""
        worker.inject_fault("paged.step", delay_s=0.1)
        it = worker.generate_stream("midstream failure probe prompt",
                                    max_new_tokens=200, temperature=0.0,
                                    timeout_s=120)
        first = next(it)
        assert first  # tokens flowed before the fault arms
        worker.inject_fault("paged.step", error=RuntimeError("boom"),
                            times=1)
        with pytest.raises(ReplicaUnavailable):
            for _ in it:
                pass
        worker.reset_faults()
        # the worker CONTAINED the crash (engine reset): it still serves
        ok = worker.generate("post failure sanity", max_new_tokens=3,
                             temperature=0.0, timeout_s=120)
        assert ok.finish_reason in ("stop", "length")
        assert worker.stats()["tick_failures"] >= 1

    def test_admission_shed_drain_close_no_orphans(self):
        """A max_queue=0 worker sheds typed 429 without touching decode;
        drain closes it and close() REAPS the process — active_children
        must not know it afterwards."""
        pr = ProcessReplica(_spec(max_queue=0), _tokenizer(), replica_id=7,
                            build_timeout_s=300.0)
        pid = pr.pid
        try:
            with pytest.raises(ServiceOverloaded) as exc_info:
                pr.generate("cannot even queue", max_new_tokens=2,
                            timeout_s=30)
            assert exc_info.value.status == 429
            with pytest.raises(ServiceOverloaded):
                pr.check_admission()
            out = pr.drain(deadline_s=5.0)
            assert out["drained"] is True
        finally:
            pr.close()
        assert pr.closed
        with pytest.raises(ReplicaUnavailable):
            pr.generate("after drain-close", max_new_tokens=2, timeout_s=10)
        assert pid not in [p.pid for p in multiprocessing.active_children()]

    def test_sigkill_fails_inflight_typed_then_respawns(self, worker):
        """LAST (kills the module worker): a real SIGKILL mid-request fails
        the blocked caller with the typed death error, latches ``broken``
        for the supervisor, and ``respawn()`` brings a fresh worker from
        the same spec that serves again."""
        worker.inject_fault("paged.step", delay_s=0.2)  # keep it in flight
        outcome: dict = {}

        def call():
            try:
                outcome["r"] = worker.generate(
                    "inflight kill probe", max_new_tokens=100,
                    temperature=0.0, timeout_s=60,
                )
            except Exception as exc:  # noqa: BLE001 — typed or bust
                outcome["r"] = exc

        t = threading.Thread(target=call)
        t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and worker.backlog() < 1:
            time.sleep(0.01)
        assert worker.backlog() >= 1, "request never reached the worker"
        worker.kill()  # real SIGKILL — no handlers, no unwinding
        t.join(timeout=30)
        assert not t.is_alive(), "caller hung across the worker SIGKILL"
        assert isinstance(outcome["r"], ReplicaUnavailable), outcome
        assert worker.broken
        fresh = worker.respawn()
        try:
            ok = fresh.generate("respawned worker serves", max_new_tokens=3,
                                temperature=0.0, timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
        finally:
            fresh.close()
        assert multiprocessing.active_children() == []
