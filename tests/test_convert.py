"""HF checkpoint conversion parity: build tiny torch models in-memory,
convert their state dicts, and require numerical agreement between our JAX
forward pass and the torch reference forward. This is the strongest form of
the reference's mock-backend strategy (SURVEY.md §4) — instead of canned
outputs, the real conversion path is validated against the source framework.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from sentio_tpu.models.convert import (  # noqa: E402
    convert_cross_encoder,
    convert_encoder,
    convert_llama,
    encoder_config_from_hf,
    llama_config_from_hf,
)


@pytest.fixture(scope="module")
def tiny_hf_llama():
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10_000.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg).eval()
    return model, cfg


class TestLlamaConversion:
    def test_logits_match_torch(self, tiny_hf_llama):
        model, hf_cfg = tiny_hf_llama
        cfg = llama_config_from_hf(hf_cfg, dtype="float32")
        params = convert_llama(model.state_dict(), cfg)

        ids = np.array([[1, 5, 9, 2, 77, 33], [3, 8, 120, 4, 6, 11]], np.int32)
        with torch.no_grad():
            ref = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()

        from sentio_tpu.models.llama import llama_forward

        got, _ = llama_forward(params, cfg, jnp.asarray(ids))
        np.testing.assert_allclose(np.asarray(got), ref, atol=2e-4, rtol=2e-3)

    def test_config_mapping(self, tiny_hf_llama):
        _, hf_cfg = tiny_hf_llama
        cfg = llama_config_from_hf(hf_cfg)
        assert cfg.dim == 32 and cfg.n_kv_heads == 2 and cfg.mlp_dim == 64
        assert cfg.rope_theta == 10_000.0

    def test_tied_embeddings_fallback(self, tiny_hf_llama):
        model, hf_cfg = tiny_hf_llama
        cfg = llama_config_from_hf(hf_cfg, dtype="float32")
        sd = {k: v for k, v in model.state_dict().items() if k != "lm_head.weight"}
        params = convert_llama(sd, cfg)
        np.testing.assert_array_equal(
            params["lm_head"]["kernel"], params["embed_tokens"]["embedding"].T
        )


@pytest.fixture(scope="module")
def tiny_hf_bert():
    cfg = transformers.BertConfig(
        vocab_size=100,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=64,
        type_vocab_size=2,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    model = transformers.BertModel(cfg).eval()
    return model, cfg


@pytest.fixture(scope="module")
def tiny_hf_mixtral():
    # vocab 512 ≥ ByteTokenizer's 261 floor so the serving round-trip test
    # can use the default tokenizer
    cfg = transformers.MixtralConfig(
        vocab_size=512,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=64,
        rope_theta=10_000.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.MixtralForCausalLM(cfg).eval()
    return model, cfg


class TestMoeConversion:
    def test_logits_match_torch(self, tiny_hf_mixtral):
        """HF Mixtral routes top-k with NO capacity limit; the converter's
        default capacity is no-drop (E/k), so logits must agree as-is."""
        from sentio_tpu.models.convert import convert_moe, moe_config_from_hf
        from sentio_tpu.models.moe import moe_forward

        model, hf_cfg = tiny_hf_mixtral
        cfg = moe_config_from_hf(hf_cfg, dtype="float32")
        assert cfg.capacity_factor == cfg.n_experts / cfg.experts_per_token
        params = convert_moe(model.state_dict(), cfg)

        ids = np.array([[1, 5, 9, 2, 77, 33], [3, 8, 120, 4, 6, 11]], np.int32)
        with torch.no_grad():
            ref = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()

        got, _, _ = moe_forward(params, cfg, jnp.asarray(ids))
        np.testing.assert_allclose(np.asarray(got), ref, atol=5e-4, rtol=5e-3)

    def test_config_mapping(self, tiny_hf_mixtral):
        from sentio_tpu.models.convert import moe_config_from_hf

        _, hf_cfg = tiny_hf_mixtral
        cfg = moe_config_from_hf(hf_cfg)
        assert cfg.n_experts == 4
        assert cfg.experts_per_token == 2
        assert cfg.dim == 32 and cfg.n_kv_heads == 2

    def test_checkpoint_roundtrip_serves(self, tiny_hf_mixtral, tmp_path):
        """convert → save_pytree → load_model → GeneratorEngine greedy."""
        from dataclasses import replace

        from sentio_tpu.config import GeneratorConfig
        from sentio_tpu.models.convert import convert_moe, moe_config_from_hf
        from sentio_tpu.models.moe import moe_serving_forward
        from sentio_tpu.runtime.checkpoint import save_pytree
        from sentio_tpu.runtime.engine import GeneratorEngine
        from sentio_tpu.runtime.weights import load_model

        model, hf_cfg = tiny_hf_mixtral
        cfg = replace(moe_config_from_hf(hf_cfg, dtype="float32"))
        params = convert_moe(model.state_dict(), cfg)
        ck = str(tmp_path / "moe-ck")
        save_pytree(ck, params, meta={"family": "moe", "config": cfg.__dict__})

        loaded, loaded_cfg, _ = load_model(ck, expect_family="moe")
        assert loaded_cfg.n_experts == cfg.n_experts

        eng = GeneratorEngine(
            config=GeneratorConfig(model_preset="tiny", max_new_tokens=6),
            model_config=loaded_cfg, params=loaded,
            forward_fn=moe_serving_forward,
        )
        out = eng.generate(["hello"], max_new_tokens=6, temperature=0.0)[0]
        assert len(out.tokens) >= 1

        # config-driven path: checkpoint_path alone must auto-select the
        # MoE family from the checkpoint meta (no explicit forward_fn)
        auto = GeneratorEngine(
            config=GeneratorConfig(
                model_preset="tiny", max_new_tokens=6, checkpoint_path=ck
            ),
        )
        from sentio_tpu.models.moe import MoeConfig

        assert isinstance(auto.model_config, MoeConfig)
        assert auto.forward_fn is moe_serving_forward
        auto_out = auto.generate(["hello"], max_new_tokens=6, temperature=0.0)[0]
        assert auto_out.tokens == out.tokens


class TestEncoderConversion:
    def test_hidden_states_match_torch(self, tiny_hf_bert):
        model, hf_cfg = tiny_hf_bert
        cfg = encoder_config_from_hf(hf_cfg, dtype="float32")
        params = convert_encoder(model.state_dict(), cfg)

        ids = np.array([[2, 45, 17, 9, 0, 0], [3, 7, 99, 41, 22, 8]], np.int32)
        mask = np.array([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], np.int32)
        with torch.no_grad():
            ref = model(
                torch.tensor(ids, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
            ).last_hidden_state.numpy()

        from sentio_tpu.models.transformer import encoder_forward

        got = encoder_forward(
            params, cfg, jnp.asarray(ids), jnp.asarray(mask, bool),
            type_ids=jnp.zeros_like(jnp.asarray(ids)),
        )
        # compare only unpadded positions (BERT computes padded ones too but
        # they never feed pooling)
        m = mask.astype(bool)
        np.testing.assert_allclose(np.asarray(got)[m], ref[m], atol=5e-4, rtol=2e-3)

    def test_prefixed_state_dict(self, tiny_hf_bert):
        model, hf_cfg = tiny_hf_bert
        cfg = encoder_config_from_hf(hf_cfg, dtype="float32")
        sd = {f"bert.{k}": v for k, v in model.state_dict().items()}
        params = convert_encoder(sd, cfg)
        assert params["embed_tokens"]["embedding"].shape == (100, 32)


class TestCrossEncoderConversion:
    def test_scores_match_torch_roberta_head(self):
        cfg = transformers.XLMRobertaConfig(
            vocab_size=120,
            hidden_size=32,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=66,  # usable 64 after the 2-slot offset
            type_vocab_size=1,
            num_labels=1,
            pad_token_id=1,
            attn_implementation="eager",
        )
        torch.manual_seed(2)
        model = transformers.XLMRobertaForSequenceClassification(cfg).eval()

        enc_cfg = encoder_config_from_hf(cfg, dtype="float32")
        assert enc_cfg.max_len == 64
        params = convert_cross_encoder(model.state_dict(), enc_cfg, position_offset=2)
        assert "pooler" in params

        ids = np.array([[0, 45, 17, 9, 2], [0, 7, 99, 41, 2]], np.int32)
        mask = np.ones_like(ids)
        with torch.no_grad():
            ref = model(
                torch.tensor(ids, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
            ).logits.numpy()[:, 0]

        from sentio_tpu.models.cross_encoder import cross_encoder_scores

        got = cross_encoder_scores(
            params, enc_cfg, jnp.asarray(ids), jnp.asarray(mask, bool),
            type_ids=jnp.zeros_like(jnp.asarray(ids)),
        )
        np.testing.assert_allclose(np.asarray(got), ref, atol=5e-4, rtol=2e-3)


class TestDtypeStorage:
    def test_load_dir_casts_to_requested_dtype(self, tiny_hf_llama, tmp_path):
        """--dtype bfloat16 must reach the stored arrays (half the disk/RAM
        for 8B-class checkpoints), not just the config metadata."""
        model, _ = tiny_hf_llama
        src = tmp_path / "hf"
        model.save_pretrained(src)

        from sentio_tpu.models.convert import load_llama_dir

        params, cfg = load_llama_dir(src, dtype="bfloat16")
        assert str(params["embed_tokens"]["embedding"].dtype) == "bfloat16"
        assert str(params["layers_0"]["attn"]["wq"]["kernel"].dtype) == "bfloat16"

        params32, _ = load_llama_dir(src, dtype="float32")
        assert params32["lm_head"]["kernel"].dtype == np.float32
