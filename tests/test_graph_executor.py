import asyncio

import pytest

from sentio_tpu.graph.executor import END, GraphBuilder, GraphError
from sentio_tpu.graph.state import (
    add_retrieved_documents,
    best_documents,
    create_initial_state,
    set_response,
)
from sentio_tpu.models.document import Document


def _linear_graph():
    return (
        GraphBuilder()
        .add_node("a", lambda s: {"response": "A"})
        .add_node("b", lambda s: {"response": s["response"] + "B"})
        .add_edge("a", "b")
        .add_edge("b", END)
        .set_entry("a")
        .compile()
    )


def test_linear_invoke_merges_updates():
    graph = _linear_graph()
    out = graph.invoke(create_initial_state("q"))
    assert out["response"] == "AB"
    assert out["metadata"]["graph_path"] == ["a", "b"]
    assert set(out["metadata"]["node_timings_ms"]) == {"a", "b"}


def test_async_nodes():
    async def anode(state):
        await asyncio.sleep(0)
        return {"response": "async!"}

    graph = (
        GraphBuilder().add_node("n", anode).add_edge("n", END).set_entry("n").compile()
    )
    out = graph.invoke(create_initial_state("q"))
    assert out["response"] == "async!"


def test_conditional_routing():
    def router(state):
        return "long" if len(state["query"]) > 5 else "short"

    graph = (
        GraphBuilder()
        .add_node("start", lambda s: {})
        .add_node("long", lambda s: {"response": "long path"})
        .add_node("short", lambda s: {"response": "short path"})
        .add_conditional_edge("start", router)
        .add_edge("long", END)
        .add_edge("short", END)
        .set_entry("start")
        .compile()
    )
    assert graph.invoke(create_initial_state("tiny"))["response"] == "short path"
    assert graph.invoke(create_initial_state("a longer query"))["response"] == "long path"


def test_soft_fail_records_error_and_continues():
    def boom(state):
        raise RuntimeError("kernel exploded")

    graph = (
        GraphBuilder()
        .add_node("boom", boom)
        .add_node("after", lambda s: {"response": "survived"})
        .add_edge("boom", "after")
        .add_edge("after", END)
        .set_entry("boom")
        .compile()
    )
    out = graph.invoke(create_initial_state("q"))
    assert out["response"] == "survived"
    assert "kernel exploded" in out["metadata"]["boom_error"]


def test_hard_fail_propagates():
    def boom(state):
        raise RuntimeError("fatal")

    graph = (
        GraphBuilder()
        .add_node("boom", boom, soft_fail=False)
        .add_edge("boom", END)
        .set_entry("boom")
        .compile()
    )
    with pytest.raises(RuntimeError, match="fatal"):
        graph.invoke(create_initial_state("q"))


def test_cycle_hits_step_limit():
    builder = (
        GraphBuilder()
        .add_node("a", lambda s: {})
        .add_node("b", lambda s: {})
        .add_edge("a", "b")
        .add_edge("b", "a")
        .set_entry("a")
    )
    builder.max_steps = 10
    with pytest.raises(GraphError, match="step limit"):
        builder.compile().invoke(create_initial_state("q"))


def test_structural_validation():
    with pytest.raises(GraphError):
        GraphBuilder().compile()  # no entry
    with pytest.raises(GraphError):
        GraphBuilder().add_node("a", lambda s: {}).add_edge("a", "ghost").set_entry("a").compile()
    with pytest.raises(GraphError):
        GraphBuilder().add_node("a", lambda s: {}).add_node("a", lambda s: {})


def test_metadata_merge_not_replace():
    graph = (
        GraphBuilder()
        .add_node("a", lambda s: {"metadata": {"k1": 1}})
        .add_node("b", lambda s: {"metadata": {"k2": 2}})
        .add_edge("a", "b")
        .add_edge("b", END)
        .set_entry("a")
        .compile()
    )
    out = graph.invoke(create_initial_state("q", metadata={"k0": 0}))
    assert out["metadata"]["k0"] == 0
    assert out["metadata"]["k1"] == 1
    assert out["metadata"]["k2"] == 2


def test_state_helpers():
    state = create_initial_state("what is jax?", metadata={"user_top_k": 3})
    assert state["query_id"]
    docs = [Document(text="t", id="d1")]
    state = add_retrieved_documents(state, docs)
    assert state["metadata"]["num_retrieved"] == 1
    assert best_documents(state)[0].id == "d1"
    state = set_response(state, "answer", model="tiny")
    assert state["response"] == "answer"
    assert state["metadata"]["model"] == "tiny"


def test_document_content_fallback():
    doc = Document(text="", metadata={"content": "from metadata"})
    assert doc.content == "from metadata"
    assert Document(text="direct").content == "direct"
    assert Document(text="x", metadata={"rerank_score": 0.5}).score() == 0.5
