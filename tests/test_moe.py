"""MoE decoder (models/moe.py): routing math, capacity semantics, dense
parity, cache-path parity, and expert-parallel sharding."""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.config import MeshConfig
from sentio_tpu.models.moe import (
    MoeConfig,
    expert_capacity,
    init_cache,
    init_moe,
    moe_forward,
    moe_loss,
    moe_mlp,
    route_topk,
)
from sentio_tpu.parallel.mesh import build_mesh
from sentio_tpu.parallel.sharding import MOE_EP_RULES, shard_params

pytestmark = [pytest.mark.slow, pytest.mark.mesh]


@pytest.fixture(scope="module")
def cfg():
    return MoeConfig.tiny()


@pytest.fixture(scope="module")
def f32_cfg():
    return replace(MoeConfig.tiny(), dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    return init_moe(jax.random.PRNGKey(0), cfg)


class TestRouting:
    def test_topk_dispatches_to_top_experts(self):
        logits = jnp.asarray(
            [[5.0, 1.0, 0.0, -1.0], [0.0, 0.0, 6.0, 5.0]], jnp.float32
        )
        dispatch, combine, _ = route_topk(logits, k=2, capacity=2)
        d = np.asarray(dispatch)
        # token 0 → experts 0 and 1; token 1 → experts 2 and 3
        assert d[0, 0].any() and d[0, 1].any() and not d[0, 2:].any()
        assert d[1, 2].any() and d[1, 3].any() and not d[1, :2].any()
        # gates renormalize to 1 per token
        c = np.asarray(combine)
        np.testing.assert_allclose(c.sum(axis=(1, 2)), [1.0, 1.0], atol=1e-5)

    def test_capacity_drops_overflow_tokens(self):
        # every token's top-1 is expert 0 with capacity 1: only the first
        # token keeps that choice, later tokens lose it
        logits = jnp.asarray([[9.0, 1.0]] * 4, jnp.float32)
        dispatch, combine, _ = route_topk(logits, k=1, capacity=1)
        d = np.asarray(dispatch)
        assert d[0, 0, 0]
        assert not d[1:, 0].any()

    def test_capacity_formula(self, cfg):
        c = expert_capacity(cfg, 128)
        per = 128 * cfg.experts_per_token / cfg.n_experts
        assert c >= per  # capacity_factor >= 1 never under-provisions


class TestMoeMlp:
    def test_matches_per_token_reference(self, f32_cfg):
        """Dispatch/combine einsums must equal the naive per-token loop when
        capacity is ample (nothing dropped)."""
        cfg = replace(f32_cfg, capacity_factor=8.0)
        p = init_moe(jax.random.PRNGKey(1), cfg)
        mp = p["layers_0"]["moe"]
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((2, 5, cfg.dim)), jnp.float32)

        out, aux = moe_mlp(mp, cfg, x)

        flat = np.asarray(x.reshape(-1, cfg.dim))
        logits = flat @ np.asarray(mp["router"]["kernel"])
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        expected = np.zeros_like(flat)
        for g in range(flat.shape[0]):
            order = np.argsort(-probs[g])[: cfg.experts_per_token]
            gates = probs[g][order]
            gates = gates / gates.sum()
            for e, w in zip(order, gates):
                wg = np.asarray(mp["w_gate"][e])
                wu = np.asarray(mp["w_up"][e])
                wd = np.asarray(mp["w_down"][e])
                h = flat[g]
                silu = lambda v: v / (1 + np.exp(-v))
                expected[g] += w * ((silu(h @ wg) * (h @ wu)) @ wd)
        np.testing.assert_allclose(
            np.asarray(out).reshape(-1, cfg.dim), expected, atol=1e-3
        )
        assert np.isfinite(float(aux))

    def test_dropped_tokens_pass_residual_through(self, f32_cfg):
        """A dropped token's MoE output is zero, so the block reduces to the
        residual stream for it."""
        cfg = replace(f32_cfg, n_experts=2, experts_per_token=1,
                      capacity_factor=0.01)
        p = init_moe(jax.random.PRNGKey(1), cfg)
        mp = p["layers_0"]["moe"]
        x = jnp.ones((1, 8, cfg.dim), jnp.float32)
        out, _ = moe_mlp(mp, cfg, x)
        # capacity 1 per expert, 8 identical tokens → at most 2 kept
        norms = np.linalg.norm(np.asarray(out)[0], axis=-1)
        assert (norms < 1e-6).sum() >= 6


class TestMoeForward:
    def test_decode_matches_full_forward(self, cfg):
        # ample capacity so the T=12 prefill and T=1 decode calls route
        # identically (capacity depends on the token count per call)
        cfg = replace(cfg, capacity_factor=8.0)
        params = init_moe(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(3)
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 12)), jnp.int32)

        full_logits, _, _ = moe_forward(params, cfg, ids)

        cache = init_cache(cfg, batch=2, max_len=32)
        _, cache, _ = moe_forward(
            params, cfg, ids[:, :8],
            positions=jnp.broadcast_to(jnp.arange(8)[None], (2, 8)),
            cache=cache, cache_index=0,
        )
        logits = None
        for t in range(8, 12):
            logits, cache, _ = moe_forward(
                params, cfg, ids[:, t : t + 1],
                positions=jnp.full((2, 1), t, jnp.int32),
                cache=cache, cache_index=t,
            )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full_logits[:, 11]),
            atol=0.08,  # bf16 accumulation noise only
        )

    def test_padding_takes_no_expert_capacity(self, f32_cfg):
        """With capacity exactly fitting the real tokens, a front-loaded pad
        run must not evict real tokens from their experts."""
        cfg = replace(f32_cfg, n_experts=2, experts_per_token=1,
                      capacity_factor=1.0)
        p = init_moe(jax.random.PRNGKey(1), cfg)
        mp = p["layers_0"]["moe"]
        rng = np.random.default_rng(8)
        x = jnp.asarray(rng.standard_normal((1, 8, cfg.dim)), jnp.float32)
        pad = np.zeros((1, 8), bool)
        pad[0, 4:] = True  # only the LAST 4 tokens are real
        pad_mask = jnp.asarray(pad)

        out_masked, aux = moe_mlp(mp, cfg, x, pad_mask)
        o = np.asarray(out_masked)[0]
        # real tokens got expert outputs (pads upstream claimed no slots)
        assert (np.linalg.norm(o[4:], axis=-1) > 1e-6).all()
        assert np.isfinite(float(aux))

    def test_serving_adapter_two_tuple(self, params, cfg):
        from sentio_tpu.models.moe import moe_serving_forward

        ids = jnp.ones((2, 4), jnp.int32)
        logits, cache = moe_serving_forward(params, cfg, ids)
        assert logits.shape == (2, 4, cfg.vocab_size)
        assert cache is None

    def test_loss_finite_and_aux_contributes(self, params, cfg):
        rng = np.random.default_rng(4)
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, 17)), jnp.int32)
        mask = jnp.ones((4, 17), bool)
        loss = float(moe_loss(params, cfg, ids, mask))
        assert np.isfinite(loss)
        no_aux = replace(cfg, router_aux_weight=0.0)
        assert float(moe_loss(params, no_aux, ids, mask)) < loss


class TestMoeServing:
    def test_generator_engine_serves_moe(self, params, cfg):
        """The model-family seam: GeneratorEngine runs MoE checkpoints
        through the same prefill/decode/stream paths as Llama."""
        from sentio_tpu.config import GeneratorConfig
        from sentio_tpu.models.moe import moe_serving_forward
        from sentio_tpu.runtime.engine import GeneratorEngine

        eng = GeneratorEngine(
            config=GeneratorConfig(model_preset="tiny", max_new_tokens=8),
            model_config=cfg,
            params=params,
            forward_fn=moe_serving_forward,
        )
        r = eng.generate(["hello experts"], max_new_tokens=8, temperature=0.0)[0]
        r2 = eng.generate(["hello experts"], max_new_tokens=8, temperature=0.0)[0]
        assert r.tokens == r2.tokens  # greedy decode is deterministic
        assert r.finish_reason in ("stop", "length")

        streamed = list(eng.stream("hello experts", max_new_tokens=6,
                                   temperature=0.0))
        assert len(streamed) >= 1


    def test_paged_engine_serves_moe(self, cfg):
        """The DEFAULT serving path (paged continuous batching) runs MoE:
        fused decode ticks route per layer, prefill goes through the family
        seam. Ample capacity makes routing batch-size-independent, so paged
        greedy must match the dense engine exactly (with tight capacity the
        two are both valid but can drop different tokens, since capacity is
        a function of the tokens-per-call)."""
        from sentio_tpu.config import GeneratorConfig
        from sentio_tpu.models.moe import init_moe, moe_serving_forward
        from sentio_tpu.runtime.engine import GeneratorEngine
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        acfg = replace(cfg, capacity_factor=8.0)
        params = init_moe(jax.random.PRNGKey(0), acfg)
        prompts = ["routed experts on pages", "second request here"]

        paged = ContinuousBatchingEngine(
            model_config=acfg, params=params, forward_fn=moe_serving_forward,
            max_slots=4, page_size=16, max_pages_per_seq=8, steps_per_tick=4,
        )
        res = paged.run_all(prompts, max_new_tokens=8, temperature=0.0)

        eng = GeneratorEngine(
            config=GeneratorConfig(model_preset="tiny", max_new_tokens=8),
            model_config=acfg, params=params, forward_fn=moe_serving_forward,
        )
        dense = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert [r.tokens for r in res] == [r.tokens for r in dense]

    def test_engines_reject_family_mismatch(self, cfg):
        from sentio_tpu.models.llama import LlamaConfig, llama_forward
        from sentio_tpu.models.moe import moe_serving_forward
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        # moe forward against a dense config
        with pytest.raises(ValueError, match="does not match"):
            ContinuousBatchingEngine(
                model_config=LlamaConfig.tiny(), forward_fn=moe_serving_forward
            )
        # dense forward against a moe config
        with pytest.raises(ValueError, match="does not match"):
            ContinuousBatchingEngine(model_config=cfg, forward_fn=llama_forward)

    def test_moe_config_alone_auto_selects_family(self, cfg):
        """A MoeConfig with no params random-inits MoE weights and routes —
        never silently degrades to a dense Llama."""
        from sentio_tpu.models.moe import moe_serving_forward
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        paged = ContinuousBatchingEngine(
            model_config=cfg, max_slots=2, page_size=16, max_pages_per_seq=4,
        )
        assert paged.forward_fn is moe_serving_forward
        assert "moe" in paged.params["layers_0"]


class TestExpertParallel:
    def test_ep_sharded_loss_matches(self, params, cfg):
        rng = np.random.default_rng(5)
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, 17)), jnp.int32)
        mask = jnp.ones((4, 17), bool)
        ref = float(moe_loss(params, cfg, ids, mask))
        mesh = build_mesh(MeshConfig(dp_size=2, ep_size=2, tp_size=2))
        sharded = shard_params(params, mesh, MOE_EP_RULES)
        got = float(jax.jit(lambda p, i, m: moe_loss(p, cfg, i, m))(sharded, ids, mask))
        assert abs(got - ref) < 2e-2

    def test_ep_rules_place_experts_on_ep(self, params):
        mesh = build_mesh(MeshConfig(dp_size=2, ep_size=2, tp_size=2))
        sharded = shard_params(params, mesh, MOE_EP_RULES)
        spec = sharded["layers_0"]["moe"]["w_gate"].sharding.spec
        assert spec[0] == "ep" and spec[2] == "tp"
        spec_down = sharded["layers_0"]["moe"]["w_down"].sharding.spec
        assert spec_down[0] == "ep" and spec_down[1] == "tp"
        # router replicated (spec entries all None)
        router_spec = sharded["layers_0"]["moe"]["router"]["kernel"].sharding.spec
        assert all(entry is None for entry in router_spec)

    def test_paged_serving_on_ep_mesh(self, cfg):
        """Mesh-sharded MoE through the DEFAULT serving path: experts on ep,
        kv pool heads on tp, greedy tokens matching the single-device run."""
        from sentio_tpu.models.moe import init_moe, moe_serving_forward
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        acfg = replace(cfg, capacity_factor=8.0)
        params = init_moe(jax.random.PRNGKey(0), acfg)
        mesh = build_mesh(MeshConfig(dp_size=2, ep_size=2, tp_size=2))
        sharded = shard_params(params, mesh, MOE_EP_RULES)
        prompts = ["experts on a mesh", "second lane"]

        served = ContinuousBatchingEngine(
            model_config=acfg, params=sharded, mesh=mesh,
            forward_fn=moe_serving_forward,
            max_slots=4, page_size=16, max_pages_per_seq=8, steps_per_tick=4,
        ).run_all(prompts, max_new_tokens=8, temperature=0.0)

        single = ContinuousBatchingEngine(
            model_config=acfg, params=params, forward_fn=moe_serving_forward,
            max_slots=4, page_size=16, max_pages_per_seq=8, steps_per_tick=4,
        ).run_all(prompts, max_new_tokens=8, temperature=0.0)
        assert [r.tokens for r in served] == [r.tokens for r in single]

    def test_ep_train_step(self, params, cfg):
        import optax

        rng = np.random.default_rng(6)
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, 17)), jnp.int32)
        mask = jnp.ones((4, 17), bool)
        mesh = build_mesh(MeshConfig(dp_size=2, ep_size=2, tp_size=2))
        sharded = shard_params(params, mesh, MOE_EP_RULES)
        tx = optax.adamw(1e-3)
        opt = tx.init(sharded)

        def step(p, o, i, m):
            loss, g = jax.value_and_grad(lambda q: moe_loss(q, cfg, i, m))(p)
            up, o = tx.update(g, o, p)
            return optax.apply_updates(p, up), o, loss

        p2, o2, loss = jax.jit(step)(sharded, opt, ids, mask)
        assert np.isfinite(float(loss))
        # params actually moved
        delta = sum(
            float(jnp.abs(a - b).sum())
            for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(sharded))
        )
        assert delta > 0
