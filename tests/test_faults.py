"""Fault injection: the framework's failure seams are armed with
deterministic fault rules and the degradation ladder must hold — the
pipeline never turns a component failure into a hard error (SURVEY.md §5:
every reference graph node absorbs errors and degrades; here that contract
is actually testable instead of mock-simulated)."""

from __future__ import annotations

import numpy as np
import pytest

from sentio_tpu.config import (
    EmbedderConfig,
    GeneratorConfig,
    RerankConfig,
    Settings,
)
from sentio_tpu.infra import faults
from sentio_tpu.models.document import Document


@pytest.fixture(autouse=True)
def clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def stack(docs):
    """hash-embedder + echo-generator pipeline over the shared doc fixture."""
    from sentio_tpu.graph.factory import GraphConfig, build_basic_graph
    from sentio_tpu.ops.bm25 import BM25Index
    from sentio_tpu.ops.dense_index import TpuDenseIndex
    from sentio_tpu.ops.embedder import get_embedder
    from sentio_tpu.ops.generator import create_generator
    from sentio_tpu.ops.reranker import get_reranker
    from sentio_tpu.ops.retrievers import DenseRetriever, HybridRetriever, SparseRetriever

    settings = Settings(
        embedder=EmbedderConfig(provider="hash", dim=32),
        generator=GeneratorConfig(provider="echo", use_verifier=False),
        rerank=RerankConfig(enabled=True, kind="passthrough"),
    )
    embedder = get_embedder(settings.embedder)
    dense = TpuDenseIndex(dim=32, dtype="float32")
    dense.add(docs, embedder.embed_many([d.text for d in docs]))
    sparse = BM25Index().build(docs)
    retriever = HybridRetriever(
        retrievers=[DenseRetriever(embedder, dense), SparseRetriever(sparse)],
        config=settings.retrieval,
    )
    generator = create_generator(settings=settings)
    graph = build_basic_graph(
        retriever, generator,
        reranker=get_reranker("passthrough", config=settings.rerank),
        config=GraphConfig(settings=settings),
    )
    return graph


def run_graph(graph, query="what does the fox do?"):
    from sentio_tpu.graph.state import create_initial_state

    return graph.invoke(create_initial_state(query, metadata={"mode": "fast"}))


class TestRuleMechanics:
    def test_unarmed_hit_is_noop(self):
        faults.hit("nowhere")  # must not raise

    def test_times_limits_firing(self):
        with faults.inject("p", error=RuntimeError("boom"), times=2) as rule:
            for _ in range(2):
                with pytest.raises(RuntimeError):
                    faults.hit("p")
            faults.hit("p")  # third hit passes
            assert rule.hits == 3 and rule.fired == 2

    def test_skip_passes_the_first_hits_then_fires(self):
        """``skip=N`` arms 'the N+1th dispatch dies' BEFORE the work
        starts — the deterministic mid-stream kill shape (at least one
        delivered chunk, then death), no consumer-timing race."""
        with faults.inject("p", error=RuntimeError("late boom"),
                           times=1, skip=2) as rule:
            faults.hit("p")  # skipped
            faults.hit("p")  # skipped
            with pytest.raises(RuntimeError):
                faults.hit("p")
            faults.hit("p")  # times=1 exhausted
            assert rule.hits == 4 and rule.fired == 1

    def test_probability_is_seed_deterministic(self):
        def count(seed):
            n = 0
            with faults.inject("p", error=ValueError("x"), probability=0.5, seed=seed):
                for _ in range(50):
                    try:
                        faults.hit("p")
                    except ValueError:
                        n += 1
            return n

        assert count(7) == count(7)
        assert 10 < count(7) < 40

    def test_delay_only(self):
        import time

        with faults.inject("p", delay_s=0.05):
            t0 = time.perf_counter()
            faults.hit("p")
            assert time.perf_counter() - t0 >= 0.05

    def test_context_exit_disarms(self):
        with faults.inject("p", error=RuntimeError("x")):
            pass
        faults.hit("p")
        assert faults.active_rules() == {}


class TestDegradationLadder:
    def test_dense_leg_down_hybrid_still_answers(self, stack):
        with faults.inject("retriever.dense", error=TimeoutError("device lost")):
            state = run_graph(stack)
        assert state["metadata"]["num_retrieved"] > 0  # sparse leg carried it
        assert state["response"]

    def test_both_legs_down_soft_fails_to_empty(self, stack):
        with faults.inject("retriever.dense", error=TimeoutError("x")), \
             faults.inject("retriever.sparse", error=TimeoutError("y")):
            state = run_graph(stack)
        # retrieval failed entirely; the graph absorbs it (retrieve_error
        # metadata) and the pipeline still produces a response rather than
        # erroring the request
        assert not state.get("retrieved_documents")
        assert "retrieval_error" in state["metadata"]
        assert state["response"] is not None

    def test_reranker_down_keeps_original_order(self, docs):
        from sentio_tpu.ops.reranker import CrossEncoderReranker
        from sentio_tpu.models.transformer import EncoderConfig

        rr = CrossEncoderReranker(RerankConfig(batch_size=8),
                                  model_config=EncoderConfig.tiny())
        with faults.inject("reranker.score", error=RuntimeError("kernel oom")):
            result = rr.rerank("query", docs, top_k=3)
        assert [d.id for d in result.documents] == [d.id for d in docs[:3]]

    def test_embedder_batch_fault_then_recovers(self):
        from sentio_tpu.ops.embedder import get_embedder

        embedder = get_embedder(EmbedderConfig(provider="hash", dim=32))
        with faults.inject("embedder.batch",
                           error=RuntimeError("embed kernel oom"),
                           times=1) as rule:
            with pytest.raises(RuntimeError):
                embedder.embed_many(["hello"])
            out = embedder.embed_many(["hello"])  # recovered
        assert rule.fired == 1
        assert out.shape == (1, 32)

    def test_generate_fault_exhausts_then_recovers(self):
        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.runtime.engine import GeneratorEngine

        engine = GeneratorEngine(
            config=GeneratorConfig(model_preset="tiny", max_new_tokens=4),
            model_config=LlamaConfig.tiny(),
        )
        with faults.inject("engine.generate", error=TimeoutError("deadline"), times=1):
            with pytest.raises(TimeoutError):
                engine.generate(["hello"])
            out = engine.generate(["hello"])  # recovered
        assert len(out) == 1


class TestStallFaults:
    """The hang fault class (ISSUE 10): a stall rule blocks INSIDE the
    injection point — for a bounded duration, or until the test releases
    an event — and composes with raise. This is how chaos wedges a pump
    exactly like a hung device dispatch (nothing raises, nothing returns)."""

    def test_stall_duration_bounded_by_budget(self):
        import time

        with faults.inject("p", stall_s=0.15) as rule:
            t0 = time.perf_counter()
            faults.hit("p")
            dt = time.perf_counter() - t0
        assert dt >= 0.15
        assert rule.stalled == 1

    def test_stall_event_released_mid_test_at_paged_step(self):
        """A pump-shaped thread wedges at ``paged.step`` until the test
        sets the release event; the stall_s cap bounds the worst case."""
        import threading
        import time

        release = threading.Event()
        unwedged = threading.Event()

        def pump():
            faults.hit("paged.step")
            unwedged.set()

        with faults.inject("paged.step", stall_event=release, stall_s=30.0,
                           times=1) as rule:
            t = threading.Thread(target=pump, daemon=True)
            t.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and rule.stalled == 0:
                time.sleep(0.005)
            assert rule.stalled == 1, "pump never entered the stall"
            assert not unwedged.is_set(), "stall did not actually block"
            release.set()
            t.join(timeout=5)
            assert unwedged.is_set(), "release did not free the stalled hit"
            # times=1: a second hit passes straight through
            faults.hit("paged.step")
            assert rule.stalled == 1

    def test_stall_at_engine_reset(self):
        """``engine.reset`` — the crash-containment path itself — can be
        wedged: the reset blocks for the stall duration, then completes
        normally (stall, unlike raise, does not fail the reset)."""
        import time

        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        engine = ContinuousBatchingEngine(
            max_slots=2, page_size=8, max_pages_per_seq=4,
        )
        with faults.inject("engine.reset", stall_s=0.1, times=1) as rule:
            t0 = time.perf_counter()
            engine.reset()
            assert time.perf_counter() - t0 >= 0.1
        assert rule.stalled == 1
        assert engine.allocator.free_pages == engine.allocator.num_pages - 1

    def test_stall_then_raise_composition(self):
        """stall + error on one rule: the hit blocks first, THEN raises —
        a dispatch that hangs and then dies, the worst-case compound."""
        import time

        with faults.inject("p", stall_s=0.1,
                           error=RuntimeError("died after the hang")) as rule:
            t0 = time.perf_counter()
            with pytest.raises(RuntimeError, match="died after the hang"):
                faults.hit("p")
            assert time.perf_counter() - t0 >= 0.1
        assert rule.stalled == 1 and rule.fired == 1

    def test_unfired_rule_never_stalls(self):
        import time

        with faults.inject("p", stall_s=5.0, times=0):
            t0 = time.perf_counter()
            faults.hit("p")
            assert time.perf_counter() - t0 < 1.0


class TestSupervisorFaultPoints:
    """The replica-supervision seams (ISSUE 8): ``engine.reset`` lets chaos
    force the crash-containment reset itself to fail (the path that latches
    a service broken), and ``replica.rebuild`` lets chaos exercise
    rebuild-fails-then-succeeds with the supervisor's backoff."""

    def test_engine_reset_fault_point_fires_then_clears(self):
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        engine = ContinuousBatchingEngine(
            max_slots=2, page_size=8, max_pages_per_seq=4,
        )
        with faults.inject("engine.reset",
                           error=RuntimeError("reset denied"),
                           times=1) as rule:
            with pytest.raises(RuntimeError, match="reset denied"):
                engine.reset()
            engine.reset()  # second attempt proceeds normally
        assert rule.hits == 2 and rule.fired == 1
        # the reset actually rebuilt the decode state
        assert engine.allocator.free_pages == engine.allocator.num_pages - 1

    def test_replica_rebuild_fails_then_succeeds(self):
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine
        from sentio_tpu.runtime.replica import (
            HEALTH_HEALTHY,
            HEALTH_QUARANTINED,
            ReplicaSet,
        )
        from sentio_tpu.runtime.service import PagedGenerationService

        engine = ContinuousBatchingEngine(
            max_slots=2, page_size=8, max_pages_per_seq=4, steps_per_tick=2,
        )
        svc = PagedGenerationService(engine)
        rs = ReplicaSet([svc], supervise=False, quarantine_backoff_s=0.0)
        try:
            rs._quarantine(0, "seeded by test")
            with faults.inject("replica.rebuild",
                               error=RuntimeError("no rebuild capacity"),
                               times=1) as rule:
                assert rs._rebuild(0) is False
                assert rule.fired == 1
            replica = rs.health_summary()["replicas"][0]
            assert replica["state"] == HEALTH_QUARANTINED
            assert "rebuild failed" in replica["reason"]
            assert replica["rebuilds"] == 0
            # backoff 0 → immediately due again; unarmed point now passes
            # and the replica re-enters rotation on a working fresh engine
            assert rs._rebuild(0) is True
            replica = rs.health_summary()["replicas"][0]
            assert replica["state"] == HEALTH_HEALTHY
            assert replica["rebuilds"] == 1
            ok = rs.generate("post rebuild request", max_new_tokens=2,
                             temperature=0.0, timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
        finally:
            rs.close()
