"""HTTP surface tests over the real ASGI-equivalent aiohttp app.

Mirrors the reference's API test pattern (src/tests/api/conftest.py there:
TestClient over the app with fake backends injected) — here the fakes are
the hash embedder + echo generator, so the WHOLE stack runs: middleware,
validation, rate limits, handlers, graph, indexes.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from sentio_tpu.config import (
    AuthConfig,
    EmbedderConfig,
    GeneratorConfig,
    RerankConfig,
    ServeConfig,
    Settings,
)
from sentio_tpu.serve.app import create_app
from sentio_tpu.serve.dependencies import DependencyContainer

pytestmark = pytest.mark.slow


def fast_settings(**over) -> Settings:
    s = Settings(
        embedder=EmbedderConfig(provider="hash", dim=32),
        generator=GeneratorConfig(provider="echo", use_verifier=False, max_new_tokens=32),
        rerank=RerankConfig(enabled=True, kind="passthrough"),
    )
    for key, value in over.items():
        setattr(s, key, value)
    return s


def run(coro):
    return asyncio.run(coro)


async def with_client(settings, fn, container=None):
    container = container or DependencyContainer(settings=settings)
    app = create_app(container=container)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client, container)
    finally:
        await client.close()


async def seed(client, texts):
    for text in texts:
        resp = await client.post("/embed", json={"content": text})
        assert resp.status == 200, await resp.text()


class TestChatEndpoint:
    def test_chat_happy_path(self):
        async def body(client, container):
            await seed(client, ["jax compiles python to xla", "tpus have a systolic mxu"])
            resp = await client.post("/chat", json={"question": "what compiles to xla?"})
            assert resp.status == 200
            data = await resp.json()
            assert data["answer"]
            assert isinstance(data["sources"], list) and data["sources"]
            assert data["metadata"]["degraded"] is False
            assert "latency_ms" in data["metadata"]

        run(with_client(fast_settings(), body))

    def test_chat_validation_errors(self):
        async def body(client, container):
            for payload, field in [
                ({}, "question"),
                ({"question": ""}, "question"),
                ({"question": "x" * 3000}, "question"),
                ({"question": "ok", "top_k": 0}, "top_k"),
                ({"question": "ok", "top_k": 99}, "top_k"),
                ({"question": "ok", "temperature": 3.0}, "temperature"),
                ({"question": "ok", "mode": "bogus"}, "mode"),
            ]:
                resp = await client.post("/chat", json=payload)
                assert resp.status == 422, (payload, resp.status)
                data = await resp.json()
                assert any(e["field"] == field for e in data["details"])

        run(with_client(fast_settings(), body))

    def test_chat_user_top_k_respected(self):
        async def body(client, container):
            await seed(client, [f"fact number {i} about topic" for i in range(8)])
            resp = await client.post("/chat", json={"question": "facts about topic", "top_k": 2})
            data = await resp.json()
            assert len(data["sources"]) <= 2

        run(with_client(fast_settings(), body))

    def test_degradation_ladder_never_500s(self):
        class Boom:
            def invoke(self, *a, **k):
                raise RuntimeError("device on fire")

        async def body(client, container):
            container.override("graph", Boom())
            resp = await client.post("/chat", json={"question": "anything at all"})
            assert resp.status == 200
            data = await resp.json()
            assert data["metadata"]["degraded"] is True
            assert data["metadata"]["tier"] in ("query_cache", "disk_cache", "template", "apology")
            assert data["answer"]

        run(with_client(fast_settings(), body))

    def test_chat_stream_sse(self):
        async def body(client, container):
            await seed(client, ["streaming tokens over sse"])
            resp = await client.post(
                "/chat", json={"question": "stream me an answer", "stream": True}
            )
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/event-stream")
            raw = (await resp.read()).decode()
            assert "data:" in raw and "[DONE]" in raw

        run(with_client(fast_settings(), body))

    def test_chat_stream_sse_keepalive_during_silence(self):
        """ISSUE 10 satellite: while the producer is silent past the
        configured interval (a slow — or wedged — decode), the SSE wire
        carries comment keepalives so the client can tell 'still working'
        from a dead connection; real events still follow."""
        import time as _time

        async def body(client, container):
            def slow_stream(**kwargs):
                _time.sleep(0.4)  # silence > several keepalive intervals
                yield ("token", "late answer")

            container.chat_handler.stream_chat_sync = slow_stream
            resp = await client.post(
                "/chat", json={"question": "slow stream", "stream": True})
            assert resp.status == 200
            raw = (await resp.read()).decode()
            assert ": keepalive" in raw, raw
            assert "late answer" in raw and "[DONE]" in raw

        run(with_client(
            fast_settings(serve=ServeConfig(sse_keepalive_s=0.05)), body))


class TestEmbedAndClear:
    def test_embed_validates_and_indexes(self):
        async def body(client, container):
            resp = await client.post("/embed", json={"content": "a document body"})
            assert resp.status == 200
            data = await resp.json()
            assert data["stats"]["chunks_stored"] == 1
            assert container.dense_index.size == 1

            resp = await client.post("/embed", json={"content": ""})
            assert resp.status == 422

        run(with_client(fast_settings(), body))

    def test_embed_rate_limited(self):
        settings = fast_settings(
            serve=ServeConfig(rate_limit_embed_per_min=3, rate_limit_default_per_min=100)
        )

        async def body(client, container):
            statuses = []
            for i in range(5):
                resp = await client.post("/embed", json={"content": f"doc {i}"})
                statuses.append(resp.status)
            assert statuses[:3] == [200, 200, 200]
            assert 429 in statuses[3:]
            limited = await client.post("/embed", json={"content": "one more"})
            assert limited.headers.get("Retry-After")

        run(with_client(settings, body))

    def test_clear(self):
        async def body(client, container):
            await seed(client, ["to be deleted"])
            resp = await client.post("/clear")
            assert resp.status == 200
            assert (await resp.json())["documents_removed"] == 1
            assert container.dense_index.size == 0

        run(with_client(fast_settings(), body))


class TestHealthAndInfo:
    def test_health_suite(self):
        async def body(client, container):
            basic = await client.get("/health")
            assert basic.status == 200
            assert (await basic.json())["status"] == "healthy"

            live = await client.get("/health/live")
            assert (await live.json())["status"] == "alive"

            ready = await client.get("/health/ready")
            assert ready.status == 200  # create_app initializes eagerly

            detailed = await client.get("/health/detailed")
            assert detailed.status == 200
            report = await detailed.json()
            assert report["components"]["embedder"]["healthy"]
            assert report["components"]["dense_index"]["healthy"]
            # second call inside the 10s window is served from cache
            again = await (await client.get("/health/detailed")).json()
            assert again["cached"] is True

        run(with_client(fast_settings(), body))

    def test_health_replica_degraded_stays_200_unhealthy_503(self):
        """Replica failure domains on /health: 1 ≤ serving < N reports
        ``degraded`` with HTTP 200 (k8s must keep routing to the half-alive
        pod while the supervisor rebuilds), and ``unhealthy`` → 503 only at
        ZERO serving replicas (restarting is now the best move)."""

        class HalfAliveSet:
            def health_summary(self):
                return {
                    "status": "degraded", "healthy_replicas": 1,
                    "serving_replicas": 1, "total_replicas": 2,
                    "replicas": [
                        {"replica": 0, "state": "HEALTHY", "since_s": 5.0,
                         "rebuilds": 0},
                        {"replica": 1, "state": "REBUILDING",
                         "since_s": 1.0, "rebuilds": 0,
                         "reason": "engine latched broken"},
                    ],
                }

            def close(self):
                pass

        class DeadSet(HalfAliveSet):
            def health_summary(self):
                return {
                    "status": "unhealthy", "healthy_replicas": 0,
                    "serving_replicas": 0, "total_replicas": 2,
                    "replicas": [],
                }

        async def body(client, container):
            container.override("generation_service", HalfAliveSet())
            resp = await client.get("/health")
            assert resp.status == 200
            data = await resp.json()
            assert data["status"] == "degraded"
            assert data["replicas"]["serving_replicas"] == 1
            assert data["replicas"]["replicas"][1]["state"] == "REBUILDING"
            container.override("generation_service", DeadSet())
            resp = await client.get("/health")
            assert resp.status == 503
            assert (await resp.json())["status"] == "unhealthy"

        run(with_client(fast_settings(), body))

    def test_info(self):
        async def body(client, container):
            resp = await client.get("/info")
            data = await resp.json()
            assert data["service"] == "sentio-tpu"
            assert data["retrieval"]["strategy"] == "hybrid"
            assert data["generator"]["provider"] == "echo"

        run(with_client(fast_settings(), body))

    def test_metrics_endpoints(self):
        async def body(client, container):
            await client.post("/chat", json={"question": "count this request"})
            prom = await client.get("/metrics")
            assert prom.status == 200
            assert "requests" in (await prom.text())
            perf = await client.get("/metrics/performance")
            assert perf.status == 200
            assert "metrics" in await perf.json()

        run(with_client(fast_settings(), body))

    def test_security_headers(self):
        async def body(client, container):
            resp = await client.get("/health")
            assert resp.headers["X-Content-Type-Options"] == "nosniff"
            assert resp.headers["X-Frame-Options"] == "DENY"

        run(with_client(fast_settings(), body))

    def test_ui_page(self):
        async def body(client, container):
            resp = await client.get("/")
            assert resp.status == 200
            page = await resp.text()
            assert "sentio-tpu" in page
            # upload flow + health badge (reference streamlit_app.py:27-318:
            # client-side chunking into /embed, backend health indicator)
            assert 'type="file"' in page and "/embed" in page
            assert "chunks(" in page
            assert "/health" in page and 'id="dot"' in page

        run(with_client(fast_settings(), body))


    def test_info_speculative_resolution(self):
        """/info names the exact reason a configured draft is inactive
        (the round-4 dead-knob gap — operators must never see a dead knob
        reported as active)."""

        async def body(client, container):
            data = await (await client.get("/info")).json()
            spec = data["generator"]["speculative"]
            assert spec["draft_configured"] is True
            assert spec["active"] is False
            assert "PREFILL_CHUNK" in spec["ignored_reason"]

        settings = fast_settings(generator=GeneratorConfig(
            provider="tpu", model_preset="tiny", use_verifier=False,
            draft_checkpoint_path="/nonexistent-draft", prefill_chunk=512,
            use_paged_decode=True,
        ))
        run(with_client(settings, body,
                        container=DependencyContainer(settings=settings,
                                                      mesh=None)))

    def test_info_speculative_contiguous_mesh_gating(self):
        """USE_PAGED_KV=0 + a device mesh: the contiguous SpeculativeDecoder
        is never constructed (dependencies.speculative is single-chip-only),
        so /info must report active=false with the mesh named as the reason
        — not a dead knob shown as live."""

        async def body(client, container):
            # the 8 virtual CPU devices build a real dp mesh by default
            assert container.mesh is not None
            data = await (await client.get("/info")).json()
            spec = data["generator"]["speculative"]
            assert spec["draft_configured"] is True
            assert spec["active"] is False
            assert "mesh" in spec["ignored_reason"]

        settings = fast_settings(generator=GeneratorConfig(
            provider="tpu", model_preset="tiny", use_verifier=False,
            draft_checkpoint_path="/nonexistent-draft",
            use_paged_decode=False,
        ))
        run(with_client(settings, body))


class TestAuth:
    def test_auth_flow(self):
        settings = fast_settings(auth=AuthConfig(enabled=True, jwt_secret="s" * 32))

        async def body(client, container):
            # protected endpoint rejects anonymous
            resp = await client.post("/chat", json={"question": "who goes there"})
            assert resp.status == 401
            # health stays open
            assert (await client.get("/health")).status == 200

            container.auth_manager.create_user("ada", "Correct-Horse-Battery-9", role="admin")
            tok = await client.post(
                "/auth/token", json={"username": "ada", "password": "Correct-Horse-Battery-9"}
            )
            assert tok.status == 200
            access = (await tok.json())["access_token"]

            ok = await client.post(
                "/chat",
                json={"question": "authorized now"},
                headers={"Authorization": f"Bearer {access}"},
            )
            assert ok.status == 200

        run(with_client(settings, body))


class TestPagedServing:
    """Concurrent /chat requests must coalesce on the device: the paged
    continuous-batching service (runtime/service.py) is the default decode
    path, and concurrent requests share its decode ticks instead of
    serializing one generation per request (the round-1 gap)."""

    def test_concurrent_chat_through_paged_decode(self):
        settings = fast_settings(
            generator=GeneratorConfig(
                provider="tpu", model_preset="tiny", use_verifier=False,
                max_new_tokens=24, mode="fast",  # greedy: deterministic
                use_paged_decode=True, kv_page_size=16,
                kv_max_pages_per_seq=8, max_batch_size=4,
            ),
        )

        async def body(client, container):
            await seed(client, [
                "jax compiles python functions to xla programs",
                "tpus multiply matrices in a systolic array",
                "paged kv caches avoid memory fragmentation",
            ])
            service = container.generation_service
            assert service is not None, "paged decode service was not built"
            questions = [
                "what compiles python to xla?",
                "how do tpus multiply matrices quickly?",
                "why do paged kv caches help memory?",
                "what is a systolic array used for?",
            ]
            # overlap is guaranteed by construction: this container's engine
            # is fresh, so the first admitted request pays multi-second jit
            # tracing+compile inside its first tick, during which the other
            # (near-simultaneous) requests reach the inbox and join at the
            # next tick — decode ticks are ~ms, compile is ~s
            resps = await asyncio.gather(*[
                client.post("/chat", json={"question": q}) for q in questions
            ])
            for resp in resps:
                assert resp.status == 200, await resp.text()
                data = await resp.json()
                assert data["metadata"]["degraded"] is False
                assert data["metadata"]["generator"] == "tpu"
            stats = service.stats()
            assert stats["completed"] >= len(questions)
            assert stats["max_active_slots"] >= 2, (
                f"concurrent chats never shared a decode tick: {stats}"
            )
            # every per-request page returned to the pool after the burst —
            # except what the radix prefix cache retained (warmed template
            # head + the admitted prompts' full-page spans)
            held = stats.get("prefix_cache_pages", 0)
            assert stats["free_pages"] == stats["total_pages"] - 1 - held

            # the decode-engine stats must be PUBLISHED, not just collected:
            # prometheus gauges on /metrics, full dict on /metrics/performance
            prom = await (await client.get("/metrics")).text()
            assert 'sentio_tpu_serving_stat{stat="max_active_slots"}' in prom
            assert 'sentio_tpu_serving_stat{stat="free_pages"}' in prom
            assert 'sentio_tpu_serving_events_total{event="completed"}' in prom
            perf = await (await client.get("/metrics/performance")).json()
            assert perf["serving"]["completed"] >= len(questions)
            assert "avg_active_slots" in perf["serving"]

        run(with_client(settings, body))


class TestStreamingParity:
    """The SSE path must traverse the SAME graph semantics as /chat:
    select (dedup + token budget) before streaming, verify after
    (reference factory.py:191-208 — streaming uses identical stages)."""

    def test_stream_carries_sources_tokens_and_verdict(self):
        async def body(client, container):
            await seed(client, ["alpha document about streaming"])
            resp = await client.post(
                "/chat", json={"question": "what about streaming?", "stream": True}
            )
            assert resp.status == 200
            import json as _json

            events = []
            for line in (await resp.read()).decode().splitlines():
                if line.startswith("data:"):
                    data = line[5:].strip()
                    if data == "[DONE]":
                        events.append(("done", None))
                    else:
                        events.append(next(iter(_json.loads(data).items())))
            kinds = [k for k, _ in events]
            assert kinds[0] == "sources", kinds
            assert "token" in kinds
            assert "verdict" in kinds, "verifier must audit the streamed answer"
            assert kinds[-1] == "done"
            # verify comes after every token (post-stream audit)
            assert kinds.index("verdict") > max(
                i for i, k in enumerate(kinds) if k == "token"
            )

        settings = fast_settings()
        settings.generator.use_verifier = True
        run(with_client(settings, body))

    def test_stream_enforces_selector_budget(self):
        async def body(client, container):
            # many docs, tiny budget: selection must cap what streams
            await seed(client, [f"budget doc {i} " + "x" * 200 for i in range(8)])
            settings = container.settings
            settings.generator.context_token_budget = 60  # ~240 chars → 1 doc
            resp = await client.post(
                "/chat", json={"question": "budget doc", "top_k": 8, "stream": True}
            )
            import json as _json

            sources = None
            for line in (await resp.read()).decode().splitlines():
                if line.startswith("data:") and '"sources"' in line:
                    sources = _json.loads(line[5:].strip())["sources"]
                    break
            assert sources is not None, "stream must announce selected sources"
            assert 1 <= len(sources) <= 2, (
                f"token budget not enforced before streaming: {len(sources)} docs"
            )

        run(with_client(fast_settings(), body))


class TestPagedStreamingService:
    def test_generate_stream_matches_generate(self):
        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine
        from sentio_tpu.runtime.service import PagedGenerationService

        cfg = LlamaConfig.tiny()

        def build():
            return PagedGenerationService(ContinuousBatchingEngine(
                model_config=cfg, max_slots=2, page_size=16,
                max_pages_per_seq=8, steps_per_tick=4,
            ))

        svc_a, svc_b = build(), build()
        try:
            want = svc_a.generate("stream parity prompt", max_new_tokens=12,
                                  temperature=0.0)
            pieces = list(svc_b.generate_stream(
                "stream parity prompt", max_new_tokens=12, temperature=0.0
            ))
            assert "".join(pieces) == want.text
            # incremental: more than one chunk for a 12-token answer at
            # steps_per_tick=4 (unless the model EOS'd in the first tick)
            if len(want.tokens) > 4:
                assert len(pieces) >= 2
        finally:
            svc_a.close()
            svc_b.close()


class TestSseStreamResume:
    """ISSUE 14 satellite: session continuity on the SSE wire. A replica
    dying mid-stream under a 2-replica set must be INVISIBLE to the SSE
    client — the delivered prefix replays onto the survivor and the wire
    carries one gapless, duplicate-free token sequence. Only an exhausted
    resume budget still surfaces the typed mid-stream error event (the
    pre-resume wire format, unchanged)."""

    QUESTION = "what compiles python to xla programs?"

    @staticmethod
    def _container(settings):
        # meshless replicas, like every direct-engine replica test: the
        # conftest forces 8 virtual CPU devices, and the dp-split mesh
        # path shards each replica's pool onto a submesh while the shared
        # weights stay on the full mesh — a layout mismatch that predates
        # (and is orthogonal to) stream resumption
        return DependencyContainer(settings=settings, mesh=None)

    @staticmethod
    def _settings(**serve_over):
        return fast_settings(
            generator=GeneratorConfig(
                provider="tpu", model_preset="tiny", use_verifier=False,
                max_new_tokens=24, mode="fast",  # greedy: deterministic
                use_paged_decode=True, kv_page_size=16,
                kv_max_pages_per_seq=8, max_batch_size=4,
                # a 24-token answer must span several delivered chunks or
                # there is no "mid-stream" window to kill inside: an idle
                # queue runs the BIG tick (decode_max_tick_steps, default
                # 64), which would ship the whole answer in one harvest
                decode_steps_per_tick=4, decode_max_tick_steps=4,
            ),
            serve=ServeConfig(
                replicas=2,
                # no supervisor thread: the drill flips exactly one fault
                # and must not race an async rebuild (supervised recovery
                # is drilled in test_chaos)
                replica_supervise=False,
                **serve_over,
            ),
        )

    @staticmethod
    def _sse_events(raw: str) -> list:
        import json as _json

        events = []
        for line in raw.splitlines():
            if not line.startswith("data:"):
                continue
            payload = line[len("data:"):].strip()
            if payload == "[DONE]":
                events.append(("done", None))
                continue
            obj = _json.loads(payload)
            (kind, value), = obj.items()
            events.append((kind, value))
        return events

    def test_midstream_kill_is_invisible_on_the_wire(self):
        from sentio_tpu.infra import faults
        from sentio_tpu.infra.flight import get_flight_recorder

        async def body(client, container):
            await seed(client, ["jax compiles python functions to xla"])
            # reference: the same question, no fault — greedy decode makes
            # the answer deterministic, so the faulted run must match it
            resp = await client.post("/chat", json={
                "question": self.QUESTION, "stream": True,
                "temperature": 0.0})
            assert resp.status == 200
            reference = self._sse_events((await resp.read()).decode())
            want = "".join(v for k, v in reference if k == "token")
            assert want, reference
            # the serve engine pipelines dispatch (decode_pipeline_depth=2,
            # the production default): tick 1's tokens harvest — and
            # deliver — at tick 2, so the FIRST tick whose death finds a
            # delivered chunk is tick 3 (skip=2). The victim replica is
            # whichever pump is decoding this one stream, so no routing
            # determinism is needed; the resume replays onto the idle
            # sibling
            faults.arm("paged.step", faults.FaultRule(
                error=RuntimeError("sse drill: midstream death"),
                times=1, skip=2))
            try:
                resp = await client.post("/chat", json={
                    "question": self.QUESTION, "stream": True,
                    "temperature": 0.0})
                assert resp.status == 200
                raw = (await resp.read()).decode()
            finally:
                faults.reset()
            events = self._sse_events(raw)
            got = "".join(v for k, v in events if k == "token")
            # gapless, duplicate-free: byte-identical to the no-fault run
            assert got == want, (got, want)
            kinds = [k for k, _ in events]
            assert "error" not in kinds, events
            assert kinds[-1] == "done", events
            # the resume is visible to OPERATORS: stats, flight, /metrics
            stats = container.generation_service.stats()
            assert stats["stream_resumes"] == 1, stats["stream_resumes"]
            resumed = [t for t in get_flight_recorder().timeline()
                       if t.get("event") == "stream_resumed"]
            assert resumed and resumed[-1]["replayed_tokens"] >= 1
            prom = await (await client.get("/metrics")).text()
            assert 'sentio_tpu_stream_resumes_total{outcome="resumed"}' \
                in prom

        settings = self._settings()
        run(with_client(settings, body, container=self._container(settings)))

    def test_exhausted_budget_keeps_typed_error_wire_format(self):
        from sentio_tpu.infra import faults

        async def body(client, container):
            await seed(client, ["jax compiles python functions to xla"])
            # ticks 1+2 pass (pipelined dispatch: tick 1's tokens DELIVER
            # at tick 2), hit 3 kills the victim mid-stream, hit 4 kills
            # the RESUMED attempt on the survivor — the budget (1,
            # following the failover budget) is spent, so the client gets
            # the pre-resume contract: a typed mid-stream error event, then
            # [DONE]; no new event kinds, no prose after real tokens
            faults.arm("paged.step", faults.FaultRule(
                error=RuntimeError("sse drill: double death"),
                times=2, skip=2))
            try:
                resp = await client.post("/chat", json={
                    "question": self.QUESTION, "stream": True,
                    "temperature": 0.0})
                assert resp.status == 200  # mid-stream: the 200 is committed
                raw = (await resp.read()).decode()
            finally:
                faults.reset()
            events = self._sse_events(raw)
            kinds = [k for k, _ in events]
            assert kinds.count("error") == 1, events
            error = next(v for k, v in events if k == "error")
            assert error["code"], error
            assert "retryable" in error, error
            # tokens were delivered before the death; the error event ends
            # the stream (with [DONE]) instead of appending apology prose
            assert kinds.index("error") > kinds.index("token"), events
            assert kinds[-1] == "done", events
            assert set(kinds) <= {"sources", "token", "error", "done"}
            stats = container.generation_service.stats()
            assert stats["resume_exhausted"] == 1, stats
            prom = await (await client.get("/metrics")).text()
            assert 'sentio_tpu_stream_resumes_total{outcome="exhausted"}' \
                in prom

        settings = self._settings(crash_retry_budget=0)
        run(with_client(settings, body, container=self._container(settings)))

    def test_per_request_resumable_opt_out(self):
        """ISSUE 15 satellite: the env-only PR 14 opt-out becomes
        per-request — body ``resumable: false`` (and the ``X-Resumable``
        header) ride HTTP → handler → generator → ReplicaSet, so a
        mid-stream death under an opted-out stream keeps the typed
        mid-stream error event even though the resume budget was
        available; an opted-IN sibling request on the same set still
        resumes."""
        from sentio_tpu.infra import faults

        async def body(client, container):
            await seed(client, ["jax compiles python functions to xla"])

            async def faulted_stream(payload, headers=None):
                faults.arm("paged.step", faults.FaultRule(
                    error=RuntimeError("sse drill: opt-out death"),
                    times=1, skip=2))
                try:
                    resp = await client.post("/chat", json=payload,
                                             headers=headers or {})
                    assert resp.status == 200
                    return self._sse_events((await resp.read()).decode())
                finally:
                    faults.reset()

            # body-field opt-out: delivered tokens + typed error event
            events = await faulted_stream({
                "question": self.QUESTION, "stream": True,
                "temperature": 0.0, "resumable": False})
            kinds = [k for k, _ in events]
            assert kinds.count("error") == 1, events
            assert kinds.index("error") > kinds.index("token"), events
            assert kinds[-1] == "done", events
            # header opt-out: same typed wire contract
            events = await faulted_stream(
                {"question": self.QUESTION, "stream": True,
                 "temperature": 0.0},
                headers={"X-Resumable": "0"})
            assert [k for k, _ in events].count("error") == 1, events
            stats = container.generation_service.stats()
            # the opt-out is per-request, not a latched mode: nothing was
            # resumed (test_midstream_kill_is_invisible_on_the_wire pins
            # that a default request on this same config DOES resume)
            assert stats["stream_resumes"] == 0, stats
            prom = await (await client.get("/metrics")).text()
            assert 'sentio_tpu_stream_resumes_total{outcome="opt_out"}' \
                in prom

        settings = self._settings()
        run(with_client(settings, body, container=self._container(settings)))

    def test_resumable_field_validation(self):
        async def body(client, container):
            resp = await client.post("/chat", json={
                "question": "any", "stream": True, "resumable": "nope"})
            assert resp.status == 422
            data = await resp.json()
            assert any(e["field"] == "resumable" for e in data["details"])

        run(with_client(fast_settings(), body))


class TestOverloadMapping:
    """Typed shed/deadline errors → HTTP 429/503/504 + Retry-After — the
    overload story's wire contract (ServiceOverloaded must NEVER be eaten
    by the degradation ladder into a 200 apology)."""

    def test_shed_maps_to_429_with_retry_after(self):
        from sentio_tpu.infra.exceptions import ServiceOverloaded

        class SheddingGraph:
            def invoke(self, *a, **k):
                raise ServiceOverloaded(
                    "decode queue full", status=429, retry_after_s=7.0)

        async def body(client, container):
            container.override("graph", SheddingGraph())
            resp = await client.post("/chat", json={"question": "any"})
            assert resp.status == 429
            assert resp.headers.get("Retry-After") == "7"
            data = await resp.json()
            assert data["error"]["code"] == "OVERLOADED"
            assert data["error"]["retryable"] is True

        run(with_client(fast_settings(), body))

    def test_draining_maps_to_503(self):
        from sentio_tpu.infra.exceptions import ServiceOverloaded

        class DrainingGraph:
            def invoke(self, *a, **k):
                raise ServiceOverloaded("service is draining", status=503,
                                        retry_after_s=5.0)

        async def body(client, container):
            container.override("graph", DrainingGraph())
            resp = await client.post("/chat", json={"question": "any"})
            assert resp.status == 503
            assert resp.headers.get("Retry-After") == "5"

        run(with_client(fast_settings(), body))

    def test_deadline_exceeded_maps_to_504(self):
        from sentio_tpu.infra.exceptions import DeadlineExceededError

        class ExpiredGraph:
            def invoke(self, *a, **k):
                raise DeadlineExceededError("deadline expired mid-decode")

        async def body(client, container):
            container.override("graph", ExpiredGraph())
            resp = await client.post("/chat", json={"question": "any"})
            assert resp.status == 504
            data = await resp.json()
            assert data["error"]["code"] == "DEADLINE_EXCEEDED"

        run(with_client(fast_settings(), body))

    def test_replica_unavailable_maps_to_503_with_retry_after(self):
        """A broken/closed decode replica surfaces as a typed 503 +
        Retry-After (ReplicaUnavailable) instead of the old untyped
        RuntimeError → opaque 500 — the supervisor rebuilds replicas in
        place, so 'come back shortly' is the honest wire answer."""
        from sentio_tpu.infra.exceptions import ReplicaUnavailable

        class BrokenReplicaGraph:
            def invoke(self, *a, **k):
                raise ReplicaUnavailable(
                    "paged decode engine is down (reset failed; awaiting "
                    "supervised rebuild)", retry_after_s=4.0)

        async def body(client, container):
            container.override("graph", BrokenReplicaGraph())
            resp = await client.post("/chat", json={"question": "any"})
            assert resp.status == 503
            assert resp.headers.get("Retry-After") == "4"
            data = await resp.json()
            assert data["error"]["code"] == "SERVICE_UNAVAILABLE"
            assert data["error"]["retryable"] is True
            # NOT a degraded 200 apology: the ladder is bypassed
            assert "answer" not in data

        run(with_client(fast_settings(), body))

    def test_stream_precheck_sheds_replica_unavailable_before_sse(self):
        """The SSE pre-check path: every replica down → typed 503 before
        the 200 status line commits (previously the untyped RuntimeError
        was swallowed and the stream limped into the degraded ladder)."""
        from sentio_tpu.infra.exceptions import ReplicaUnavailable

        class DownSet:
            supports_tenants = True

            def check_admission(self, deadline_ts=None, tenant=None,
                                priority=None, prompt=None):
                raise ReplicaUnavailable(
                    "no serving replica available", retry_after_s=2.0)

        async def body(client, container):
            container.override("generation_service", DownSet())
            resp = await client.post(
                "/chat", json={"question": "stream me", "stream": True})
            assert resp.status == 503
            assert resp.headers.get("Retry-After") == "2"

        run(with_client(fast_settings(), body))

    def test_ladder_still_catches_plain_failures(self):
        """Regression guard: ONLY typed shed errors skip the ladder — a
        plain pipeline crash still degrades to 200."""

        class Boom:
            def invoke(self, *a, **k):
                raise RuntimeError("device on fire")

        async def body(client, container):
            container.override("graph", Boom())
            resp = await client.post("/chat", json={"question": "any"})
            assert resp.status == 200
            assert (await resp.json())["metadata"]["degraded"] is True

        run(with_client(fast_settings(), body))

    def test_stream_precheck_sheds_before_sse(self):
        """stream=True is shed with a REAL 429 before the SSE 200 status
        line commits (after prepare the only option is degrading)."""
        from sentio_tpu.infra.exceptions import ServiceOverloaded

        class FakeService:
            def check_admission(self, deadline_ts=None):
                raise ServiceOverloaded("decode queue full", status=429,
                                        retry_after_s=3.0)

        async def body(client, container):
            container.override("generation_service", FakeService())
            resp = await client.post(
                "/chat", json={"question": "stream me", "stream": True})
            assert resp.status == 429
            assert resp.headers.get("Retry-After") == "3"

        run(with_client(fast_settings(), body))

    def test_deadline_ms_validation(self):
        async def body(client, container):
            for bad in (0, -5, "fast", True, 3_600_001):
                resp = await client.post(
                    "/chat", json={"question": "ok", "deadline_ms": bad})
                assert resp.status == 422, bad
                data = await resp.json()
                assert any(e["field"] == "deadline_ms" for e in data["details"])

        run(with_client(fast_settings(), body))

    def test_deadline_header_rides_metadata_to_flight_record(self):
        """X-Deadline-Ms lands in the flight record and in state.metadata
        (the echo provider ignores it, so the request still succeeds)."""
        from sentio_tpu.infra.flight import get_flight_recorder

        async def body(client, container):
            await seed(client, ["deadline plumbing document"])
            resp = await client.post(
                "/chat",
                json={"question": "deadline plumbing?", "thread_id": "dl-test"},
                headers={"X-Deadline-Ms": "30000"},
            )
            assert resp.status == 200
            record = get_flight_recorder().get("dl-test")
            assert record is not None
            assert 0 < record["deadline_ms"] <= 30000

        run(with_client(fast_settings(), body))


class TestUpload:
    """Multipart binary-document ingest (/upload) — the browser file path
    the reference serves via Streamlit (streamlit_app.py:27-318 there)."""

    @staticmethod
    def make_docx(tmp_path, text="uploaded docx speaks of pallas kernels"):
        import zipfile

        path = tmp_path / "doc.docx"
        xml = (
            '<?xml version="1.0"?><w:document><w:body>'
            f"<w:p><w:r><w:t>{text}</w:t></w:r></w:p>"
            "</w:body></w:document>"
        )
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("word/document.xml", xml)
        return path

    def test_docx_roundtrip(self, tmp_path):
        import aiohttp

        path = self.make_docx(tmp_path)

        async def body(client, container):
            form = aiohttp.FormData()
            form.add_field("file", path.read_bytes(), filename="doc.docx",
                           content_type="application/octet-stream")
            resp = await client.post("/upload", data=form)
            assert resp.status == 200, await resp.text()
            data = await resp.json()
            [entry] = data["files"]
            assert entry["filename"] == "doc.docx"
            assert entry["chunks_embedded"] >= 1 and "error" not in entry
            # the uploaded content is immediately retrievable
            resp = await client.post("/chat", json={"question": "what speaks of pallas?"})
            chat = await resp.json()
            assert any("doc.docx" in str(s.get("metadata", {}).get("source", ""))
                       for s in chat["sources"])

        run(with_client(fast_settings(), body))

    def test_text_file_via_upload(self, tmp_path):
        import aiohttp

        async def body(client, container):
            form = aiohttp.FormData()
            form.add_field("file", b"plain text about ring attention",
                           filename="notes.txt")
            resp = await client.post("/upload", data=form)
            assert resp.status == 200
            [entry] = (await resp.json())["files"]
            assert entry["chunks_embedded"] >= 1

        run(with_client(fast_settings(), body))

    def test_unsupported_suffix_and_bad_docx(self, tmp_path):
        import aiohttp

        async def body(client, container):
            form = aiohttp.FormData()
            form.add_field("file", b"\x7fELF", filename="a.exe")
            form.add_field("file", b"not a zip", filename="broken.docx")
            resp = await client.post("/upload", data=form)
            assert resp.status == 422  # every file failed
            data = await resp.json()
            errors = {f["filename"]: f.get("error", "") for f in data["files"]}
            assert "unsupported" in errors["a.exe"]
            assert errors["broken.docx"]

        run(with_client(fast_settings(), body))

    def test_non_multipart_rejected(self):
        async def body(client, container):
            resp = await client.post("/upload", json={"file": "nope"})
            assert resp.status == 422

        run(with_client(fast_settings(), body))

    def test_request_cap_returns_413(self):
        import aiohttp

        from sentio_tpu.config import ServeConfig

        async def body(client, container):
            form = aiohttp.FormData()
            form.add_field("file", b"x" * 4096, filename="big.txt")
            resp = await client.post("/upload", data=form)
            assert resp.status == 413
            data = await resp.json()
            assert "cap" in data["files"][-1]["error"]

        run(with_client(fast_settings(serve=ServeConfig(max_upload_mb=0)), body))

    def test_skipped_part_bytes_count_toward_cap(self):
        import aiohttp

        from sentio_tpu.config import ServeConfig

        async def body(client, container):
            form = aiohttp.FormData()
            # unsupported type would be skipped — its bytes must still trip
            # the request cap rather than streaming through uncounted
            form.add_field("file", b"y" * 4096, filename="huge.exe")
            resp = await client.post("/upload", data=form)
            assert resp.status == 413

        run(with_client(fast_settings(serve=ServeConfig(max_upload_mb=0)), body))
