import asyncio

import numpy as np
import pytest

from sentio_tpu.config import EmbedderConfig, RetrievalConfig, Settings
from sentio_tpu.models.document import Document
from sentio_tpu.ops.bm25 import BM25Index
from sentio_tpu.ops.dense_index import TpuDenseIndex
from sentio_tpu.ops.embedder import HashEmbedder
from sentio_tpu.ops.reranker import (
    CrossEncoderReranker,
    PassthroughReranker,
    Reranker,
    get_reranker,
)
from sentio_tpu.ops.retrievers import (
    DenseRetriever,
    HybridRetriever,
    RetrieverError,
    SparseRetriever,
    create_retriever,
)
from sentio_tpu.ops.scorers import (
    KeywordMatchScorer,
    MMRScorer,
    RecencyScorer,
    SemanticSimilarityScorer,
)


@pytest.fixture()
def stack(docs):
    emb = HashEmbedder(EmbedderConfig(provider="hash", dim=64))
    dense = TpuDenseIndex(dim=64, dtype="float32")
    dense.add(docs, emb.embed_many([d.text for d in docs]))
    sparse = BM25Index().build(docs)
    return emb, dense, sparse


class TestLegs:
    def test_dense_retriever(self, stack, docs):
        emb, dense, _ = stack
        r = DenseRetriever(embedder=emb, index=dense)
        out = r.retrieve(docs[1].text, top_k=3)
        assert out[0].id == "d2"  # identical text embeds identically

    def test_sparse_retriever(self, stack):
        _, _, sparse = stack
        r = SparseRetriever(index=sparse)
        out = r.retrieve("systolic array", top_k=3)
        assert out and out[0].id == "d2"

    def test_async_wrapper(self, stack):
        _, _, sparse = stack
        r = SparseRetriever(index=sparse)
        out = asyncio.run(r.aretrieve("fox dog", top_k=2))
        assert len(out) == 2


class TestHybrid:
    def test_fuses_both_legs(self, stack):
        emb, dense, sparse = stack
        hybrid = HybridRetriever(
            retrievers=[DenseRetriever(emb, dense), SparseRetriever(sparse)],
            config=RetrievalConfig(fusion_method="rrf"),
        )
        out = hybrid.retrieve("quick brown fox", top_k=4)
        assert out
        assert all("hybrid_score" in d.metadata for d in out)
        ids = [d.id for d in out]
        assert len(ids) == len(set(ids))  # dedup across legs

    def test_failed_leg_degrades(self, stack):
        class BrokenRetriever(DenseRetriever):
            def retrieve(self, query, top_k=10):
                raise RuntimeError("device gone")

        emb, dense, sparse = stack
        hybrid = HybridRetriever(
            retrievers=[BrokenRetriever(emb, dense), SparseRetriever(sparse)],
            config=RetrievalConfig(),
        )
        out = hybrid.retrieve("fox", top_k=3)
        assert out  # sparse leg alone still answers

    def test_all_legs_failed_raises(self, stack):
        class Broken(SparseRetriever):
            def retrieve(self, query, top_k=10):
                raise RuntimeError("nope")

        _, _, sparse = stack
        hybrid = HybridRetriever(retrievers=[Broken(sparse)], config=RetrievalConfig())
        with pytest.raises(RetrieverError):
            hybrid.retrieve("q")

    def test_scorer_plugins_apply(self, stack, docs):
        emb, dense, sparse = stack
        hybrid = HybridRetriever(
            retrievers=[SparseRetriever(sparse)],
            config=RetrievalConfig(),
            scorers=[KeywordMatchScorer(weight=2.0)],
        )
        out = hybrid.retrieve("systolic array matrix", top_k=3)
        assert out[0].id == "d2"

    def test_broken_scorer_ignored(self, stack):
        class BadScorer:
            name, weight = "bad", 1.0

            def score(self, query, documents):
                raise ValueError("boom")

        _, _, sparse = stack
        hybrid = HybridRetriever(
            retrievers=[SparseRetriever(sparse)],
            config=RetrievalConfig(),
            scorers=[BadScorer()],
        )
        assert hybrid.retrieve("fox", top_k=2)


class TestFactory:
    def test_strategies(self, stack):
        emb, dense, sparse = stack
        s = Settings()
        s.retrieval.strategy = "dense"
        assert isinstance(create_retriever(s, emb, dense, sparse), DenseRetriever)
        s.retrieval.strategy = "bm25"
        assert isinstance(create_retriever(s, emb, dense, sparse), SparseRetriever)
        s.retrieval.strategy = "hybrid"
        r = create_retriever(s, emb, dense, sparse)
        assert isinstance(r, HybridRetriever) and len(r.retrievers) == 2

    def test_missing_components_raise(self, stack):
        _, _, sparse = stack
        s = Settings()
        s.retrieval.strategy = "dense"
        with pytest.raises(RetrieverError):
            create_retriever(s, None, None, sparse)
        s.retrieval.strategy = "weird"
        with pytest.raises(RetrieverError):
            create_retriever(s, None, None, sparse)


class TestScorers:
    def test_keyword_overlap(self, docs):
        s = KeywordMatchScorer()
        scores = s.score("quick brown fox", docs)
        assert scores[0] == 1.0  # d1 contains all three
        assert scores[1] == 0.0  # d2 contains none

    def test_recency_decay(self):
        import time

        now = time.time()
        docs = [
            Document(text="new", metadata={"timestamp": now}),
            Document(text="old", metadata={"timestamp": now - 365 * 86400}),
            Document(text="unknown"),
        ]
        s = RecencyScorer(half_life_days=30)
        scores = s.score("q", docs)
        assert scores[0] > 0.99
        assert scores[1] < 0.01
        assert scores[2] == 0.5

    def test_semantic_uses_one_batch(self, docs):
        calls = []

        class CountingEmbedder(HashEmbedder):
            def embed_many(self, texts):
                calls.append(len(texts))
                return super().embed_many(texts)

        emb = CountingEmbedder(EmbedderConfig(provider="hash", dim=64))
        s = SemanticSimilarityScorer(embedder=emb)
        scores = s.score("the quick brown fox", docs)
        assert calls == [len(docs) + 1]  # one batched call, not N+1
        assert scores.shape == (len(docs),)
        assert (scores >= 0).all() and (scores <= 1).all()

    def test_mmr_penalizes_duplicates(self):
        emb = HashEmbedder(EmbedderConfig(provider="hash", dim=128))
        docs = [
            Document(text="the quick brown fox jumps over dogs", id="dup1"),
            Document(text="the quick brown fox jumps over dogs", id="dup2"),
            Document(text="the quick brown turtle swims in rivers", id="other"),
        ]
        s = MMRScorer(embedder=emb, lambda_param=0.5)
        scores = s.score("the quick brown fox", docs)
        # a duplicate wins on relevance, but its twin (redundancy 1.0) is
        # pushed below the diverse doc by a clear margin
        assert scores[2] > min(scores[0], scores[1])

    def test_hash_embedder_cross_process_deterministic(self):
        import subprocess
        import sys

        code = (
            "import sys; sys.path.insert(0, '/root/repo');"
            "from sentio_tpu.config import EmbedderConfig;"
            "from sentio_tpu.ops.embedder import HashEmbedder;"
            "v = HashEmbedder(EmbedderConfig(provider='hash', dim=16)).embed('a b c');"
            "print(','.join(f'{x:.8f}' for x in v))"
        )
        outs = {
            subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True,
                env={"PYTHONHASHSEED": seed, "PATH": "/usr/bin:/bin",
                     "JAX_PLATFORMS": "cpu"},
            ).stdout.strip()
            for seed in ("0", "1", "31337")
        }
        assert len(outs) == 1 and "" not in outs


class TestRerankers:
    def test_passthrough_keeps_order(self, docs):
        r = PassthroughReranker()
        result = r.rerank("q", docs[:4], top_k=3)
        assert [d.id for d in result.documents] == [d.id for d in docs[:3]]
        assert not result.fallback_used

    def test_cross_encoder_scores_and_orders(self, docs):
        r = CrossEncoderReranker()
        result = r.rerank("systolic array", docs[:5], top_k=3)
        assert len(result.documents) == 3
        assert result.scores == sorted(result.scores, reverse=True)
        assert all("rerank_score" in d.metadata for d in result.documents)

    def test_failure_falls_back_to_original_order(self, docs):
        class BrokenReranker(Reranker):
            name = "broken"

            def _score(self, query, documents):
                raise RuntimeError("device OOM")

        result = BrokenReranker().rerank("q", docs[:4], top_k=4)
        assert result.fallback_used
        assert [d.id for d in result.documents] == [d.id for d in docs[:4]]
        np.testing.assert_allclose(result.scores, [1.0, 0.9, 0.8, 0.7])

    def test_empty_docs(self):
        assert PassthroughReranker().rerank("q", []).documents == []

    def test_registry(self):
        assert isinstance(get_reranker("passthrough"), PassthroughReranker)
        with pytest.raises(ValueError):
            get_reranker("bogus")

    def test_async(self, docs):
        result = asyncio.run(PassthroughReranker().arerank("q", docs[:2]))
        assert len(result.documents) == 2


def test_rerank_overrides_stale_hybrid_score(docs):
    """Reranked docs must sort by rerank order downstream — a leftover
    hybrid_score would win in Document.score() and undo the rerank."""
    from sentio_tpu.models.document import Document as D

    scored = [
        D(text=d.text, id=d.id, metadata={**d.metadata, "hybrid_score": 1.0 - 0.1 * i})
        for i, d in enumerate(docs[:4])
    ]

    class ReverseReranker(Reranker):
        name = "reverse"

        def _score(self, query, documents):
            return np.arange(len(documents), dtype=np.float32)  # reverse order

    result = ReverseReranker().rerank("q", scored, top_k=4)
    assert [d.id for d in result.documents] == [d.id for d in reversed(scored)]
    resorted = sorted(result.documents, key=lambda d: d.score(), reverse=True)
    assert [d.id for d in resorted] == [d.id for d in result.documents]


def test_semantic_and_mmr_share_one_embed(docs):
    calls = []

    class CountingEmbedder(HashEmbedder):
        def embed_many(self, texts):
            calls.append(len(texts))
            return super().embed_many(texts)

    emb = CountingEmbedder(EmbedderConfig(provider="hash", dim=64))
    sem = SemanticSimilarityScorer(embedder=emb)
    mmr = MMRScorer(embedder=emb)
    sem.score("shared query", docs)
    mmr.score("shared query", docs)
    assert calls == [len(docs) + 1]  # second scorer reused the memoized batch


class TestWebCachePreHit:
    """Reference hybrid.py:96-107,146-182: a cached web-results collection is
    consulted before fusing, its hits prepended to the dense leg."""

    def _stack(self, docs):
        from sentio_tpu.config import EmbedderConfig, RetrievalConfig
        from sentio_tpu.models.document import Document
        from sentio_tpu.ops.bm25 import BM25Index
        from sentio_tpu.ops.dense_index import TpuDenseIndex
        from sentio_tpu.ops.embedder import get_embedder
        from sentio_tpu.ops.retrievers import (
            DenseRetriever, HybridRetriever, SparseRetriever,
        )

        embedder = get_embedder(EmbedderConfig(provider="hash", dim=32))
        index = TpuDenseIndex(dim=32)
        index.add(docs, embedder.embed_many([d.text for d in docs]))
        cache_doc = Document(
            text="cached web result about the quick brown fox jumping",
            id="web-1", metadata={"source": "web"},
        )
        cache_index = TpuDenseIndex(dim=32)
        cache_index.add([cache_doc], embedder.embed_many([cache_doc.text]))
        hybrid = HybridRetriever(
            retrievers=[
                DenseRetriever(embedder, index),
                SparseRetriever(BM25Index().build(docs)),
            ],
            config=RetrievalConfig(),
            web_cache=DenseRetriever(embedder, cache_index, name="web_cache"),
        )
        return hybrid

    def test_cache_hits_outrank_fresh_dense(self, docs):
        hybrid = self._stack(docs)
        out = hybrid.retrieve("quick brown fox", top_k=5)
        assert any(d.id == "web-1" for d in out), "cache hit must surface"
        # without the cache leg the web doc cannot appear at all — the
        # pre-hit is what injects it at dense rank 0 (docs both legs agree
        # on may still outrank it, same as the reference's fusion)
        hybrid.web_cache = None
        out_plain = hybrid.retrieve("quick brown fox", top_k=5)
        assert not any(d.id == "web-1" for d in out_plain)

    def test_cache_leg_failure_degrades(self, docs):
        class Boom:
            name = "web_cache"

            async def aretrieve(self, q, top_k=10):
                raise RuntimeError("cache store down")

        hybrid = self._stack(docs)
        hybrid.web_cache = Boom()
        out = hybrid.retrieve("quick brown fox", top_k=5)
        assert out, "hybrid must keep serving when the cache leg dies"

    def test_factory_wires_web_cache_index(self, settings, docs):
        from sentio_tpu.config import EmbedderConfig
        from sentio_tpu.ops.bm25 import BM25Index
        from sentio_tpu.ops.dense_index import TpuDenseIndex
        from sentio_tpu.ops.embedder import get_embedder
        from sentio_tpu.ops.retrievers import create_retriever

        embedder = get_embedder(EmbedderConfig(provider="hash", dim=32))
        index = TpuDenseIndex(dim=32)
        index.add(docs, embedder.embed_many([d.text for d in docs]))
        cache_index = TpuDenseIndex(dim=32)
        retriever = create_retriever(
            settings=settings, embedder=embedder, dense_index=index,
            bm25_index=BM25Index().build(docs), web_cache_index=cache_index,
        )
        assert retriever.web_cache is not None
        assert retriever.web_cache.name == "web_cache"
