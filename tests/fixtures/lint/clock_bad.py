"""Seeded wall-clock violation — analyzer test fixture, never imported."""
import time


def elapsed(t0):
    return time.time() - t0  # VIOLATION wall-clock-duration


def stamp():
    return time.time()  # wall-clock: persisted timestamp — allowed
