"""Seeded violation: ReplicaSet-style routing state mutated lock-free.

Cross-replica routing counters are touched by every submitter thread, so
the guarded-by contract matters here exactly as much as in the service —
this fixture is the router-shaped regression the lock checker must catch.
"""
import threading


class BadReplicaRouter:
    def __init__(self):
        self._lock = threading.Lock()
        self._routed = 0  # guarded-by: _lock

    def route(self):
        self._routed += 1
        return self._routed
