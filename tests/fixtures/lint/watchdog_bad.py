"""Seeded violations: unbounded blocking calls a watchdog cannot see past.

A thread wedged inside a zero-argument ``.join()``/``.wait()``/``.get()``
raises nothing — the hang fault class the pump watchdog exists to detect.
The framework's own supervisor threads must never block that way: this
fixture is the regression the blocking checker must catch (one unbounded
join anywhere, plus an unbounded wait and an unbounded queue get inside a
supervisor-named loop).
"""
import queue
import threading


class BadWatchdog:
    def __init__(self):
        self._thread = threading.Thread(
            target=lambda: None, name="replica-supervisor"
        )
        self._work = queue.Queue()
        self._wake = threading.Event()

    def shutdown(self):
        self._thread.join()  # blocks forever on a wedged thread

    def _supervise_loop(self):
        while True:
            self._wake.wait()  # the detection loop itself can wedge here
            item = self._work.get()  # and here
            if item is None:
                return

    def bounded_ok(self):
        # timeouts pass; str.join / dict.get style calls with args pass
        self._thread.join(timeout=5.0)
        self._wake.wait(0.5)
        return ",".join(["a", "b"]) + str({}.get("k"))
