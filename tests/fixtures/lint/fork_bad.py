"""Seeded no-fork violations (analysis/forkcheck.py): every fork-flavored
process creation the rule must catch — JAX-after-fork deadlocks. Each
numbered line below is pinned by tests/test_lint.py."""

import multiprocessing
import os
from multiprocessing import Pool
from os import fork


def direct_syscalls():
    pid = os.fork()  # line 12: os.fork attribute call
    if pid == 0:
        fork()  # line 14: from-imported bare fork


def fork_contexts():
    ctx = multiprocessing.get_context("fork")  # line 18: fork context
    multiprocessing.set_start_method("forkserver")  # line 19: forkserver
    return ctx


def default_method_workers(ctx):
    p = multiprocessing.Process(target=print)  # line 24: default = fork
    q = ctx.Pool(2)  # line 25: unvetted context worker pool
    return p, q


def clean_forms():
    # none of these fire: spawn context, annotated vetted site, unrelated
    # string args and attribute names
    ctx = multiprocessing.get_context("spawn")
    p = ctx.Process(target=print)  # lint: allow(no-fork) — spawn context
    {"fork": 1}.get("fork")
    return ctx, p
