"""Seeded retrace violations — analyzer test fixture, never imported."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("steps",))
def decode(tok, steps):
    out = tok
    for _ in range(steps):
        out = out + 1
    return out


def drive(prompts, tok):
    n = len(prompts)
    return decode(tok, steps=n)  # VIOLATION retrace-unbounded-static


@jax.jit
def branchy(x):
    if x.sum() > 0:  # VIOLATION retrace-traced-branch
        return x
    return -x


@jax.jit
def casty(x):
    return int(x)  # VIOLATION retrace-traced-cast


class Host:
    def __init__(self):
        self.scale = 2.0

    def build(self):
        @jax.jit
        def f(x):
            return x * self.scale  # VIOLATION retrace-host-state

        return f
