"""Seeded violations for the phase-timer-under-lock rule: phase regions
entered while an annotated lock is held fold lock wait/hold time into the
open phase. The correctly-ordered method (timer outside, lock inside) and
the lock-free region must produce nothing."""

import threading


class BadPump:
    def __init__(self):
        self._mutex = threading.Lock()
        self._timer = object()
        self._pending = []  # guarded-by: _mutex

    def bad_nested(self):
        with self._mutex:
            with self._timer.phase("inbox_drain"):  # finding: lock held
                self._pending.clear()

    def bad_combined(self):
        # items evaluate left to right: the lock is held when the phase
        # region opens
        with self._mutex, self._timer.phase("deliver"):  # finding
            self._pending.clear()

    def _sweep_locked(self):
        # `_locked` suffix: the caller holds the lock by contract
        with self._timer.phase("other"):  # finding
            self._pending.clear()

    def good_order(self):
        # timer OUTSIDE the lock: the mutex wait is honestly part of the
        # phase being measured
        with self._timer.phase("inbox_drain"):
            with self._mutex:
                self._pending.clear()

    def good_unlocked(self):
        with self._timer.phase("decode_dispatch"):
            return len([])
