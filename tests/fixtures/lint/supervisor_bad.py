"""Seeded violation: replica-supervisor health state mutated lock-free.

The ReplicaSet supervisor flips per-replica health states from its own
thread while the router reads them on every submitter thread — a
lock-free transition would let the router keep routing into a replica
mid-quarantine. This fixture is the supervisor-shaped regression the
lock checker must catch.
"""
import threading


class BadSupervisor:
    def __init__(self):
        self._mutex = threading.Lock()
        self._health = ["HEALTHY"]  # guarded-by: _mutex

    def quarantine(self, idx):
        self._health[idx] = "QUARANTINED"
        return self._health[idx]
