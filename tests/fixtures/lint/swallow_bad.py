"""Seeded BaseException swallow — analyzer test fixture, never imported."""


def guard(fn):
    try:
        return fn()
    except BaseException:  # VIOLATION baseexception-swallow
        return None


def cleanup(fn):
    try:
        return fn()
    except BaseException:
        raise  # re-raises: no finding
