"""Seeded lock-discipline violation — analyzer test fixture, never imported."""
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def drain(self):
        out = list(self._items)  # VIOLATION lock-discipline
        with self._lock:
            self._items.clear()
        return out

    def _compact_locked(self):
        # name convention: caller holds the lock — no finding
        self._items.sort()

    def peek(self):  # lock-held: _lock
        return list(self._items)  # caller-holds marker — no finding
