"""Seeded socket-no-timeout violations (tests/test_lint.py pins the exact
findings): raw sockets and recv loops with no deadline wiring — the
unbounded network blocking the vetted transport module exists to prevent.
Line numbers matter to the test; edit with care."""

import socket
from socket import create_connection


def leaky_listener():  # no settimeout anywhere in this scope
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # FINDING: bare socket
    s.bind(("0.0.0.0", 9000))
    return s


def leaky_dial(host):
    return create_connection((host, 9000))  # FINDING: no timeout=


def leaky_reader(sock):
    chunks = []
    while True:
        data = sock.recv(4096)  # FINDING: zero-timeout recv loop
        if not data:
            break
        chunks.append(data)
    return b"".join(chunks)


def wired_listener():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # clean: wired below
    s.settimeout(0.2)
    return s


def wired_dial(host):
    return create_connection((host, 9000), timeout=5.0)  # clean: bounded


def wired_reader(conn):
    conn.settimeout(0.5)
    while True:
        if not conn.recv(4096):  # clean: scope wires a deadline
            return


def not_a_socket(transport):
    while True:
        frame = transport.recv()  # clean: not socket-shaped (transport owns deadlines)
        if frame is None:
            return
