"""Seeded lock-order inversions — analyzer test fixture, never imported."""
import threading


class Router:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()
        self._d = threading.Lock()
        self._e = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:  # VIOLATION lock-order-inversion (a->b, cycle with reverse)
                pass

    def reverse(self):
        with self._b:
            with self._a:  # VIOLATION lock-order-inversion (b->a closes the cycle)
                pass

    def fan_in(self):
        with self._c:
            self._grab_a()  # VIOLATION lock-order-inversion (call edge c->a)

    def _grab_a(self):
        with self._a:
            self._touch_c()  # VIOLATION lock-order-inversion (call edge a->c)

    def _touch_c(self):
        with self._c:
            pass

    def relock(self):
        with self._a:
            with self._a:  # VIOLATION lock-order-inversion (self-deadlock)
                pass

    def consistent(self):
        # one global order, never reversed: produces edges but no finding
        with self._d:
            with self._e:
                pass
