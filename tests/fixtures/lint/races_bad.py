"""Seeded thread-role / cross-thread-race violations — analyzer test
fixture, never imported."""
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.depth = 0
        self.safe = 0  # guarded-by: _lock
        self.ticks = 0  # guarded-by: engine-thread

    def start(self):
        threading.Thread(
            target=self._pump_loop, name="paged-decode-pump"
        ).start()
        threading.Thread(
            target=self._scrape_loop, name="worker-telemetry"
        ).start()
        threading.Thread(target=self._orphan_loop).start()  # VIOLATION thread-role
        threading.Thread(
            target=self._orphan_loop, name="mystery-helper"  # VIOLATION thread-role
        ).start()

    def _pump_loop(self):
        self.depth += 1  # VIOLATION cross-thread-race (anchor: first write)
        self.ticks += 1  # owner role writing its own state: no finding
        with self._lock:
            self.safe += 1  # annotated: the lock checker owns this attr

    def _scrape_loop(self):
        self.depth -= 1
        self.ticks += 1  # VIOLATION cross-thread-race (foreign role)
        with self._lock:
            self.safe -= 1

    def _orphan_loop(self):
        pass
