"""Seeded failure-surface violations — analyzer test fixture, never
imported. One violation per rule: an untyped raise reaching a serving
boundary, a typed catch re-raised untyped, a silent broad swallow, a
codec-incompatible SentioError subclass, and a frame kind emitted on both
transports but dispatched on only one."""
import threading

_K_DATA = "data"
_K_EXTRA = "extra"


class SentioError(Exception):
    def __init__(self, message, details=None):
        super().__init__(message)
        self.details = details or {}


class BadWireError(SentioError):  # VIOLATION codec-roundtrip
    def __init__(self, message, slot):
        super().__init__(message)
        self.slot = slot


def _risky():
    raise ValueError("boom")  # VIOLATION untyped-boundary-escape


class Pump:
    def start(self):
        threading.Thread(
            target=self._pump_loop, name="paged-decode-pump"
        ).start()

    def _pump_loop(self):
        _risky()

    def rethrow(self):
        try:
            _risky()
        except SentioError as exc:
            raise RuntimeError(str(exc))  # VIOLATION typed-error-untyped-rethrow

    def swallow(self):
        try:
            _risky()
        except Exception:  # VIOLATION broad-except-swallow
            pass


class Wire:
    def send(self, frame):
        del frame


# frame-emit: fixture-wire via=pipe,socket
def emit_frames(wire):
    wire.send((0, _K_DATA, {}))
    wire.send((0, _K_EXTRA, {}))  # VIOLATION frame-kind-unhandled (socket side)


# frame-dispatch: fixture-wire via=pipe
def receive_pipe(frame):
    _req, kind, _payload = frame
    if kind == _K_DATA:
        return "data"
    if kind == _K_EXTRA:
        return "extra"
    return ""


# frame-dispatch: fixture-wire via=socket
def receive_socket(frame):
    _req, kind, _payload = frame
    if kind == _K_DATA:
        return "data"
    return ""
