"""Clean module — the analyzer must report nothing here."""
import threading
import time
from functools import partial

import jax

BUCKETS = (8, 16, 32)


def bucket_size(n, buckets):
    for b in sorted(buckets):
        if n <= b:
            return b
    return n


@partial(jax.jit, static_argnames=("steps",))
def run(x, steps):
    return x + steps


def drive(x, prompts):
    # bounded: routed through the bucketing helper
    return run(x, steps=bucket_size(len(prompts), BUCKETS))


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self._n += 1

    def snapshot(self):
        with self._lock:
            return self._n


def measure(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
