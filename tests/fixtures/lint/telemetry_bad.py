"""Seeded telemetry-unbounded-labels violations (tests/test_lint.py pins
the exact findings): record_*/merge call sites whose label values derive
from request-scoped identifiers — the per-traffic series-cardinality
explosion the bounded-label discipline exists to prevent. Line numbers
matter to the test; edit with care."""


def leaky_tenant_counter(metrics, tenant):
    metrics.record_shed(tenant)  # FINDING: tenant name as a label value


def leaky_request_gauge(metrics, state):
    rid = state["request_id"]
    metrics.set_replica_stat(0, rid, 1.0)  # FINDING: request id key


def leaky_merge(metrics, payload):
    metrics.merge_worker_series(  # FINDING: prompt-derived series dict
        0, {"counters": {payload.prompt: 1.0}})


def leaky_fstring(metrics, req):
    metrics.record_compiles(f"user:{req.user_id}")  # FINDING: f-string label


def bounded_reason(metrics):
    metrics.record_shed("queue_full")  # clean: typed enum value


def bounded_tenant_pair(metrics, tenant):
    # clean: exempt by design — the tenant gauge set is capped by
    # TenantFairQueue.MAX_TRACKED eviction
    metrics.record_tenant_admitted(tenant)
    metrics.record_tenant_shed(tenant, "fairness")


def bounded_flight_tick(recorder, request_id):
    # clean: flight record_tick is a deque, not a label space
    recorder.record_tick(event="handoff", request_id=request_id)


def suppressed_site(metrics, tenant_id):
    metrics.record_events(tenant_id)  # lint: allow(telemetry-unbounded-labels)


def not_telemetry(registry, request_id):
    registry.note_request(request_id)  # clean: not a record_*/merge call
