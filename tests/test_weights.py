"""Checkpoint → serving wiring: `cli convert` output loads back through
config (checkpoint_path/tokenizer_path) into live engine/embedder/reranker
instances with real weights and a real HF tokenizer — the full "switch from
hosted APIs to in-process models" path."""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from sentio_tpu.config import EmbedderConfig, GeneratorConfig, RerankConfig  # noqa: E402
from sentio_tpu.runtime.checkpoint import save_pytree  # noqa: E402
from sentio_tpu.runtime.weights import WeightsError, load_model  # noqa: E402


@pytest.fixture(scope="module")
def hf_tokenizer_dir(tmp_path_factory):
    """A real HF tokenizer built fully offline (WordLevel over a tiny vocab)."""
    from tokenizers import Tokenizer, models, pre_tokenizers

    words = ["hello", "world", "tpu", "matrix", "the", "what", "is", "a"]
    vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
    for w in words:
        vocab[w] = len(vocab)
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, pad_token="<pad>", bos_token="<s>",
        eos_token="</s>", unk_token="<unk>",
    )
    d = tmp_path_factory.mktemp("hf_tok")
    fast.save_pretrained(d)
    return str(d)


@pytest.fixture(scope="module")
def llama_ckpt(tmp_path_factory):
    from sentio_tpu.models.convert import convert_llama, llama_config_from_hf

    cfg = transformers.LlamaConfig(
        vocab_size=32, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=1,
        max_position_embeddings=64,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    our_cfg = llama_config_from_hf(cfg, dtype="float32")
    params = convert_llama(model.state_dict(), our_cfg)
    d = tmp_path_factory.mktemp("ck") / "llama"
    save_pytree(d, params, meta={"family": "llama", "config": our_cfg.__dict__})
    return str(d)


class TestLoadModel:
    def test_loads_params_config_tokenizer(self, llama_ckpt, hf_tokenizer_dir):
        params, cfg, tok = load_model(
            llama_ckpt, expect_family="llama", tokenizer_path=hf_tokenizer_dir
        )
        assert cfg.dim == 16 and cfg.n_kv_heads == 1
        assert params["embed_tokens"]["embedding"].shape == (32, 16)
        assert tok is not None and tok.encode("hello world") != []

    def test_family_mismatch_raises(self, llama_ckpt):
        with pytest.raises(WeightsError):
            load_model(llama_ckpt, expect_family="encoder")

    def test_missing_checkpoint_raises(self, tmp_path):
        with pytest.raises(WeightsError):
            load_model(str(tmp_path / "nope"))

    def test_oversized_tokenizer_rejected(self, tmp_path, llama_ckpt):
        """A tokenizer with more ids than the model vocab would index out of
        bounds on device — refuse at load time."""
        from tokenizers import Tokenizer, models, pre_tokenizers

        vocab = {f"w{i}": i for i in range(64)}  # > model vocab of 32
        vocab["<unk>"] = 64
        tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
        tok.pre_tokenizer = pre_tokenizers.Whitespace()
        fast = transformers.PreTrainedTokenizerFast(tokenizer_object=tok, unk_token="<unk>")
        d = tmp_path / "big_tok"
        fast.save_pretrained(d)
        with pytest.raises(WeightsError):
            load_model(llama_ckpt, tokenizer_path=str(d))


class TestEngineFromCheckpoint:
    def test_generate_with_converted_weights(self, llama_ckpt, hf_tokenizer_dir):
        from sentio_tpu.runtime.engine import GeneratorEngine

        engine = GeneratorEngine(
            config=GeneratorConfig(
                checkpoint_path=llama_ckpt, tokenizer_path=hf_tokenizer_dir,
                max_new_tokens=4,
            ),
        )
        assert engine.model_config.dim == 16  # config came from the checkpoint
        out = engine.generate(["hello world"], max_new_tokens=4)
        assert len(out) == 1 and isinstance(out[0].text, str)

    def test_embedder_from_checkpoint(self, tmp_path, hf_tokenizer_dir):
        from sentio_tpu.models.convert import convert_encoder, encoder_config_from_hf
        from sentio_tpu.ops.embedder import TpuEmbedder

        cfg = transformers.BertConfig(
            vocab_size=32, hidden_size=16, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=64, type_vocab_size=2,
        )
        torch.manual_seed(1)
        our_cfg = encoder_config_from_hf(cfg, dtype="float32")
        params = convert_encoder(transformers.BertModel(cfg).state_dict(), our_cfg)
        d = tmp_path / "enc"
        save_pytree(d, params, meta={"family": "encoder", "config": our_cfg.__dict__})

        emb = TpuEmbedder(EmbedderConfig(
            provider="tpu", checkpoint_path=str(d), tokenizer_path=hf_tokenizer_dir,
        ))
        vec = emb.embed("hello tpu world")
        assert vec.shape == (16,)
        assert np.isfinite(vec).all()
        np.testing.assert_allclose(np.linalg.norm(vec), 1.0, rtol=1e-4)

    def test_reranker_from_checkpoint(self, tmp_path, hf_tokenizer_dir, docs):
        from sentio_tpu.models.convert import convert_cross_encoder, encoder_config_from_hf
        from sentio_tpu.ops.reranker import CrossEncoderReranker

        cfg = transformers.XLMRobertaConfig(
            vocab_size=32, hidden_size=16, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=66, type_vocab_size=1, num_labels=1,
            pad_token_id=1,
        )
        torch.manual_seed(2)
        model = transformers.XLMRobertaForSequenceClassification(cfg)
        our_cfg = encoder_config_from_hf(cfg, dtype="float32")
        params = convert_cross_encoder(model.state_dict(), our_cfg, position_offset=2)
        d = tmp_path / "xenc"
        save_pytree(d, params, meta={"family": "cross-encoder", "config": our_cfg.__dict__})

        rr = CrossEncoderReranker(RerankConfig(
            checkpoint_path=str(d), tokenizer_path=hf_tokenizer_dir, batch_size=4,
        ))
        result = rr.rerank("what is a tpu", docs[:4], top_k=2)
        assert len(result.documents) == 2
        assert all(np.isfinite(s) for s in result.scores)
