import numpy as np
import pytest

from sentio_tpu.config import EmbedderConfig
from sentio_tpu.ops.embedder import (
    EmbeddingCache,
    HashEmbedder,
    TpuEmbedder,
    get_embedder,
)


class TestEmbeddingCache:
    def test_hit_miss_and_stats(self):
        cache = EmbeddingCache(max_size=10, ttl_s=100)
        assert cache.get("a") is None
        cache.put("a", np.ones(4, np.float32))
        assert cache.get("a") is not None
        stats = cache.stats()
        assert stats["hits"] == 1 and stats["misses"] == 1

    def test_lfu_eviction(self):
        cache = EmbeddingCache(max_size=2, ttl_s=0)
        cache.put("hot", np.zeros(2))
        cache.put("cold", np.ones(2))
        for _ in range(5):
            cache.get("hot")
        cache.put("new", np.full(2, 2.0))  # evicts "cold" (fewest hits)
        assert cache.get("hot") is not None
        assert cache.get("cold") is None

    def test_ttl_expiry(self, monkeypatch):
        import time as time_mod

        cache = EmbeddingCache(max_size=10, ttl_s=1.0)
        cache.put("x", np.zeros(2))
        # TTLs clock on the monotonic perf_counter (NTP-step immune)
        real = time_mod.perf_counter()
        monkeypatch.setattr(
            "sentio_tpu.ops.embedder.time.perf_counter", lambda: real + 10
        )
        assert cache.get("x") is None


class TestHashEmbedder:
    def test_deterministic_and_normalized(self):
        emb = HashEmbedder(EmbedderConfig(provider="hash", dim=64))
        a = emb.embed("hello world")
        b = emb.embed("hello world")
        np.testing.assert_array_equal(a, b)
        assert a.shape == (64,)
        assert abs(np.linalg.norm(a) - 1.0) < 1e-5

    def test_related_texts_correlate(self):
        emb = HashEmbedder(EmbedderConfig(provider="hash", dim=256))
        base = emb.embed("the quick brown fox jumps")
        related = emb.embed("the quick brown fox runs")
        unrelated = emb.embed("quantum chromodynamics lattice")
        assert float(base @ related) > float(base @ unrelated)

    def test_cache_and_stats(self):
        emb = HashEmbedder(EmbedderConfig(provider="hash", dim=32))
        emb.embed_many(["a", "b"])
        emb.embed_many(["a", "c"])  # "a" cached
        stats = emb.get_stats()
        assert stats["requests"] == 2
        assert stats["texts"] == 4
        assert stats["cache"]["hits"] == 1

    def test_warm_up(self):
        emb = HashEmbedder(EmbedderConfig(provider="hash", dim=16))
        assert emb.warm_up() is True

    def test_async_paths(self):
        import asyncio

        emb = HashEmbedder(EmbedderConfig(provider="hash", dim=16))

        async def run():
            one = await emb.embed_async("solo")
            many = await emb.embed_many_async(["x", "y"])
            return one, many

        one, many = asyncio.run(run())
        assert one.shape == (16,) and many.shape == (2, 16)


class TestTpuEmbedder:
    @pytest.fixture(scope="class")
    def embedder(self):
        return TpuEmbedder(EmbedderConfig(provider="tpu", model_preset="tiny", batch_size=8))

    def test_shapes_and_norm(self, embedder):
        out = embedder.embed_many(["short", "a rather longer sentence here"])
        assert out.shape == (2, embedder.dimension)
        np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-4)

    def test_deterministic(self, embedder):
        a = embedder.embed("same text")
        embedder.cache = EmbeddingCache(10, 0)  # bypass cache
        b = embedder.embed("same text")
        np.testing.assert_allclose(a, b, atol=1e-6)

    def test_bucketing_stable(self, embedder):
        """Same text must embed identically whatever batch it rides in
        (padding/bucketing must not leak into results)."""
        solo = embedder.embed("invariant text")
        embedder.cache = EmbeddingCache(10, 0)
        batched = embedder.embed_many(["invariant text", "x" * 200])[0]
        np.testing.assert_allclose(solo, batched, atol=1e-5)


def test_registry_fallback():
    emb = get_embedder(EmbedderConfig(provider="unknown-thing", dim=8))
    assert isinstance(emb, HashEmbedder)
    assert isinstance(get_embedder(EmbedderConfig(provider="hash", dim=8)), HashEmbedder)


def test_batch_bucketing_avoids_recompiles():
    """Distinct miss-counts within one batch bucket must reuse one program."""
    import jax

    emb = TpuEmbedder(EmbedderConfig(provider="tpu", model_preset="tiny", batch_size=8))
    emb.embed_many(["a", "b", "c"])  # compiles (B=4 bucket, seq=16 bucket)
    compiled = emb._fwd._cache_size() if hasattr(emb._fwd, "_cache_size") else None
    emb.cache = EmbeddingCache(10, 0)
    emb.embed_many(["d", "e", "f", "g"])  # same B=4 bucket -> no new compile
    if compiled is not None:
        assert emb._fwd._cache_size() == compiled


class TestEmbedDevice:
    def test_embed_device_matches_embed_many(self, settings):
        from sentio_tpu.config import EmbedderConfig
        from sentio_tpu.models.transformer import EncoderConfig
        from sentio_tpu.ops.embedder import TpuEmbedder

        emb = TpuEmbedder(EmbedderConfig(provider="tpu", dim=64),
                          model_config=EncoderConfig.tiny())
        texts = ["the quick fox", "jax compiles to xla"]
        dev = np.asarray(emb.embed_device(texts), np.float32)
        host = emb.embed_many(texts)
        np.testing.assert_allclose(dev, host, atol=1e-5)

    def test_embed_device_cache_hit_path(self, settings):
        import time

        from sentio_tpu.config import EmbedderConfig
        from sentio_tpu.models.transformer import EncoderConfig
        from sentio_tpu.ops.embedder import TpuEmbedder

        emb = TpuEmbedder(EmbedderConfig(provider="tpu", dim=64),
                          model_config=EncoderConfig.tiny())
        emb.embed_many(["warm me"])  # populates cache synchronously
        out = emb.embed_device(["warm me"])
        assert isinstance(out, np.ndarray)  # served from cache, no device call

        # miss path fills the cache from the background thread
        emb.embed_device(["fresh text"])
        for _ in range(50):
            if emb.cache.get("fresh text") is not None:
                break
            time.sleep(0.05)
        assert emb.cache.get("fresh text") is not None
