"""Eval subsystem: the runner wiring, the OpenAI-compatible provider, and
the loopback baseline — the measurement path behind BASELINE.md's matrix."""

import pytest

from sentio_tpu.eval.dataset import build_bundle
from sentio_tpu.eval.runner import run_eval


@pytest.fixture(scope="module")
def mock_server():
    from sentio_tpu.eval.baseline import MockModelServer

    server = MockModelServer(dim=64).start()
    yield server
    server.stop()


class TestOpenAIProvider:
    def test_chat_roundtrip(self, mock_server):
        from sentio_tpu.ops.generator import OpenAIProvider

        provider = OpenAIProvider(base_url=mock_server.base_url + "/v1")
        out = provider.chat("[1] Source: a.md\nhello", max_new_tokens=16, temperature=0.0)
        assert isinstance(out, str) and out

    def test_stream_falls_back_to_chat(self, mock_server):
        # the mock server has no SSE support; stream must still yield text
        from sentio_tpu.ops.generator import OpenAIProvider

        provider = OpenAIProvider(base_url=mock_server.base_url + "/v1")
        chunks = list(provider.stream("question?", max_new_tokens=16, temperature=0.0))
        assert "".join(chunks)

    def test_registered_and_configurable(self):
        from sentio_tpu.config import GeneratorConfig
        from sentio_tpu.ops.generator import OpenAIProvider, create_generator, get_provider

        from sentio_tpu.config import Settings
        from sentio_tpu.ops.generator import EchoProvider

        assert get_provider("openai").name == "openai"
        # default settings (provider=tpu, no engine) degrade to echo
        gen = create_generator(settings=None, engine=None)
        assert isinstance(gen.provider, EchoProvider)
        cfg = GeneratorConfig(provider="openai", api_base="http://x/v1", api_model="m")
        s = Settings()
        s.generator = cfg
        gen = create_generator(settings=s)
        assert isinstance(gen.provider, OpenAIProvider)
        assert gen.provider.base_url == "http://x/v1"
        assert gen.provider.model == "m"

    def test_retries_then_raises(self):
        from sentio_tpu.ops.generator import OpenAIProvider

        provider = OpenAIProvider(
            base_url="http://127.0.0.1:9/v1", max_retries=1, timeout_s=0.2
        )
        with pytest.raises(RuntimeError, match="after 2 attempts"):
            provider.chat("x", max_new_tokens=4, temperature=0.0)

    def test_api_v1_404_fallback_switches_base(self, mock_server):
        """OpenRouter-style /api/v1 vs /v1 drift (reference openai.py:124-144
        there): a 404 on the configured base retries once against the
        stripped base and keeps it on success."""
        from sentio_tpu.ops.generator import OpenAIProvider

        provider = OpenAIProvider(base_url=mock_server.base_url + "/api/v1")
        out = provider.chat("[1] Source: a.md\nhello", max_new_tokens=8,
                            temperature=0.0)
        assert isinstance(out, str) and out
        assert provider.base_url == mock_server.base_url + "/v1"
        # subsequent calls go straight to the working base
        assert provider.chat("again?", max_new_tokens=8, temperature=0.0)

    def test_usage_tracked_per_call(self, mock_server):
        from sentio_tpu.ops.generator import OpenAIProvider

        provider = OpenAIProvider(base_url=mock_server.base_url + "/v1")
        provider.chat("count my tokens please", max_new_tokens=8, temperature=0.0)
        usage = provider.last_usage
        assert usage["prompt_tokens"] >= 1 and usage["completion_tokens"] >= 1

    def test_switch_base_concurrent_threads_no_flap_no_leak(
        self, mock_server, monkeypatch
    ):
        """Racing 404 fallbacks from concurrent worker threads must
        converge on ONE base-URL switch (compare-and-swap under the
        provider lock), every call must still succeed — including a thread
        whose 404 landed on the retired base mid-switch — and every pooled
        client ever built must reach close()."""
        import threading

        import httpx

        from sentio_tpu.ops.generator import OpenAIProvider

        created = []
        real_client = httpx.Client

        class TrackingClient(real_client):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                created.append(self)

        monkeypatch.setattr(httpx, "Client", TrackingClient)
        provider = OpenAIProvider(base_url=mock_server.base_url + "/api/v1")
        n = 8
        start = threading.Barrier(n)
        errors = []

        def worker(i):
            try:
                start.wait(timeout=10)
                out = provider.chat(f"[1] Source: a.md\nquestion {i}?",
                                    max_new_tokens=4, temperature=0.0)
                assert out
            except Exception as exc:  # noqa: BLE001 — collected for assert
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        # converged on the stripped base, no flapping back
        assert provider.base_url == mock_server.base_url + "/v1"
        assert provider.chat("settled?", max_new_tokens=4, temperature=0.0)
        provider.close()
        assert getattr(provider, "_client_cached", None) is None
        assert getattr(provider, "_retired_clients", []) == []
        # nothing leaked: every client ever constructed was closed
        assert created and all(c.is_closed for c in created)



class TestEvalDataset:
    def test_bundle_deterministic(self):
        a = build_bundle(n_docs=64, n_queries=8, seed=3)
        b = build_bundle(n_docs=64, n_queries=8, seed=3)
        assert [d.text for d in a.documents] == [d.text for d in b.documents]
        assert a.queries == b.queries
        # gold ids all exist in the corpus
        ids = {d.id for d in a.documents}
        assert all(gold in ids for _, gold in a.queries)


class TestRunEval:
    def test_retrieval_configs_produce_rows(self):
        payload = run_eval(
            scale="tiny", n_docs=64, n_queries=6, new_tokens=4,
            skip_baseline=True, configs={"sparse_api", "dense", "hybrid_rerank"},
        )
        rows = {r["config"]: r for r in payload["rows"]}
        assert set(rows) == {"1-bm25+api-llm", "2-dense-tpu", "3-hybrid+rerank"}
        for r in rows.values():
            assert 0.0 <= r["recall@10"] <= 1.0
            assert r["p50_ms"] > 0 and r["qps"] > 0
        # BM25 is near-exact on the entity bundle — the sparse config must
        # find the gold doc for most paraphrased questions
        assert rows["1-bm25+api-llm"]["recall@10"] >= 0.5

    def test_full_graph_config_uses_paged_service(self):
        payload = run_eval(
            scale="tiny", n_docs=48, n_queries=3, concurrency=2,
            new_tokens=4, verifier_tokens=4, skip_baseline=True,
            configs={"batched"},
        )
        (row,) = payload["rows"]
        assert row["config"] == "5-batched-dp"
        assert row["decode_ticks"] > 0, "paged continuous batching must be live"
        assert row.get("errors", 0) == 0

    def test_baseline_measured(self):
        bundle = build_bundle(n_docs=48, n_queries=4)
        from sentio_tpu.eval.baseline import measure_baseline

        result = measure_baseline(bundle.documents, bundle.queries, dim=64)
        assert result.n_queries == 4
        assert result.p50_ms > 0
        assert result.extras["http_calls"]["chat"] >= 4


class TestQuantQualityGate:
    """KV_QUANT=int8 quality gate: the int8 full-graph eval is measured
    against a bf16 run over the same bundle in the same process, and the
    delta is gated by the COMMITTED tolerances in eval/quant_gate.json —
    a quantization quality regression fails tier-1 here instead of being
    suspected in production."""

    GATE_ARGS = dict(
        scale="tiny", n_docs=48, n_queries=4, concurrency=2,
        new_tokens=8, verifier_tokens=4, skip_baseline=True,
        configs={"full_paged"},
    )

    def test_int8_recall_and_answers_within_committed_tolerance(self):
        import json
        from pathlib import Path

        gate_path = (Path(__file__).resolve().parents[1] / "sentio_tpu"
                     / "eval" / "quant_gate.json")
        gate = json.loads(gate_path.read_text())

        bf16 = run_eval(**self.GATE_ARGS)
        int8 = run_eval(**self.GATE_ARGS, kv_quant="int8")
        (bf_row,) = bf16["rows"]
        (i8_row,) = int8["rows"]
        assert int8["kv_quant"] == "int8"

        assert i8_row.get("errors", 0) <= gate["errors_max"], i8_row
        drop = bf_row["recall@10"] - i8_row["recall@10"]
        assert drop <= gate["recall_at_10_max_drop"], (
            f"int8 recall@10 dropped {drop:.3f} vs bf16 "
            f"(gate {gate['recall_at_10_max_drop']}): {bf_row} vs {i8_row}")
        # collapsed/empty int8 decodes move the answer-length metric even
        # when retrieval recall cannot see them
        bf_chars = bf_row.get("answer_chars_mean", 0.0)
        i8_chars = i8_row.get("answer_chars_mean", 0.0)
        assert bf_chars > 0, bf_row
        assert i8_chars >= gate["answer_chars_min_ratio"] * bf_chars, (
            f"int8 mean answer length {i8_chars} vs bf16 {bf_chars} "
            f"(gate ratio {gate['answer_chars_min_ratio']})")


class TestVerifyGate:
    """VERIFY_MODE=gated quality gate: a gated full-graph eval run is
    measured against an always-verify (sync) run over the same bundle in
    the same process, and per-query FINAL verdicts (async verdicts awaited
    off the flight record) are gated by the COMMITTED tolerances in
    eval/verify_gate.json — a confidence-calibration regression that skips
    audits which would have warned/failed drops agreement and fails tier-1
    here instead of shipping silently."""

    GATE_ARGS = dict(
        scale="tiny", n_docs=48, n_queries=4, concurrency=2,
        new_tokens=8, verifier_tokens=4, skip_baseline=True,
        configs={"full_paged"},
    )

    def test_gated_verdicts_agree_with_always_verify(self):
        import json
        from pathlib import Path

        gate_path = (Path(__file__).resolve().parents[1] / "sentio_tpu"
                     / "eval" / "verify_gate.json")
        gate = json.loads(gate_path.read_text())

        sync = run_eval(**self.GATE_ARGS, verify_mode="sync")
        gated = run_eval(**self.GATE_ARGS, verify_mode="gated")
        (sync_row,) = sync["rows"]
        (gated_row,) = gated["rows"]
        assert gated["verify_mode"] == "gated"
        assert gated_row.get("errors", 0) <= gate["errors_max"], gated_row

        sync_v = sync_row.get("verdicts") or {}
        gated_v = gated_row.get("verdicts") or {}
        assert sync_v and gated_v, (
            f"both runs must record per-query verdicts: {sync_row} "
            f"vs {gated_row}")
        common = set(sync_v) & set(gated_v)
        assert common, (sync_v, gated_v)
        # a skipped audit asserts the answer would have PASSED — count it
        # as agreement only against a sync pass
        agree = sum(
            1 for q in common
            if gated_v[q] == sync_v[q]
            or (gated_v[q] == "skipped_confident" and sync_v[q] == "pass")
        )
        agreement = agree / len(common)
        assert agreement >= gate["min_verdict_agreement"], (
            f"gated-vs-sync verdict agreement {agreement:.3f} below the "
            f"committed gate {gate['min_verdict_agreement']}: "
            f"{gated_v} vs {sync_v}")
        skip_rate = gated_row.get("verify_skip_rate", 0.0)
        assert skip_rate <= gate["max_skip_rate"], (
            f"gated skip rate {skip_rate} exceeds the committed ceiling "
            f"{gate['max_skip_rate']} — the confidence score is calling "
            f"random-init decodes confident")
