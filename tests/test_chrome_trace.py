"""Chrome/Perfetto trace exporter (infra/chrome_trace.py).

Golden test: a deterministic fake flight record round-trips to the
committed JSON byte-for-byte (the exporter is a pure function over plain
dicts). Schema test: phase slices nest exactly inside their tick slice
and the phase durations sum to the tick's pump wall time within 5% —
the invariant that makes the Perfetto view trustworthy."""

import json
from pathlib import Path

from sentio_tpu.infra.chrome_trace import build_chrome_trace, flight_to_chrome
from sentio_tpu.infra.flight import FlightRecorder, set_flight_recorder
from sentio_tpu.infra.phases import TICK_PHASES

GOLDEN = Path(__file__).parent / "fixtures" / "chrome_trace_golden.json"

# a deterministic two-tick, one-request, one-health-event flight timeline
# (the exact field shapes FlightRecorder.timeline()/records() emit)
FAKE_TICKS = [
    {
        "tick": 1, "t_s": 0.0100, "replica": 0,
        "dur_ms": 6.0, "pump_ms": 8.0,
        "phase_ms": {
            "inbox_drain": 1.0, "admission_build": 1.0,
            "prefill_dispatch": 2.0, "decode_dispatch": 2.0,
            "device_wait": 1.5, "deliver": 0.4, "other": 0.1,
        },
        "active_slots": 2, "queue_depth": 1, "inbox_depth": 0,
        "prefill_tokens": 32, "decode_tokens": 8, "free_pages": 10,
        "xla_compiles": 0,
    },
    {
        "tick": 2, "t_s": 0.0200, "replica": 0,
        "dur_ms": 4.0, "pump_ms": 5.0,
        "phase_ms": {
            "inbox_drain": 0.2, "admission_build": 0.3,
            "prefill_dispatch": 0.0, "decode_dispatch": 1.5,
            "device_wait": 2.5, "deliver": 0.4, "other": 0.1,
        },
        "active_slots": 2, "queue_depth": 0, "inbox_depth": 0,
        "prefill_tokens": 0, "decode_tokens": 8, "free_pages": 10,
        "xla_compiles": 0,
    },
    {
        "tick": 3, "t_s": 0.0250, "replica": 0,
        "event": "replica_health", "state": "QUARANTINED",
        "prior": "HEALTHY", "reason": "stalled",
    },
]

FAKE_RECORDS = [
    {
        "request_id": "req-1", "status": "done", "t_start_s": 0.001,
        "latency_ms": 30.0, "endpoint": "/chat", "mode": "fast",
        "question_chars": 24,
        "engine": {
            "replica_id": 0, "t_submit_s": 0.004, "ttft_ms": 8.0,
            "tokens": 8, "prompt_tokens": 16, "prefix_hit_tokens": 0,
            "finish_reason": "stop", "tpot_ms": 1.5,
            "tick_first": 0, "tick_last": 2,
        },
        "verify": {
            "mode": "async", "outcome": "pass", "confidence": 0.9,
            "verdict_ms": 12.0,
        },
    },
]


def _build():
    return build_chrome_trace(FAKE_TICKS, FAKE_RECORDS)


class TestGolden:
    def test_round_trips_to_committed_json(self):
        """Deterministic: the committed artifact IS the exporter's output.
        On intentional format changes, regenerate with
        ``python -m tests.test_chrome_trace`` and review the diff."""
        got = _build()
        want = json.loads(GOLDEN.read_text())
        assert got == want

    def test_deterministic(self):
        assert _build() == _build()


class TestSchema:
    def _events(self):
        return _build()["traceEvents"]

    def _tick_slices(self):
        return [e for e in self._events()
                if e["ph"] == "X" and e["name"].startswith("tick ")]

    def test_phases_nest_inside_their_tick(self):
        """Every phase slice sits on the tick's pid/tid and falls entirely
        within the tick's [ts, ts+dur] window — Perfetto renders them as
        children of the tick, never bleeding into a neighbour."""
        events = self._events()
        ticks = self._tick_slices()
        assert len(ticks) == 2
        phase_names = set(TICK_PHASES)
        phase_slices = [e for e in events
                        if e["ph"] == "X" and e["name"] in phase_names]
        assert phase_slices, "no phase slices emitted"
        for phase in phase_slices:
            parents = [
                t for t in ticks
                if t["pid"] == phase["pid"] and t["tid"] == phase["tid"]
                and t["ts"] - 1e-6 <= phase["ts"]
                and phase["ts"] + phase["dur"] <= t["ts"] + t["dur"] + 1e-6
            ]
            assert len(parents) == 1, (
                f"phase {phase['name']} at ts={phase['ts']} does not nest "
                f"in exactly one tick (found {len(parents)})"
            )

    def test_phase_sum_matches_tick_wall_within_5pct(self):
        events = self._events()
        phase_names = set(TICK_PHASES)
        for tick in self._tick_slices():
            inside = [
                e for e in events
                if e["ph"] == "X" and e["name"] in phase_names
                and e["pid"] == tick["pid"] and e["tid"] == tick["tid"]
                and tick["ts"] - 1e-6 <= e["ts"] < tick["ts"] + tick["dur"]
            ]
            total = sum(e["dur"] for e in inside)
            assert abs(total - tick["dur"]) <= 0.05 * tick["dur"], (
                f"{tick['name']}: phase sum {total}µs vs wall {tick['dur']}µs"
            )

    def test_request_span_and_marks(self):
        events = self._events()
        req = [e for e in events if e["name"] == "request req-1"]
        assert len(req) == 1 and req[0]["ph"] == "X"
        assert req[0]["ts"] == 1000.0  # 0.001 s → µs
        assert req[0]["dur"] == 30000.0
        engine = [e for e in events if e["name"] == "engine"]
        assert len(engine) == 1
        assert engine[0]["tid"] == req[0]["tid"]
        first = [e for e in events if e["name"] == "first_token"]
        assert len(first) == 1 and first[0]["ph"] == "i"
        # submit 0.004 s + ttft 8 ms = 12 ms
        assert first[0]["ts"] == 12000.0
        verify = [e for e in events if e["name"].startswith("verify:")]
        assert len(verify) == 1
        assert verify[0]["name"] == "verify:pass"
        # async verdict trails the answer: starts at request end
        assert verify[0]["ts"] == req[0]["ts"] + req[0]["dur"]
        assert verify[0]["dur"] == 12000.0

    def test_health_instant(self):
        events = self._events()
        health = [e for e in events if e["name"].startswith("health:")]
        assert len(health) == 1
        assert health[0]["ph"] == "i" and health[0]["s"] == "p"
        assert health[0]["args"]["state"] == "QUARANTINED"

    def test_metadata_rows(self):
        events = self._events()
        procs = [e for e in events if e["name"] == "process_name"]
        assert [p["args"]["name"] for p in procs] == ["replica 0"]
        threads = [e for e in events if e["name"] == "thread_name"]
        assert {t["args"]["name"] for t in threads} == {
            "pump", "request lane 1"}


class TestLiveRecorder:
    def test_flight_to_chrome_full_timeline(self):
        rec = FlightRecorder()
        set_flight_recorder(rec)
        try:
            rec.start_request("live-1", endpoint="/chat", mode="fast")
            rec.note_engine_submit("live-1", replica_id=0)
            rec.record_tick(replica=0, dur_ms=1.0, pump_ms=1.2,
                            phase_ms={p: 1.2 / len(TICK_PHASES)
                                      for p in TICK_PHASES})
            rec.finish_engine("live-1", ttft_ms=0.5, finish_reason="stop")
            rec.finish_request("live-1", status="done")
            trace = flight_to_chrome(rec)
            names = {e["name"] for e in trace["traceEvents"]}
            assert "request live-1" in names
            assert any(n.startswith("tick ") for n in names)
        finally:
            set_flight_recorder(None)

    def test_flight_to_chrome_single_request_window(self):
        rec = FlightRecorder()
        rec.start_request("solo", endpoint="/chat")
        rec.note_engine_submit("solo", replica_id=0)
        rec.record_tick(replica=0, dur_ms=1.0, pump_ms=1.0,
                        phase_ms={"other": 1.0})
        rec.finish_engine("solo", finish_reason="stop")
        rec.finish_request("solo", status="done")
        trace = flight_to_chrome(rec, request_id="solo")
        assert trace is not None
        names = {e["name"] for e in trace["traceEvents"]}
        assert "request solo" in names
        assert flight_to_chrome(rec, request_id="missing") is None


if __name__ == "__main__":
    # regenerate the golden artifact (review the diff before committing)
    GOLDEN.write_text(json.dumps(_build(), indent=1, sort_keys=True) + "\n")
    print(f"rewrote {GOLDEN}")
