import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.config import MeshConfig
from sentio_tpu.models.llama import LlamaConfig, init_llama, llama_forward
from sentio_tpu.parallel.batcher import Batcher, BatcherClosed, bucket_size
from sentio_tpu.parallel.mesh import (
    MeshError,
    batch_multiple,
    build_mesh,
    resolve_spec,
)
from sentio_tpu.parallel.sharding import (
    LLAMA_TP_RULES,
    batch_sharding,
    describe_shardings,
    shard_params,
    spec_for,
)
from jax.sharding import PartitionSpec as P

pytestmark = pytest.mark.mesh


class TestMesh:
    def test_resolve_defaults_all_dp(self):
        spec = resolve_spec(MeshConfig(), 8)
        assert spec.shape == (1, 8, 1, 1, 1, 1)

    def test_resolve_tp(self):
        spec = resolve_spec(MeshConfig(tp_size=4), 8)
        assert spec.shape == (1, 2, 1, 1, 1, 4)

    def test_resolve_rejects_indivisible(self):
        with pytest.raises(MeshError):
            resolve_spec(MeshConfig(tp_size=3), 8)

    def test_resolve_rejects_overcommit(self):
        with pytest.raises(MeshError):
            resolve_spec(MeshConfig(dp_size=4, tp_size=4), 8)

    def test_build_mesh_axes(self):
        mesh = build_mesh(MeshConfig(tp_size=2, sp_size=2))
        assert dict(mesh.shape) == {"dcn": 1, "dp": 2, "pp": 1, "ep": 1, "sp": 2, "tp": 2}
        assert batch_multiple(mesh) == 2

    def test_mesh_uses_all_devices(self):
        mesh = build_mesh(MeshConfig())
        assert mesh.devices.size == len(jax.devices())

    def test_multi_slice_dcn_mesh_runs_train_step(self):
        """dcn > 1 (multi-slice pods) must build on the virtual mesh —
        host-platform devices have no slice_index, so build_mesh falls back
        to a plain layout — and a train step with the batch sharded over
        (dcn, dp) must compile and run (gradient psums cross the dcn axis)."""
        import optax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from sentio_tpu.parallel.sharding import LLAMA_TP_RULES, shard_params

        mesh = build_mesh(MeshConfig(dcn_size=2, dp_size=2, tp_size=2))
        assert dict(mesh.shape)["dcn"] == 2

        cfg = LlamaConfig.tiny()
        params = shard_params(
            init_llama(jax.random.PRNGKey(0), cfg), mesh, LLAMA_TP_RULES
        )
        tx = optax.adamw(1e-3)
        opt = tx.init(params)
        from sentio_tpu.models.llama import llama_loss

        def step(p, o, ids, mask):
            loss, g = jax.value_and_grad(lambda q: llama_loss(q, cfg, ids, mask))(p)
            up, o = tx.update(g, o, p)
            return optax.apply_updates(p, up), o, loss

        rng = np.random.default_rng(0)
        data_spec = NamedSharding(mesh, P(("dcn", "dp")))
        ids = jax.device_put(
            jnp.asarray(rng.integers(1, cfg.vocab_size, (8, 17)), jnp.int32),
            data_spec,
        )
        mask = jax.device_put(jnp.ones((8, 17), bool), data_spec)
        _, _, loss = jax.jit(step)(params, opt, ids, mask)
        assert np.isfinite(float(loss))


class TestShardingRules:
    def test_llama_rule_resolution(self):
        assert spec_for("layers_3/attn/wq/kernel", LLAMA_TP_RULES, 2) == P(None, "tp")
        assert spec_for("layers_0/attn/wo/kernel", LLAMA_TP_RULES, 2) == P("tp", None)
        assert spec_for("layers_9/mlp/w_up/kernel", LLAMA_TP_RULES, 2) == P(None, "tp")
        assert spec_for("layers_9/mlp/w_down/kernel", LLAMA_TP_RULES, 2) == P("tp", None)
        assert spec_for("embed_tokens/embedding", LLAMA_TP_RULES, 2) == P("tp", None)
        assert spec_for("final_norm/scale", LLAMA_TP_RULES, 1) == P(None)
        assert spec_for("something/unmatched", LLAMA_TP_RULES, 2) == P()

    def test_tp_sharded_forward_matches_replicated(self):
        cfg = LlamaConfig(
            vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            mlp_dim=128, max_len=64, rope_theta=10_000.0, dtype="float32",
        )
        params = init_llama(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(np.random.default_rng(1).integers(1, 500, (4, 8)), jnp.int32)
        ref, _ = llama_forward(params, cfg, ids)

        mesh = build_mesh(MeshConfig(tp_size=2))
        sharded = shard_params(params, mesh, LLAMA_TP_RULES)
        ids_sharded = jax.device_put(ids, batch_sharding(mesh))
        out, _ = llama_forward(sharded, cfg, ids_sharded)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=1e-4)

    def test_describe_shardings_covers_all_params(self):
        cfg = LlamaConfig.tiny()
        params = init_llama(jax.random.PRNGKey(0), cfg)
        mesh = build_mesh(MeshConfig(tp_size=2))
        desc = describe_shardings(params, mesh, LLAMA_TP_RULES)
        n_leaves = len(jax.tree_util.tree_leaves(params))
        assert len(desc) == n_leaves
        assert desc["layers_0/attn/wq/kernel"] == "PartitionSpec(None, 'tp')"


class TestBatcher:
    def test_coalesces_concurrent_submits(self):
        async def run():
            sizes = []

            async def process(items):
                sizes.append(len(items))
                return [x * 2 for x in items]

            batcher = Batcher(process, max_size=4, deadline_ms=50.0)
            results = await asyncio.gather(*[batcher.submit(i) for i in range(4)])
            await batcher.close()
            return results, sizes

        results, sizes = asyncio.run(run())
        assert sorted(results) == [0, 2, 4, 6]
        assert max(sizes) > 1  # actually coalesced

    def test_deadline_flushes_partial_batch(self):
        async def run():
            async def process(items):
                return items

            batcher = Batcher(process, max_size=100, deadline_ms=5.0)
            result = await asyncio.wait_for(batcher.submit("only"), timeout=2.0)
            await batcher.close()
            return result

        assert asyncio.run(run()) == "only"

    def test_failed_batch_fails_futures_not_batcher(self):
        async def run():
            calls = {"n": 0}

            async def process(items):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError("device OOM")
                return items

            batcher = Batcher(process, max_size=2, deadline_ms=1.0)
            with pytest.raises(RuntimeError, match="device OOM"):
                await batcher.submit("a")
            ok = await batcher.submit("b")  # batcher survives
            stats = batcher.stats.snapshot()
            await batcher.close()
            return ok, stats

        ok, stats = asyncio.run(run())
        assert ok == "b"
        assert stats["errors"] == 1
        assert stats["batches"] == 2

    def test_result_count_mismatch_is_error(self):
        async def run():
            async def process(items):
                return items[:-1]

            batcher = Batcher(process, max_size=1, deadline_ms=1.0)
            with pytest.raises(RuntimeError, match="returned"):
                await batcher.submit("x")
            await batcher.close()

        asyncio.run(run())

    def test_closed_batcher_rejects(self):
        async def run():
            async def process(items):
                return items

            batcher = Batcher(process, max_size=1, deadline_ms=1.0)
            await batcher.submit("warm")
            await batcher.close()
            with pytest.raises(BatcherClosed):
                await batcher.submit("late")

        asyncio.run(run())

    def test_bucket_size(self):
        from sentio_tpu.parallel.batcher import floor_bucket

        assert bucket_size(1, [2, 4, 8]) == 2
        assert bucket_size(3, [2, 4, 8]) == 4
        assert bucket_size(8, [2, 4, 8]) == 8
        assert bucket_size(9, [2, 4, 8]) == 9  # exact size, never smaller
        assert floor_bucket(9, [2, 4, 8]) == 8
        assert floor_bucket(3, [2, 4, 8]) == 2
        assert floor_bucket(1, [2, 4, 8]) == 2  # min bucket floor
