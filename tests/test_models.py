import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.models.cross_encoder import cross_encoder_scores, init_cross_encoder
from sentio_tpu.models.llama import (
    LlamaConfig,
    init_cache,
    init_llama,
    llama_forward,
    llama_loss,
)
from sentio_tpu.models.tokenizer import (
    ByteTokenizer,
    WordHashTokenizer,
    batch_encode,
    batch_encode_pairs,
    get_tokenizer,
)
from sentio_tpu.models.transformer import (
    EncoderConfig,
    encoder_forward,
    init_encoder,
    mean_pool,
)

pytestmark = pytest.mark.slow

CFG = LlamaConfig.tiny()
ECFG = EncoderConfig.tiny()
F32_CFG = LlamaConfig(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    mlp_dim=128, max_len=256, rope_theta=10_000.0, dtype="float32",
)


@pytest.fixture(scope="module")
def llama_params():
    return init_llama(jax.random.PRNGKey(0), F32_CFG)


def _ids(batch=2, t=12):
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.integers(1, 500, size=(batch, t)), jnp.int32)


class TestTokenizers:
    def test_byte_roundtrip(self):
        tok = ByteTokenizer()
        for text in ("hello world", "naïve café 北京 🚀", ""):
            assert tok.decode(tok.encode(text)) == text

    def test_byte_specials(self):
        tok = ByteTokenizer()
        ids = tok.encode("hi", add_bos=True, add_eos=True)
        assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
        assert tok.decode(ids) == "hi"  # specials skipped in decode

    def test_hash_deterministic(self):
        tok = WordHashTokenizer()
        assert tok.encode("the quick fox") == tok.encode("The Quick FOX")
        assert tok.encode("a b") != tok.encode("a c")
        assert all(0 <= i < tok.vocab_size for i in tok.encode("x y z"))

    def test_batch_encode_pads_and_masks(self):
        tok = ByteTokenizer()
        ids, mask = batch_encode(tok, ["ab", "abcdef"], max_len=10)
        assert ids.shape == (2, 6)
        assert mask[0].sum() == 2 and mask[1].sum() == 6
        assert (ids[0, 2:] == tok.pad_id).all()

    def test_batch_encode_truncates(self):
        tok = ByteTokenizer()
        ids, mask = batch_encode(tok, ["x" * 100], max_len=8)
        assert ids.shape == (1, 8)

    def test_pair_encoding_structure(self):
        tok = ByteTokenizer()
        ids, mask, types = batch_encode_pairs(tok, [("query", "document")], max_len=32)
        row = ids[0][mask[0]]
        assert row[0] == tok.cls_id
        assert (row == tok.sep_id).sum() == 2
        assert types[0][mask[0]].max() == 1  # second segment marked
        assert types[0][0] == 0

    def test_get_tokenizer_registry(self):
        assert isinstance(get_tokenizer("byte"), ByteTokenizer)
        with pytest.raises(ValueError):
            get_tokenizer("nope")


class TestEncoder:
    def test_forward_shape_and_pooling(self):
        params = init_encoder(jax.random.PRNGKey(1), ECFG)
        ids = _ids(3, 16) % ECFG.vocab_size
        mask = jnp.ones_like(ids, bool)
        hidden = encoder_forward(params, ECFG, ids, mask)
        assert hidden.shape == (3, 16, ECFG.dim)
        emb = mean_pool(hidden, mask)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(emb), axis=-1), 1.0, rtol=1e-5)

    def test_padding_does_not_change_embedding(self):
        cfg = EncoderConfig(vocab_size=512, dim=64, n_layers=2, n_heads=2,
                            mlp_dim=128, max_len=64, dtype="float32")
        params = init_encoder(jax.random.PRNGKey(1), cfg)
        ids = _ids(1, 8) % cfg.vocab_size
        mask = jnp.ones_like(ids, bool)
        emb_short = mean_pool(encoder_forward(params, cfg, ids, mask), mask)
        padded = jnp.pad(ids, ((0, 0), (0, 6)))
        pmask = jnp.pad(mask, ((0, 0), (0, 6)))
        emb_padded = mean_pool(encoder_forward(params, cfg, padded, pmask), pmask)
        np.testing.assert_allclose(np.asarray(emb_short), np.asarray(emb_padded), atol=1e-5)


class TestCrossEncoder:
    def test_scores_shape_and_determinism(self):
        params = init_cross_encoder(jax.random.PRNGKey(2), ECFG)
        tok = ByteTokenizer(vocab_size=512)
        ids, mask, types = batch_encode_pairs(
            tok, [("q one", "doc a"), ("q one", "doc b"), ("q two", "doc c")], 48
        )
        args = (jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(types))
        s1 = cross_encoder_scores(params, ECFG, *args)
        s2 = cross_encoder_scores(params, ECFG, *args)
        assert s1.shape == (3,)
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))


class TestLlama:
    def test_logits_shape(self, llama_params):
        ids = _ids()
        logits, cache = llama_forward(llama_params, F32_CFG, ids)
        assert logits.shape == (2, 12, F32_CFG.vocab_size)
        assert logits.dtype == jnp.float32
        assert cache is None

    def test_causality(self, llama_params):
        """Changing a future token must not affect earlier logits."""
        ids = _ids(1, 10)
        logits_a, _ = llama_forward(llama_params, F32_CFG, ids)
        altered = ids.at[0, 7].set((ids[0, 7] + 1) % 500)
        logits_b, _ = llama_forward(llama_params, F32_CFG, altered)
        np.testing.assert_allclose(
            np.asarray(logits_a[0, :7]), np.asarray(logits_b[0, :7]), atol=1e-5
        )
        assert not np.allclose(np.asarray(logits_a[0, 7]), np.asarray(logits_b[0, 7]))

    def test_prefill_matches_full_forward(self, llama_params):
        ids = _ids(2, 12)
        full, _ = llama_forward(llama_params, F32_CFG, ids)
        cache = init_cache(F32_CFG, 2, 32)
        pre, cache = llama_forward(llama_params, F32_CFG, ids, cache=cache, cache_index=0)
        np.testing.assert_allclose(np.asarray(full), np.asarray(pre), atol=1e-4)

    def test_incremental_decode_matches_full(self, llama_params):
        """Token-by-token decode through the cache == one full forward."""
        ids = _ids(1, 8)
        full, _ = llama_forward(llama_params, F32_CFG, ids)
        cache = init_cache(F32_CFG, 1, 16)
        step_logits = []
        for t in range(8):
            pos = jnp.full((1, 1), t, jnp.int32)
            lg, cache = llama_forward(
                llama_params, F32_CFG, ids[:, t : t + 1],
                positions=pos, cache=cache, cache_index=t,
            )
            step_logits.append(np.asarray(lg[0, 0]))
        np.testing.assert_allclose(
            np.stack(step_logits), np.asarray(full[0]), atol=1e-4
        )

    def test_cache_not_mutated_in_place(self, llama_params):
        ids = _ids(1, 4)
        cache = init_cache(F32_CFG, 1, 8)
        before = np.asarray(cache["k"]).copy()
        llama_forward(llama_params, F32_CFG, ids, cache=cache, cache_index=0)
        np.testing.assert_array_equal(before, np.asarray(cache["k"]))

    def test_loss_finite_and_masked(self, llama_params):
        ids = _ids(2, 12)
        mask = jnp.ones_like(ids, bool)
        loss = llama_loss(llama_params, F32_CFG, ids, mask)
        assert np.isfinite(float(loss))
        # loss over garbage ~ log(vocab) at init
        assert 3.0 < float(loss) < 9.0

    def test_loss_ignores_padding(self, llama_params):
        ids = _ids(1, 8)
        mask = jnp.ones_like(ids, bool)
        loss_a = llama_loss(llama_params, F32_CFG, ids, mask)
        padded = jnp.pad(ids, ((0, 0), (0, 4)), constant_values=7)
        pmask = jnp.pad(mask, ((0, 0), (0, 4)))
        loss_b = llama_loss(llama_params, F32_CFG, padded, pmask)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)


class TestRaggedBatchDecode:
    def test_ragged_decode_matches_solo(self, llama_params):
        """Coalesced sequences of unequal length must decode identically to
        solo runs — per-row cache_index writes each row at its own slot."""
        rng = np.random.default_rng(3)
        seq_a = jnp.asarray(rng.integers(1, 500, (1, 5)), jnp.int32)
        seq_b = jnp.asarray(rng.integers(1, 500, (1, 3)), jnp.int32)

        def solo_next(seq):
            cache = init_cache(F32_CFG, 1, 16)
            lg, _ = llama_forward(llama_params, F32_CFG, seq, cache=cache, cache_index=0)
            return np.asarray(lg[0, seq.shape[1] - 1])

        expected_a, expected_b = solo_next(seq_a), solo_next(seq_b)

        # batched: right-pad to common length, aligned prefill
        lens = jnp.asarray([5, 3], jnp.int32)
        batch = jnp.zeros((2, 5), jnp.int32)
        batch = batch.at[0].set(seq_a[0]).at[1, :3].set(seq_b[0])
        cache = init_cache(F32_CFG, 2, 16)
        lg, cache = llama_forward(llama_params, F32_CFG, batch, cache=cache, cache_index=0)
        got_a = np.asarray(lg[0, 4])
        got_b = np.asarray(lg[1, 2])
        np.testing.assert_allclose(got_a, expected_a, atol=1e-4)
        np.testing.assert_allclose(got_b, expected_b, atol=1e-4)

        # now decode one step per row at its own position/index
        next_tok = jnp.asarray([[int(got_a.argmax())], [int(got_b.argmax())]], jnp.int32)
        lg2, cache = llama_forward(
            llama_params, F32_CFG, next_tok,
            positions=lens[:, None], cache=cache, cache_index=lens,
        )

        # solo continuation for row b (the shorter one, previously corrupted)
        cache_b = init_cache(F32_CFG, 1, 16)
        _, cache_b = llama_forward(llama_params, F32_CFG, seq_b, cache=cache_b, cache_index=0)
        lg_b, _ = llama_forward(
            llama_params, F32_CFG, next_tok[1:2],
            positions=jnp.asarray([[3]]), cache=cache_b, cache_index=3,
        )
        np.testing.assert_allclose(np.asarray(lg2[1, 0]), np.asarray(lg_b[0, 0]), atol=1e-4)
