"""Speculative decoding (runtime/speculative.py): greedy exactness against
the plain engine, acceptance accounting, EOS/budget handling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.config import GeneratorConfig
from sentio_tpu.models.llama import LlamaConfig, init_llama
from sentio_tpu.runtime.engine import GeneratorEngine
from sentio_tpu.runtime.speculative import SpeculativeDecoder, SpeculativeError

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def target_engine():
    cfg = LlamaConfig.tiny()
    return GeneratorEngine(
        config=GeneratorConfig(model_preset="tiny", max_new_tokens=16),
        model_config=cfg,
        params=init_llama(jax.random.PRNGKey(0), cfg),
    )


class TestGreedyExactness:
    def test_same_weights_draft_accepts_everything(self, target_engine):
        """Draft == target: every proposal agrees, so the decoder must emit
        target-greedy tokens at ~k+1 tokens per verify."""
        spec = SpeculativeDecoder(
            target_engine, target_engine.params, target_engine.model_config, k=4
        )
        prompts = ["speculate on this", "another prompt"]
        got = spec.generate(prompts, max_new_tokens=12)
        ref = target_engine.generate(prompts, max_new_tokens=12, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in ref]
        # perfect agreement: acceptance near the k+1 ceiling
        assert spec.tokens_per_round > 3.0

    def test_weak_draft_still_exact(self, target_engine):
        """An unrelated random draft mostly disagrees — output must STILL be
        bit-identical to target greedy; only speed differs."""
        draft_cfg = LlamaConfig.tiny()
        draft_params = init_llama(jax.random.PRNGKey(999), draft_cfg)
        spec = SpeculativeDecoder(target_engine, draft_params, draft_cfg, k=3)
        prompts = ["a different draft model", "with other weights", "third"]
        got = spec.generate(prompts, max_new_tokens=14)
        ref = target_engine.generate(prompts, max_new_tokens=14, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in ref]
        # weak draft: most rounds emit just the correction token
        assert 1.0 <= spec.tokens_per_round <= 4.0

    def test_smaller_draft_geometry(self, target_engine):
        """The realistic shape: a shallower/narrower draft of the same
        vocab."""
        draft_cfg = LlamaConfig(
            vocab_size=512, dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
            mlp_dim=64, max_len=512, rope_theta=10_000.0,
        )
        draft_params = init_llama(jax.random.PRNGKey(7), draft_cfg)
        spec = SpeculativeDecoder(target_engine, draft_params, draft_cfg, k=4)
        prompts = ["tiny draft, tiny target"]
        got = spec.generate(prompts, max_new_tokens=10)
        ref = target_engine.generate(prompts, max_new_tokens=10, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in ref]


class TestMoeTarget:
    def test_moe_target_llama_draft_exact(self):
        """Routed target + dense draft: routing sees the spec path's pad
        mask, so with batch-size-independent (ample) capacity the output is
        still greedy-exact."""
        from dataclasses import replace

        from sentio_tpu.models.moe import MoeConfig, init_moe

        cfg = replace(MoeConfig.tiny(), capacity_factor=8.0)
        engine = GeneratorEngine(
            config=GeneratorConfig(model_preset="tiny", max_new_tokens=12),
            model_config=cfg,
            params=init_moe(jax.random.PRNGKey(0), cfg),
        )
        draft_cfg = LlamaConfig.tiny()
        spec = SpeculativeDecoder(
            engine, init_llama(jax.random.PRNGKey(3), draft_cfg), draft_cfg, k=3
        )
        prompts = ["routed target", "dense draft"]
        got = spec.generate(prompts, max_new_tokens=10)
        ref = engine.generate(prompts, max_new_tokens=10, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in ref]


class TestSampledSpeculation:
    def test_acceptance_kernel_preserves_target_distribution(self):
        """The whole-point property of rejection-sampling speculation: the
        marginal of the FIRST emitted token equals the target distribution,
        for an arbitrary (mismatched) draft. Empirical check over 40k
        independent single-round draws on a toy vocab."""
        from sentio_tpu.runtime.speculative import accept_and_correct

        v, k, n = 6, 1, 40_000
        rng = np.random.default_rng(0)
        p_t = rng.dirichlet(np.ones(v))          # target dist
        q = rng.dirichlet(np.ones(v) * 0.3)      # very different draft dist

        tprobs = jnp.asarray(
            np.broadcast_to(p_t, (n, k + 1, v)).copy(), jnp.float32
        )
        qdists = jnp.asarray(np.broadcast_to(q, (n, k, v)).copy(), jnp.float32)
        keys = jax.random.split(jax.random.PRNGKey(1), n + 1)
        drafts = jax.random.categorical(
            keys[0], jnp.log(qdists[:, 0] + 1e-20), axis=-1
        )[:, None].astype(jnp.int32)

        def one(key, d):
            n_acc, corr = accept_and_correct(
                key, d[None], qdists[:1], tprobs[:1]
            )
            # first emitted token: the draft if accepted, else the correction
            return jnp.where(n_acc[0] > 0, d[0], corr[0])

        emitted = np.asarray(jax.vmap(one)(keys[1:], drafts))
        freq = np.bincount(emitted, minlength=v) / n
        np.testing.assert_allclose(freq, p_t, atol=0.015)

    def test_sampled_generate_runs_and_is_seed_deterministic(self, target_engine):
        draft_cfg = LlamaConfig.tiny()
        draft_params = init_llama(jax.random.PRNGKey(999), draft_cfg)
        spec = SpeculativeDecoder(target_engine, draft_params, draft_cfg, k=3)

        target_engine._rng = jax.random.PRNGKey(42)
        a = spec.generate(["sampled round"], max_new_tokens=10, temperature=0.7)
        target_engine._rng = jax.random.PRNGKey(42)
        b = spec.generate(["sampled round"], max_new_tokens=10, temperature=0.7)
        assert a[0].tokens == b[0].tokens  # same rng → same stream
        assert 1 <= len(a[0].tokens) <= 10

    def test_sampled_vs_greedy_paths_differ_only_by_sampling(self, target_engine):
        """temperature→0 sampled acceptance degenerates to greedy: the
        categorical at inv_t=1e6-scaled logits is argmax almost surely."""
        spec = SpeculativeDecoder(
            target_engine, target_engine.params, target_engine.model_config, k=3
        )
        greedy = spec.generate(["limit check"], max_new_tokens=8, temperature=0.0)
        cold = spec.generate(["limit check"], max_new_tokens=8, temperature=1e-5)
        assert greedy[0].tokens == cold[0].tokens


class TestServingIntegration:
    def test_provider_routes_greedy_calls_through_spec(self, target_engine):
        from sentio_tpu.ops.generator import TpuProvider

        spec = SpeculativeDecoder(
            target_engine, target_engine.params, target_engine.model_config, k=3
        )
        provider = TpuProvider(engine=target_engine, speculative=spec)
        before = dict(spec.stats)
        text = provider.chat("route me", max_new_tokens=6, temperature=0.0)
        assert spec.stats["rounds"] > before["rounds"]  # greedy spec path
        # sampled calls also route through spec (rejection-sampling
        # acceptance is distribution-exact)
        before = dict(spec.stats)
        provider.chat("sampled", max_new_tokens=6, temperature=0.7)
        assert spec.stats["rounds"] > before["rounds"]
        assert isinstance(text, str)

    def test_container_builds_spec_from_draft_checkpoint(self, tmp_path):
        from sentio_tpu.config import Settings
        from sentio_tpu.models.llama import LlamaConfig, init_llama
        from sentio_tpu.runtime.checkpoint import save_pytree
        from sentio_tpu.serve.dependencies import DependencyContainer

        draft_cfg = LlamaConfig.tiny()
        ck = str(tmp_path / "draft-ck")
        save_pytree(
            ck, init_llama(jax.random.PRNGKey(5), draft_cfg),
            meta={"family": "llama", "config": draft_cfg.__dict__},
        )
        settings = Settings()
        settings.generator.provider = "tpu"
        settings.generator.model_preset = "tiny"
        settings.generator.draft_checkpoint_path = ck
        settings.generator.speculative_k = 2
        settings.generator.use_paged_decode = False
        container = DependencyContainer(settings=settings)
        # the 8-device test conftest would give DI a CPU mesh; production
        # single-chip serving (where spec applies) has mesh=None
        container.override("mesh", None)
        spec = container.speculative
        assert spec is not None and spec.k == 2
        gen = container.generator
        assert gen.provider.speculative is spec


class TestContracts:
    def test_vocab_mismatch_rejected(self, target_engine):
        draft_cfg = LlamaConfig(
            vocab_size=300, dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
            mlp_dim=64, max_len=512,
        )
        with pytest.raises(SpeculativeError, match="vocab"):
            SpeculativeDecoder(
                target_engine, init_llama(jax.random.PRNGKey(1), draft_cfg),
                draft_cfg,
            )

    def test_bad_k_rejected(self, target_engine):
        with pytest.raises(SpeculativeError, match="k must"):
            SpeculativeDecoder(
                target_engine, target_engine.params,
                target_engine.model_config, k=0,
            )

    def test_finish_reasons_match_plain_engine(self, target_engine):
        spec = SpeculativeDecoder(
            target_engine, target_engine.params, target_engine.model_config, k=2
        )
        got = spec.generate(["finish reason check"], max_new_tokens=8)[0]
        ref = target_engine.generate(
            ["finish reason check"], max_new_tokens=8, temperature=0.0
        )[0]
        assert got.finish_reason == ref.finish_reason
        assert got.prompt_tokens == ref.prompt_tokens
