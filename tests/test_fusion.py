import pytest

from sentio_tpu.models.document import Document
from sentio_tpu.ops.fusion import fuse


def _docs(ids_scores):
    return [
        Document(text=f"text {i}", id=i, metadata={"score": s}) for i, s in ids_scores
    ]


def test_rrf_prefers_doc_in_both_lists():
    a = _docs([("x", 9.0), ("y", 5.0), ("z", 1.0)])
    b = _docs([("y", 0.8), ("w", 0.5)])
    fused = fuse([a, b], method="rrf", rrf_k=60)
    assert fused[0].id == "y"  # appears in both lists
    assert fused[0].metadata["hybrid_score"] == pytest.approx(1 / 61 + 1 / 62)


def test_rrf_ignores_weights_but_weighted_rrf_uses_them():
    a = _docs([("a", 1.0)])
    b = _docs([("b", 1.0)])
    plain = fuse([a, b], method="rrf", weights=[0.1, 10.0])
    assert plain[0].metadata["hybrid_score"] == pytest.approx(plain[1].metadata["hybrid_score"])
    weighted = fuse([a, b], method="weighted_rrf", weights=[0.1, 10.0])
    assert weighted[0].id == "b"


def test_comb_sum_minmax_normalizes_scales():
    # list A scores in [0, 100], list B in [0, 1]; normalization equalizes them
    a = _docs([("a1", 100.0), ("a2", 50.0), ("a3", 0.0)])
    b = _docs([("b1", 1.0), ("a2", 0.6), ("b3", 0.0)])
    fused = fuse([a, b], method="comb_sum", weights=[1.0, 1.0])
    by_id = {d.id: d.metadata["hybrid_score"] for d in fused}
    assert by_id["a1"] == pytest.approx(1.0)
    assert by_id["a2"] == pytest.approx(0.5 + 0.6)
    assert fused[0].id == "a2"


def test_dedup_merges_metadata():
    a = [Document(text="t", id="d", metadata={"score": 1.0, "from_dense": True})]
    b = [Document(text="t", id="d", metadata={"score": 5.0, "from_sparse": True})]
    fused = fuse([a, b], method="rrf")
    assert len(fused) == 1
    assert fused[0].metadata["from_dense"] and fused[0].metadata["from_sparse"]


def test_top_k_truncates():
    a = _docs([(f"d{i}", 10.0 - i) for i in range(10)])
    assert len(fuse([a], method="rrf", top_k=3)) == 3


def test_unknown_method_raises():
    with pytest.raises(ValueError):
        fuse([], method="bogus")


def test_constant_scores_normalize_to_one():
    a = _docs([("a", 5.0), ("b", 5.0)])
    fused = fuse([a], method="comb_sum")
    assert all(d.metadata["hybrid_score"] == pytest.approx(1.0) for d in fused)
