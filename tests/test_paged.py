"""Paged KV cache + continuous batching (runtime/paged.py).

The correctness bar: a paged, continuously-batched greedy decode must emit
EXACTLY the tokens the contiguous-cache GeneratorEngine emits for the same
params — paging is a memory layout, not a model change.
"""

import numpy as np
import pytest

from sentio_tpu.config import GeneratorConfig
from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.runtime.engine import GeneratorEngine
from sentio_tpu.runtime.paged import (
    ContinuousBatchingEngine,
    PageAllocator,
    init_pool,
)

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def contiguous(cfg):
    return GeneratorEngine(
        config=GeneratorConfig(provider="tpu", model_preset="tiny", max_new_tokens=16),
        model_config=cfg,
        rng_seed=0,
    )


@pytest.fixture(scope="module")
def paged(cfg, contiguous):
    # share the exact same params so greedy outputs are comparable
    return ContinuousBatchingEngine(
        model_config=cfg,
        params=contiguous.params,
        tokenizer=contiguous.tokenizer,
        max_slots=4,
        page_size=16,
        max_pages_per_seq=8,
    )


class TestAllocator:
    def test_alloc_free_roundtrip(self):
        a = PageAllocator(9)
        assert a.free_pages == 8
        pages = a.alloc(5)
        assert len(set(pages)) == 5 and 0 not in pages
        a.free(pages)
        assert a.free_pages == 8

    def test_exhaustion_raises(self):
        a = PageAllocator(4)
        a.alloc(3)
        with pytest.raises(MemoryError):
            a.alloc(1)

    def test_scratch_never_freed_into_pool(self):
        a = PageAllocator(4)
        a.free([0, 0])
        assert a.free_pages == 3


class TestPool:
    def test_shapes(self, cfg):
        pool = init_pool(cfg, num_pages=5, page_size=8)
        assert pool.k.shape == (cfg.n_layers, 5, 8, cfg.n_kv_heads, cfg.head_dim)
        assert pool.num_pages == 5


class TestPagedMatchesContiguous:
    def test_single_prompt_greedy(self, contiguous, paged):
        prompt = "paged equivalence check"
        ref = contiguous.generate([prompt], max_new_tokens=12, temperature=0.0)[0]
        got = paged.run_all([prompt], max_new_tokens=12, temperature=0.0)[0]
        assert got.tokens == ref.tokens
        assert got.text == ref.text
        assert got.finish_reason == ref.finish_reason

    def test_mixed_length_batch_greedy(self, contiguous, paged):
        prompts = ["a", "a much longer prompt that spans several pages of cache " * 2, "mid size"]
        refs = [contiguous.generate([p], max_new_tokens=10, temperature=0.0)[0] for p in prompts]
        got = paged.run_all(prompts, max_new_tokens=10, temperature=0.0)
        for r, g in zip(refs, got):
            assert g.tokens == r.tokens

    def test_pages_reclaimed_after_drain(self, paged):
        before = paged.allocator.free_pages
        paged.run_all(["reclaim one", "reclaim two"], max_new_tokens=6)
        assert paged.allocator.free_pages == before
        assert all(not s.active for s in paged.slots)


class TestContinuousAdmission:
    def test_staggered_arrivals_match_isolated_runs(self, contiguous, paged):
        """Requests joining mid-flight must not perturb rows already decoding."""
        early = "first request decoding"
        late = "latecomer joins the batch"
        ref_early = contiguous.generate([early], max_new_tokens=12, temperature=0.0)[0]
        ref_late = contiguous.generate([late], max_new_tokens=12, temperature=0.0)[0]

        rid_early = paged.submit(early, max_new_tokens=12, temperature=0.0)
        done = {}
        ticks = 0
        rid_late = None
        while paged.has_work or rid_late is None:
            if ticks == 3 and rid_late is None:
                rid_late = paged.submit(late, max_new_tokens=12, temperature=0.0)
            for r in paged.step():
                done[r.request_id] = r
            ticks += 1
            assert ticks < 200
        assert done[rid_early].tokens == ref_early.tokens
        assert done[rid_late].tokens == ref_late.tokens

    def test_more_requests_than_slots(self, paged):
        prompts = [f"queue pressure {i}" for i in range(9)]  # > max_slots=4
        results = paged.run_all(prompts, max_new_tokens=5)
        assert len(results) == 9
        assert all(len(r.tokens) <= 5 for r in results)
        assert all(not s.active for s in paged.slots)

    def test_stats_shape(self, paged):
        s = paged.stats()
        assert s["max_slots"] == 4
        assert s["active_slots"] == 0
        # idle engine: every page is either free or retained by the radix
        # prefix cache (plus the reserved scratch page)
        assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
            == s["total_pages"] - 1


class TestPagedAttentionKernel:
    def test_kernel_matches_xla_gather(self, cfg):
        """Pallas page-table walk (interpret mode) ≡ XLA gather attention."""
        import jax
        import jax.numpy as jnp

        from sentio_tpu.kernels.paged_attention import paged_attention
        from sentio_tpu.runtime.paged import _paged_attn_xla

        rng = np.random.default_rng(0)
        b, h, hkv, d, page, num_pages, nb = 3, 4, 2, 16, 8, 13, 4
        q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((num_pages, page, hkv, d)), jnp.float32)
        vp = jnp.asarray(rng.standard_normal((num_pages, page, hkv, d)), jnp.float32)
        # each row owns a distinct shuffled set of pages; varied lengths
        table = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]], jnp.int32)
        lens = jnp.asarray([5, 17, 30], jnp.int32)

        ref = _paged_attn_xla(q, kp, vp, table, lens, h // hkv)[:, 0]
        got = paged_attention(q[:, 0], kp, vp, table, lens, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_engine_with_kernel_matches_contiguous(self, cfg, contiguous):
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=contiguous.params, tokenizer=contiguous.tokenizer,
            max_slots=2, page_size=16, max_pages_per_seq=8, use_pallas=True,
        )
        prompt = "kernel path equivalence"
        ref = contiguous.generate([prompt], max_new_tokens=8, temperature=0.0)[0]
        got = eng.run_all([prompt], max_new_tokens=8, temperature=0.0)[0]
        assert got.tokens == ref.tokens

    def test_int8_engine_with_kernel_churn_conserves_pages(self, cfg, contiguous):
        """KV_QUANT=int8 + the quantization-native Pallas kernel (interpret
        on CPU) through an admission-churn workload, with the sanitizer
        (armed for this module) checking pool conservation on the dict-repr
        pool every tick."""
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=contiguous.params,
            tokenizer=contiguous.tokenizer, max_slots=2, page_size=16,
            max_pages_per_seq=4, use_pallas=True, kv_quant="int8",
        )
        before = eng.allocator.free_pages + (
            eng._radix.pages_held if eng._radix is not None else 0)
        results = eng.run_all(
            [f"churn request {i} padding to cross pages" for i in range(5)],
            max_new_tokens=6, temperature=0.0,
        )
        assert len(results) == 5
        assert all(r.finish_reason in ("stop", "length") for r in results)
        after = eng.allocator.free_pages + (
            eng._radix.pages_held if eng._radix is not None else 0)
        assert after == before
        assert all(not s.active for s in eng.slots)


class TestBudgets:
    def test_length_budget_respected(self, paged):
        r = paged.run_all(["short budget"], max_new_tokens=3)[0]
        assert len(r.tokens) <= 3

    def test_per_row_temperatures(self, cfg, contiguous):
        """Greedy and hot rows coexist in one batch; greedy row stays exact."""
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=contiguous.params, tokenizer=contiguous.tokenizer,
            max_slots=2, page_size=16, max_pages_per_seq=8, rng_seed=7,
        )
        ref = contiguous.generate(["cold row"], max_new_tokens=8, temperature=0.0)[0]
        rid_cold = eng.submit("cold row", max_new_tokens=8, temperature=0.0)
        eng.submit("hot row", max_new_tokens=8, temperature=1.5)
        done = {}
        while eng.has_work:
            for r in eng.step():
                done[r.request_id] = r
        assert done[rid_cold].tokens == ref.tokens


class TestMultiStepTick:
    def test_steps_per_tick_greedy_equivalence(self, cfg, contiguous):
        """Fusing N decode sub-steps into one dispatch is a scheduling
        change, not a model change: greedy tokens must be bit-identical."""
        prompts = ["alpha prompt", "a", "gamma prompt with a longer tail of text"]
        outs = {}
        for steps in (1, 4, 8):
            eng = ContinuousBatchingEngine(
                model_config=cfg, params=contiguous.params,
                tokenizer=contiguous.tokenizer, max_slots=4, page_size=16,
                max_pages_per_seq=8, steps_per_tick=steps,
            )
            outs[steps] = [
                r.tokens for r in eng.run_all(prompts, max_new_tokens=20, temperature=0.0)
            ]
        assert outs[1] == outs[4] == outs[8]

    def test_fewer_ticks_with_fused_steps(self, cfg, contiguous):
        def count_ticks(steps):
            eng = ContinuousBatchingEngine(
                model_config=cfg, params=contiguous.params,
                tokenizer=contiguous.tokenizer, max_slots=2, page_size=16,
                max_pages_per_seq=8, steps_per_tick=steps,
            )
            eng.submit("count the ticks", max_new_tokens=16, temperature=0.0)
            ticks = 0
            while eng.has_work:
                eng.step()
                ticks += 1
                assert ticks < 100
            return ticks

        assert count_ticks(8) <= (count_ticks(1) + 7) // 8 + 1


class TestBatchedAdmission:
    def test_admit_scatter_fault_point_armed(self, paged):
        """The ``paged.admit_scatter`` chaos seam is live: a benign delay
        rule armed at the prefill-scatter dispatch must be hit during
        admission without disturbing the decode output."""
        from sentio_tpu.infra import faults

        faults.reset()
        try:
            with faults.inject("paged.admit_scatter", delay_s=0.01) as rule:
                out = paged.run_all(["fault point probe"],
                                    max_new_tokens=4, temperature=0.0)
            assert rule.hits >= 1
            assert out[0].tokens
        finally:
            faults.reset()

    def test_burst_admission_dispatch_count(self, cfg, contiguous):
        """Admitting N same-width-bucket requests must cost at most
        ceil(N / max_batch_bucket) prefill dispatches, not N."""
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=contiguous.params,
            tokenizer=contiguous.tokenizer, max_slots=8, page_size=16,
            max_pages_per_seq=8,
        )
        calls = []
        real = eng._prefill_scatter

        def counting(*args, **kwargs):
            calls.append(args[1].shape)  # ids [rows, width]
            return real(*args, **kwargs)

        eng._prefill_scatter = counting
        n = 6  # same width bucket
        rids = [
            eng.submit(f"burst request {i}", max_new_tokens=4, temperature=0.0)
            for i in range(n)
        ]
        done = {r.request_id: r for r in eng.step()}  # one tick admits the burst
        max_bucket = max(eng.ADMIT_BUCKETS)
        assert len(calls) <= -(-n // max_bucket), calls
        # and the admitted rows decode to the same greedy tokens as isolated runs
        while eng.has_work:
            for r in eng.step():
                done[r.request_id] = r
        assert set(done) == set(rids)
        ref = contiguous.generate(["burst request 0"], max_new_tokens=4, temperature=0.0)[0]
        assert done[rids[0]].tokens == ref.tokens

    def test_mixed_width_burst_groups_by_bucket(self, cfg, contiguous):
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=contiguous.params,
            tokenizer=contiguous.tokenizer, max_slots=8, page_size=16,
            max_pages_per_seq=8,
        )
        calls = []
        real = eng._prefill_scatter

        def counting(*args, **kwargs):
            calls.append(args[1].shape)
            return real(*args, **kwargs)

        eng._prefill_scatter = counting
        eng.submit("short", max_new_tokens=2, temperature=0.0)
        eng.submit("x" * 60, max_new_tokens=2, temperature=0.0)  # wider bucket
        eng.submit("tiny", max_new_tokens=2, temperature=0.0)
        eng.step()
        widths = sorted(shape[1] for shape in calls)
        assert len(calls) == 2  # two width groups, one dispatch each
        assert widths[0] < widths[1]


class TestMeshShardedEngine:
    def test_tp_sharded_pool_matches_single_device(self, cfg, contiguous):
        import jax

        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import build_mesh
        from sentio_tpu.parallel.sharding import LLAMA_TP_RULES, shard_params

        mesh = build_mesh(MeshConfig(dp_size=4, tp_size=2))
        params = shard_params(contiguous.params, mesh, LLAMA_TP_RULES)
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=params, tokenizer=contiguous.tokenizer,
            mesh=mesh, max_slots=4, page_size=16, max_pages_per_seq=8,
            steps_per_tick=4,
        )
        from sentio_tpu.parallel.mesh import AXIS_TP

        assert eng.pool.k.sharding.spec == jax.sharding.PartitionSpec(
            None, None, None, AXIS_TP, None
        )
        prompts = ["mesh request one", "mesh request two"]
        got = eng.run_all(prompts, max_new_tokens=8, temperature=0.0)
        ref = contiguous.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in ref]

    def test_kv_heads_not_divisible_by_tp_raises(self, cfg, contiguous):
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import build_mesh

        mesh = build_mesh(MeshConfig(dp_size=1, sp_size=2, tp_size=4))
        with pytest.raises(ValueError, match="n_kv_heads"):
            ContinuousBatchingEngine(
                model_config=cfg, params=contiguous.params,
                tokenizer=contiguous.tokenizer, mesh=mesh, max_slots=2,
            )

    def test_reset_preserves_pool_sharding(self, cfg, contiguous):
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import AXIS_TP, build_mesh
        from sentio_tpu.parallel.sharding import LLAMA_TP_RULES, shard_params

        mesh = build_mesh(MeshConfig(dp_size=4, tp_size=2))
        params = shard_params(contiguous.params, mesh, LLAMA_TP_RULES)
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=params, tokenizer=contiguous.tokenizer,
            mesh=mesh, max_slots=2, page_size=16, max_pages_per_seq=8,
        )
        eng.reset()
        assert AXIS_TP in str(eng.pool.k.sharding.spec)
        assert eng.run_all(["after reset"], max_new_tokens=4)[0].finish_reason


class TestPipelinedTicks:
    """pipeline_depth=2 dispatches tick N+1 before fetching tick N — a pure
    scheduling change: greedy outputs must be bit-identical to depth 1,
    including under heavy slot churn and staggered admissions."""

    def _run(self, contiguous, cfg, depth, prompts, max_new, slots=4,
             steps=4, max_tick=8):
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=contiguous.params,
            tokenizer=contiguous.tokenizer, max_slots=slots, page_size=16,
            max_pages_per_seq=8, steps_per_tick=steps, max_tick_steps=max_tick,
            pipeline_depth=depth,
        )
        return [r.tokens for r in eng.run_all(prompts, max_new_tokens=max_new,
                                              temperature=0.0)]

    def test_greedy_equivalence(self, cfg, contiguous):
        prompts = ["alpha prompt", "a", "third prompt with a longer tail of text"]
        a = self._run(contiguous, cfg, 1, prompts, 20)
        b = self._run(contiguous, cfg, 2, prompts, 20)
        assert a == b

    def test_slot_churn_equivalence(self, cfg, contiguous):
        # 10 short requests through 2 slots: constant retire + reuse while a
        # speculative tick is in flight — exercises the stale-lane guard
        prompts = [f"churn request {i}" for i in range(10)]
        a = self._run(contiguous, cfg, 1, prompts, 5, slots=2)
        b = self._run(contiguous, cfg, 2, prompts, 5, slots=2)
        assert a == b

    def test_staggered_equivalence(self, cfg, contiguous):
        def staggered(depth):
            eng = ContinuousBatchingEngine(
                model_config=cfg, params=contiguous.params,
                tokenizer=contiguous.tokenizer, max_slots=4, page_size=16,
                max_pages_per_seq=8, steps_per_tick=4, pipeline_depth=depth,
            )
            rid_a = eng.submit("early request", max_new_tokens=16, temperature=0.0)
            done, ticks, rid_b = {}, 0, None
            while eng.has_work or rid_b is None:
                if ticks == 2 and rid_b is None:
                    rid_b = eng.submit("latecomer request", max_new_tokens=10,
                                       temperature=0.0)
                for r in eng.step():
                    done[r.request_id] = r
                ticks += 1
                assert ticks < 300
            return done[rid_a].tokens, done[rid_b].tokens

        assert staggered(1) == staggered(2)

    def test_varied_max_new_equivalence(self, cfg, contiguous):
        def run(depth):
            eng = ContinuousBatchingEngine(
                model_config=cfg, params=contiguous.params,
                tokenizer=contiguous.tokenizer, max_slots=4, page_size=16,
                max_pages_per_seq=8, steps_per_tick=4, max_tick_steps=16,
                pipeline_depth=depth,
            )
            rids = [eng.submit(f"varied {i}", max_new_tokens=n, temperature=0.0)
                    for i, n in enumerate([1, 7, 23, 4, 16])]
            done = {}
            ticks = 0
            while eng.has_work:
                for r in eng.step():
                    done[r.request_id] = r
                ticks += 1
                assert ticks < 300
            return [done[r].tokens for r in rids]

        assert run(1) == run(2)


class TestSingleTokenBurst:
    def test_max_new_one_burst_no_scan(self, cfg, contiguous):
        """max_new=1 bursts fold deferred first tokens with a direct fetch —
        no masked decode scan — and still match the contiguous engine."""
        for depth in (1, 2):
            eng = ContinuousBatchingEngine(
                model_config=cfg, params=contiguous.params,
                tokenizer=contiguous.tokenizer, max_slots=4, page_size=16,
                max_pages_per_seq=8, steps_per_tick=4, pipeline_depth=depth,
            )
            prompts = [f"one token {i}" for i in range(6)]
            sub_steps_before = eng.total_sub_steps
            got = eng.run_all(prompts, max_new_tokens=1, temperature=0.0)
            assert eng.total_sub_steps == sub_steps_before, "no scan should run"
            refs = [
                contiguous.generate([p], max_new_tokens=1, temperature=0.0)[0]
                for p in prompts
            ]
            assert [r.tokens for r in got] == [r.tokens for r in refs]
