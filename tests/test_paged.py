"""Paged KV cache + continuous batching (runtime/paged.py).

The correctness bar: a paged, continuously-batched greedy decode must emit
EXACTLY the tokens the contiguous-cache GeneratorEngine emits for the same
params — paging is a memory layout, not a model change.
"""

import numpy as np
import pytest

from sentio_tpu.config import GeneratorConfig
from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.runtime.engine import GeneratorEngine
from sentio_tpu.runtime.paged import (
    ContinuousBatchingEngine,
    PageAllocator,
    init_pool,
)


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def contiguous(cfg):
    return GeneratorEngine(
        config=GeneratorConfig(provider="tpu", model_preset="tiny", max_new_tokens=16),
        model_config=cfg,
        rng_seed=0,
    )


@pytest.fixture(scope="module")
def paged(cfg, contiguous):
    # share the exact same params so greedy outputs are comparable
    return ContinuousBatchingEngine(
        model_config=cfg,
        params=contiguous.params,
        tokenizer=contiguous.tokenizer,
        max_slots=4,
        page_size=16,
        max_pages_per_seq=8,
    )


class TestAllocator:
    def test_alloc_free_roundtrip(self):
        a = PageAllocator(9)
        assert a.free_pages == 8
        pages = a.alloc(5)
        assert len(set(pages)) == 5 and 0 not in pages
        a.free(pages)
        assert a.free_pages == 8

    def test_exhaustion_raises(self):
        a = PageAllocator(4)
        a.alloc(3)
        with pytest.raises(MemoryError):
            a.alloc(1)

    def test_scratch_never_freed_into_pool(self):
        a = PageAllocator(4)
        a.free([0, 0])
        assert a.free_pages == 3


class TestPool:
    def test_shapes(self, cfg):
        pool = init_pool(cfg, num_pages=5, page_size=8)
        assert pool.k.shape == (cfg.n_layers, 5, 8, cfg.n_kv_heads, cfg.head_dim)
        assert pool.num_pages == 5


class TestPagedMatchesContiguous:
    def test_single_prompt_greedy(self, contiguous, paged):
        prompt = "paged equivalence check"
        ref = contiguous.generate([prompt], max_new_tokens=12, temperature=0.0)[0]
        got = paged.run_all([prompt], max_new_tokens=12, temperature=0.0)[0]
        assert got.tokens == ref.tokens
        assert got.text == ref.text
        assert got.finish_reason == ref.finish_reason

    def test_mixed_length_batch_greedy(self, contiguous, paged):
        prompts = ["a", "a much longer prompt that spans several pages of cache " * 2, "mid size"]
        refs = [contiguous.generate([p], max_new_tokens=10, temperature=0.0)[0] for p in prompts]
        got = paged.run_all(prompts, max_new_tokens=10, temperature=0.0)
        for r, g in zip(refs, got):
            assert g.tokens == r.tokens

    def test_pages_reclaimed_after_drain(self, paged):
        before = paged.allocator.free_pages
        paged.run_all(["reclaim one", "reclaim two"], max_new_tokens=6)
        assert paged.allocator.free_pages == before
        assert all(not s.active for s in paged.slots)


class TestContinuousAdmission:
    def test_staggered_arrivals_match_isolated_runs(self, contiguous, paged):
        """Requests joining mid-flight must not perturb rows already decoding."""
        early = "first request decoding"
        late = "latecomer joins the batch"
        ref_early = contiguous.generate([early], max_new_tokens=12, temperature=0.0)[0]
        ref_late = contiguous.generate([late], max_new_tokens=12, temperature=0.0)[0]

        rid_early = paged.submit(early, max_new_tokens=12, temperature=0.0)
        done = {}
        ticks = 0
        rid_late = None
        while paged.has_work or rid_late is None:
            if ticks == 3 and rid_late is None:
                rid_late = paged.submit(late, max_new_tokens=12, temperature=0.0)
            for r in paged.step():
                done[r.request_id] = r
            ticks += 1
            assert ticks < 200
        assert done[rid_early].tokens == ref_early.tokens
        assert done[rid_late].tokens == ref_late.tokens

    def test_more_requests_than_slots(self, paged):
        prompts = [f"queue pressure {i}" for i in range(9)]  # > max_slots=4
        results = paged.run_all(prompts, max_new_tokens=5)
        assert len(results) == 9
        assert all(len(r.tokens) <= 5 for r in results)
        assert all(not s.active for s in paged.slots)

    def test_stats_shape(self, paged):
        s = paged.stats()
        assert s["max_slots"] == 4
        assert s["active_slots"] == 0
        assert s["free_pages"] == s["total_pages"] - 1  # minus scratch


class TestPagedAttentionKernel:
    def test_kernel_matches_xla_gather(self, cfg):
        """Pallas page-table walk (interpret mode) ≡ XLA gather attention."""
        import jax
        import jax.numpy as jnp

        from sentio_tpu.kernels.paged_attention import paged_attention
        from sentio_tpu.runtime.paged import _paged_attn_xla

        rng = np.random.default_rng(0)
        b, h, hkv, d, page, num_pages, nb = 3, 4, 2, 16, 8, 13, 4
        q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((num_pages, page, hkv, d)), jnp.float32)
        vp = jnp.asarray(rng.standard_normal((num_pages, page, hkv, d)), jnp.float32)
        # each row owns a distinct shuffled set of pages; varied lengths
        table = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]], jnp.int32)
        lens = jnp.asarray([5, 17, 30], jnp.int32)

        ref = _paged_attn_xla(q, kp, vp, table, lens, h // hkv)[:, 0]
        got = paged_attention(q[:, 0], kp, vp, table, lens, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_engine_with_kernel_matches_contiguous(self, cfg, contiguous):
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=contiguous.params, tokenizer=contiguous.tokenizer,
            max_slots=2, page_size=16, max_pages_per_seq=8, use_pallas=True,
        )
        prompt = "kernel path equivalence"
        ref = contiguous.generate([prompt], max_new_tokens=8, temperature=0.0)[0]
        got = eng.run_all([prompt], max_new_tokens=8, temperature=0.0)[0]
        assert got.tokens == ref.tokens


class TestBudgets:
    def test_length_budget_respected(self, paged):
        r = paged.run_all(["short budget"], max_new_tokens=3)[0]
        assert len(r.tokens) <= 3

    def test_per_row_temperatures(self, cfg, contiguous):
        """Greedy and hot rows coexist in one batch; greedy row stays exact."""
        eng = ContinuousBatchingEngine(
            model_config=cfg, params=contiguous.params, tokenizer=contiguous.tokenizer,
            max_slots=2, page_size=16, max_pages_per_seq=8, rng_seed=7,
        )
        ref = contiguous.generate(["cold row"], max_new_tokens=8, temperature=0.0)[0]
        rid_cold = eng.submit("cold row", max_new_tokens=8, temperature=0.0)
        eng.submit("hot row", max_new_tokens=8, temperature=1.5)
        done = {}
        while eng.has_work:
            for r in eng.step():
                done[r.request_id] = r
        assert done[rid_cold].tokens == ref.tokens
