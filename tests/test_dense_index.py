import numpy as np
import pytest

from sentio_tpu.config import MeshConfig
from sentio_tpu.models.document import Document
from sentio_tpu.ops.dense_index import DenseIndexError, TpuDenseIndex
from sentio_tpu.parallel.mesh import build_mesh


def _corpus(n=20, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    embs = rng.standard_normal((n, dim)).astype(np.float32)
    docs = [Document(text=f"doc {i}", id=f"d{i}") for i in range(n)]
    return docs, embs


class TestSingleDevice:
    def test_exact_topk_matches_numpy(self):
        docs, embs = _corpus(50, 16)
        index = TpuDenseIndex(dim=16, dtype="float32")
        index.add(docs, embs)
        q = np.random.default_rng(1).standard_normal(16).astype(np.float32)
        hits = index.search(q, top_k=5)
        # numpy reference: cosine similarity
        en = embs / np.linalg.norm(embs, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q)
        expected = np.argsort(-(en @ qn))[:5]
        assert [h[0].id for h in hits] == [f"d{i}" for i in expected]
        np.testing.assert_allclose(
            [h[1] for h in hits], np.sort(en @ qn)[::-1][:5], atol=1e-5
        )

    def test_batch_search(self):
        docs, embs = _corpus(30, 8)
        index = TpuDenseIndex(dim=8, dtype="float32")
        index.add(docs, embs)
        qs = np.random.default_rng(2).standard_normal((4, 8)).astype(np.float32)
        results = index.search_batch(qs, top_k=3)
        assert len(results) == 4
        singles = [index.search(q, top_k=3) for q in qs]
        for batch_row, single in zip(results, singles):
            assert [d.id for d, _ in batch_row] == [d.id for d, _ in single]

    def test_delete_and_upsert(self):
        docs, embs = _corpus(10, 8)
        index = TpuDenseIndex(dim=8, dtype="float32")
        index.add(docs, embs)
        assert index.size == 10
        assert index.delete(["d3", "nope"]) == 1
        assert index.size == 9
        q = embs[3]
        assert all(d.id != "d3" for d, _ in index.search(q, top_k=9))
        # upsert d5 with d3's old embedding: must now match where d3 did
        index.add([Document(text="new d5", id="d5")], embs[3:4])
        assert index.size == 9
        top = index.search(embs[3], top_k=1)[0]
        assert top[0].id == "d5" and top[0].text == "new d5"

    def test_retrieve_sets_metadata(self):
        docs, embs = _corpus(5, 8)
        index = TpuDenseIndex(dim=8, dtype="float32")
        index.add(docs, embs)
        out = index.retrieve(embs[0], top_k=2)
        assert out[0].metadata["retriever"] == "dense"
        assert "score" in out[0].metadata

    def test_empty_and_validation(self):
        index = TpuDenseIndex(dim=8)
        assert index.search(np.zeros(8, np.float32)) == []
        with pytest.raises(DenseIndexError):
            index.add([Document(text="x")], np.zeros((1, 4), np.float32))
        with pytest.raises(DenseIndexError):
            index.add([Document(text="x"), Document(text="y")], np.zeros((1, 8)))

    def test_save_load_roundtrip(self, tmp_path):
        docs, embs = _corpus(12, 8)
        index = TpuDenseIndex(dim=8, dtype="float32")
        index.add(docs, embs)
        index.delete(["d0"])
        index.save(tmp_path / "dense")
        loaded = TpuDenseIndex.load(tmp_path / "dense", dtype="float32")
        assert loaded.size == 11
        q = embs[5]
        orig = [(d.id, round(s, 5)) for d, s in index.search(q, 5)]
        new = [(d.id, round(s, 5)) for d, s in loaded.search(q, 5)]
        assert orig == new

    def test_top_k_larger_than_corpus(self):
        docs, embs = _corpus(3, 8)
        index = TpuDenseIndex(dim=8, dtype="float32")
        index.add(docs, embs)
        assert len(index.search(embs[0], top_k=50)) == 3


class TestShardedIndex:
    def test_sharded_matches_single_device(self):
        mesh = build_mesh(MeshConfig())  # 8-way dp over CPU devices
        docs, embs = _corpus(40, 16, seed=3)
        plain = TpuDenseIndex(dim=16, dtype="float32")
        plain.add(docs, embs)
        sharded = TpuDenseIndex(dim=16, mesh=mesh, dtype="float32")
        sharded.add(docs, embs)
        qs = np.random.default_rng(4).standard_normal((3, 16)).astype(np.float32)
        for q in qs:
            a = [(d.id, round(s, 4)) for d, s in plain.search(q, 7)]
            b = [(d.id, round(s, 4)) for d, s in sharded.search(q, 7)]
            assert a == b

    def test_sharded_small_corpus(self):
        """Fewer docs than devices — padding rows must never surface."""
        mesh = build_mesh(MeshConfig())
        docs, embs = _corpus(3, 8, seed=5)
        index = TpuDenseIndex(dim=8, mesh=mesh, dtype="float32")
        index.add(docs, embs)
        hits = index.search(embs[1], top_k=3)
        assert len(hits) == 3
        assert hits[0][0].id == "d1"


def test_compaction_bounds_dead_rows():
    docs, embs = _corpus(20, 8)
    index = TpuDenseIndex(dim=8, dtype="float32")
    index.add(docs, embs)
    # churn: upsert the same corpus repeatedly (tombstones old rows each time)
    for _ in range(5):
        fresh = [Document(text=d.text, id=d.id) for d in docs]
        index.add(fresh, embs)
    assert index.size == 20
    total_rows = len(index._documents)
    assert total_rows <= 20 * 1.5  # compaction kept the table bounded
    hits = index.search(embs[4], top_k=1)
    assert hits[0][0].id == "d4"


def test_duplicate_ids_in_one_add_batch():
    index = TpuDenseIndex(dim=8, dtype="float32")
    rng = np.random.default_rng(9)
    embs = rng.standard_normal((3, 8)).astype(np.float32)
    index.add(
        [Document(text="first", id="x"), Document(text="second", id="x"),
         Document(text="other", id="y")],
        embs,
    )
    assert index.size == 2  # last write wins for 'x'
    top = index.search(embs[1], top_k=1)[0]
    assert top[0].text == "second"
    assert index.delete(["x"]) == 1
    assert index.size == 1
    assert all(d.id == "y" for d, _ in index.search(embs[2], top_k=5))


class TestDeviceQueryPath:
    def test_search_batch_accepts_device_arrays(self, docs):
        import jax.numpy as jnp

        from sentio_tpu.ops.embedder import HashEmbedder
        from sentio_tpu.config import EmbedderConfig

        emb = HashEmbedder(EmbedderConfig(provider="hash", dim=32))
        vecs = emb.embed_many([d.text for d in docs])
        idx = TpuDenseIndex(dim=32, dtype="float32")
        idx.add(docs, vecs)
        q = vecs[2:3]
        host_hits = idx.search_batch(q, top_k=3)
        dev_hits = idx.search_batch(jnp.asarray(q), top_k=3)
        assert [d.id for d, _ in host_hits[0]] == [d.id for d, _ in dev_hits[0]]
        for (_, a), (_, b) in zip(host_hits[0], dev_hits[0]):
            assert abs(a - b) < 1e-4
