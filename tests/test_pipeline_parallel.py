"""Pipeline parallelism (parallel/pipeline.py): GPipe schedule over the pp
mesh axis must reproduce the plain stacked forward bit-for-bit-close, compose
with dp/tp, and differentiate through the ppermute handoffs."""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.config import MeshConfig
from sentio_tpu.models.llama import (
    LlamaConfig,
    init_llama,
    llama_loss,
    stack_layer_params,
    unstack_layer_params,
)
from sentio_tpu.parallel.mesh import build_mesh
from sentio_tpu.parallel.pipeline import (
    PipelineError,
    pipeline_loss,
    shard_stacked_params,
)

pytestmark = [pytest.mark.slow, pytest.mark.mesh]


@pytest.fixture(scope="module")
def cfg():
    # 4 layers so pp=2 gives two layers per stage (a real scan per stage)
    return replace(LlamaConfig.tiny(), n_layers=4)


@pytest.fixture(scope="module")
def params(cfg):
    return init_llama(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def batch(cfg):
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, 33)), jnp.int32)
    mask = jnp.ones((4, 33), bool)
    return ids, mask


def test_stack_unstack_roundtrip(cfg, params):
    stacked = stack_layer_params(params, cfg)
    back = unstack_layer_params(stacked, cfg)
    for path_leaf, orig_leaf in zip(
        jax.tree.leaves(back), jax.tree.leaves(params)
    ):
        np.testing.assert_array_equal(np.asarray(path_leaf), np.asarray(orig_leaf))


def test_pipeline_matches_reference_loss(cfg, params, batch):
    ids, mask = batch
    ref = float(llama_loss(params, cfg, ids, mask))
    mesh = build_mesh(MeshConfig(dp_size=2, pp_size=2, tp_size=2))
    stacked = shard_stacked_params(stack_layer_params(params, cfg), mesh)
    got = float(
        jax.jit(lambda s, i, m: pipeline_loss(s, cfg, i, m, mesh, n_micro=2))(
            stacked, ids, mask
        )
    )
    assert abs(got - ref) < 2e-2, (got, ref)


def test_pipeline_single_stage_path(cfg, params, batch):
    ids, mask = batch
    ref = float(llama_loss(params, cfg, ids, mask))
    mesh = build_mesh(MeshConfig(dp_size=8, pp_size=1))
    stacked = shard_stacked_params(stack_layer_params(params, cfg), mesh)
    got = float(
        jax.jit(lambda s, i, m: pipeline_loss(s, cfg, i, m, mesh, n_micro=2))(
            stacked, ids, mask
        )
    )
    assert abs(got - ref) < 2e-2, (got, ref)


def test_pipeline_four_stages(cfg, params, batch):
    ids, mask = batch
    ref = float(llama_loss(params, cfg, ids, mask))
    mesh = build_mesh(MeshConfig(dp_size=2, pp_size=4))
    stacked = shard_stacked_params(stack_layer_params(params, cfg), mesh)
    got = float(
        jax.jit(lambda s, i, m: pipeline_loss(s, cfg, i, m, mesh, n_micro=4))(
            stacked, ids, mask
        )
    )
    assert abs(got - ref) < 2e-2, (got, ref)


def test_pipeline_respects_pad_mask(cfg, params):
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, 33)), jnp.int32)
    mask = np.ones((4, 33), bool)
    mask[:, 25:] = False  # right-padded tail
    mask = jnp.asarray(mask)
    ref = float(llama_loss(params, cfg, ids, mask))
    mesh = build_mesh(MeshConfig(dp_size=4, pp_size=2))
    stacked = shard_stacked_params(stack_layer_params(params, cfg), mesh)
    got = float(
        jax.jit(lambda s, i, m: pipeline_loss(s, cfg, i, m, mesh, n_micro=2))(
            stacked, ids, mask
        )
    )
    assert abs(got - ref) < 2e-2, (got, ref)


def test_pipeline_grad_matches_reference(cfg, params, batch):
    ids, mask = batch
    mesh = build_mesh(MeshConfig(dp_size=2, pp_size=2, tp_size=2))
    stacked = shard_stacked_params(stack_layer_params(params, cfg), mesh)

    ref_grads = jax.grad(lambda p: llama_loss(p, cfg, ids, mask))(params)
    ref_stacked = stack_layer_params(ref_grads, cfg)

    got = jax.jit(
        jax.grad(lambda s: pipeline_loss(s, cfg, ids, mask, mesh, n_micro=2))
    )(stacked)

    ref_leaves = jax.tree.leaves(ref_stacked)
    got_leaves = jax.tree.leaves(jax.device_get(got))
    assert len(ref_leaves) == len(got_leaves)
    for r, g in zip(ref_leaves, got_leaves):
        r = np.asarray(r, np.float32)
        g = np.asarray(g, np.float32)
        denom = max(np.abs(r).max(), 1e-3)
        assert np.abs(r - g).max() / denom < 0.15, np.abs(r - g).max()


def test_pipeline_rejects_bad_geometry(cfg, params, batch):
    ids, mask = batch
    mesh = build_mesh(MeshConfig(dp_size=2, pp_size=4))
    cfg3 = replace(cfg, n_layers=3)
    params3 = init_llama(jax.random.PRNGKey(0), cfg3)
    with pytest.raises(PipelineError):
        shard_stacked_params(stack_layer_params(params3, cfg3), mesh)

    stacked = shard_stacked_params(stack_layer_params(params, cfg), mesh)
    with pytest.raises(PipelineError):
        pipeline_loss(stacked, cfg, ids, mask, mesh, n_micro=3)
