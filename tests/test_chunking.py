import pytest

from sentio_tpu.config import ChunkingConfig
from sentio_tpu.models.document import Document
from sentio_tpu.ops.chunking import ChunkingError, TextChunker


def test_short_text_single_chunk():
    chunker = TextChunker(ChunkingConfig(chunk_size=100, chunk_overlap=10))
    assert chunker.split_text("hello world") == ["hello world"]


def test_empty_text_no_chunks():
    chunker = TextChunker(ChunkingConfig())
    assert chunker.split_text("") == []
    assert chunker.split_text("   \n  ") == []


def test_chunks_respect_size():
    text = "para one.\n\n" + ("word " * 200) + "\n\nfinal para."
    chunker = TextChunker(ChunkingConfig(chunk_size=120, chunk_overlap=20))
    chunks = chunker.split_text(text)
    assert len(chunks) > 1
    assert all(len(c) <= 120 for c in chunks)


def test_no_content_lost():
    text = "alpha beta gamma. " * 50
    chunker = TextChunker(ChunkingConfig(chunk_size=80, chunk_overlap=0))
    chunks = chunker.split_text(text)
    assert "".join(chunks).replace(" ", "") == text.replace(" ", "").rstrip()


def test_overlap_carried():
    text = "abcdefghij " * 30
    chunker = TextChunker(ChunkingConfig(chunk_size=50, chunk_overlap=10, strategy="fixed"))
    chunks = chunker.split_text(text)
    for prev, nxt in zip(chunks, chunks[1:]):
        assert prev[-5:] in text  # overlap region exists in source


def test_split_documents_preserves_parent_metadata():
    chunker = TextChunker(ChunkingConfig(chunk_size=40, chunk_overlap=5))
    doc = Document(text="sentence one. " * 20, metadata={"source": "a.txt"}, id="doc-1")
    chunks = chunker.split([doc])
    assert len(chunks) > 1
    for i, c in enumerate(chunks):
        assert c.metadata["parent_id"] == "doc-1"
        assert c.metadata["chunk_index"] == i
        assert c.metadata["source"] == "a.txt"
        assert c.id == f"doc-1:{i}"
    stats = chunker.get_stats()
    assert stats["documents"] == 1
    assert stats["chunks"] == len(chunks)


def test_invalid_config_rejected():
    with pytest.raises(ChunkingError):
        TextChunker(ChunkingConfig(chunk_size=0))
    with pytest.raises(ChunkingError):
        TextChunker(ChunkingConfig(chunk_size=10, chunk_overlap=10))
    with pytest.raises(ChunkingError):
        TextChunker(ChunkingConfig(strategy="bogus"))


def test_pack_no_infinite_loop_on_exact_size_piece():
    # regression: a piece of exactly chunk_size chars after a flush used to spin forever
    chunker = TextChunker(ChunkingConfig(chunk_size=10, chunk_overlap=3))
    chunks = chunker.split_text("abcd abcdefghi x")
    assert chunks
    assert all(len(c) <= 10 for c in chunks)


def test_sentence_strategy():
    text = "First sentence here. Second one follows! Third asks? Fourth ends."
    chunker = TextChunker(ChunkingConfig(chunk_size=45, chunk_overlap=0, strategy="sentence"))
    chunks = chunker.split_text(text)
    assert len(chunks) >= 2
    assert all(len(c) <= 45 for c in chunks)
    rejoined = " ".join(chunks)
    for word in ("First", "Second", "Third", "Fourth"):
        assert word in rejoined
