"""Confidence-gated verification (ISSUE 11): the score (ops/confidence.py),
the gate node + router (graph/nodes.py), the detached-node executor leg
(graph/executor.py), and the async verify_pending surface — everything the
serve-level acceptance tests (test_serve.py::TestConfidenceGatedVerify)
assume, tested in isolation without an engine."""

from __future__ import annotations

import asyncio
import threading
import time

import pytest

from sentio_tpu.config import GeneratorConfig, Settings
from sentio_tpu.models.document import Document
from sentio_tpu.ops.confidence import confidence_score, retrieval_support


def _doc(score: float, text: str = "doc") -> Document:
    return Document(text=text, metadata={"score": score})


class TestConfidenceScore:
    def test_no_logprob_signal_is_none_never_a_number(self):
        # absence of evidence is not confidence: the gate must verify
        assert confidence_score(None, None, [_doc(1.0)]) is None

    def test_confident_decode_with_separated_source_clears_default(self):
        # near-certain tokens (mean prob ~0.98, worst ~0.95) + a dominant
        # top document: above the committed default threshold of 0.75
        conf = confidence_score(-0.02, -0.05, [_doc(0.9), _doc(0.1)])
        assert conf is not None
        assert conf > GeneratorConfig().verify_confidence_threshold

    def test_uncertain_decode_scores_low(self):
        # near-uniform token probability (random-init decodes): tiny score
        conf = confidence_score(-5.0, -8.0, [_doc(0.9), _doc(0.1)])
        assert conf is not None and conf < 0.3

    def test_bad_worst_token_drags_an_otherwise_confident_answer(self):
        good = confidence_score(-0.02, -0.05, [_doc(0.9), _doc(0.1)])
        spiky = confidence_score(-0.02, -6.0, [_doc(0.9), _doc(0.1)])
        assert spiky < good

    def test_score_clamped_to_unit_interval(self):
        assert 0.0 <= confidence_score(0.0, 0.0, [_doc(1.0)]) <= 1.0
        assert 0.0 <= confidence_score(-100.0, -100.0, []) <= 1.0

    def test_retrieval_support_shapes(self):
        assert retrieval_support([]) == 0.0
        assert retrieval_support([_doc(0.5)]) == 0.5
        dominant = retrieval_support([_doc(1.0), _doc(0.05)])
        flat = retrieval_support([_doc(0.5), _doc(0.5)])
        assert dominant > 0.9
        assert abs(flat - 0.5) < 1e-6
        assert retrieval_support([_doc(0.0), _doc(0.0)]) == 0.0


class TestGateNode:
    def _settings(self, threshold: float) -> Settings:
        s = Settings()
        s.generator.verify_confidence_threshold = threshold
        return s

    def _state(self, logprob_mean=-0.02, logprob_min=-0.05):
        meta = {"query_id": "gate-test"}
        if logprob_mean is not None:
            meta["logprob_mean"] = logprob_mean
            meta["logprob_min"] = logprob_min
        return {
            "query": "q", "response": "an answer",
            "selected_documents": [_doc(0.9), _doc(0.1)],
            "metadata": meta,
        }

    def test_confident_answer_short_circuits_with_typed_verdict(self):
        from sentio_tpu.graph.nodes import (
            confidence_gate_router,
            create_confidence_gate_node,
        )

        gate = create_confidence_gate_node(self._settings(0.1))
        update = gate(self._state())
        assert update["evaluation"]["verdict"] == "skipped_confident"
        assert update["metadata"]["verify_skipped"] == "confident"
        merged = dict(self._state())
        merged["metadata"] = {**merged["metadata"], **update["metadata"]}
        from sentio_tpu.graph.executor import END

        assert confidence_gate_router(merged) == END

    def test_below_threshold_routes_to_verify(self):
        from sentio_tpu.graph.nodes import (
            confidence_gate_router,
            create_confidence_gate_node,
        )

        gate = create_confidence_gate_node(self._settings(1.1))
        update = gate(self._state())
        assert "evaluation" not in update
        assert update["metadata"]["verify_confidence"] is not None
        assert confidence_gate_router(self._state()) == "verify"

    def test_no_logprobs_never_skips(self):
        from sentio_tpu.graph.nodes import create_confidence_gate_node

        gate = create_confidence_gate_node(self._settings(0.0))
        update = gate(self._state(logprob_mean=None))
        assert "evaluation" not in update
        assert update["metadata"]["verify_confidence"] is None


class TestDetachedExecutor:
    def test_detached_node_runs_off_path_and_joins(self):
        from sentio_tpu.graph.executor import END, GraphBuilder, wait_detached

        release = threading.Event()
        ran: list[str] = []

        async def slow_audit(state):
            release.wait(timeout=10.0)
            ran.append(state["query"])
            return {"evaluation": {"verdict": "pass"}}  # discarded

        def fast_head(state):
            return {"response": "answer"}

        graph = (
            GraphBuilder()
            .add_node("head", fast_head)
            .add_node("audit", slow_audit, detached=True)
            .add_edge("head", "audit")
            .add_edge("audit", END)
            .set_entry("head")
            .compile()
        )
        t0 = time.perf_counter()
        state = graph.invoke({"query": "q", "metadata": {}})
        returned_ms = (time.perf_counter() - t0) * 1e3
        # the graph returned WITHOUT waiting for the held audit ...
        assert returned_ms < 5_000
        assert state["metadata"]["audit_pending"] is True
        # ... the detached node's update was NOT merged ...
        assert not state.get("evaluation")
        assert ran == []
        # ... and joins once released
        release.set()
        assert wait_detached(timeout_s=10.0)
        assert ran == ["q"]

    def test_detached_failure_is_contained(self):
        from sentio_tpu.graph.executor import END, GraphBuilder, wait_detached

        async def boom(state):
            raise RuntimeError("detached audit exploded")

        graph = (
            GraphBuilder()
            .add_node("head", lambda s: {"response": "x"})
            .add_node("audit", boom, detached=True)
            .add_edge("head", "audit")
            .add_edge("audit", END)
            .set_entry("head")
            .compile()
        )
        state = graph.invoke({"query": "q", "metadata": {}})
        assert state["response"] == "x"
        assert wait_detached(timeout_s=10.0)


class TestPagedLogprobSurfacing:
    """Leg 1 of the tentpole: the paged engine's fused decode scan carries
    per-slot logprob accumulators and every PagedResult reports them."""

    def test_run_all_carries_logprob_accumulators(self):
        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        eng = ContinuousBatchingEngine(
            model_config=LlamaConfig.tiny(), max_slots=2, page_size=16,
            max_pages_per_seq=4, steps_per_tick=4,
        )
        results = eng.run_all(
            ["logprob surfacing probe", "second logprob probe"],
            max_new_tokens=6, temperature=0.0,
        )
        for r in results:
            # every sampled token contributes one observation: the emitted
            # tokens plus the EOS sample when the request stopped on EOS
            expected = len(r.tokens) + (1 if r.finish_reason == "stop" else 0)
            assert r.logprob_count == expected, r
            assert r.logprob_count >= 1
            # log-probabilities: all non-positive, min bounds the mean,
            # the sum of non-positives cannot exceed the worst single one
            assert r.logprob_min <= 0.0
            assert r.logprob_min <= r.logprob_mean <= 0.0
            assert r.logprob_sum <= r.logprob_min + 1e-6
            # a byte-vocab softmax cannot be flat-zero: the signal is real
            assert r.logprob_mean < -1e-6

    def test_pipelined_ticks_report_same_accumulators(self):
        """pipeline_depth=2 harvests a tick late — the lp fetch must come
        from the SAME record as the folded tokens, so depth 1 and depth 2
        greedy runs agree exactly."""
        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine

        cfg = LlamaConfig.tiny()
        d1 = ContinuousBatchingEngine(
            model_config=cfg, max_slots=2, page_size=16,
            max_pages_per_seq=4, steps_per_tick=4, pipeline_depth=1,
        )
        d2 = ContinuousBatchingEngine(
            model_config=cfg, params=d1.params, tokenizer=d1.tokenizer,
            max_slots=2, page_size=16, max_pages_per_seq=4,
            steps_per_tick=4, pipeline_depth=2,
        )
        (r1,) = d1.run_all(["pipelined logprob parity"], max_new_tokens=8)
        (r2,) = d2.run_all(["pipelined logprob parity"], max_new_tokens=8)
        assert r1.tokens == r2.tokens
        assert r1.logprob_count == r2.logprob_count
        assert abs(r1.logprob_sum - r2.logprob_sum) < 1e-4
        assert abs(r1.logprob_min - r2.logprob_min) < 1e-5


class TestGraphWiring:
    class _FakeRetriever:
        name = "fake"

        async def aretrieve(self, query, top_k=10):
            return [_doc(0.9, "alpha"), _doc(0.1, "beta")]

    class _FakeVerifier:
        def __init__(self):
            self.calls = []

        def verify(self, query, answer, documents, **kwargs):
            from sentio_tpu.ops.verifier import VerifyResult

            self.calls.append(answer)
            return VerifyResult(verdict="pass")

    def _generator(self):
        from sentio_tpu.ops.generator import LLMGenerator

        return LLMGenerator()

    def _settings(self, mode: str, threshold: float = 0.75) -> Settings:
        s = Settings()
        s.generator.verify_mode = mode
        s.generator.verify_confidence_threshold = threshold
        return s

    def _build(self, mode: str, verifier, threshold: float = 0.75):
        from sentio_tpu.graph.factory import GraphConfig, build_basic_graph

        settings = self._settings(mode, threshold)
        return build_basic_graph(
            self._FakeRetriever(), self._generator(), reranker=None,
            verifier=verifier,
            config=GraphConfig(use_reranker=False, settings=settings),
        )

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError, match="verify_mode"):
            self._build("yolo", self._FakeVerifier())

    def test_gated_graph_has_gate_and_detached_verify(self):
        graph = self._build("gated", self._FakeVerifier())
        assert "verify_gate" in graph.nodes
        assert graph.nodes["verify"].detached is True
        sync = self._build("sync", self._FakeVerifier())
        assert "verify_gate" not in sync.nodes
        assert sync.nodes["verify"].detached is False

    def test_async_mode_returns_with_verify_pending_then_verdict_lands(self):
        from sentio_tpu.graph.executor import wait_detached
        from sentio_tpu.graph.state import create_initial_state
        from sentio_tpu.infra.flight import FlightRecorder, set_flight_recorder

        recorder = FlightRecorder()
        set_flight_recorder(recorder)
        try:
            verifier = self._FakeVerifier()
            graph = self._build("async", verifier)
            state = graph.invoke(create_initial_state(
                "what is alpha?",
                metadata={"mode": "fast", "query_id": "asyncv1"},
            ))
            assert state["metadata"]["verify_pending"] is True
            # answer returned before (or without) the audit's merge
            assert state.get("response")
            assert not state.get("evaluation")
            assert wait_detached(timeout_s=30.0)
            assert verifier.calls, "detached verify never ran"
            record = recorder.get("asyncv1")
            assert record["verify"]["outcome"] == "pass"
            assert record["verify"]["mode"] == "async"
            assert record["verify"]["verdict_ms"] >= 0.0
        finally:
            set_flight_recorder(None)
