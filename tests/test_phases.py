"""Tick-phase time attribution (infra/phases.py + the pump/engine wiring).

The tier-1 conservation gate (ISSUE 12 acceptance): for a sanitized
multi-request run, every tick's ``sum(phase_ms)`` equals its ``pump_ms``
within tolerance, duty-cycle fractions sum to 1±0.01, and the ``phase_ms``
key set is exactly the fixed bounded ``TICK_PHASES`` — the metrics
cardinality guard drops anything else."""

import threading
import time

import pytest

from sentio_tpu.infra.flight import FlightRecorder, set_flight_recorder
from sentio_tpu.infra.metrics import MetricsCollector, set_metrics
from sentio_tpu.infra.phases import (
    DUTY_STATES,
    HOST_PHASES,
    TICK_PHASES,
    PhaseTimer,
    duty_fractions,
)
from sentio_tpu.runtime.paged import ContinuousBatchingEngine
from sentio_tpu.runtime.service import PagedGenerationService


@pytest.fixture()
def recorder():
    rec = FlightRecorder()
    set_flight_recorder(rec)
    yield rec
    set_flight_recorder(None)


@pytest.fixture()
def metrics():
    m = MetricsCollector()
    set_metrics(m)
    yield m
    set_metrics(None)


def _engine(**kw):
    defaults = dict(max_slots=4, page_size=16, max_pages_per_seq=4,
                    steps_per_tick=4, max_tick_steps=8, pipeline_depth=2)
    defaults.update(kw)
    return ContinuousBatchingEngine(**defaults)


class TestPhaseTimer:
    def test_add_and_context(self):
        t = PhaseTimer()
        t.add("deliver", 0.25)
        with t.phase("inbox_drain"):
            pass
        assert t.acc["deliver"] == 0.25
        assert t.acc["inbox_drain"] >= 0.0
        assert t.total() >= 0.25

    def test_unknown_key_rejected(self):
        """A typo'd phase must fail at the writer — the bounded-set
        guarantee is enforced where the key is minted."""
        t = PhaseTimer()
        with pytest.raises(KeyError):
            t.add("not_a_phase", 1.0)
        with pytest.raises(KeyError):
            t.phase("not_a_phase")

    def test_snapshot_and_reset(self):
        t = PhaseTimer()
        t.add("other", 0.002)
        snap = t.snapshot_ms()
        assert set(snap) == set(TICK_PHASES)
        assert snap["other"] == 2.0
        t.reset()
        assert t.total() == 0.0


class TestDutyFractions:
    def test_sums_to_one(self):
        out = duty_fractions(
            {"inbox_drain": 0.1, "device_wait": 0.3, "deliver": 0.1}, 1.0)
        assert set(out) == set(DUTY_STATES)
        assert sum(out.values()) == pytest.approx(1.0, abs=1e-6)
        assert out["host"] == pytest.approx(0.2, abs=1e-6)
        assert out["device"] == pytest.approx(0.3, abs=1e-6)

    def test_skew_clamped_and_renormalized(self):
        # busy marginally exceeding elapsed (clock skew): idle clamps at 0
        # and the fractions still sum to 1
        out = duty_fractions({"other": 0.8, "device_wait": 0.4}, 1.0)
        assert out["idle"] == 0.0
        assert sum(out.values()) == pytest.approx(1.0, abs=1e-6)

    def test_zero_elapsed_is_idle(self):
        assert duty_fractions({}, 0.0) == {
            "host": 0.0, "device": 0.0, "idle": 1.0}

    def test_host_phase_rollup_covers_everything_but_device(self):
        assert set(HOST_PHASES) | {"device_wait"} == set(TICK_PHASES)


class TestConservation:
    """THE acceptance gate: phase decomposition conserves wall time."""

    def _run_traffic(self, svc, n=8, tokens=8):
        threads = [
            threading.Thread(
                target=svc.generate, args=(f"phase probe request {i} ",),
                kwargs={"max_new_tokens": tokens},
            )
            for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)

    def test_per_tick_conservation_and_bounded_keys(self, recorder, metrics):
        svc = PagedGenerationService(_engine())
        try:
            self._run_traffic(svc)
        finally:
            svc.close()
        ticks = [e for e in recorder.timeline() if "phase_ms" in e]
        assert len(ticks) >= 3, "multi-request run produced too few ticks"
        for tick in ticks:
            phase_ms = tick["phase_ms"]
            # the fixed bounded key set — exactly, not just a subset
            assert set(phase_ms) == set(TICK_PHASES)
            assert all(v >= 0.0 for v in phase_ms.values())
            # conservation: phases tile the pump iteration ("other" absorbs
            # the residual by construction; rounding leaves sub-ms slack)
            total = sum(phase_ms.values())
            assert total == pytest.approx(
                tick["pump_ms"], rel=0.05, abs=0.5), (
                f"phase sum {total} != pump_ms {tick['pump_ms']}: {phase_ms}"
            )
            # the engine-step subset is bounded by its measured dur_ms span
            engine_ms = (phase_ms["admission_build"]
                         + phase_ms["prefill_dispatch"]
                         + phase_ms["decode_dispatch"]
                         + phase_ms["device_wait"])
            assert engine_ms <= tick["dur_ms"] * 1.05 + 0.5
        # at least one tick paid real dispatch/wait time
        assert any(
            t["phase_ms"]["decode_dispatch"] + t["phase_ms"]["device_wait"]
            > 0.0
            for t in ticks
        )

    def test_duty_cycle_sums_to_one(self, recorder, metrics):
        svc = PagedGenerationService(_engine())
        try:
            self._run_traffic(svc)
            stats = svc.stats()
        finally:
            svc.close()
        duty = stats["duty_cycle"]
        assert set(duty) == set(DUTY_STATES)
        assert sum(duty.values()) == pytest.approx(1.0, abs=0.01)
        # phase totals carry the same bounded key set
        assert set(stats["phase_seconds"]) == set(TICK_PHASES)
        assert stats["duty_elapsed_s"] > 0
        # traffic ran: the window cannot be pure idle
        assert duty["idle"] < 1.0
        assert duty["host"] + duty["device"] > 0.0

    def test_phase_histogram_and_cardinality_guard(self, recorder, metrics):
        svc = PagedGenerationService(_engine())
        try:
            self._run_traffic(svc, n=4)
        finally:
            svc.close()
        histos = metrics.memory.snapshot()["histograms"]
        recorded = {k for k in histos if k.startswith("tick_phase(")}
        assert recorded, "pump recorded no tick phases"
        assert recorded <= {f"tick_phase{(p,)}" for p in TICK_PHASES}
        # the guard: an unknown phase key is dropped, not minted as a series
        metrics.record_tick_phases({"bogus_phase": 1.0, "deliver": 0.001})
        histos = metrics.memory.snapshot()["histograms"]
        assert not any("bogus_phase" in k for k in histos)
        assert any("deliver" in k for k in histos)

    def test_reset_duty_cycle_rebases_window(self, recorder, metrics):
        svc = PagedGenerationService(_engine())
        try:
            self._run_traffic(svc, n=2, tokens=4)
            before = svc.stats()["phase_seconds"]
            assert sum(before.values()) > 0
            svc.reset_duty_cycle()
            time.sleep(0.01)
            after = svc.stats()
            assert sum(after["phase_seconds"].values()) == pytest.approx(
                0.0, abs=1e-6)
            assert after["duty_cycle"]["idle"] == pytest.approx(1.0, abs=0.01)
        finally:
            svc.close()

    def test_finishing_tick_stays_in_request_window(self, recorder, metrics):
        """Regression (review): the pump must record the tick BEFORE
        delivering results — finish_engine stamps tick_last from the
        recorder sequence, and the window filter (first < tick <= last)
        would otherwise exclude the very tick each request finished in
        (a generation finishing in its first tick would report an EMPTY
        window). The completed phase split is amended on afterwards."""
        svc = PagedGenerationService(_engine())
        try:
            svc.generate("window probe", max_new_tokens=4,
                         request_id="win-1")
        finally:
            svc.close()  # pump joined: the final tick's amend has landed
        record = recorder.get("win-1")
        assert record is not None
        assert record["ticks"], "finishing tick missing from the window"
        last = record["ticks"][-1]
        assert last["tick"] == record["engine"]["tick_last"]
        # the amended phase decomposition rides the window's final tick
        assert set(last["phase_ms"]) == set(TICK_PHASES)
        assert "pump_ms" in last

    def test_amend_tick(self, recorder):
        seq = recorder.record_tick(replica=0, dur_ms=1.0)
        t_before = recorder.timeline()[-1]["t_s"]
        assert recorder.amend_tick(
            seq, pump_ms=2.0, phase_ms={"other": 2.0}) == 1
        evt = recorder.timeline()[-1]
        assert evt["pump_ms"] == 2.0
        assert evt["phase_ms"] == {"other": 2.0}
        assert evt["t_s"] >= t_before  # restamped to the span's end
        assert recorder.amend_tick(10_000, pump_ms=1.0) == 0

    def test_direct_engine_step_publishes_phases(self, recorder):
        eng = _engine(pipeline_depth=1)
        eng.run_all(["direct engine probe"], max_new_tokens=4)
        phases = eng.last_step_phases
        assert set(phases) <= set(TICK_PHASES)
        assert sum(phases.values()) > 0.0


class TestReplicaAggregation:
    def test_failed_tick_flushes_partial_phases(self, recorder, metrics):
        """ISSUE 13 satellite: a pump iteration that ends in a tick failure
        still records a ``phase_ms`` decomposition (partial engine snapshot,
        residual folded into ``other``; the same bounded key set and
        conservation contract as a successful tick) — chaos-round Perfetto
        traces must not hole every failed tick."""
        from sentio_tpu.infra import faults

        svc = PagedGenerationService(_engine(), retry_budget=1)
        try:
            with faults.inject("paged.step",
                               error=RuntimeError("phase flush probe"),
                               times=1) as rule:
                result = svc.generate("phase flush probe request",
                                      max_new_tokens=4, timeout_s=120)
            assert rule.fired == 1
            # the ticket was requeued past the failed tick (crash
            # containment) and finished normally
            assert result.finish_reason in ("stop", "length")
            stats = svc.stats()
            assert stats["tick_failures"] == 1
        finally:
            faults.reset()
            svc.close()
        failed = [e for e in recorder.timeline()
                  if e.get("event") == "tick_failure"]
        assert len(failed) == 1, "failed tick recorded no flight event"
        tick = failed[0]
        phase_ms = tick["phase_ms"]
        assert set(phase_ms) == set(TICK_PHASES)
        assert all(v >= 0.0 for v in phase_ms.values())
        assert sum(phase_ms.values()) == pytest.approx(
            tick["pump_ms"], rel=0.05, abs=0.5)
        # the failed iteration's wall time landed in the duty totals too
        # (phase_seconds grew by at least the failed tick's pump span)
        assert sum(stats["phase_seconds"].values()) * 1e3 >= (
            tick["pump_ms"] * 0.5
        )

    def test_replica_set_duty_cycle(self, recorder, metrics):
        from sentio_tpu.runtime.replica import ReplicaSet

        e0 = _engine(max_slots=2)
        e1 = ContinuousBatchingEngine(
            params=e0.params, tokenizer=e0.tokenizer, max_slots=2,
            page_size=16, max_pages_per_seq=4, steps_per_tick=4,
            max_tick_steps=8, pipeline_depth=2)
        rs = ReplicaSet(
            [PagedGenerationService(e0), PagedGenerationService(e1)],
            supervise=False,
        )
        try:
            threads = [
                threading.Thread(
                    target=rs.generate, args=(f"replica duty probe {i} ",),
                    kwargs={"max_new_tokens": 4},
                )
                for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            stats = rs.stats()
        finally:
            rs.close()
        assert sum(stats["duty_cycle"].values()) == pytest.approx(
            1.0, abs=0.01)
        assert set(stats["phase_seconds"]) == set(TICK_PHASES)
        for row in stats["replicas"]:
            assert sum(row["duty_cycle"].values()) == pytest.approx(
                1.0, abs=0.01)
