import asyncio
import logging
import time

import pytest

from sentio_tpu.config import AuthConfig, CacheConfig
from sentio_tpu.infra.auth import JWT, AuthManager, hash_password, verify_password
from sentio_tpu.infra.caching import (
    AdaptiveStrategy,
    CacheManager,
    MemoryCache,
    NullL2Cache,
    SizeAwareStrategy,
)
from sentio_tpu.infra.exceptions import (
    AuthError,
    CircuitOpenError,
    ErrorCode,
    ErrorHandler,
    ForbiddenError,
    RateLimitError,
    SentioError,
    ValidationError,
)
from sentio_tpu.infra.resilience import (
    CircuitBreaker,
    CircuitState,
    FallbackResponseCache,
    LLMFallback,
    ResilientCall,
    RetryPolicy,
    embedding_fallback,
    with_retry,
)
from sentio_tpu.infra.security import (
    CSRFProtection,
    InputValidator,
    IPRateLimiter,
    LogSanitizer,
    sanitize_text,
)


class TestCircuitBreaker:
    def test_opens_after_threshold_and_recovers(self):
        breaker = CircuitBreaker(failure_threshold=3, recovery_timeout_s=0.05,
                                 success_threshold=1)

        def boom():
            raise RuntimeError("x")

        for _ in range(3):
            with pytest.raises(RuntimeError):
                breaker.call(boom)
        assert breaker.state == CircuitState.OPEN
        with pytest.raises(CircuitOpenError):
            breaker.call(lambda: "fine")
        time.sleep(0.06)
        assert breaker.call(lambda: "recovered") == "recovered"
        assert breaker.state == CircuitState.CLOSED

    def test_half_open_failure_reopens(self):
        breaker = CircuitBreaker(failure_threshold=1, recovery_timeout_s=0.02)
        with pytest.raises(RuntimeError):
            breaker.call(lambda: (_ for _ in ()).throw(RuntimeError()))
        time.sleep(0.03)
        with pytest.raises(RuntimeError):
            breaker.call(lambda: (_ for _ in ()).throw(RuntimeError()))
        assert breaker.state == CircuitState.OPEN

    def test_async_breaker(self):
        breaker = CircuitBreaker(failure_threshold=1, recovery_timeout_s=10)

        async def run():
            async def boom():
                raise ValueError("async fail")

            with pytest.raises(ValueError):
                await breaker.acall(boom)
            with pytest.raises(CircuitOpenError):
                await breaker.acall(boom)

        asyncio.run(run())
        assert breaker.health()["state"] == "open"


class TestRetry:
    def test_retries_then_succeeds(self):
        calls = {"n": 0}

        @with_retry(RetryPolicy(max_attempts=3, base_delay_s=0.001))
        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "done"

        assert flaky() == "done"
        assert calls["n"] == 3

    def test_exhaustion_raises_last(self):
        policy = RetryPolicy(max_attempts=2, base_delay_s=0.001)
        with pytest.raises(ValueError, match="always"):
            policy.run(lambda: (_ for _ in ()).throw(ValueError("always")))

    def test_nonpositive_max_attempts_raises_value_error(self):
        # used to fall off the loop and `raise None` (an opaque TypeError)
        policy = RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError, match="max_attempts"):
            policy.run(lambda: "never")
        with pytest.raises(ValueError, match="max_attempts"):
            asyncio.run(RetryPolicy(max_attempts=-1).arun(None))

    def test_injected_rng_makes_jitter_deterministic(self):
        import random as _random

        a = RetryPolicy(base_delay_s=0.1, rng=_random.Random(42))
        b = RetryPolicy(base_delay_s=0.1, rng=_random.Random(42))
        assert [a.delay(i) for i in range(4)] == [b.delay(i) for i in range(4)]

    def test_resilient_call_timeout(self):
        async def run():
            rc = ResilientCall("slow", timeout_s=0.02,
                               retry=RetryPolicy(max_attempts=1, base_delay_s=0.001))

            async def sleepy():
                await asyncio.sleep(1.0)

            from sentio_tpu.infra.exceptions import TimeoutError_

            with pytest.raises(TimeoutError_):
                await rc.execute(sleepy)

        asyncio.run(run())


class TestFallbacks:
    def test_response_cache_roundtrip(self, tmp_path):
        cache = FallbackResponseCache(cache_dir=str(tmp_path), ttl_s=100)
        assert cache.get("what is jax?") is None
        cache.put("what is jax?", "a library")
        assert cache.get("What is JAX?  ") == "a library"  # normalized key
        fresh = FallbackResponseCache(cache_dir=str(tmp_path), ttl_s=100)
        assert fresh.get("what is jax?") == "a library"  # disk persisted

    def test_response_cache_ttl(self, tmp_path):
        cache = FallbackResponseCache(cache_dir=str(tmp_path), ttl_s=0.01)
        cache.put("q", "a")
        time.sleep(0.02)
        assert cache.get("q") is None

    def test_expired_deletion_persists_to_disk(self, tmp_path):
        cache = FallbackResponseCache(cache_dir=str(tmp_path), ttl_s=0.01)
        cache.put("q", "a")
        time.sleep(0.02)
        assert cache.get("q") is None
        # a fresh instance loads from disk: the expired entry must NOT
        # resurrect (pre-fix, deletion only ever happened in memory)
        fresh = FallbackResponseCache(cache_dir=str(tmp_path), ttl_s=1e9)
        assert fresh.get("q") is None

    def test_max_entries_lru_cap(self, tmp_path):
        cache = FallbackResponseCache(cache_dir=str(tmp_path), ttl_s=0,
                                      max_entries=3)
        for i in range(6):
            cache.put(f"question {i}", f"answer {i}")
            time.sleep(0.002)  # distinct write stamps for eviction order
        # only the newest 3 survive, in memory AND on disk
        assert cache.get("question 0") is None
        assert cache.get("question 5") == "answer 5"
        fresh = FallbackResponseCache(cache_dir=str(tmp_path), ttl_s=0)
        assert len(fresh._store) <= 3
        assert fresh.get("question 5") == "answer 5"

    def test_eviction_is_recency_based_not_fifo(self, tmp_path):
        cache = FallbackResponseCache(cache_dir=str(tmp_path), ttl_s=0,
                                      max_entries=3)
        for i in range(3):
            cache.put(f"q{i}", f"a{i}")
            time.sleep(0.002)
        # touch the OLDEST-written entry, then overflow: the least recently
        # USED entry (q1) must go, and the hot q0 must survive
        assert cache.get("q0") == "a0"
        time.sleep(0.002)
        cache.put("q3", "a3")
        assert cache.get("q1") is None
        assert cache.get("q0") == "a0"

    def test_llm_fallback_templates(self):
        fb = LLMFallback(prompts_dir="prompts")
        assert "knowledge base" in fb.no_retrieval("my question")
        assert "unavailable" in fb.no_llm("some context")
        assert fb.apology()

    def test_embedding_fallback_deterministic_unit(self):
        import numpy as np

        a = embedding_fallback("hello", 32)
        b = embedding_fallback("HELLO", 32)
        assert a == b  # case-normalized
        assert abs(np.linalg.norm(a) - 1.0) < 1e-5


class TestCaching:
    def test_lru_eviction_order(self):
        cache = MemoryCache(max_entries=2)
        cache.set("a", 1)
        cache.set("b", 2)
        cache.get("a")  # refresh a
        cache.set("c", 3)  # evicts b
        assert cache.get("a") == 1
        assert cache.get("b") is None
        assert cache.get("c") == 3

    def test_ttl_and_cleanup(self):
        cache = MemoryCache(max_entries=10, default_ttl_s=0.01)
        cache.set("x", 1)
        time.sleep(0.02)
        assert cache.get("x") is None
        cache.set("y", 2, ttl_s=0.01)
        time.sleep(0.02)
        assert cache.cleanup_expired() == 1

    def test_pattern_clear(self):
        cache = MemoryCache()
        cache.set("query:a", 1)
        cache.set("query:b", 2)
        cache.set("emb:c", 3)
        assert cache.clear("query:*") == 2
        assert cache.get("emb:c") == 3

    def test_manager_typed_helpers(self):
        mgr = CacheManager(CacheConfig(backend="memory"))
        mgr.set_query_response("  What is JAX? ", {"answer": "lib"})
        assert mgr.get_query_response("what is jax?") == {"answer": "lib"}
        assert mgr.stats()["l1"]["entries"] == 1

    def test_manager_off_backend(self):
        mgr = CacheManager(CacheConfig(backend="off"))
        mgr.set("k", "v")
        assert mgr.get("k") is None

    def test_multi_tier_l2_promotion(self):
        class DictL2(NullL2Cache):
            def __init__(self):
                self.store = {}

            async def get(self, key):
                return self.store.get(key)

            async def set(self, key, value, ttl_s):
                self.store[key] = value

        async def run():
            l2 = DictL2()
            mgr = CacheManager(CacheConfig(backend="multi_tier"), l2=l2)
            await mgr.aset("k", "v")
            assert l2.store["k"] == "v"
            mgr.l1.clear()
            assert await mgr.aget("k") == "v"  # L2 hit
            assert mgr.l1.get("k") == "v"  # promoted to L1

        asyncio.run(run())

    def test_size_aware_strategy(self):
        s = SizeAwareStrategy(max_bytes=10)
        assert s.should_cache("k", "short") is True
        assert s.should_cache("k", "x" * 100) is False

    def test_adaptive_strategy_ttl_scales(self):
        s = AdaptiveStrategy(base_ttl_s=100)
        for _ in range(9):
            s.record("hot:q", hit=True)
        s.record("hot:q", hit=False)
        for _ in range(10):
            s.record("cold:q", hit=False)
        assert s.ttl_for("hot:x", 1) > s.ttl_for("cold:x", 1)


class TestAuth:
    def _mgr(self):
        return AuthManager(AuthConfig(enabled=True, jwt_secret="test-secret",
                                      max_failed_attempts=2, lockout_s=0.05,
                                      min_password_len=8))

    def test_password_hash_roundtrip(self):
        stored = hash_password("Secret123")
        assert verify_password("Secret123", stored)
        assert not verify_password("wrong", stored)
        assert not verify_password("Secret123", "garbage")

    def test_jwt_roundtrip_and_tamper(self):
        jwt = JWT("secret")
        token = jwt.encode({"sub": "alice", "exp": time.time() + 10})
        assert jwt.decode(token)["sub"] == "alice"
        with pytest.raises(AuthError):
            jwt.decode(token[:-3] + "xxx")
        with pytest.raises(AuthError):
            JWT("other-secret").decode(token)

    def test_jwt_expiry(self):
        jwt = JWT("secret")
        token = jwt.encode({"sub": "a", "exp": time.time() - 1})
        with pytest.raises(AuthError) as exc_info:
            jwt.decode(token)
        assert exc_info.value.code == ErrorCode.TOKEN_EXPIRED

    def test_full_auth_flow(self):
        mgr = self._mgr()
        mgr.create_user("alice", "Str0ngPass", role="user")
        tokens = mgr.authenticate("alice", "Str0ngPass")
        payload = mgr.verify_token(tokens["access_token"])
        assert payload["sub"] == "alice"
        assert "chat" in payload["scopes"]
        refreshed = mgr.refresh(tokens["refresh_token"])
        assert mgr.verify_token(refreshed["access_token"])["sub"] == "alice"
        with pytest.raises(AuthError):
            mgr.verify_token(tokens["refresh_token"])  # wrong token type

    def test_lockout_after_failures(self):
        mgr = AuthManager(AuthConfig(enabled=True, jwt_secret="s",
                                     max_failed_attempts=2, lockout_s=60,
                                     min_password_len=8))
        mgr.create_user("bob", "Str0ngPass")
        for _ in range(2):
            with pytest.raises(AuthError):
                mgr.authenticate("bob", "wrong")
        with pytest.raises(AuthError) as exc_info:
            mgr.authenticate("bob", "Str0ngPass")
        assert exc_info.value.code == ErrorCode.ACCOUNT_LOCKED
        mgr._users["bob"].locked_until = 0.0  # simulate lockout expiry
        assert mgr.authenticate("bob", "Str0ngPass")["access_token"]

    def test_password_policy(self):
        mgr = self._mgr()
        for bad in ("short1A", "alllowercase1", "ALLUPPER1", "NoDigitsHere"):
            with pytest.raises(ValueError):
                mgr.create_user(f"u{bad}", bad)

    def test_api_keys(self):
        mgr = self._mgr()
        mgr.create_user("svc", "Str0ngPass", role="service")
        key = mgr.create_api_key("svc")
        payload = mgr.verify_api_key(key)
        assert payload["role"] == "service"
        assert mgr.revoke_api_key(key)
        with pytest.raises(AuthError):
            mgr.verify_api_key(key)

    def test_rbac(self):
        mgr = self._mgr()
        payload = {"role": "user", "scopes": ["read", "chat"]}
        mgr.require_scopes(payload, "read")
        with pytest.raises(ForbiddenError):
            mgr.require_scopes(payload, "admin")
        with pytest.raises(ForbiddenError):
            mgr.require_role(payload, "admin")

    def test_sessions(self):
        mgr = self._mgr()
        s = mgr.create_session("alice")
        assert mgr.get_session(s.session_id).username == "alice"
        assert mgr.end_session(s.session_id)
        assert mgr.get_session(s.session_id) is None


class TestSecurity:
    def test_sanitize_redacts_secrets(self):
        text = 'calling with api_key="sk-12345secret" and Authorization: Bearer abc123'
        out = sanitize_text(text)
        assert "sk-12345secret" not in out
        assert "[REDACTED]" in out

    def test_sanitize_redacts_jwt_and_api_keys(self):
        jwt = JWT("s").encode({"sub": "x"})
        out = sanitize_text(f"token {jwt} key stk_{'a' * 20}")
        assert "[REDACTED_JWT]" in out
        assert "[REDACTED_KEY]" in out

    def test_log_filter(self, caplog):
        logger = logging.getLogger("test_sanitize")
        logger.addFilter(LogSanitizer())
        with caplog.at_level(logging.INFO, logger="test_sanitize"):
            logger.info("password=SuperSecret99")
        assert "SuperSecret99" not in caplog.text

    def test_input_validator_query(self):
        v = InputValidator(max_query_chars=50)
        assert v.validate_query("  what is jax?\x00 ") == "what is jax?"
        with pytest.raises(ValidationError):
            v.validate_query("")
        with pytest.raises(ValidationError):
            v.validate_query("x" * 51)
        with pytest.raises(ValidationError):
            v.validate_query("<script>alert(1)</script>")
        with pytest.raises(ValidationError):
            v.validate_query(42)

    def test_input_validator_metadata(self):
        v = InputValidator()
        assert v.validate_metadata(None) == {}
        assert v.validate_metadata({"k": "v", "n": 3})["n"] == 3
        with pytest.raises(ValidationError):
            v.validate_metadata({"k": ["no", "lists"]})

    def test_rate_limiter_window(self):
        rl = IPRateLimiter()
        rl.configure("/embed", per_minute=2)
        rl.check("1.2.3.4", "/embed")
        rl.check("1.2.3.4", "/embed")
        with pytest.raises(RateLimitError) as exc_info:
            rl.check("1.2.3.4", "/embed")
        assert exc_info.value.details["retry_after_s"] > 0
        rl.check("5.6.7.8", "/embed")  # other IPs unaffected

    def test_rate_limiter_load_factor(self):
        rl = IPRateLimiter()
        rl.configure("/chat", per_minute=10)
        rl.load_factor = 0.1  # under pressure: 1/min
        rl.check("9.9.9.9", "/chat")
        with pytest.raises(RateLimitError):
            rl.check("9.9.9.9", "/chat")

    def test_csrf(self):
        csrf = CSRFProtection()
        token = csrf.issue("sess-1")
        assert csrf.verify("sess-1", token)
        assert not csrf.verify("sess-2", token)
        assert not csrf.verify("sess-1", "junk")


class TestExceptions:
    def test_error_serialization(self):
        err = ValidationError("bad input", details={"field": "question"})
        status, body = ErrorHandler.handle(err)
        assert status == 422
        assert body["error"]["code"] == "VALIDATION_ERROR"
        assert body["error"]["details"]["field"] == "question"

    def test_unknown_exception_opaque(self):
        status, body = ErrorHandler.handle(RuntimeError("secret internals"))
        assert status == 500
        assert "secret internals" not in str(body)

    def test_rate_limit_carries_retry_after(self):
        err = RateLimitError(retry_after_s=12.0)
        assert err.status == 429
        assert err.details["retry_after_s"] == 12.0


class TestMonitoring:
    def test_thresholds_and_trend(self):
        from sentio_tpu.infra.monitoring import PerformanceMonitor

        mon = PerformanceMonitor()
        fired = []
        mon.set_threshold("latency", 100.0)
        mon.on_alert(fired.append)
        for v in (50, 150, 250):
            mon.record("latency", v)
        assert len(fired) == 2
        assert mon.trend("latency")["direction"] == "rising"
        summary = mon.summary("latency")
        assert summary["count"] == 3 and summary["max"] == 250

    def test_health_verdict(self):
        from sentio_tpu.infra.monitoring import ResourceMonitor

        verdict = ResourceMonitor().health_verdict()
        assert verdict["status"] in ("healthy", "degraded", "unhealthy")
        assert "system" in verdict


class TestMetrics:
    def test_record_and_export(self):
        from sentio_tpu.infra.metrics import MetricsCollector

        m = MetricsCollector()
        m.record_request("/chat", 200, 0.12)
        m.record_llm("generate", 0.5, tokens=64)
        m.record_breaker("tpu", "open")
        m.record_batch_occupancy("chat", 0.75)
        snap = m.export_json()
        assert any("requests" in k for k in snap["counters"])
        assert snap["gauges"]["breaker_state('tpu',)"] == 2.0
        text = m.export_prometheus()
        assert b"sentio_requests_total" in text

    def test_track_request_context(self):
        from sentio_tpu.infra.metrics import MetricsCollector

        m = MetricsCollector()
        with m.track_request("/info"):
            pass
        with pytest.raises(ValueError):
            with m.track_request("/info"):
                raise ValueError("x")
        snap = m.export_json()
        assert snap["counters"]["requests('/info', '200')"] == 1.0
        assert snap["counters"]["requests('/info', '500')"] == 1.0


class TestTracing:
    def test_mock_spans_when_disabled(self):
        from sentio_tpu.config import ObservabilityConfig
        from sentio_tpu.infra.tracing import TracingManager, trace_function

        mgr = TracingManager(ObservabilityConfig(tracing_enabled=False))
        with mgr.span("op", key="value") as span:
            span.set_attribute("more", 1)

        @trace_function("custom", manager=mgr)
        def traced():
            return 42

        assert traced() == 42

    def test_otel_spans_when_enabled(self):
        from sentio_tpu.config import ObservabilityConfig
        from sentio_tpu.infra.tracing import TracingManager

        mgr = TracingManager(ObservabilityConfig(tracing_enabled=True))
        with mgr.span("real-op", component="test"):
            pass
        mgr.shutdown()

    def test_profile_step_works_without_profiler(self):
        from sentio_tpu.config import ObservabilityConfig
        from sentio_tpu.infra.tracing import TracingManager

        mgr = TracingManager(ObservabilityConfig(tracing_enabled=False))
        with mgr.profile_step("decode", step=3):
            pass


def test_csrf_malformed_timestamp_returns_false():
    csrf = CSRFProtection()
    assert csrf.verify("sess", "abc.def") is False
    assert csrf.verify("sess", "..") is False


def test_rate_limiter_sweeps_idle_keys():
    rl = IPRateLimiter()
    rl._checks_since_sweep = 0
    for i in range(100):
        rl.check(f"10.0.0.{i}", "/x")
    # age everything out and force a sweep (the limiter clocks windows on
    # the monotonic perf_counter, not the NTP-steppable epoch clock)
    with rl._lock:
        for key in list(rl._events):
            rl._events[key] = [time.perf_counter() - 120.0]
        rl._checks_since_sweep = 10_000
    rl.check("fresh-ip", "/x")
    assert len(rl._events) <= 2


class TestInflightGauge:
    def test_inflight_tracks_and_floors_at_zero(self):
        from sentio_tpu.infra.metrics import MetricsCollector

        m = MetricsCollector(enabled=True)
        m.adjust_inflight(+1)
        m.adjust_inflight(+1)
        assert m.export_json()["gauges"]["inflight()"] == 2.0
        m.adjust_inflight(-1)
        m.adjust_inflight(-1)
        m.adjust_inflight(-1)  # never below zero
        assert m.export_json()["gauges"]["inflight()"] == 0.0

    def test_track_request_brackets_inflight(self):
        from sentio_tpu.infra.metrics import MetricsCollector

        m = MetricsCollector(enabled=True)
        with m.track_request("/chat"):
            assert m.export_json()["gauges"]["inflight()"] == 1.0
        assert m.export_json()["gauges"]["inflight()"] == 0.0
