"""Failure-surface contracts (tier-1): the RPC exception codec must
round-trip EVERY SentioError subclass with its full wire surface, and
every chaos injection point planted in the package must be armed by at
least one test or bench mode (an orphaned point is dead chaos coverage).

The static halves of these contracts live in the analyzer
(sentio_tpu/analysis/failures.py, gated by test_lint.py); this file is
the runtime half — a future subclass with an incompatible ``__init__``
fails HERE, not in a chaos drill.
"""

import json
from pathlib import Path

import pytest

from sentio_tpu.infra import exceptions as exc_mod
from sentio_tpu.runtime.worker import _decode_exc, _encode_exc

REPO = Path(__file__).resolve().parents[1]


def _all_subclasses(cls):
    out = []
    for sub in cls.__subclasses__():
        out.append(sub)
        out.extend(_all_subclasses(sub))
    return out


def _taxonomy():
    """Every SentioError subclass the codec promises to round-trip —
    auto-discovered, so a new subclass joins the gate by existing.
    Test-local subclasses (other modules) are exactly the ones the codec
    deliberately degrades; they are covered separately below."""
    return sorted(
        (c for c in _all_subclasses(exc_mod.SentioError)
         if c.__module__ == "sentio_tpu.infra.exceptions"),
        key=lambda c: c.__name__,
    )


class TestCodecExhaustiveness:
    def test_taxonomy_discovered(self):
        names = {c.__name__ for c in _taxonomy()}
        assert {"ServiceOverloaded", "ReplicaUnavailable",
                "DeadlineExceededError", "GraphError"} <= names

    @pytest.mark.parametrize("cls", _taxonomy(), ids=lambda c: c.__name__)
    def test_roundtrip_preserves_wire_surface(self, cls):
        exc = cls(
            "wire probe",
            details={"k": "v", "retry_after_s": 7.25},
            retryable=True,
        )
        wire = _encode_exc(exc)
        json.dumps(wire)  # every frame payload must serialize
        back = _decode_exc(wire)
        assert type(back) is cls
        assert back.message == "wire probe"
        assert back.status == exc.status
        assert back.code == exc.code
        assert back.retryable is True
        assert back.details["k"] == "v"
        assert back.details["retry_after_s"] == 7.25
        assert getattr(back, "soft_fail_exempt", False) == getattr(
            exc, "soft_fail_exempt", False)

    def test_out_of_module_subclass_degrades_not_crashes(self):
        """The seeded codec regression, runtime half: a SentioError
        subclass the decode path cannot resolve by name degrades to a
        RuntimeError naming the original type — a worker bug must not
        masquerade as a retryable typed error, and decode must never
        crash the dispatcher."""

        class RogueError(exc_mod.SentioError):
            def __init__(self, message, slot):
                super().__init__(message)
                self.slot = slot

        wire = _encode_exc(RogueError("boom", 3))
        back = _decode_exc(wire)
        assert type(back) is RuntimeError
        assert "RogueError" in str(back)
        assert "boom" in str(back)


class TestFaultPointCoverage:
    def test_every_fault_point_armed(self):
        from sentio_tpu.analysis.failures import (
            collect_armed_points,
            collect_fault_points,
        )
        from sentio_tpu.analysis.runner import PACKAGE_ROOT, parse_paths

        pkg, errs = parse_paths([PACKAGE_ROOT])
        assert errs == []
        arming, errs = parse_paths([REPO / "tests", REPO / "bench.py"])
        assert errs == []
        points = collect_fault_points(pkg)
        armed = collect_armed_points(arming)
        orphans = sorted(set(points) - set(armed))
        assert not orphans, (
            f"fault points never armed by any test or bench mode (dead "
            f"chaos coverage): {orphans} — planted at "
            f"{[points[o] for o in orphans]}"
        )

    def test_committed_inventory_current(self):
        """analysis/fault_points.json is the committed chaos-coverage
        map; regenerate with
        ``python -m sentio_tpu.analysis.failures > sentio_tpu/analysis/fault_points.json``."""
        from sentio_tpu.analysis.failures import fault_point_inventory

        committed = json.loads(
            (REPO / "sentio_tpu/analysis/fault_points.json").read_text())
        assert committed == fault_point_inventory(), (
            "fault-point inventory drifted — regenerate "
            "sentio_tpu/analysis/fault_points.json"
        )
