"""Multi-replica serving tier (runtime/replica.py) — tier 1.

The contract under test, end to end on tiny engines (conftest arms
SENTIO_SANITIZE=1 for this module, so every tick self-checks):

* **radix-prefix affinity** — a session's follow-up routes to the replica
  whose radix cache holds its prefix, and that request's
  ``prefix_hit_tokens`` proves the KV was actually reused (not just that
  routing picked a replica); stickiness yields to least-loaded when the
  hit replica is backlogged;
* **weighted fair queueing** — a flooding tenant is capped at its
  fair-share quota below total capacity, so a second tenant's FIRST
  request is admitted (the acceptance criterion, asserted both on the
  queue in isolation and through real engines under load);
* **N=1 equivalence** — a single-replica set is a pass-through: same
  greedy tokens, same stats keys the serving gauges read;
* **chaos** — a faulted tick on one replica is contained by that replica's
  crash-containment (PR 5 fault points); every caller terminates and the
  set keeps serving;
* **fan-out lifecycle** — warmup warms every replica before returning,
  drain drains concurrently, leaked pumps sum without double-count.
"""

import threading
import time

import pytest

from sentio_tpu.infra import faults
from sentio_tpu.infra.exceptions import ServiceOverloaded
from sentio_tpu.runtime.paged import ContinuousBatchingEngine, PagedResult
from sentio_tpu.runtime.replica import (
    DEFAULT_TENANT,
    PRIORITY_BATCH,
    ReplicaSet,
    TenantFairQueue,
)
from sentio_tpu.runtime.service import PagedGenerationService


def _engine(base=None, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 4)
    kw.setdefault("steps_per_tick", 2)
    if base is not None:
        kw.setdefault("params", base.params)
        kw.setdefault("tokenizer", base.tokenizer)
    return ContinuousBatchingEngine(**kw)


@pytest.fixture(scope="module")
def replica_set():
    """One 2-replica set for the module: each new engine recompiles its jit
    variants, so tests share the set (the chaos drill resets, not poisons)."""
    e0 = _engine()
    e1 = _engine(base=e0)
    rs = ReplicaSet(
        [PagedGenerationService(e0, max_queue=8),
         PagedGenerationService(e1, max_queue=8)],
    )
    yield rs
    rs.close()


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.reset()


def _assert_pages_conserved(rs):
    for s in rs.stats()["replicas"]:
        assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
            == s["total_pages"] - 1, s


class TestTenantFairQueue:
    def test_flood_capped_and_second_tenant_admitted(self):
        """THE fairness criterion: a saturating single-tenant flood is
        quota-capped below capacity, and a second tenant's first request is
        admitted within its quota."""
        q = TenantFairQueue(capacity=16)
        shed = None
        for _ in range(20):
            try:
                q.admit("hot", 10)
            except ServiceOverloaded as exc:
                shed = exc
                break
        assert shed is not None and shed.status == 429
        assert shed.details["shed_reason"] == "tenant_quota"
        hot = q.stats()["per_tenant"]["hot"]
        assert hot["pending"] < q.capacity, "flood consumed the whole capacity"
        # the idle tenant's FIRST request lands inside the reserved headroom
        assert q.admit("idle", 10) == "idle"
        assert q.stats()["per_tenant"]["idle"]["admitted"] == 1
        # the hot tenant stays capped (its quota HALVED once idle is active)
        with pytest.raises(ServiceOverloaded):
            q.admit("hot", 10)
        # releases restore admission
        for _ in range(hot["pending"]):
            q.release("hot", 10)
        assert q.admit("hot", 10) == "hot"

    def test_weights_scale_quotas(self):
        q = TenantFairQueue(capacity=30, weights={"big": 2.0, "small": 1.0},
                            headroom=0)
        # both active: big's quota should be ~2x small's
        q.admit("big", 1)
        q.admit("small", 1)
        big_quota = small_quota = 0
        with q._mutex:
            big_quota = q._quota_locked("big", q._tenants["big"])
            small_quota = q._quota_locked("small", q._tenants["small"])
        assert big_quota == 2 * small_quota

    def test_batch_tier_sheds_before_interactive(self):
        q = TenantFairQueue(capacity=10, batch_shed_fraction=0.5, headroom=1)
        for _ in range(5):
            q.admit("a", 1)
        with pytest.raises(ServiceOverloaded) as exc_info:
            q.admit("b", 1, priority=PRIORITY_BATCH)
        assert exc_info.value.status == 503
        assert exc_info.value.details["shed_reason"] == "priority_batch"
        q.admit("b", 1)  # interactive still admits at the same load

    def test_deficit_rate_limits_contended_tenant_only(self):
        q = TenantFairQueue(capacity=100, refill_tokens_per_s=1.0,
                            burst_tokens=10)
        # burn the burst while ALONE: never deficit-shed (idle capacity is
        # not rationed), even far past the credit
        for _ in range(30):
            q.admit("solo", 5)
        with q._mutex:
            assert q._tenants["solo"].deficit < 0
        # a second tenant appears → solo is now contended and broke
        q.admit("other", 1)
        with pytest.raises(ServiceOverloaded) as exc_info:
            q.admit("solo", 5)
        assert exc_info.value.details["shed_reason"] == "tenant_deficit"
        assert exc_info.value.details["retry_after_s"] >= 0.5
        # the fresh tenant has full burst credit
        q.admit("other", 5)

    def test_release_corrects_estimate_to_actual(self):
        q = TenantFairQueue(capacity=10, refill_tokens_per_s=1.0,
                            burst_tokens=100)
        q.admit("t", 60)
        with q._mutex:
            assert q._tenants["t"].deficit == pytest.approx(40, abs=1)
        q.release("t", 60, actual_tokens=10)  # stopped early: credit back
        with q._mutex:
            assert q._tenants["t"].deficit == pytest.approx(90, abs=1)
        assert q.stats()["per_tenant"]["t"]["tokens"] == 10

    def test_tenant_cardinality_bounded(self):
        q = TenantFairQueue(capacity=10_000)
        # 20 over the cap: few enough that the shared overflow bucket stays
        # inside its own fair-share quota (overflow tenants still queue)
        for i in range(TenantFairQueue.MAX_TRACKED + 20):
            charged = q.admit(f"t{i}", 1)
        assert charged == TenantFairQueue.OVERFLOW_TENANT
        assert len(q.stats()["per_tenant"]) <= TenantFairQueue.MAX_TRACKED + 1

    def test_tenant_metrics_recorded(self):
        from sentio_tpu.infra.metrics import MetricsCollector, set_metrics

        collector = MetricsCollector()
        set_metrics(collector)
        try:
            q = TenantFairQueue(capacity=4, headroom=1)
            for _ in range(4):
                try:
                    q.admit("m", 1)
                except ServiceOverloaded:
                    pass
            counters = collector.memory.snapshot()["counters"]
            assert counters.get("tenant_admitted('m',)", 0) >= 1
            assert counters.get("tenant_shed('m', 'tenant_quota')", 0) >= 1
        finally:
            set_metrics(None)


class TestIsolation:
    def test_shared_engine_rejected(self, replica_set):
        svc = replica_set._services[0]
        with pytest.raises(ValueError, match="share"):
            ReplicaSet([svc, PagedGenerationService(svc.engine)])

    def test_sanitizer_guard_named_per_replica(self, replica_set):
        guard = replica_set._services[1].engine._san
        assert guard is not None and "[r1]" in guard.name


class TestRouting:
    SESSION = ("session head for affinity routing spanning several pages "
               "of cached prefix easily")

    def test_two_turn_session_lands_on_prefix_holder(self, replica_set):
        rs = replica_set
        first = rs.generate(self.SESSION + " turn one", max_new_tokens=3,
                            temperature=0.0, timeout_s=120)
        assert first.finish_reason in ("stop", "length")
        toks = rs._route_tokens(self.SESSION + " turn two")
        peeks = [svc.engine.peek_prefix(toks) for svc in rs._services]
        holder = max(range(len(peeks)), key=lambda i: peeks[i])
        assert peeks[holder] > 0, "first turn left no cached prefix"
        routed, hit = rs._route(toks)
        assert routed == holder and hit == peeks[holder]
        # end to end: the second turn's result PROVES the KV reuse
        hits_before = rs.stats()["replicas"][holder]["prefix_hit_tokens"]
        second = rs.generate(self.SESSION + " turn two", max_new_tokens=3,
                             temperature=0.0, timeout_s=120)
        assert second.prefix_hit_tokens > 0
        hits_after = rs.stats()["replicas"][holder]["prefix_hit_tokens"]
        assert hits_after - hits_before >= second.prefix_hit_tokens

    def test_stickiness_yields_under_backlog(self, replica_set, monkeypatch):
        rs = replica_set
        toks = rs._route_tokens(self.SESSION + " turn three")
        holder, hit = rs._route(toks)
        assert hit > 0
        # the prefix holder reports a backlog past the stickiness bound:
        # routing must fall through to least-loaded (the OTHER replica)
        monkeypatch.setattr(rs._services[holder], "backlog", lambda: 10_000)
        monkeypatch.setattr(rs._services[holder], "projected_wait",
                            lambda: 100.0)
        routed, hit2 = rs._route(toks)
        assert routed != holder and hit2 == 0
        stats = rs.stats()["routing"]
        assert stats["affinity_overflow"] >= 1

    def test_cold_prompt_routes_least_loaded(self, replica_set, monkeypatch):
        rs = replica_set
        toks = rs._route_tokens("entirely novel prompt with no cached head")
        assert all(svc.engine.peek_prefix(toks) == 0 for svc in rs._services)
        monkeypatch.setattr(rs._services[0], "projected_wait", lambda: 9.0)
        monkeypatch.setattr(rs._services[1], "projected_wait", lambda: 0.1)
        assert rs._route(toks)[0] == 1

    def test_peek_prefix_takes_no_refcounts_and_no_lru_touch(self):
        from sentio_tpu.runtime.radix import RadixPrefixCache

        class _Alloc:
            def free(self, ids):
                pass

        cache = RadixPrefixCache(page_size=4, allocator=_Alloc())
        toks = list(range(8))
        node, _donated = cache.insert(toks, 0, [1, 2])
        before = (node.refcount, node.last_used)
        assert cache.peek_prefix(toks + [99]) == 8
        assert cache.peek_prefix(toks[:5]) == 4  # page-aligned partial
        assert cache.peek_prefix([7, 7, 7, 7]) == 0
        assert (node.refcount, node.last_used) == before, (
            "peek_prefix must not pin or LRU-touch nodes"
        )
        # match() by contrast DOES touch LRU — the probe is the exception
        cache.match(toks)
        assert node.last_used != before[1]


class TestEquivalence:
    def test_n1_set_is_a_pass_through(self):
        engine = _engine()
        svc = PagedGenerationService(engine)
        rs = ReplicaSet([svc])
        try:
            prompt = "single replica equivalence check prompt"
            direct = svc.generate(prompt, max_new_tokens=6, temperature=0.0,
                                  timeout_s=120)
            routed = rs.generate(prompt, max_new_tokens=6, temperature=0.0,
                                 timeout_s=120)
            assert routed.tokens == direct.tokens
            stats = rs.stats()
            # every key the serving gauges read must survive aggregation
            for key in ("active_slots", "queued", "queued_inbox",
                        "free_pages", "total_pages", "completed", "ticks",
                        "max_queue", "shed", "expired", "pump_leaked",
                        "avg_active_slots", "max_active_slots",
                        "pool_hbm_bytes", "draining"):
                assert key in stats, key
            assert stats["n_replicas"] == 1
            assert stats["completed"] == svc.stats()["completed"]
        finally:
            rs.close()


class TestWfqThroughEngines:
    def test_flooding_tenant_cannot_starve_second_tenant(self):
        """End to end through real engines: tenant A floods past its quota
        (typed 429s observed, reason ``tenant_quota``), and tenant B's
        request — arriving mid-flood — is admitted and completes. A
        dedicated set with a large headroom pins A's quota at 4 of the 16
        queue slots, so the quota layer (not the per-replica queue bound)
        is provably what capped the flood."""
        e0 = _engine()
        e1 = _engine(base=e0)
        rs = ReplicaSet(
            [PagedGenerationService(e0, max_queue=8),
             PagedGenerationService(e1, max_queue=8)],
            tenant_headroom=12,  # capacity 16 → lone-tenant quota 4
        )
        outcomes: list = []

        def flood(i):
            try:
                outcomes.append(rs.generate(
                    f"tenant a flood request number {i}", max_new_tokens=12,
                    temperature=0.0, timeout_s=120, tenant="team-a",
                ))
            except ServiceOverloaded as exc:
                outcomes.append(exc)

        try:
            threads = [threading.Thread(target=flood, args=(i,))
                       for i in range(10)]
            for t in threads:
                t.start()
            # the first admissions pay the fresh engines' compile (seconds),
            # so the flood saturates its 4-slot quota long before anything
            # completes; wait until that is observable
            deadline = time.monotonic() + 60
            saturated = False
            while time.monotonic() < deadline and not saturated:
                a = rs.tenants.stats()["per_tenant"].get("team-a")
                saturated = bool(a and a["shed"] >= 1)
                time.sleep(0.002)
            assert saturated, "flood never hit tenant A's quota"
            # mid-flood, tenant B's FIRST request is admitted within its
            # quota and completes — A cannot starve it
            result_b = rs.generate("tenant b first request", max_new_tokens=3,
                                   temperature=0.0, timeout_s=120,
                                   tenant="team-b")
            assert result_b.finish_reason in ("stop", "length")
            for t in threads:
                t.join(timeout=180)
            sheds = [o for o in outcomes if isinstance(o, ServiceOverloaded)]
            dones = [o for o in outcomes if isinstance(o, PagedResult)]
            assert sheds, "the flood was never shed"
            assert all(e.details.get("shed_reason") == "tenant_quota"
                       and e.details.get("tenant") == "team-a"
                       for e in sheds), sheds
            assert dones, "the flood tenant must still be served within quota"
            tenants = rs.tenants.stats()["per_tenant"]
            assert tenants["team-a"]["shed"] >= 1
            assert tenants["team-b"]["shed"] == 0
            assert tenants["team-b"]["admitted"] == 1
            _assert_pages_conserved(rs)
        finally:
            rs.close()


class TestChaos:
    def test_one_replica_faults_others_keep_serving(self, replica_set):
        """PR 5 fault points through the set: a one-shot tick fault hits
        whichever replica ticks next; its crash containment requeues, the
        other replica never notices, every caller terminates."""
        rs = replica_set
        outcomes: dict = {}

        def call(i):
            try:
                outcomes[i] = rs.generate(
                    f"chaos replica load {i}", max_new_tokens=4,
                    temperature=0.0, timeout_s=120,
                )
            except Exception as exc:  # noqa: BLE001 — typed errors terminal
                outcomes[i] = exc

        with faults.inject("paged.step", error=RuntimeError("replica chaos"),
                           times=2) as rule:
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert not any(t.is_alive() for t in threads)
        assert rule.fired >= 1
        assert len(outcomes) == 6
        # the set survived: a post-chaos request works end to end
        ok = rs.generate("post replica chaos sanity", max_new_tokens=3,
                         timeout_s=120)
        assert ok.finish_reason in ("stop", "length")
        agg = rs.stats()
        assert agg["tick_failures"] >= 1
        _assert_pages_conserved(rs)


class TestLifecycleFanOut:
    def test_warmup_warms_every_replica(self):
        e0 = _engine()
        e1 = _engine(base=e0)
        rs = ReplicaSet([PagedGenerationService(e0),
                         PagedGenerationService(e1)])
        try:
            out = rs.warmup(max_new_tokens=2)
            assert out["replicas"] == 2
            assert out["prompts"] > 0
            for s in rs.stats()["replicas"]:
                assert s["completed"] > 0, (
                    f"replica {s['replica']} was never warmed: {s}"
                )
        finally:
            rs.close()

    def test_drain_concurrent_and_aggregated(self, replica_set):
        out = replica_set.drain(deadline_s=30.0)
        assert out["drained"] is True
        assert out["abandoned"] == 0
        assert [r["replica"] for r in out["replicas"]] == [0, 1]
        with pytest.raises((RuntimeError, ServiceOverloaded)):
            replica_set.generate("after drain", max_new_tokens=2)

    def test_leaked_pump_sums_without_double_count(self):
        e0 = _engine()
        e1 = _engine(base=e0)
        svc0 = PagedGenerationService(e0)
        svc1 = PagedGenerationService(e1)
        rs = ReplicaSet([svc0, svc1])
        release = threading.Event()

        class StuckPump:
            name = "paged-decode-pump"
            daemon = True

            def join(self, timeout=None):
                pass

            def is_alive(self):
                return not release.is_set()

        with svc1._mutex:
            svc1._pump = StuckPump()
        rs.close()
        stats = rs.stats()
        assert stats["pump_leaked"] == 1
        assert [s["pump_leaked"] for s in stats["replicas"]] == [0, 1]
        release.set()


class TestMeshSplit:
    def test_split_dp_into_disjoint_submeshes(self):
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import AXIS_DP, build_mesh, split_mesh_dp

        mesh = build_mesh(MeshConfig())  # 8 virtual CPU devices, all on dp
        subs = split_mesh_dp(mesh, 2)
        assert len(subs) == 2
        seen = set()
        for sub in subs:
            assert sub.shape[AXIS_DP] == mesh.shape[AXIS_DP] // 2
            ids = {d.id for d in sub.devices.flat}
            assert not (ids & seen), "replicas share devices"
            seen |= ids
        assert len(seen) == len(list(mesh.devices.flat))

    def test_ragged_split_raises(self):
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import MeshError, build_mesh, split_mesh_dp

        mesh = build_mesh(MeshConfig())
        with pytest.raises(MeshError, match="not divisible"):
            split_mesh_dp(mesh, 3)
