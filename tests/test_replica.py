"""Multi-replica serving tier (runtime/replica.py) — tier 1.

The contract under test, end to end on tiny engines (conftest arms
SENTIO_SANITIZE=1 for this module, so every tick self-checks):

* **radix-prefix affinity** — a session's follow-up routes to the replica
  whose radix cache holds its prefix, and that request's
  ``prefix_hit_tokens`` proves the KV was actually reused (not just that
  routing picked a replica); stickiness yields to least-loaded when the
  hit replica is backlogged;
* **weighted fair queueing** — a flooding tenant is capped at its
  fair-share quota below total capacity, so a second tenant's FIRST
  request is admitted (the acceptance criterion, asserted both on the
  queue in isolation and through real engines under load);
* **N=1 equivalence** — a single-replica set is a pass-through: same
  greedy tokens, same stats keys the serving gauges read;
* **chaos** — a faulted tick on one replica is contained by that replica's
  crash-containment (PR 5 fault points); every caller terminates and the
  set keeps serving;
* **fan-out lifecycle** — warmup warms every replica before returning,
  drain drains concurrently, leaked pumps sum without double-count.
"""

import threading
import time

import pytest

from sentio_tpu.infra import faults
from sentio_tpu.infra.exceptions import ReplicaUnavailable, ServiceOverloaded
from sentio_tpu.runtime.paged import ContinuousBatchingEngine, PagedResult
from sentio_tpu.runtime.replica import (
    DEFAULT_TENANT,
    HEALTH_DEGRADED,
    HEALTH_HEALTHY,
    HEALTH_QUARANTINED,
    HEALTH_REBUILDING,
    PRIORITY_BATCH,
    ReplicaSet,
    TenantFairQueue,
)
from sentio_tpu.runtime.service import PagedGenerationService


def _engine(base=None, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 4)
    kw.setdefault("steps_per_tick", 2)
    if base is not None:
        kw.setdefault("params", base.params)
        kw.setdefault("tokenizer", base.tokenizer)
    return ContinuousBatchingEngine(**kw)


@pytest.fixture(scope="module")
def replica_set():
    """One 2-replica set for the module: each new engine recompiles its jit
    variants, so tests share the set (the chaos drill resets, not poisons)."""
    e0 = _engine()
    e1 = _engine(base=e0)
    rs = ReplicaSet(
        [PagedGenerationService(e0, max_queue=8),
         PagedGenerationService(e1, max_queue=8)],
        # no supervisor thread: routing/health tests flip states by hand
        # and must not race an async rebuild (the supervised path is
        # drilled end to end in test_chaos + TestSupervisor below)
        supervise=False,
    )
    yield rs
    rs.close()


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.reset()


def _assert_pages_conserved(rs):
    for s in rs.stats()["replicas"]:
        assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
            == s["total_pages"] - 1, s


class TestTenantFairQueue:
    def test_flood_capped_and_second_tenant_admitted(self):
        """THE fairness criterion: a saturating single-tenant flood is
        quota-capped below capacity, and a second tenant's first request is
        admitted within its quota."""
        q = TenantFairQueue(capacity=16)
        shed = None
        for _ in range(20):
            try:
                q.admit("hot", 10)
            except ServiceOverloaded as exc:
                shed = exc
                break
        assert shed is not None and shed.status == 429
        assert shed.details["shed_reason"] == "tenant_quota"
        hot = q.stats()["per_tenant"]["hot"]
        assert hot["pending"] < q.capacity, "flood consumed the whole capacity"
        # the idle tenant's FIRST request lands inside the reserved headroom
        assert q.admit("idle", 10) == "idle"
        assert q.stats()["per_tenant"]["idle"]["admitted"] == 1
        # the hot tenant stays capped (its quota HALVED once idle is active)
        with pytest.raises(ServiceOverloaded):
            q.admit("hot", 10)
        # releases restore admission
        for _ in range(hot["pending"]):
            q.release("hot", 10)
        assert q.admit("hot", 10) == "hot"

    def test_weights_scale_quotas(self):
        q = TenantFairQueue(capacity=30, weights={"big": 2.0, "small": 1.0},
                            headroom=0)
        # both active: big's quota should be ~2x small's
        q.admit("big", 1)
        q.admit("small", 1)
        big_quota = small_quota = 0
        with q._mutex:
            big_quota = q._quota_locked("big", q._tenants["big"])
            small_quota = q._quota_locked("small", q._tenants["small"])
        assert big_quota == 2 * small_quota

    def test_batch_tier_sheds_before_interactive(self):
        q = TenantFairQueue(capacity=10, batch_shed_fraction=0.5, headroom=1)
        for _ in range(5):
            q.admit("a", 1)
        with pytest.raises(ServiceOverloaded) as exc_info:
            q.admit("b", 1, priority=PRIORITY_BATCH)
        assert exc_info.value.status == 503
        assert exc_info.value.details["shed_reason"] == "priority_batch"
        q.admit("b", 1)  # interactive still admits at the same load

    def test_deficit_rate_limits_contended_tenant_only(self):
        q = TenantFairQueue(capacity=100, refill_tokens_per_s=1.0,
                            burst_tokens=10)
        # burn the burst while ALONE: never deficit-shed (idle capacity is
        # not rationed), even far past the credit
        for _ in range(30):
            q.admit("solo", 5)
        with q._mutex:
            assert q._tenants["solo"].deficit < 0
        # a second tenant appears → solo is now contended and broke
        q.admit("other", 1)
        with pytest.raises(ServiceOverloaded) as exc_info:
            q.admit("solo", 5)
        assert exc_info.value.details["shed_reason"] == "tenant_deficit"
        assert exc_info.value.details["retry_after_s"] >= 0.5
        # the fresh tenant has full burst credit
        q.admit("other", 5)

    def test_release_corrects_estimate_to_actual(self):
        q = TenantFairQueue(capacity=10, refill_tokens_per_s=1.0,
                            burst_tokens=100)
        q.admit("t", 60)
        with q._mutex:
            assert q._tenants["t"].deficit == pytest.approx(40, abs=1)
        q.release("t", 60, actual_tokens=10)  # stopped early: credit back
        with q._mutex:
            assert q._tenants["t"].deficit == pytest.approx(90, abs=1)
        assert q.stats()["per_tenant"]["t"]["tokens"] == 10

    def test_tenant_cardinality_bounded(self):
        q = TenantFairQueue(capacity=10_000)
        # 20 over the cap: few enough that the shared overflow bucket stays
        # inside its own fair-share quota (overflow tenants still queue)
        for i in range(TenantFairQueue.MAX_TRACKED + 20):
            charged = q.admit(f"t{i}", 1)
        assert charged == TenantFairQueue.OVERFLOW_TENANT
        assert len(q.stats()["per_tenant"]) <= TenantFairQueue.MAX_TRACKED + 1

    def test_tenant_metrics_recorded(self):
        from sentio_tpu.infra.metrics import MetricsCollector, set_metrics

        collector = MetricsCollector()
        set_metrics(collector)
        try:
            q = TenantFairQueue(capacity=4, headroom=1)
            for _ in range(4):
                try:
                    q.admit("m", 1)
                except ServiceOverloaded:
                    pass
            counters = collector.memory.snapshot()["counters"]
            assert counters.get("tenant_admitted('m',)", 0) >= 1
            assert counters.get("tenant_shed('m', 'tenant_quota')", 0) >= 1
        finally:
            set_metrics(None)


class TestIsolation:
    def test_shared_engine_rejected(self, replica_set):
        svc = replica_set._services[0]
        with pytest.raises(ValueError, match="share"):
            ReplicaSet([svc, PagedGenerationService(svc.engine)])

    def test_sanitizer_guard_named_per_replica(self, replica_set):
        guard = replica_set._services[1].engine._san
        assert guard is not None and "[r1]" in guard.name


class TestRouting:
    SESSION = ("session head for affinity routing spanning several pages "
               "of cached prefix easily")

    def test_two_turn_session_lands_on_prefix_holder(self, replica_set):
        rs = replica_set
        first = rs.generate(self.SESSION + " turn one", max_new_tokens=3,
                            temperature=0.0, timeout_s=120)
        assert first.finish_reason in ("stop", "length")
        toks = rs._route_tokens(self.SESSION + " turn two")
        peeks = [svc.engine.peek_prefix(toks) for svc in rs._services]
        holder = max(range(len(peeks)), key=lambda i: peeks[i])
        assert peeks[holder] > 0, "first turn left no cached prefix"
        routed, hit = rs._route(toks)
        assert routed == holder and hit == peeks[holder]
        # end to end: the second turn's result PROVES the KV reuse
        hits_before = rs.stats()["replicas"][holder]["prefix_hit_tokens"]
        second = rs.generate(self.SESSION + " turn two", max_new_tokens=3,
                             temperature=0.0, timeout_s=120)
        assert second.prefix_hit_tokens > 0
        hits_after = rs.stats()["replicas"][holder]["prefix_hit_tokens"]
        assert hits_after - hits_before >= second.prefix_hit_tokens

    def test_stickiness_yields_under_backlog(self, replica_set, monkeypatch):
        rs = replica_set
        toks = rs._route_tokens(self.SESSION + " turn three")
        holder, hit = rs._route(toks)
        assert hit > 0
        # the prefix holder reports a backlog past the stickiness bound:
        # routing must fall through to least-loaded (the OTHER replica)
        monkeypatch.setattr(rs._services[holder], "backlog", lambda: 10_000)
        monkeypatch.setattr(rs._services[holder], "projected_wait",
                            lambda: 100.0)
        routed, hit2 = rs._route(toks)
        assert routed != holder and hit2 == 0
        stats = rs.stats()["routing"]
        assert stats["affinity_overflow"] >= 1

    def test_cold_prompt_routes_least_loaded(self, replica_set, monkeypatch):
        rs = replica_set
        toks = rs._route_tokens("entirely novel prompt with no cached head")
        assert all(svc.engine.peek_prefix(toks) == 0 for svc in rs._services)
        monkeypatch.setattr(rs._services[0], "projected_wait", lambda: 9.0)
        monkeypatch.setattr(rs._services[1], "projected_wait", lambda: 0.1)
        assert rs._route(toks)[0] == 1

    def test_peek_prefix_takes_no_refcounts_and_no_lru_touch(self):
        from sentio_tpu.runtime.radix import RadixPrefixCache

        class _Alloc:
            def free(self, ids):
                pass

        cache = RadixPrefixCache(page_size=4, allocator=_Alloc())
        toks = list(range(8))
        node, _donated = cache.insert(toks, 0, [1, 2])
        before = (node.refcount, node.last_used)
        assert cache.peek_prefix(toks + [99]) == 8
        assert cache.peek_prefix(toks[:5]) == 4  # page-aligned partial
        assert cache.peek_prefix([7, 7, 7, 7]) == 0
        assert (node.refcount, node.last_used) == before, (
            "peek_prefix must not pin or LRU-touch nodes"
        )
        # match() by contrast DOES touch LRU — the probe is the exception
        cache.match(toks)
        assert node.last_used != before[1]


class TestEquivalence:
    def test_n1_set_is_a_pass_through(self):
        engine = _engine()
        svc = PagedGenerationService(engine)
        rs = ReplicaSet([svc])
        try:
            prompt = "single replica equivalence check prompt"
            direct = svc.generate(prompt, max_new_tokens=6, temperature=0.0,
                                  timeout_s=120)
            routed = rs.generate(prompt, max_new_tokens=6, temperature=0.0,
                                 timeout_s=120)
            assert routed.tokens == direct.tokens
            stats = rs.stats()
            # every key the serving gauges read must survive aggregation
            for key in ("active_slots", "queued", "queued_inbox",
                        "free_pages", "total_pages", "completed", "ticks",
                        "max_queue", "shed", "expired", "pump_leaked",
                        "avg_active_slots", "max_active_slots",
                        "pool_hbm_bytes", "draining"):
                assert key in stats, key
            assert stats["n_replicas"] == 1
            assert stats["completed"] == svc.stats()["completed"]
        finally:
            rs.close()


class TestWfqThroughEngines:
    def test_flooding_tenant_cannot_starve_second_tenant(self):
        """End to end through real engines: tenant A floods past its quota
        (typed 429s observed, reason ``tenant_quota``), and tenant B's
        request — arriving mid-flood — is admitted and completes. A
        dedicated set with a large headroom pins A's quota at 4 of the 16
        queue slots, so the quota layer (not the per-replica queue bound)
        is provably what capped the flood."""
        e0 = _engine()
        e1 = _engine(base=e0)
        rs = ReplicaSet(
            [PagedGenerationService(e0, max_queue=8),
             PagedGenerationService(e1, max_queue=8)],
            tenant_headroom=12,  # capacity 16 → lone-tenant quota 4
        )
        outcomes: list = []

        def flood(i):
            try:
                outcomes.append(rs.generate(
                    f"tenant a flood request number {i}", max_new_tokens=12,
                    temperature=0.0, timeout_s=120, tenant="team-a",
                ))
            except ServiceOverloaded as exc:
                outcomes.append(exc)

        try:
            threads = [threading.Thread(target=flood, args=(i,))
                       for i in range(10)]
            for t in threads:
                t.start()
            # the first admissions pay the fresh engines' compile (seconds),
            # so the flood saturates its 4-slot quota long before anything
            # completes; wait until that is observable
            deadline = time.monotonic() + 60
            saturated = False
            while time.monotonic() < deadline and not saturated:
                a = rs.tenants.stats()["per_tenant"].get("team-a")
                saturated = bool(a and a["shed"] >= 1)
                time.sleep(0.002)
            assert saturated, "flood never hit tenant A's quota"
            # mid-flood, tenant B's FIRST request is admitted within its
            # quota and completes — A cannot starve it
            result_b = rs.generate("tenant b first request", max_new_tokens=3,
                                   temperature=0.0, timeout_s=120,
                                   tenant="team-b")
            assert result_b.finish_reason in ("stop", "length")
            for t in threads:
                t.join(timeout=180)
            sheds = [o for o in outcomes if isinstance(o, ServiceOverloaded)]
            dones = [o for o in outcomes if isinstance(o, PagedResult)]
            assert sheds, "the flood was never shed"
            assert all(e.details.get("shed_reason") == "tenant_quota"
                       and e.details.get("tenant") == "team-a"
                       for e in sheds), sheds
            assert dones, "the flood tenant must still be served within quota"
            tenants = rs.tenants.stats()["per_tenant"]
            assert tenants["team-a"]["shed"] >= 1
            assert tenants["team-b"]["shed"] == 0
            assert tenants["team-b"]["admitted"] == 1
            _assert_pages_conserved(rs)
        finally:
            rs.close()


class TestChaos:
    def test_one_replica_faults_others_keep_serving(self, replica_set):
        """PR 5 fault points through the set: a one-shot tick fault hits
        whichever replica ticks next; its crash containment requeues, the
        other replica never notices, every caller terminates."""
        rs = replica_set
        outcomes: dict = {}

        def call(i):
            try:
                outcomes[i] = rs.generate(
                    f"chaos replica load {i}", max_new_tokens=4,
                    temperature=0.0, timeout_s=120,
                )
            except Exception as exc:  # noqa: BLE001 — typed errors terminal
                outcomes[i] = exc

        with faults.inject("paged.step", error=RuntimeError("replica chaos"),
                           times=2) as rule:
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert not any(t.is_alive() for t in threads)
        assert rule.fired >= 1
        assert len(outcomes) == 6
        # the set survived: a post-chaos request works end to end
        ok = rs.generate("post replica chaos sanity", max_new_tokens=3,
                         timeout_s=120)
        assert ok.finish_reason in ("stop", "length")
        agg = rs.stats()
        assert agg["tick_failures"] >= 1
        _assert_pages_conserved(rs)


class TestHealthRouting:
    """Acceptance: the router NEVER selects a QUARANTINED/REBUILDING
    replica; DEGRADED replicas take traffic only when no healthy replica
    has queue headroom; zero serving replicas is a typed 503."""

    @pytest.fixture(autouse=True)
    def _restore_states(self, replica_set):
        yield
        with replica_set._mutex:
            for h in replica_set._health:
                h.state = HEALTH_HEALTHY

    def _set_state(self, rs, idx, state):
        with rs._mutex:
            rs._health[idx].state = state

    def test_router_never_selects_quarantined_or_rebuilding(self, replica_set):
        rs = replica_set
        toks = rs._route_tokens("health exclusion probe prompt")
        for state in (HEALTH_QUARANTINED, HEALTH_REBUILDING):
            self._set_state(rs, 0, state)
            for _ in range(8):
                assert rs._route(toks, count=False)[0] == 1, state
            self._set_state(rs, 0, HEALTH_HEALTHY)
            self._set_state(rs, 1, state)
            for _ in range(8):
                assert rs._route(toks, count=False)[0] == 0, state
            self._set_state(rs, 1, HEALTH_HEALTHY)

    def test_affinity_never_overrides_quarantine(self, replica_set):
        """Even the replica holding a session's cached prefix is skipped
        once quarantined — cache reuse never beats exclusion."""
        rs = replica_set
        toks = rs._route_tokens(TestRouting.SESSION + " turn four")
        holder, hit = rs._route(toks, count=False)
        if hit == 0:  # session prefix evicted: seed it again
            rs.generate(TestRouting.SESSION + " turn four",
                        max_new_tokens=2, temperature=0.0, timeout_s=120)
            holder, hit = rs._route(toks, count=False)
        assert hit > 0
        self._set_state(rs, holder, HEALTH_QUARANTINED)
        routed, _routed_hit = rs._route(toks, count=False)
        assert routed != holder

    def test_degraded_taken_only_without_healthy_headroom(self, replica_set,
                                                          monkeypatch):
        rs = replica_set
        toks = rs._route_tokens("entirely cold degraded routing probe")
        self._set_state(rs, 0, HEALTH_DEGRADED)
        # healthy replica 1 has headroom: degraded 0 is not even eligible
        assert rs._route(toks, count=False)[0] == 1
        # healthy replica saturated at its admission bound: degraded joins
        monkeypatch.setattr(rs._services[1], "backlog",
                            lambda: rs._services[1].max_queue)
        monkeypatch.setattr(rs._services[1], "projected_wait", lambda: 99.0)
        assert rs._route(toks, count=False)[0] == 0

    def test_all_down_is_typed_503_with_retry_hint(self, replica_set):
        rs = replica_set
        self._set_state(rs, 0, HEALTH_QUARANTINED)
        self._set_state(rs, 1, HEALTH_REBUILDING)
        with pytest.raises(ReplicaUnavailable) as exc_info:
            rs.generate("nowhere to go", max_new_tokens=2)
        assert exc_info.value.status == 503
        assert exc_info.value.details["retry_after_s"] >= 1.0
        # the SSE pre-check sheds the same way, BEFORE a 200 commits
        with pytest.raises(ReplicaUnavailable):
            rs.check_admission(prompt="nowhere to go")

    def test_health_summary_degraded_vs_unhealthy(self, replica_set):
        rs = replica_set
        assert rs.health_summary()["status"] == "healthy"
        self._set_state(rs, 0, HEALTH_QUARANTINED)
        summary = rs.health_summary()
        assert summary["status"] == "degraded"
        assert summary["healthy_replicas"] == 1
        assert summary["serving_replicas"] == 1
        # DEGRADED still serves: not unhealthy
        self._set_state(rs, 1, HEALTH_DEGRADED)
        assert rs.health_summary()["status"] == "degraded"
        self._set_state(rs, 1, HEALTH_REBUILDING)
        summary = rs.health_summary()
        assert summary["status"] == "unhealthy"
        assert summary["serving_replicas"] == 0


class TestSupervisor:
    """N=1 supervisor equivalence (no router involved): a single replica
    that latches broken quarantines immediately, answers typed 503s while
    down, is rebuilt in place by the supervisor pass, and serves again.
    Driven via _supervise_once for determinism (the async supervisor
    thread is exercised by the replica-kill drill in test_chaos)."""

    def test_n1_quarantine_rebuild_recover(self):
        engine = _engine()
        svc = PagedGenerationService(engine, retry_budget=0)
        svc.generate("n1 supervisor warm", max_new_tokens=2, timeout_s=180)
        rs = ReplicaSet([svc], supervise=False, quarantine_backoff_s=0.0,
                        failover_budget=1)
        try:
            with faults.inject("paged.step",
                               error=RuntimeError("n1 kill"), times=1), \
                 faults.inject("engine.reset",
                               error=RuntimeError("n1 reset denied"),
                               times=1):
                with pytest.raises(ReplicaUnavailable):
                    rs.generate("doomed", max_new_tokens=4, timeout_s=120)
            assert svc.broken
            # the caller-path breaker quarantined it without any supervisor
            assert rs.health_summary()["replicas"][0]["state"] \
                == HEALTH_QUARANTINED
            # while down: typed 503 + Retry-After, from generate AND from
            # the stream pre-check — never an untyped 500
            with pytest.raises(ReplicaUnavailable) as exc_info:
                rs.generate("while down", max_new_tokens=2)
            assert exc_info.value.status == 503
            with pytest.raises(ReplicaUnavailable):
                rs.check_admission()
            # one supervisor pass rebuilds in place (backoff 0 → due now)
            rs._supervise_once()
            summary = rs.health_summary()
            assert summary["status"] == "healthy", summary
            assert summary["replicas"][0]["rebuilds"] == 1
            ok = rs.generate("recovered", max_new_tokens=3, timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
            # the rebuilt engine is a fresh instance on the same weights
            assert rs._services[0] is not svc
            assert rs._services[0].engine is not engine
            assert rs._services[0].engine.params is engine.params
        finally:
            faults.reset()
            rs.close()

    def test_breaker_trips_on_tick_failure_burst(self):
        """Tick failures (with SUCCESSFUL resets — callers keep succeeding
        via requeue) still quarantine once the burst threshold is crossed:
        a replica that crashes every few ticks is a liability even though
        crash containment hides it from callers."""
        engine = _engine()
        svc = PagedGenerationService(engine, retry_budget=3)
        svc.generate("burst warm", max_new_tokens=2, timeout_s=180)
        rs = ReplicaSet([svc], supervise=False, breaker_tick_failures=2,
                        quarantine_backoff_s=60.0)
        try:
            with faults.inject("paged.step",
                               error=RuntimeError("flaky tick"), times=2):
                ok = rs.generate("survives the flaky ticks",
                                 max_new_tokens=4, timeout_s=120)
            assert ok.finish_reason in ("stop", "length")
            assert svc.tick_failure_count >= 2
            rs._supervise_once()
            state = rs.health_summary()["replicas"][0]["state"]
            assert state == HEALTH_QUARANTINED
            assert "tick failures" in \
                rs.health_summary()["replicas"][0]["reason"]
        finally:
            faults.reset()
            rs.close()

    def test_degraded_on_failure_then_clean_window_heals(self):
        engine = _engine()
        svc = PagedGenerationService(engine)
        rs = ReplicaSet([svc], supervise=False, breaker_window_s=0.3,
                        breaker_min_samples=50)
        try:
            rs._note_failure(0, ReplicaUnavailable("transient"))
            rs._supervise_once()
            assert rs.health_summary()["replicas"][0]["state"] \
                == HEALTH_DEGRADED
            time.sleep(0.4)  # window expires
            rs._supervise_once()
            assert rs.health_summary()["replicas"][0]["state"] \
                == HEALTH_HEALTHY
        finally:
            rs.close()

    def test_failover_releases_and_recharges_wfq(self):
        """Failover must not double-count tenant quota: after a failed-over
        generate completes, the tenant's pending count is zero and exactly
        one admission per attempt was recorded."""
        e0 = _engine()
        e1 = _engine(base=e0)
        svc0 = PagedGenerationService(e0, retry_budget=0)
        svc1 = PagedGenerationService(e1, retry_budget=0)
        svc0.generate("failover warm zero", max_new_tokens=2, timeout_s=180)
        svc1.generate("failover warm one", max_new_tokens=2, timeout_s=180)
        rs = ReplicaSet([svc0, svc1], supervise=False, failover_budget=1)
        try:
            with faults.inject("paged.step",
                               error=RuntimeError("kill once"), times=1), \
                 faults.inject("engine.reset",
                               error=RuntimeError("reset denied"), times=1):
                result = rs.generate("failover rider", max_new_tokens=4,
                                     temperature=0.0, timeout_s=120,
                                     tenant="team-f")
            assert result.finish_reason in ("stop", "length")
            stats = rs.stats()
            assert stats["failovers"] == 1
            tenant = stats["tenants"]["per_tenant"]["team-f"]
            assert tenant["pending"] == 0, "reservation leaked"
            assert tenant["admitted"] == 2, "one admission per attempt"
            # exactly one replica died and the set degraded, not collapsed
            assert [svc0.broken, svc1.broken].count(True) == 1
            assert rs.health_summary()["status"] == "degraded"
        finally:
            faults.reset()
            rs.close()


class TestStallTolerance:
    """ISSUE 10: the watchdog/handoff/rebuild-pool layer in isolation
    (the supervised end-to-end wedge is drilled in test_chaos)."""

    def test_heartbeat_age_none_when_idle(self):
        svc = PagedGenerationService(_engine(), tick_stall_budget_s=30.0)
        try:
            assert svc.heartbeat_age() is None  # no pump yet
            svc.generate("heartbeat idle probe", max_new_tokens=2,
                         timeout_s=180)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and svc.heartbeat_age() is not None:
                time.sleep(0.01)
            # pump drained and exited (or idles with zero pending): an idle
            # service is never stalled
            assert svc.heartbeat_age() is None
        finally:
            svc.close()

    def test_recharge_keeps_accounting_balanced(self):
        """The handoff's WFQ move: release + re-admit atomically — pending
        unchanged, one admission recorded; an over-quota tenant sheds typed
        with its reservation RESTORED so the caller's release balances."""
        q = TenantFairQueue(capacity=8, headroom=0)  # lone-tenant quota: 8
        q.admit("t", 10)
        q.recharge("t", 10)
        t = q.stats()["per_tenant"]["t"]
        assert t["pending"] == 1 and t["admitted"] == 2
        # t holds 6 while alone (within its lone quota of 8) ...
        for _ in range(5):
            q.admit("t", 10)
        # ... then a second tenant activates, HALVING t's quota to 4: a
        # handoff recharge now finds t over quota -> typed shed, with the
        # original reservation restored (pending untouched)
        q.admit("u", 10)
        with pytest.raises(ServiceOverloaded) as exc_info:
            q.recharge("t", 10)
        assert exc_info.value.details["shed_reason"] == "tenant_quota"
        assert q.stats()["per_tenant"]["t"]["pending"] == 6
        # unknown / already-released tenants are a no-op, never a crash
        q.recharge("ghost", 10)

    def test_stream_ticket_stamps_bucketed_tenant_key(self, monkeypatch):
        """The PR 10 recharge gap: stream tickets used to stamp the RAW
        tenant key, so a quarantine-handoff recharge of an overflow-
        bucketed stream tenant looked up a key the fair queue had never
        registered and silently skipped the re-charge. The ticket must
        carry the CHARGED key admit() actually resolved."""
        monkeypatch.setattr(TenantFairQueue, "MAX_TRACKED", 1)
        e0 = _engine()
        svc = PagedGenerationService(e0)
        rs = ReplicaSet([svc], supervise=False)
        try:
            # fill the (shrunken) tenant table so the next fresh key buckets
            rs.generate("seed tenant table", max_new_tokens=2,
                        tenant="first", timeout_s=180)
            stamped = []
            orig = svc.generate_stream

            def spy(prompt, **kwargs):
                stamped.append(kwargs.get("tenant"))
                return orig(prompt, **kwargs)

            monkeypatch.setattr(svc, "generate_stream", spy)
            out = "".join(rs.generate_stream(
                "bucketed stream tenant probe", max_new_tokens=2,
                tenant="fresh-stream-tenant", timeout_s=180,
            ))
            assert isinstance(out, str)
            # call-time iterator carries the raw key; admission resolves the
            # overflow bucket and the ticket is re-created with THAT key
            assert stamped[0] == "fresh-stream-tenant"
            assert stamped[-1] == TenantFairQueue.OVERFLOW_TENANT
            # the key on the ticket must be rechargeable while HELD — a
            # handoff moves a still-pending ticket, and its recharge must
            # record an admission instead of no-op'ing on an unknown key
            # (the raw "fresh-stream-tenant" key would hit exactly that)
            charged = rs.tenants.admit("second-fresh-tenant", 4)
            assert charged == TenantFairQueue.OVERFLOW_TENANT == stamped[-1]
            per_before = rs.tenants.stats()["per_tenant"][charged]
            rs.tenants.recharge(stamped[-1], 4)
            per_after = rs.tenants.stats()["per_tenant"][charged]
            assert per_after["admitted"] == per_before["admitted"] + 1
            assert per_after["pending"] == per_before["pending"]
            rs.tenants.release(charged, 4)
        finally:
            rs.close()

    def test_breaker_quarantine_hands_off_inbox(self):
        """Quarantine (breaker flavor, not just stall) moves the dead
        replica's queued-never-dispatched tickets to the survivor instead
        of leaving them to ride each caller's failover loop: the blocked
        caller just wakes with the survivor's result."""
        e0 = _engine()
        e1 = _engine(base=e0)
        svc0 = PagedGenerationService(e0)
        svc1 = PagedGenerationService(e1)
        svc0.generate("handoff warm zero", max_new_tokens=2, timeout_s=180)
        svc1.generate("handoff warm one", max_new_tokens=2, timeout_s=180)
        rs = ReplicaSet([svc0, svc1], supervise=False)
        try:
            # plant a ticket straight into replica 0's inbox with WFQ
            # metadata, as the router would on a submit that raced the
            # breaker (the pump is idle-exited, so it stays undispatched
            # until a pump would spawn — generate() in a thread)
            outcome: dict = {}

            def call():
                try:
                    outcome["r"] = svc0.generate(
                        "wedged in flight", max_new_tokens=3,
                        temperature=0.0, timeout_s=60,
                    )
                except Exception as exc:  # noqa: BLE001
                    outcome["r"] = exc

            # hold replica 0's pump wedged so later tickets stay queued
            release = threading.Event()
            with faults.inject("paged.step", stall_event=release,
                               stall_s=30.0, times=1) as rule:
                t = threading.Thread(target=call)
                t.start()
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and rule.stalled == 0:
                    time.sleep(0.005)
                assert rule.stalled == 1
                # second caller piles into the wedged inbox, carrying the
                # WFQ metadata the router would have stamped (plus the
                # caller-side charge it pairs with)
                rs.tenants.admit(DEFAULT_TENANT, 8)
                outcome2: dict = {}

                def call2():
                    try:
                        outcome2["r"] = svc0.generate(
                            "second queued ticket", max_new_tokens=3,
                            temperature=0.0, timeout_s=60,
                            tenant=DEFAULT_TENANT, cost_tokens=8,
                        )
                    except Exception as exc:  # noqa: BLE001
                        outcome2["r"] = exc

                t2 = threading.Thread(target=call2)
                t2.start()
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and len(svc0._inbox) < 1:
                    time.sleep(0.005)
                # breaker-flavor quarantine: inbox moves, admitted stays
                rs._quarantine(0, "seeded breaker trip")
                t2.join(timeout=60)
                assert isinstance(outcome2["r"], PagedResult), outcome2["r"]
                assert outcome2["r"].finish_reason in ("stop", "length")
                assert rs.stats()["handed_off"] >= 1
                # the first (admitted, wedged) ticket is NOT handed off —
                # it still sits on the wedged engine
                assert not outcome
                release.set()
                t.join(timeout=60)
            # breaker quarantine leaves a WORKING service: the unwedged
            # pump finishes its admitted ticket normally
            assert isinstance(outcome.get("r"), PagedResult), outcome
            tenants = rs.tenants.stats()["per_tenant"][DEFAULT_TENANT]
            rs.tenants.release(DEFAULT_TENANT, 8)
            # caller-side admit + the handoff's recharge, reservation held
            # throughout (never double-counted, never leaked)
            assert tenants["admitted"] == 2, tenants
            assert tenants["pending"] == 1, tenants
            _assert_pages_conserved(rs)
        finally:
            faults.reset()
            rs.close()

    def test_stalled_rebuild_does_not_delay_second_quarantine(self):
        """Acceptance: a rebuild wedged via the ``replica.rebuild`` stall
        fault occupies a WORKER, not the supervisor — the detection pass
        keeps its cadence and quarantines a second replica promptly, even
        with a single rebuild worker (the second rebuild just queues)."""
        from sentio_tpu.runtime.replica import HEALTH_REBUILDING

        e0 = _engine()
        e1 = _engine(base=e0)
        svc0 = PagedGenerationService(e0, retry_budget=0)
        svc1 = PagedGenerationService(e1, retry_budget=0)
        svc0.generate("pool warm zero", max_new_tokens=2, timeout_s=180)
        svc1.generate("pool warm one", max_new_tokens=2, timeout_s=180)
        rs = ReplicaSet(
            [svc0, svc1],
            probe_interval_s=0.02, quarantine_backoff_s=0.0,
            rebuild_drain_s=0.2, failover_budget=1, rebuild_workers=1,
        )
        release = threading.Event()
        try:
            # wedge replica 0's rebuild on the worker
            rule = faults.FaultRule(stall_event=release, stall_s=60.0,
                                    times=1)
            faults.arm("replica.rebuild", rule)
            rs._quarantine(0, "seeded for wedged rebuild")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and rule.stalled == 0:
                time.sleep(0.01)
            assert rule.stalled == 1, "rebuild never started on the worker"
            assert rs.health_summary()["replicas"][0]["state"] \
                == HEALTH_REBUILDING
            # with the rebuild wedged, kill replica 1: the supervisor's
            # detection pass must quarantine it promptly
            with faults.inject("paged.step",
                               error=RuntimeError("kill two"), times=1), \
                 faults.inject("engine.reset",
                               error=RuntimeError("reset denied"), times=1):
                with pytest.raises(ReplicaUnavailable):
                    rs.generate("doomed on replica one", max_new_tokens=4,
                                timeout_s=120)
            t_kill = time.monotonic()
            deadline = time.monotonic() + 10
            state = None
            while time.monotonic() < deadline:
                state = rs.health_summary()["replicas"][1]["state"]
                if state == HEALTH_QUARANTINED:
                    break
                time.sleep(0.01)
            assert state == HEALTH_QUARANTINED, (
                f"second quarantine waited on the wedged rebuild: {state}"
            )
            assert time.monotonic() - t_kill < 5.0
            # replica 0 is still wedged mid-rebuild the whole time
            assert rs.health_summary()["replicas"][0]["state"] \
                == HEALTH_REBUILDING
            # release: replica 0's rebuild completes, then the worker picks
            # up replica 1's queued rebuild; the set returns to health
            release.set()
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if rs.health_summary()["status"] == "healthy":
                    break
                time.sleep(0.05)
            summary = rs.health_summary()
            assert summary["status"] == "healthy", summary
            assert summary["replicas"][0]["rebuilds"] == 1
            assert summary["replicas"][1]["rebuilds"] == 1
            ok = rs.generate("post pool recovery", max_new_tokens=3,
                             timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
        finally:
            release.set()
            faults.reset()
            rs.close()


class TestResumableStreamWfq:
    """ISSUE 14: WFQ tenant accounting must stay balanced across every
    resume path. Each attempt — fresh, failed-over, or resumed by
    replay-prefill — releases its reservation before re-charging, so a
    resumed stream records exactly one admission per attempt and leaves
    ``pending`` at zero whether the resume succeeded, exhausted its
    budget, or rode an overflow-bucketed tenant key."""

    PROMPT = "wfq conservation drill stream with a decent prompt body"

    @staticmethod
    def _two_replica_set(**svc_kw):
        e0 = _engine()
        e1 = _engine(base=e0)
        svc0 = PagedGenerationService(e0, **svc_kw)
        svc1 = PagedGenerationService(e1, **svc_kw)
        # both warmed BEFORE any fault arms: warmup ticks must not eat a
        # skip-counted fault hit, and idle pumps exit after draining so the
        # drill stream's replica is the only one stepping
        svc0.generate("wfq warm zero", max_new_tokens=2, timeout_s=180)
        svc1.generate("wfq warm one", max_new_tokens=2, timeout_s=180)
        return svc0, svc1

    def test_successful_resume_balances_tenant_accounting(self):
        """(a) a mid-stream death resumed onto the survivor: the stream
        completes, one admission per attempt, zero pending after."""
        svc0, svc1 = self._two_replica_set()
        rs = ReplicaSet([svc0, svc1], supervise=False, failover_budget=1)
        try:
            # tick 1 delivers a chunk (skip=1), tick 2 dies: at least one
            # token is always delivered before the death
            faults.arm("paged.step", faults.FaultRule(
                error=RuntimeError("wfq drill: midstream death"),
                times=1, skip=1))
            out = "".join(rs.generate_stream(
                self.PROMPT, max_new_tokens=8, temperature=0.0,
                timeout_s=120, tenant="team-r",
            ))
            faults.reset()
            assert out
            stats = rs.stats()
            assert stats["stream_resumes"] == 1
            assert stats["resume_exhausted"] == 0
            tenant = stats["tenants"]["per_tenant"]["team-r"]
            assert tenant["pending"] == 0, "reservation leaked"
            assert tenant["admitted"] == 2, "one admission per attempt"
        finally:
            faults.reset()
            rs.close()

    def test_exhausted_budget_balances_and_stays_typed(self):
        """(b) the resumed attempt dies too and the budget is spent: the
        caller gets the typed mid-stream error, the exhausted outcome is
        counted, and the tenant's ledger is still balanced."""
        # retry_budget=0: the survivor's failed tick kills the resumed
        # ticket typed instead of requeueing it service-side, so the second
        # death deterministically reaches the router's budget check
        svc0, svc1 = self._two_replica_set(retry_budget=0)
        rs = ReplicaSet([svc0, svc1], supervise=False, failover_budget=1)
        try:
            # hit 1 passes (a chunk delivers), hits 2+3 die: the original
            # replica mid-stream, then the survivor's resumed attempt
            faults.arm("paged.step", faults.FaultRule(
                error=RuntimeError("wfq drill: double death"),
                times=2, skip=1))
            with pytest.raises(ReplicaUnavailable):
                for _ in rs.generate_stream(
                        self.PROMPT, max_new_tokens=8, temperature=0.0,
                        timeout_s=120, tenant="team-x"):
                    pass
            faults.reset()
            stats = rs.stats()
            assert stats["stream_resumes"] == 1, "first resume still books"
            assert stats["resume_exhausted"] == 1
            tenant = stats["tenants"]["per_tenant"]["team-x"]
            assert tenant["pending"] == 0, "reservation leaked"
            assert tenant["admitted"] == 2, "one admission per attempt"
        finally:
            faults.reset()
            rs.close()

    def test_overflow_bucketed_tenant_resumes_balanced(self, monkeypatch):
        """(c) the PR 11(a) regression shape under RESUME: a stream whose
        fresh tenant key overflow-bucketed at admission must release and
        re-charge the CHARGED key on every resume attempt — the raw key
        was never registered and would silently leak the reservation."""
        monkeypatch.setattr(TenantFairQueue, "MAX_TRACKED", 1)
        svc0, svc1 = self._two_replica_set()
        rs = ReplicaSet([svc0, svc1], supervise=False, failover_budget=1)
        try:
            # fill the (shrunken) tenant table so the stream's key buckets
            rs.generate("seed tenant table", max_new_tokens=2,
                        tenant="first", timeout_s=180)
            overflow = TenantFairQueue.OVERFLOW_TENANT
            # the bucket only registers at its first admission
            before = rs.tenants.stats()["per_tenant"].get(
                overflow, {"pending": 0, "admitted": 0})
            assert before["pending"] == 0
            faults.arm("paged.step", faults.FaultRule(
                error=RuntimeError("wfq drill: bucketed death"),
                times=1, skip=1))
            out = "".join(rs.generate_stream(
                self.PROMPT, max_new_tokens=8, temperature=0.0,
                timeout_s=120, tenant="fresh-stream-tenant",
            ))
            faults.reset()
            assert out
            assert rs.stats()["stream_resumes"] == 1
            after = rs.tenants.stats()["per_tenant"][overflow]
            assert after["pending"] == 0, "bucketed reservation leaked"
            assert after["admitted"] == before["admitted"] + 2, (
                "one admission per attempt on the CHARGED key"
            )
        finally:
            faults.reset()
            rs.close()


class TestVerifyTenantCharging:
    """ROADMAP item 1 leftover: verify-node decode admissions must be
    charged to the REQUESTING tenant's WFQ quota, not the shared default —
    otherwise one tenant's verify traffic rides free and can starve every
    other tenant."""

    def _verifier_over(self, service):
        from sentio_tpu.config import GeneratorConfig
        from sentio_tpu.ops.generator import LLMGenerator, TpuProvider
        from sentio_tpu.ops.verifier import AnswerVerifier

        cfg = GeneratorConfig(provider="tpu", verifier_max_tokens=8)
        generator = LLMGenerator(
            provider=TpuProvider(service=service), config=cfg)
        return AnswerVerifier(generator=generator, config=cfg)

    def test_verify_charges_request_tenant_and_cannot_starve(self):
        """A flooding tenant's verify calls saturate ITS quota (typed sheds
        → degraded 'warn' verdicts), while another tenant's verify call
        admits mid-flood and completes — through a real TenantFairQueue."""
        import queue as _q

        release = threading.Event()
        charged: list[str] = []
        queue = TenantFairQueue(capacity=4, headroom=2)  # lone quota: 2

        class GatedSet:
            """Replica-tier-shaped fake: supports_tenants + a real WFQ in
            front of a generate that holds its admission until released
            (standing in for a slow decode)."""

            supports_tenants = True

            def generate(self, prompt, max_new_tokens=64, temperature=0.0,
                         request_id=None, deadline_ts=None, tenant=None,
                         priority=None, **kw):
                key = queue.admit(tenant or DEFAULT_TENANT, 8)
                charged.append(key)
                try:
                    release.wait(30)
                finally:
                    queue.release(key, 8)
                return PagedResult(
                    request_id=0,
                    text='{"verdict": "pass", "citations_ok": true, '
                         '"notes": []}',
                    tokens=[1], prompt_tokens=1, finish_reason="stop",
                )

        verifier = self._verifier_over(GatedSet())
        results: dict[str, object] = {}

        def verify_as(tag, tenant):
            results[tag] = verifier.verify(
                "q?", "answer", [], tenant=tenant)

        hold = [threading.Thread(target=verify_as, args=(f"a{i}", "team-a"))
                for i in range(2)]
        for t in hold:
            t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(charged) < 2:
            time.sleep(0.005)
        assert charged.count("team-a") == 2
        # 3rd team-a verify: over ITS quota → typed shed → warn verdict
        verify_as("a2", "team-a")
        warn = results["a2"]
        assert warn.verdict == "warn"
        assert any("quota" in note for note in warn.notes), warn.notes
        # team-b's verify admits inside the reserved headroom mid-flood
        b = threading.Thread(target=verify_as, args=("b0", "team-b"))
        b.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and "team-b" not in charged:
            time.sleep(0.005)
        assert "team-b" in charged, "tenant B's verify was starved"
        release.set()
        for t in hold:
            t.join(timeout=30)
        b.join(timeout=30)
        assert results["b0"].verdict == "pass"

    def test_verify_node_threads_tenant_from_metadata(self):
        import asyncio

        from sentio_tpu.graph.nodes import create_verifier_node
        from sentio_tpu.ops.verifier import VerifyResult

        captured: dict = {}

        class StubVerifier:
            def verify(self, query, answer, docs, request_id=None,
                       deadline_ts=None, tenant=None, priority=None):
                captured.update(tenant=tenant, priority=priority,
                                request_id=request_id)
                return VerifyResult(verdict="pass")

        from sentio_tpu.config import Settings

        node = create_verifier_node(StubVerifier(), settings=Settings())
        state = {
            "query": "q?",
            "response": "an answer",
            "retrieved_documents": [],
            "metadata": {"query_id": "vt-1", "tenant": "team-z",
                         "priority": "batch"},
        }
        out = asyncio.run(node(state))
        assert out["evaluation"]["verdict"] == "pass"
        assert captured["tenant"] == "team-z"
        assert captured["priority"] == "batch"
        assert captured["request_id"] == "vt-1"


class TestLifecycleFanOut:
    def test_warmup_warms_every_replica(self):
        e0 = _engine()
        e1 = _engine(base=e0)
        rs = ReplicaSet([PagedGenerationService(e0),
                         PagedGenerationService(e1)])
        try:
            out = rs.warmup(max_new_tokens=2)
            assert out["replicas"] == 2
            assert out["prompts"] > 0
            for s in rs.stats()["replicas"]:
                assert s["completed"] > 0, (
                    f"replica {s['replica']} was never warmed: {s}"
                )
        finally:
            rs.close()

    def test_drain_concurrent_and_aggregated(self, replica_set):
        out = replica_set.drain(deadline_s=30.0)
        assert out["drained"] is True
        assert out["abandoned"] == 0
        assert [r["replica"] for r in out["replicas"]] == [0, 1]
        with pytest.raises((ReplicaUnavailable, ServiceOverloaded)):
            replica_set.generate("after drain", max_new_tokens=2)

    def test_leaked_pump_sums_without_double_count(self):
        e0 = _engine()
        e1 = _engine(base=e0)
        svc0 = PagedGenerationService(e0)
        svc1 = PagedGenerationService(e1)
        rs = ReplicaSet([svc0, svc1])
        release = threading.Event()

        class StuckPump:
            name = "paged-decode-pump"
            daemon = True

            def join(self, timeout=None):
                pass

            def is_alive(self):
                return not release.is_set()

        with svc1._mutex:
            svc1._pump = StuckPump()
        rs.close()
        stats = rs.stats()
        assert stats["pump_leaked"] == 1
        assert [s["pump_leaked"] for s in stats["replicas"]] == [0, 1]
        release.set()


class TestMeshSplit:
    def test_split_dp_into_disjoint_submeshes(self):
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import AXIS_DP, build_mesh, split_mesh_dp

        mesh = build_mesh(MeshConfig())  # 8 virtual CPU devices, all on dp
        subs = split_mesh_dp(mesh, 2)
        assert len(subs) == 2
        seen = set()
        for sub in subs:
            assert sub.shape[AXIS_DP] == mesh.shape[AXIS_DP] // 2
            ids = {d.id for d in sub.devices.flat}
            assert not (ids & seen), "replicas share devices"
            seen |= ids
        assert len(seen) == len(list(mesh.devices.flat))

    def test_ragged_split_raises(self):
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import MeshError, build_mesh, split_mesh_dp

        mesh = build_mesh(MeshConfig())
        with pytest.raises(MeshError, match="not divisible"):
            split_mesh_dp(mesh, 3)
