"""End-to-end pipeline: the full retrieve→rerank→select→generate→verify graph
over real (tiny/fake) components — the golden-path test of SURVEY.md §7."""

import numpy as np
import pytest

from sentio_tpu.config import (
    EmbedderConfig,
    GeneratorConfig,
    RetrievalConfig,
    Settings,
)
from sentio_tpu.graph.executor import END
from sentio_tpu.graph.factory import GraphConfig, build_basic_graph
from sentio_tpu.graph.state import create_initial_state
from sentio_tpu.models.document import Document
from sentio_tpu.ops.bm25 import BM25Index
from sentio_tpu.ops.dense_index import TpuDenseIndex
from sentio_tpu.ops.embedder import HashEmbedder
from sentio_tpu.ops.generator import EchoProvider, LLMGenerator
from sentio_tpu.ops.reranker import CrossEncoderReranker, PassthroughReranker
from sentio_tpu.ops.retrievers import DenseRetriever, HybridRetriever, SparseRetriever
from sentio_tpu.ops.verifier import AnswerVerifier


@pytest.fixture()
def pipeline(docs, settings):
    emb = HashEmbedder(EmbedderConfig(provider="hash", dim=64))
    dense = TpuDenseIndex(dim=64, dtype="float32")
    dense.add(docs, emb.embed_many([d.text for d in docs]))
    sparse = BM25Index().build(docs)
    retriever = HybridRetriever(
        retrievers=[DenseRetriever(emb, dense), SparseRetriever(sparse)],
        config=settings.retrieval,
    )
    generator = LLMGenerator(provider=EchoProvider(), config=settings.generator)
    verifier = AnswerVerifier(generator=generator, config=settings.generator)
    reranker = PassthroughReranker()
    return retriever, generator, reranker, verifier, settings


def test_full_graph_answers_with_citations(pipeline):
    retriever, generator, reranker, verifier, settings = pipeline
    graph = build_basic_graph(
        retriever, generator, reranker=reranker, verifier=verifier,
        config=GraphConfig(settings=settings),
    )
    state = graph.invoke(create_initial_state("what is the systolic array?"))
    assert state["response"]
    assert "[1]" in state["response"]
    assert state["metadata"]["graph_path"] == ["retrieve", "rerank", "select", "generate", "verify"]
    assert state["retrieved_documents"]
    assert state["selected_documents"]
    assert state["evaluation"]["verdict"] in ("pass", "warn", "fail")
    timings = state["metadata"]["node_timings_ms"]
    assert set(timings) == {"retrieve", "rerank", "select", "generate", "verify"}


def test_graph_without_optional_stages(pipeline):
    retriever, generator, *_ , settings = pipeline
    graph = build_basic_graph(
        retriever, generator,
        config=GraphConfig(use_reranker=False, use_verifier=False, settings=settings),
    )
    state = graph.invoke(create_initial_state("quick brown fox"))
    assert state["response"]
    assert state["metadata"]["graph_path"] == ["retrieve", "select", "generate"]
    assert state.get("evaluation") == {}


def test_user_top_k_override(pipeline):
    retriever, generator, reranker, verifier, settings = pipeline
    graph = build_basic_graph(
        retriever, generator, reranker=reranker,
        config=GraphConfig(use_verifier=False, settings=settings),
    )
    state = graph.invoke(
        create_initial_state("fox", metadata={"user_top_k": 2})
    )
    assert state["metadata"]["num_retrieved"] <= 2


def test_selector_budget_and_dedup(settings):
    settings.generator.context_token_budget = 25  # ≈100 chars
    long_doc = Document(text="x" * 90, id="long", metadata={"score": 0.9})
    dup = Document(text="dup text", id="long", metadata={"score": 0.8})
    small = Document(text="short", id="small", metadata={"score": 0.7})

    from sentio_tpu.graph.nodes import create_document_selector_node

    node = create_document_selector_node(settings)
    update = node({"query": "q", "reranked_documents": [long_doc, dup, small], "metadata": {}})
    ids = [d.id for d in update["selected_documents"]]
    assert ids.count("long") == 1  # dedup
    assert "small" in ids  # budget scan continues past oversized docs
    assert update["metadata"]["context_chars"] <= 100


def test_retrieval_failure_still_produces_answer(pipeline):
    class DeadRetriever:
        name = "dead"

        async def aretrieve(self, query, top_k=10):
            raise RuntimeError("index unavailable")

    _, generator, _, _, settings = pipeline
    graph = build_basic_graph(
        DeadRetriever(), generator,
        config=GraphConfig(use_reranker=False, use_verifier=False, settings=settings),
    )
    state = graph.invoke(create_initial_state("anything"))
    # degradation ladder: no docs, but the generator still answers
    assert state["metadata"]["retrieval_error"]
    assert state["response"]
    assert "No sources" in state["response"] or "no grounded" in state["response"].lower()


def test_verifier_fail_rewrites_answer(pipeline, settings):
    retriever, _, _, _, _ = pipeline

    class FailingAuditProvider:
        name = "audit"

        def chat(self, prompt, max_new_tokens, temperature):
            if '"verdict"' in prompt or "JSON" in prompt:
                return '{"verdict": "fail", "citations_ok": false, "revised_answer": "REVISED"}'
            return "original answer [1]"

        def stream(self, *a, **k):
            yield self.chat(*a, **k)

    gen = LLMGenerator(provider=FailingAuditProvider(), config=settings.generator)
    verifier = AnswerVerifier(generator=gen, config=settings.generator)
    graph = build_basic_graph(
        retriever, gen, verifier=verifier,
        config=GraphConfig(use_reranker=False, settings=settings),
    )
    state = graph.invoke(create_initial_state("query"))
    assert state["response"] == "REVISED"
    assert state["metadata"]["answer_revised"] is True


def test_cross_encoder_in_graph(pipeline):
    retriever, generator, _, _, settings = pipeline
    graph = build_basic_graph(
        retriever, generator, reranker=CrossEncoderReranker(),
        config=GraphConfig(use_verifier=False, settings=settings),
    )
    state = graph.invoke(create_initial_state("systolic array"))
    assert state["reranked_documents"]
    assert state["metadata"]["reranker"] == "cross_encoder"
    assert state["response"]
