"""Ingestion pipeline tests: loaders, directory walk, chunk→embed→index.

Mirrors the reference's ingest suite (src/tests/ingest/
test_document_ingestor_comprehensive.py there) with the hash-embedder fake
backend (SURVEY.md §4) — full pipeline, no device model needed.
"""

import json
import zipfile

import pytest

from sentio_tpu.config import EmbedderConfig, Settings
from sentio_tpu.models.document import Document
from sentio_tpu.ops.bm25 import BM25Index
from sentio_tpu.ops.dense_index import TpuDenseIndex
from sentio_tpu.ops.embedder import HashEmbedder
from sentio_tpu.ops.ingest import DocumentIngestor, IngestError, ingest_directory


@pytest.fixture()
def ingestor(settings):
    settings.embedder = EmbedderConfig(provider="hash", dim=64)
    embedder = HashEmbedder(settings.embedder)
    return DocumentIngestor(
        embedder=embedder,
        dense_index=TpuDenseIndex(dim=64),
        sparse_index=BM25Index(),
        settings=settings,
    )


class TestLoaders:
    def test_txt_and_md(self, ingestor, tmp_path):
        (tmp_path / "a.txt").write_text("plain text body")
        (tmp_path / "b.md").write_text("# Title\n\nmarkdown body")
        docs = ingestor.load_directory(tmp_path)
        assert {d.metadata["format"] for d in docs} == {"txt", "md"}
        assert any("markdown body" in d.text for d in docs)

    def test_html_strips_tags_and_scripts(self, ingestor, tmp_path):
        (tmp_path / "page.html").write_text(
            "<html><head><script>var x=1;</script><style>.c{}</style></head>"
            "<body><h1>Heading</h1><p>visible text</p></body></html>"
        )
        [doc] = ingestor.load_file(tmp_path / "page.html")
        assert "visible text" in doc.text and "Heading" in doc.text
        assert "var x" not in doc.text and ".c{}" not in doc.text

    def test_json_extracts_string_leaves(self, ingestor, tmp_path):
        (tmp_path / "d.json").write_text(json.dumps(
            {"title": "doc title", "nested": {"body": ["part one", "part two"]}, "n": 7}
        ))
        [doc] = ingestor.load_file(tmp_path / "d.json")
        assert "doc title" in doc.text and "part two" in doc.text and "7" not in doc.text

    def test_jsonl(self, ingestor, tmp_path):
        (tmp_path / "d.jsonl").write_text('{"text": "line one"}\n{"text": "line two"}\n')
        [doc] = ingestor.load_file(tmp_path / "d.jsonl")
        assert "line one" in doc.text and "line two" in doc.text

    def test_yaml(self, ingestor, tmp_path):
        (tmp_path / "c.yaml").write_text("title: yaml title\nitems:\n  - alpha\n  - beta\n")
        [doc] = ingestor.load_file(tmp_path / "c.yaml")
        assert "yaml title" in doc.text and "beta" in doc.text

    def test_csv_tsv(self, ingestor, tmp_path):
        (tmp_path / "t.csv").write_text("name,role\nada,engineer\n")
        [doc] = ingestor.load_file(tmp_path / "t.csv")
        assert "ada engineer" in doc.text

    def test_docx_via_zipfile(self, ingestor, tmp_path):
        path = tmp_path / "w.docx"
        xml = (
            '<?xml version="1.0"?><w:document><w:body>'
            "<w:p><w:r><w:t>first paragraph</w:t></w:r></w:p>"
            "<w:p><w:r><w:t>second</w:t></w:r><w:r><w:t> half</w:t></w:r></w:p>"
            "</w:body></w:document>"
        )
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("word/document.xml", xml)
        [doc] = ingestor.load_file(path)
        assert doc.text == "first paragraph\nsecond half"

    def test_bad_docx_raises(self, ingestor, tmp_path):
        path = tmp_path / "bad.docx"
        path.write_bytes(b"not a zip")
        with pytest.raises(IngestError):
            ingestor.load_file(path)

    def test_pdf_gated_with_clear_error(self, ingestor, tmp_path):
        path = tmp_path / "x.pdf"
        path.write_bytes(b"%PDF-1.4")
        with pytest.raises(IngestError, match="PyPDF2"):
            ingestor.load_file(path)

    def test_unknown_suffix_skipped_in_directory(self, ingestor, tmp_path):
        (tmp_path / "keep.txt").write_text("keep me")
        (tmp_path / "skip.bin").write_bytes(b"\x00\x01")
        docs = ingestor.load_directory(tmp_path)
        assert len(docs) == 1
        assert ingestor.stats.files_skipped == 1

    def test_recursive_walk(self, ingestor, tmp_path):
        sub = tmp_path / "nested" / "deep"
        sub.mkdir(parents=True)
        (sub / "leaf.md").write_text("deep leaf")
        assert len(ingestor.load_directory(tmp_path)) == 1
        assert len(ingestor.load_directory(tmp_path, recursive=False)) == 0


class TestIngestPipeline:
    def test_chunks_embedded_and_indexed(self, ingestor):
        text = "sentence about tpus. " * 200  # forces multiple chunks
        stats = ingestor.ingest_documents([Document(text=text, metadata={"source": "mem"})])
        assert stats.chunks_created > 1
        assert stats.chunks_stored == stats.chunks_created
        assert ingestor.dense_index.size == stats.chunks_stored
        # sparse index rebuilt over the same corpus
        assert ingestor._sparse_index.size == stats.chunks_stored

    def test_single_document_path(self, ingestor):
        stats = ingestor.ingest_document("short body", {"source": "api"})
        assert stats.chunks_stored == 1
        [doc] = ingestor.dense_index.documents()
        assert doc.metadata["source"] == "api"
        assert doc.metadata["parent_id"]

    def test_empty_chunks_dropped(self, ingestor):
        stats = ingestor.ingest_documents([Document(text="   \n  ")])
        assert stats.chunks_stored == 0

    def test_retrieval_after_ingest(self, ingestor):
        ingestor.ingest_documents([
            Document(text="jax compiles to xla for tpus", id="d1"),
            Document(text="bm25 ranks by term frequency", id="d2"),
        ])
        hits = ingestor._sparse_index.retrieve("term frequency ranking bm25", top_k=1)
        assert hits and hits[0].metadata["parent_id"] == "d2"

    def test_clear(self, ingestor):
        ingestor.ingest_document("whatever", {})
        removed = ingestor.clear()
        assert removed == 1
        assert ingestor.dense_index.size == 0
        assert ingestor._sparse_index.size == 0

    def test_ingest_directory_helper(self, settings, tmp_path):
        settings.embedder = EmbedderConfig(provider="hash", dim=32)
        (tmp_path / "doc.txt").write_text("directory helper body")
        stats = ingest_directory(tmp_path, settings=settings)
        assert stats.documents_loaded == 1 and stats.chunks_stored >= 1


class TestPersistence:
    def test_per_call_stats_carry_loader_errors(self, ingestor, tmp_path):
        (tmp_path / "good.txt").write_text("fine body")
        (tmp_path / "bad.docx").write_bytes(b"not a zip")
        stats = ingestor.ingest_path(tmp_path)
        assert stats.chunks_stored >= 1
        assert stats.files_skipped == 1
        assert any("bad.docx" in e for e in stats.errors)

    def test_saved_index_rehydrates_container(self, settings, tmp_path):
        from sentio_tpu.serve.dependencies import DependencyContainer

        settings.embedder = EmbedderConfig(provider="hash", dim=32)
        ingestor = DocumentIngestor(
            embedder=HashEmbedder(settings.embedder),
            dense_index=TpuDenseIndex(dim=32),
            settings=settings,
        )
        ingestor.ingest_document("persisted corpus entry about rings", {"source": "s"})
        path = tmp_path / "idx"
        ingestor.dense_index.save(path)

        settings.retrieval.index_path = str(path)
        container = DependencyContainer(settings=settings)
        assert container.dense_index.size == 1
        # BM25 rehydrated from the loaded documents
        assert container.sparse_index.size == 1
        hits = container.sparse_index.retrieve("rings", top_k=1)
        assert hits and "rings" in hits[0].text
