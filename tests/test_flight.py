"""Flight recorder: ring-buffer bounds, thread-safety, TTFT/TPOT capture on
the paged serving path (/chat and its SSE stream), and the /debug/flight
endpoint's 404 + auth behavior."""

from __future__ import annotations

import asyncio
import threading

import pytest

from sentio_tpu.infra.flight import (
    FlightRecorder,
    get_flight_recorder,
    set_flight_recorder,
)


@pytest.fixture()
def recorder():
    rec = FlightRecorder(max_ticks=64, max_requests=8)
    set_flight_recorder(rec)
    yield rec
    set_flight_recorder(None)


class TestRingBuffer:
    def test_tick_ring_is_bounded(self, recorder):
        for i in range(500):
            recorder.record_tick(dur_ms=1.0, active_slots=i % 4)
        timeline = recorder.timeline()
        assert len(timeline) == 64
        # oldest events fell off; sequence numbers stay monotonic
        assert timeline[0]["tick"] == 500 - 64 + 1
        assert [e["tick"] for e in timeline] == sorted(e["tick"] for e in timeline)
        snap = recorder.snapshot()
        assert snap["ticks_recorded"] == 500
        assert snap["ticks_retained"] == 64

    def test_request_table_is_bounded_with_lru_eviction(self, recorder):
        for i in range(20):
            recorder.start_request(f"req-{i}")
        assert recorder.get("req-0") is None  # evicted
        assert recorder.get("req-19") is not None
        assert recorder.dropped_requests == 12
        assert recorder.snapshot()["requests_retained"] == 8

    def test_get_slices_the_request_tick_window(self, recorder):
        recorder.record_tick(active_slots=9)  # before the request
        recorder.start_request("r")
        recorder.note_engine_submit("r")
        recorder.record_tick(active_slots=1, queue_depth=2)
        recorder.record_tick(active_slots=2, queue_depth=0)
        recorder.finish_engine("r", ttft_ms=5.0, tokens=3)
        recorder.record_tick(active_slots=7)  # after the request
        record = recorder.get("r")
        assert [e["active_slots"] for e in record["ticks"]] == [1, 2]
        assert record["engine"]["ttft_ms"] == 5.0

    def test_unknown_request_returns_none(self, recorder):
        assert recorder.get("nope") is None

    def test_thread_safety_under_concurrent_writers(self, recorder):
        """Concurrent pump-style tick appends + request lifecycles must not
        corrupt bounds or raise. 8 writers x 200 ops is far past what one
        engine pump produces between scrapes."""
        errors: list[BaseException] = []

        def pump(tid: int):
            try:
                for i in range(200):
                    recorder.record_tick(dur_ms=0.1, active_slots=tid,
                                         queue_depth=i % 3)
                    rid = f"t{tid}-r{i % 5}"
                    recorder.start_request(rid)
                    recorder.note_engine_submit(rid)
                    recorder.add_node_timings(rid, {"generate": 1.0})
                    recorder.finish_engine(rid, ttft_ms=1.0, tokens=i)
                    recorder.finish_request(rid, status="done")
                    recorder.get(rid)
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=pump, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(recorder.timeline()) == 64
        assert recorder.snapshot()["ticks_recorded"] == 8 * 200
        assert recorder.snapshot()["requests_retained"] <= 8

    def test_start_request_resets_a_finished_record(self, recorder):
        """Multi-turn conversations pin thread_id (the trace id): turn 2
        must start a fresh record, not sum its node timings onto turn 1's."""
        recorder.start_request("thread-1", endpoint="/chat")
        recorder.add_node_timings("thread-1", {"generate": 10.0})
        recorder.finish_request("thread-1", status="done")
        recorder.start_request("thread-1", endpoint="/chat")
        record = recorder.get("thread-1")
        assert "node_timings_ms" not in record  # turn 1's timings gone
        assert record["status"] == "active"
        recorder.add_node_timings("thread-1", {"generate": 7.0})
        assert recorder.get("thread-1")["node_timings_ms"] == {"generate": 7.0}

    def test_node_timings_merge_across_invocations(self, recorder):
        recorder.add_node_timings("r", {"generate": 10.0}, graph_path=["generate"])
        recorder.add_node_timings("r", {"generate": 5.0, "verify": 2.0})
        record = recorder.get("r")
        assert record["node_timings_ms"] == {"generate": 15.0, "verify": 2.0}


class TestMetricsSnapshotHonesty:
    """Satellite: the JSON histogram export must not present windowed
    quantiles under a full-run sample count (the old snapshot silently
    truncated to 1000 observations and reported a biased p50 as if it
    covered everything)."""

    def test_true_count_dropped_and_p95(self):
        from sentio_tpu.infra.metrics import InMemoryMetrics

        mem = InMemoryMetrics()
        for i in range(1500):
            mem.observe("lat", (), float(i))
        h = mem.snapshot()["histograms"]["lat()"]
        assert h["count"] == 1500
        assert h["window"] == 1000
        assert h["dropped"] == 500
        # quantiles come from the retained window (values 500..1499)
        assert h["p50"] == 1000.0
        assert h["p95"] == 1450.0
        # mean is LIFETIME (sum over all 1500), not window-biased
        assert h["mean"] == pytest.approx(sum(range(1500)) / 1500)

    def test_small_histogram_has_zero_dropped(self):
        from sentio_tpu.infra.metrics import InMemoryMetrics

        mem = InMemoryMetrics()
        for i in range(10):
            mem.observe("x", (), float(i))
        h = mem.snapshot()["histograms"]["x()"]
        assert h["count"] == 10 and h["dropped"] == 0 and h["p95"] == 9.0


class TestTraceContextCompat:
    def test_legacy_provider_without_request_id_kwarg_stays_working(self):
        """Every real request is traced now — a provider with the pre-trace
        chat/stream signature must run untraced, not TypeError into the
        degradation ladder on 100% of traffic."""
        from sentio_tpu.ops.generator import LLMGenerator

        class Legacy:
            name = "legacy"

            def chat(self, prompt, max_new_tokens, temperature):
                return "ok"

            def stream(self, prompt, max_new_tokens, temperature):
                yield "ok"

        gen = LLMGenerator(provider=Legacy())
        assert gen.generate("q", [], request_id="rid-1") == "ok"
        assert list(gen.stream("q", [], request_id="rid-1")) == ["ok"]

    def test_single_tick_completion_records_ttft_but_no_tpot(self, recorder):
        """A generation that finishes inside its first pump tick has no
        post-first-token interval: recording tpot=0.0 would drag the
        histogram's p50 toward a throughput the engine doesn't have."""
        from sentio_tpu.infra.metrics import MetricsCollector
        from sentio_tpu.runtime.paged import PagedResult
        from sentio_tpu.runtime.service import PagedGenerationService, _Ticket

        metrics = MetricsCollector()
        ticket = _Ticket("p", 8, 0.0, request_id="one-tick", t_submit=0.0)
        result = PagedResult(request_id=0, text="abc", tokens=[1, 2, 3],
                             prompt_tokens=5, finish_reason="stop")
        PagedGenerationService._note_finished(
            ticket, result, 0.5, metrics, recorder)
        histos = metrics.memory.snapshot()["histograms"]
        assert histos["ttft('paged',)"]["count"] == 1
        assert "tpot('paged',)" not in histos
        assert recorder.get("one-tick")["engine"]["tpot_ms"] is None


# --------------------------------------------------------------- paged path


@pytest.mark.slow
class TestServiceTelemetry:
    """TTFT/TPOT + tick events recorded by the decode pump for traced
    requests, concurrent engine ticks included."""

    def _service(self):
        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine
        from sentio_tpu.runtime.service import PagedGenerationService

        engine = ContinuousBatchingEngine(
            model_config=LlamaConfig.tiny(), max_slots=4, page_size=16,
            max_pages_per_seq=4, steps_per_tick=4,
        )
        return PagedGenerationService(engine)

    def test_generate_records_ttft_tpot_and_tick_window(self, recorder):
        from sentio_tpu.infra.metrics import MetricsCollector, set_metrics

        metrics = MetricsCollector()
        set_metrics(metrics)
        try:
            service = self._service()
            result = service.generate(
                "hello flight", max_new_tokens=8, request_id="gen-1"
            )
            service.close()
            record = recorder.get("gen-1")
            assert record is not None
            engine = record["engine"]
            assert engine["ttft_ms"] >= 0.0
            assert engine["tokens"] == len(result.tokens)
            assert engine["finish_reason"] == result.finish_reason
            assert record["ticks"], "request window must hold >=1 tick event"
            tick = record["ticks"][0]
            for field in ("active_slots", "queue_depth", "free_pages",
                          "prefill_tokens", "decode_tokens", "dur_ms"):
                assert field in tick, tick
            histos = metrics.memory.snapshot()["histograms"]
            assert histos["ttft('paged',)"]["count"] >= 1
            assert "tick_duration()" in histos
        finally:
            set_metrics(None)

    def test_stream_and_concurrent_tickets_all_traced(self, recorder):
        from sentio_tpu.infra.metrics import MetricsCollector, set_metrics

        metrics = MetricsCollector()
        set_metrics(metrics)
        try:
            service = self._service()
            out: dict[str, list[str]] = {}

            def consume(rid: str):
                out[rid] = list(service.generate_stream(
                    f"prompt for {rid}", max_new_tokens=12, request_id=rid
                ))

            threads = [
                threading.Thread(target=consume, args=(f"st-{i}",))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            service.close()
            for i in range(3):
                record = recorder.get(f"st-{i}")
                assert record is not None and "engine" in record, record
                assert record["engine"]["tokens"] >= 0
            # TPOT requires >1 token over >1 tick; TTFT must always land,
            # labeled with the streaming path (blocking calls get 'paged')
            assert metrics.memory.snapshot()["histograms"][
                "ttft('stream',)"]["count"] >= 3
        finally:
            set_metrics(None)


# --------------------------------------------------------------- HTTP layer


@pytest.mark.slow
class TestFlightEndpoint:
    def _settings(self, **over):
        from sentio_tpu.config import (
            EmbedderConfig,
            GeneratorConfig,
            RerankConfig,
            Settings,
        )

        s = Settings(
            embedder=EmbedderConfig(provider="hash", dim=32),
            generator=GeneratorConfig(
                provider="tpu", model_preset="tiny", use_verifier=False,
                max_new_tokens=16, mode="fast", use_paged_decode=True,
                kv_page_size=16, kv_max_pages_per_seq=8, max_batch_size=4,
            ),
            rerank=RerankConfig(enabled=False),
        )
        for key, value in over.items():
            setattr(s, key, value)
        return s

    async def _with_client(self, settings, fn):
        from aiohttp.test_utils import TestClient, TestServer

        from sentio_tpu.serve.app import create_app
        from sentio_tpu.serve.dependencies import DependencyContainer

        container = DependencyContainer(settings=settings)
        app = create_app(container=container)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            return await fn(client, container)
        finally:
            await client.close()

    def test_chat_flight_record_roundtrip(self, recorder):
        """Acceptance: a completed /chat request's record is retrievable at
        /debug/flight/{request_id} with graph node timings AND >=1 engine
        tick event carrying occupancy/queue-depth fields."""

        async def body(client, container):
            resp = await client.post("/embed", json={
                "content": "tpus multiply matrices in a systolic array"
            })
            assert resp.status == 200
            resp = await client.post("/chat", json={
                "question": "what multiplies matrices?",
                "thread_id": "flight-chat-1",
            })
            assert resp.status == 200
            data = await resp.json()
            assert data["metadata"]["query_id"] == "flight-chat-1"

            flight = await client.get("/debug/flight/flight-chat-1")
            assert flight.status == 200
            record = await flight.json()
            assert record["status"] == "done"
            assert record["node_timings_ms"].get("generate") is not None
            assert record["engine"]["tokens"] >= 0
            assert record["engine"]["ttft_ms"] >= 0.0
            assert record["ticks"], "no engine tick events in the record"
            assert "active_slots" in record["ticks"][0]
            assert "queue_depth" in record["ticks"][0]

            missing = await client.get("/debug/flight/who-dis")
            assert missing.status == 404

        asyncio.run(self._with_client(self._settings(), body))

    def test_chat_flight_chrome_format(self, recorder):
        """?format=chrome returns the record's window as a Perfetto-openable
        Chrome trace: tick slices with nested phase slices, the request
        span, on one timeline."""

        async def body(client, container):
            await client.post("/embed", json={
                "content": "tpus multiply matrices in a systolic array"
            })
            resp = await client.post("/chat", json={
                "question": "what multiplies matrices?",
                "thread_id": "flight-chrome-1",
            })
            assert resp.status == 200

            chrome = await client.get(
                "/debug/flight/flight-chrome-1?format=chrome")
            assert chrome.status == 200
            trace = await chrome.json()
            events = trace["traceEvents"]
            names = {e["name"] for e in events}
            assert "request flight-chrome-1" in names
            assert any(n.startswith("tick ") for n in names)
            from sentio_tpu.infra.phases import TICK_PHASES

            assert names & set(TICK_PHASES), "no phase slices on the trace"

            missing = await client.get(
                "/debug/flight/who-dis?format=chrome")
            assert missing.status == 404

        asyncio.run(self._with_client(self._settings(), body))

    def test_debug_profile_window(self, recorder, tmp_path):
        """/debug/profile arms jax.profiler for the window and reports the
        trace directory; malformed/oversized windows 422."""

        async def body(client, container):
            resp = await client.get(
                f"/debug/profile?seconds=0.1&dir={tmp_path}")
            assert resp.status == 200
            out = await resp.json()
            assert out["started"] is True
            assert out["log_dir"] == str(tmp_path)

            bad = await client.get("/debug/profile?seconds=oops")
            assert bad.status == 422
            too_long = await client.get("/debug/profile?seconds=9999")
            assert too_long.status == 422

        asyncio.run(self._with_client(self._settings(), body))

    def test_sse_stream_records_ttft(self, recorder):
        """The SSE path must trace too: X-Request-Id names the record, and
        the paged pump stamps TTFT/TPOT for the streamed sequence."""

        async def body(client, container):
            await client.post("/embed", json={"content": "streaming evidence doc"})
            resp = await client.post("/chat", json={
                "question": "what streams?", "stream": True,
                "thread_id": "flight-sse-1",
            })
            assert resp.status == 200
            assert resp.headers["X-Request-Id"] == "flight-sse-1"
            await resp.read()  # drain the stream to completion

            flight = await client.get("/debug/flight/flight-sse-1")
            assert flight.status == 200
            record = await flight.json()
            assert record["status"] == "done"
            assert record["node_timings_ms"].get("generate") is not None
            assert record["engine"]["ttft_ms"] >= 0.0

        asyncio.run(self._with_client(self._settings(), body))

    def test_debug_flight_is_auth_gated(self, recorder):
        """With auth enabled, /debug/flight requires credentials (unlike
        /metrics, which stays open for scrapers)."""
        from sentio_tpu.config import AuthConfig

        settings = self._settings(auth=AuthConfig(enabled=True, jwt_secret="s" * 32))

        async def body(client, container):
            resp = await client.get("/debug/flight/anything")
            assert resp.status == 401
            # /metrics stays open
            assert (await client.get("/metrics")).status == 200

            container.auth_manager.create_user(
                "ada", "Correct-Horse-Battery-9", role="admin"
            )
            tok = await client.post("/auth/token", json={
                "username": "ada", "password": "Correct-Horse-Battery-9"
            })
            access = (await tok.json())["access_token"]
            resp = await client.get(
                "/debug/flight/anything",
                headers={"Authorization": f"Bearer {access}"},
            )
            assert resp.status == 404  # authed, but no such record

        asyncio.run(self._with_client(settings, body))
