"""Native (C++) BM25 core vs the numpy reference implementation.

Correctness bar: identical scores and rankings on the same CSR index — the
native core is a hot-loop replacement, not a different algorithm. The build
is exercised for real here (g++ is part of the image); if it ever becomes
unavailable the factory must degrade to numpy, which is also tested.
"""

import numpy as np
import pytest

from sentio_tpu.models.document import Document
from sentio_tpu.ops.bm25 import (
    BM25Index,
    BM25Params,
    NativeBM25Index,
    make_bm25_index,
)


def corpus(n=100):
    rng = np.random.default_rng(7)
    vocab = ["tpu", "mxu", "jax", "xla", "pallas", "mesh", "hbm", "ici",
             "systolic", "matmul", "shard", "compile", "kernel", "batch"]
    docs = []
    for i in range(n):
        words = rng.choice(vocab, size=rng.integers(5, 30))
        docs.append(Document(text=" ".join(words), id=f"d{i}", metadata={"i": i}))
    return docs


@pytest.fixture(scope="module")
def built():
    docs = corpus()
    ref = BM25Index(params=BM25Params(k1=0.9, b=0.4)).build(docs)
    nat = NativeBM25Index(params=BM25Params(k1=0.9, b=0.4)).build(docs)
    assert nat._get_box() is not None, "C++ core must build in this image (g++ present)"
    return ref, nat


QUERIES = ["tpu mxu matmul", "jax jax jax compile", "hbm bandwidth", "", "systolic shard kernel batch"]


class TestParity:
    def test_dense_scores_match(self, built):
        ref, nat = built
        for q in QUERIES:
            np.testing.assert_allclose(nat.scores(q), ref.scores(q), rtol=1e-5, atol=1e-6)

    def test_topk_matches(self, built):
        ref, nat = built
        for q in QUERIES:
            r = ref.search(q, top_k=10)
            n = nat.search(q, top_k=10)
            assert [i for i, _ in n] == [i for i, _ in r]
            np.testing.assert_allclose([s for _, s in n], [s for _, s in r], rtol=1e-5)

    def test_repeated_query_terms_accumulate(self, built):
        ref, nat = built
        single = nat.scores("tpu")
        double = nat.scores("tpu tpu")
        np.testing.assert_allclose(double, 2.0 * single, rtol=1e-5)
        np.testing.assert_allclose(double, ref.scores("tpu tpu"), rtol=1e-5)

    def test_scratch_clean_between_queries(self, built):
        """Back-to-back different queries must not leak accumulator state."""
        _, nat = built
        a1 = nat.scores("tpu mxu")
        nat.scores("jax xla pallas")
        a2 = nat.scores("tpu mxu")
        np.testing.assert_array_equal(a1, a2)

    def test_rebuild_detaches_handle(self, built):
        _, nat = built
        nat.build(corpus(20))
        assert nat.size == 20
        assert len(nat.scores("tpu")) == 20
        nat.build(corpus(100))  # restore module fixture state


class TestFactory:
    def test_auto_prefers_native(self):
        idx = make_bm25_index(backend="auto")
        assert isinstance(idx, NativeBM25Index)

    def test_numpy_forced(self):
        idx = make_bm25_index(backend="numpy")
        assert type(idx) is BM25Index

    def test_unknown_backend_raises(self):
        with pytest.raises(ValueError):
            make_bm25_index(backend="lucene")

    def test_retrieve_contract_through_native(self):
        docs = corpus(30)
        idx = make_bm25_index(backend="native").build(docs)
        out = idx.retrieve("tpu mxu", top_k=5)
        assert len(out) <= 5
        for d in out:
            assert d.metadata["retriever"] == "bm25"
            assert d.metadata["score"] > 0

    def test_concurrent_queries_and_rebuild(self):
        """Thread-pool retrievers + mid-flight /embed rebuilds must not race
        the native scratch or use a destroyed handle."""
        import threading

        idx = NativeBM25Index().build(corpus(200))
        expected = {q: idx.search(q, top_k=5) for q in QUERIES if q}
        errors = []

        def query_loop():
            try:
                for _ in range(50):
                    for q, want in expected.items():
                        got = idx.search(q, top_k=5)
                        # only compare when no rebuild intervened (size match)
                        if idx.size == 200 and got != want:
                            errors.append((q, got, want))
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        def rebuild_loop():
            try:
                for _ in range(10):
                    idx.build(corpus(200))
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=query_loop) for _ in range(4)]
        threads.append(threading.Thread(target=rebuild_loop))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]

    def test_persistence_roundtrip_native(self, tmp_path):
        docs = corpus(40)
        idx = NativeBM25Index(params=BM25Params(k1=1.2, b=0.6)).build(docs)
        idx.save(tmp_path / "bm25")
        loaded = NativeBM25Index.load(tmp_path / "bm25")
        assert isinstance(loaded, NativeBM25Index)
        for q in QUERIES:
            np.testing.assert_allclose(loaded.scores(q), idx.scores(q), rtol=1e-5)


class TestEmptyIndex:
    def test_empty_native_index_search_does_not_deadlock(self):
        """Regression: search on an empty native index falls back to the
        numpy base implementation, whose scores() re-enters the overridden
        native scores(). The original design held a non-reentrant instance
        lock across the fallback and self-deadlocked (observed as /chat
        hanging on a fresh server with no documents ingested); scoring is
        now lock-free so the re-entry is harmless by construction."""
        nat = NativeBM25Index().build([])
        assert nat.search("anything", top_k=5) == []
        assert nat.scores("anything").shape == (0,)
        assert nat.retrieve("anything") == []


class TestLockFreeScoring:
    def test_many_threads_score_concurrently(self):
        """Queries must not serialize on an instance lock: N threads scoring
        the same index finish with correct, identical-to-sequential results
        (lifecycle lock covers only handle create/retire)."""
        import threading

        docs = corpus(300)
        nat = NativeBM25Index().build(docs)
        assert nat._get_box() is not None
        expected = {q: nat.search(q, top_k=7) for q in ("tpu mxu", "jax xla", "hbm ici")}
        errors = []

        def worker(q):
            for _ in range(30):
                if nat.search(q, top_k=7) != expected[q]:
                    errors.append(q)
                    return

        threads = [threading.Thread(target=worker, args=(q,)) for q in expected for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors

    def test_rebuild_while_scoring_is_safe(self):
        """retire() defers destroy until in-flight searches release."""
        import threading

        nat = NativeBM25Index().build(corpus(200))
        stop = threading.Event()
        errors = []

        def scorer():
            while not stop.is_set():
                try:
                    nat.search("tpu jax kernel", top_k=5)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=scorer) for _ in range(4)]
        for t in threads:
            t.start()
        for n in (50, 150, 250, 100):
            nat.build(corpus(n))
        stop.set()
        for t in threads:
            t.join()
        assert not errors


class TestTieBound:
    def test_massive_tie_set_returns_smallest_ids(self):
        """k-th-score ties across a huge uniform corpus must not lexsort the
        whole match set; winners are the smallest doc ids, deterministically."""
        docs = [Document(text="boilerplate token", id=f"d{i}", metadata={}) for i in range(5000)]
        ref = BM25Index().build(docs)
        out = ref.search("boilerplate", top_k=10)
        assert [i for i, _ in out] == list(range(10))
        nat = NativeBM25Index().build(docs)
        assert nat.search("boilerplate", top_k=10) == out


class TestRebuildConsistency:
    def test_inflight_query_uses_handle_snapshot_after_shrink(self):
        """A query holding the old handle mid-rebuild must size buffers by
        the OLD corpus (the C++ core writes old-n_docs floats — live size
        would overflow after a shrink) and resolve indices against the OLD
        document list."""
        nat = NativeBM25Index().build(corpus(250))
        box = nat._get_box()
        assert box is not None and box.acquire()
        try:
            nat.build(corpus(40))  # shrink under the in-flight query
            assert box.n_docs == 250
            hits = nat._native_search(box, "tpu jax kernel", top_k=5)
            for di, _ in hits:
                assert 0 <= di < 250
                assert box.documents[di].id.startswith("d")
        finally:
            box.release()
        # post-rebuild queries see the new corpus
        assert all(0 <= di < 40 for di, _ in nat.search("tpu jax kernel", top_k=5))

    def test_retrieve_documents_match_scores_under_churn(self):
        """Stress: concurrent retrieves during shrinking/growing rebuilds
        return documents whose metadata is internally consistent."""
        import threading

        nat = NativeBM25Index().build(corpus(300))
        stop = threading.Event()
        errors = []

        def worker():
            while not stop.is_set():
                try:
                    for doc in nat.retrieve("tpu jax kernel shard", top_k=5):
                        if not doc.id.startswith("d"):
                            errors.append(f"bad id {doc.id}")
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))
                    return

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for n in (30, 280, 10, 300, 50):
            nat.build(corpus(n))
        stop.set()
        for t in threads:
            t.join()
        assert not errors
