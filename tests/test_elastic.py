"""Elastic worker fleet (ISSUE 20) — tier 1.

The contract under test, on tiny engines (conftest arms SENTIO_SANITIZE=1
for this module, so every tick self-checks):

* **elastic registry** — a hello with the sentinel slot ``-1`` GROWS the
  slot set (the ack carries the granted slot), ``release_slot`` returns it
  to the free list, reuse continues the epoch fence, and a redial of a
  retired slot is rejected TYPED (stopping the worker's reconnect loop);
* **runtime join** — ``ReplicaSet.add_replica`` wires a new replica into
  rotation under load: WFQ capacity re-derives, routing reaches it, and a
  supervised set arms shadow handoff exactly like a startup replica;
* **graceful scale-in** — ``retire()`` drains in-flight work so a stream
  started before the retire finishes TOKEN-EXACT vs a no-churn greedy run,
  hands never-dispatched inbox tickets to survivors (callers just wake
  with a survivor's result), refuses to retire the last serving replica,
  and parks the slot RETIRED;
* **autoscaler** — the pure policy kernel (hysteresis, per-direction
  cooldowns, min/max clamps, window warming) plus the closed actuator
  loop: sustained synthetic load scales a REAL replica out through the
  launcher seam, sustained idle retires it back — all on a synthetic
  clock, no sleeps;
* **churn chaos** — the membership fault points (``registry.elastic_join``,
  ``replica.join``, ``replica.retire``) are armed here: an injected fault
  rejects/raises typed and leaves the set serving, never half-joined; a
  flap storm under the sanitizer keeps pages conserved and leaks nothing;
* **worker_serve redial** — an advertised worker accepts a NEWER router
  connection while one is live: newest wins, the superseded link gets a
  typed final err frame, the shared service carries over.
"""

import os
import threading
import time

import pytest

from sentio_tpu.config import ServeConfig
from sentio_tpu.infra import faults
from sentio_tpu.infra.exceptions import ReplicaUnavailable
from sentio_tpu.runtime.autoscaler import AutoscalePolicy, Autoscaler
from sentio_tpu.runtime.paged import ContinuousBatchingEngine, PagedResult
from sentio_tpu.runtime.replica import (
    DEFAULT_TENANT,
    HEALTH_HEALTHY,
    HEALTH_RETIRED,
    ReplicaSet,
    WorkerRegistry,
)
from sentio_tpu.runtime.service import PagedGenerationService
from sentio_tpu.runtime.transport import FrameProtocolError, dial, send_hello


def _engine(base=None, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages_per_seq", 4)
    kw.setdefault("steps_per_tick", 2)
    if base is not None:
        kw.setdefault("params", base.params)
        kw.setdefault("tokenizer", base.tokenizer)
    return ContinuousBatchingEngine(**kw)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.reset()


def _assert_pages_conserved(rs):
    for s in rs.stats()["replicas"]:
        assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
            == s["total_pages"] - 1, s


# ==========================================================================
# AutoscalePolicy — pure decision kernel (synthetic clock, no threads)

class TestAutoscalePolicy:
    def _hot(self):
        return AutoscalePolicy(min_replicas=1, max_replicas=4,
                               window_s=1.0, out_cooldown_s=0.0,
                               in_cooldown_s=0.0)

    def _feed(self, p, t0, busy, backlog=0.0, n=4, dt=0.4):
        for i in range(n):
            p.observe(t0 + i * dt, busy, backlog)
        return t0 + (n - 1) * dt

    def test_window_warming_gates_first_decisions(self):
        p = self._hot()
        p.observe(10.0, 0.99, 0.0)
        assert p.decide(10.0, 1) == (None, "window_warming")
        # two samples but the span is < 80% of the window: still warming
        p.observe(10.3, 0.99, 0.0)
        assert p.decide(10.3, 1) == (None, "window_warming")

    def test_scale_out_on_sustained_busy_and_on_backlog(self):
        p = self._hot()
        t = self._feed(p, 10.0, busy=0.9)
        assert p.decide(t, 1) == ("out", "busy")
        q = self._hot()
        t = self._feed(q, 10.0, busy=0.3, backlog=0.7)
        assert q.decide(t, 1) == ("out", "backlog")

    def test_hysteresis_steady_band_and_clamp(self):
        p = self._hot()
        # between in_busy (0.15) and out_busy (0.75): no decision
        t = self._feed(p, 10.0, busy=0.5)
        assert p.decide(t, 2) == (None, "steady")
        # the constructor clamps in_busy <= out_busy whatever the knobs say
        weird = AutoscalePolicy(out_busy=0.4, in_busy=0.9)
        assert weird.in_busy <= weird.out_busy

    def test_min_max_clamps(self):
        p = self._hot()
        t = self._feed(p, 10.0, busy=0.95)
        assert p.decide(t, p.max_replicas) == (None, "at_max")
        assert p.saturated(t)
        q = self._hot()
        t = self._feed(q, 10.0, busy=0.0)
        assert q.decide(t, q.min_replicas) == (None, "at_min")
        assert not q.saturated(t)

    def test_out_cooldown_blocks_rescale_until_it_expires(self):
        p = AutoscalePolicy(min_replicas=1, max_replicas=8, window_s=1.0,
                            out_cooldown_s=10.0, in_cooldown_s=0.0)
        t = self._feed(p, 10.0, busy=0.95)
        assert p.decide(t, 1) == ("out", "busy")
        p.note_scaled(t, "out")
        # note_scaled cleared the window: old-fleet samples say nothing
        assert p.decide(t + 0.1, 2) == (None, "window_warming")
        t2 = self._feed(p, t + 0.5, busy=0.95)
        assert p.decide(t2, 2) == (None, "out_cooldown")
        t3 = self._feed(p, t + 11.0, busy=0.95)
        assert p.decide(t3, 2) == ("out", "busy")

    def test_in_cooldown_measured_from_last_change_either_direction(self):
        p = AutoscalePolicy(min_replicas=1, max_replicas=8, window_s=1.0,
                            out_cooldown_s=0.0, in_cooldown_s=10.0)
        # a scale-OUT starts the scale-in cooldown too: an out→in flap
        # inside in_cooldown_s is impossible by construction
        p.note_scaled(50.0, "out")
        t = self._feed(p, 50.5, busy=0.0)
        assert p.decide(t, 2) == (None, "in_cooldown")
        t2 = self._feed(p, 61.0, busy=0.0)
        assert p.decide(t2, 2) == ("in", "idle")

    def test_inert_by_default(self):
        cfg = ServeConfig()
        assert cfg.autoscale is False
        assert ServeConfig.from_env().autoscale is False


# ==========================================================================
# WorkerRegistry — elastic join / release / reuse over a real socket

class TestElasticRegistry:
    @pytest.fixture()
    def registry(self):
        reg = WorkerRegistry("elastic-token", slots=1, hello_timeout_s=5.0)
        yield reg
        reg.close()

    def _join(self, registry, slot=-1):
        t = dial(registry.address)
        try:
            ack = send_hello(t, "elastic-token", slot, os.getpid(),
                             timeout_s=5.0)
        except BaseException:
            t.close()
            raise
        return t, ack

    @staticmethod
    def _drain_wait(registry, timeout_s=5.0):
        """The ack lands on the dialer BEFORE the join event publishes
        (ack first, then queue registration, then publish) — poll."""
        deadline = time.monotonic() + timeout_s
        joined: list = []
        while time.monotonic() < deadline:
            joined.extend(registry.drain_joins())
            if joined:
                return joined
            time.sleep(0.01)
        return joined

    def test_elastic_hello_grows_the_slot_set(self, registry):
        t, ack = self._join(registry)
        try:
            assert ack["slot"] == 1  # startup owns slot 0; the set GREW
            assert ack["epoch"] == 1
            assert registry.slots == 2
            assert self._drain_wait(registry) == [1]
            assert registry.drain_joins() == []  # one event per join
            stats = registry.stats()
            assert stats["elastic_joins"] == 1
            assert stats["free_slots"] == []
            # the registration is adoptable exactly like a startup one
            transport, hello, epoch = registry.await_registration(1, 5.0)
            assert epoch == 1 and int(hello["pid"]) == os.getpid()
        finally:
            t.close()

    def test_release_then_rejoin_reuses_slot_at_higher_epoch(self, registry):
        t1, ack1 = self._join(registry)
        assert self._drain_wait(registry) == [ack1["slot"]]
        registry.await_registration(ack1["slot"], 5.0)
        t1.close()
        registry.release_slot(ack1["slot"])
        stats = registry.stats()
        assert stats["released_slots"] == 1
        assert stats["free_slots"] == [ack1["slot"]]
        # reuse keeps the epoch fence: the next incarnation on this slot
        # registers ABOVE every frame the retired one ever sent
        t2, ack2 = self._join(registry)
        try:
            assert ack2["slot"] == ack1["slot"]
            assert ack2["epoch"] > ack1["epoch"]
            assert self._drain_wait(registry) == [ack1["slot"]]
            assert registry.stats()["free_slots"] == []
        finally:
            t2.close()

    def test_redial_of_retired_slot_rejected_typed(self, registry):
        t1, ack1 = self._join(registry)
        registry.await_registration(ack1["slot"], 5.0)
        t1.close()
        registry.release_slot(ack1["slot"])
        # the retired incarnation's reconnect loop redials its EXPLICIT
        # slot: the registry must refuse typed (FrameProtocolError is
        # terminal for the dialer's backoff loop)
        t2 = dial(registry.address)
        try:
            with pytest.raises(FrameProtocolError, match="was retired"):
                send_hello(t2, "elastic-token", ack1["slot"], os.getpid(),
                           timeout_s=5.0)
        finally:
            t2.close()

    def test_injected_join_fault_rejects_typed_and_leaks_no_slot(
            self, registry):
        with faults.inject("registry.elastic_join",
                           error=RuntimeError("chaos: join storm"), times=1):
            t = dial(registry.address)
            try:
                with pytest.raises(FrameProtocolError,
                                   match="elastic join failed"):
                    send_hello(t, "elastic-token", -1, os.getpid(),
                               timeout_s=5.0)
            finally:
                t.close()
        # the fault fired BEFORE allocation: no slot grew, no join queued
        assert registry.slots == 1
        assert registry.drain_joins() == []
        # and the registry still grants joins afterwards
        t2, ack = self._join(registry)
        t2.close()
        assert ack["slot"] == 1


# ==========================================================================
# ReplicaSet — runtime join, graceful scale-in, churn chaos

class TestElasticReplicaSet:
    def test_grow_under_load_then_retire_stream_token_exact(self,
                                                            monkeypatch):
        """THE scale-in criterion: a stream in flight when its replica is
        retired finishes token-exact vs a no-churn greedy run — the drain
        completes delivered-token work before the slot parks RETIRED."""
        prompt = "elastic drill prompt"
        e0 = _engine()
        svc0 = PagedGenerationService(e0, max_queue=8)
        baseline = svc0.generate(prompt, max_new_tokens=6, temperature=0.0,
                                 timeout_s=180)
        rs = ReplicaSet([svc0], supervise=False)
        try:
            assert rs.tenants.capacity == 8
            # grow 1 → 3 at runtime
            idx1 = rs.add_replica(
                PagedGenerationService(_engine(base=e0), max_queue=8))
            idx2 = rs.add_replica(
                PagedGenerationService(_engine(base=e0), max_queue=8))
            assert (idx1, idx2) == (1, 2)
            assert rs.tenants.capacity == 24  # WFQ re-derived
            fleet = rs.stats()["fleet"]
            assert fleet["live_replicas"] == 3 and fleet["joined"] == 2
            # the joiners actually serve: spy on routing, push traffic
            routed: list = []
            orig_route = rs._route

            def spy(toks, exclude=frozenset()):
                idx, hit = orig_route(toks, exclude=exclude)
                routed.append(idx)
                return idx, hit

            monkeypatch.setattr(rs, "_route", spy)
            for i in range(6):
                out = rs.generate(f"spread load {i}", max_new_tokens=2,
                                  temperature=0.0, timeout_s=180)
                assert isinstance(out, PagedResult)
            assert set(routed) - {0}, "no joiner was ever routed to"
            # stream through the set, then retire the SERVING replica from
            # another thread while the consumer is mid-stream
            routed.clear()
            stats_out: dict = {}
            stream = rs.generate_stream(prompt, max_new_tokens=6,
                                        temperature=0.0, timeout_s=180,
                                        stats_out=stats_out)
            first = next(stream)
            serving = routed[-1]
            result: dict = {}

            def retire():
                result["r"] = rs.retire(serving, deadline_s=60.0)

            t = threading.Thread(target=retire)
            t.start()
            rest = "".join(stream)
            t.join(timeout=90)
            assert not t.is_alive()
            assert first + rest == baseline.text
            assert stats_out.get("tokens") == len(baseline.tokens)
            assert result["r"]["retired"] is True
            assert result["r"]["drained"] is True
            # the slot parked RETIRED, capacity re-derived, routing avoids it
            assert rs._health[serving].state == HEALTH_RETIRED
            assert rs.tenants.capacity == 16
            again = rs.generate(prompt, max_new_tokens=3, temperature=0.0,
                                timeout_s=180)
            assert isinstance(again, PagedResult)
            assert routed[-1] != serving
            # a second retire of the same slot is a no-op, not an error
            assert rs.retire(serving)["retired"] is False
            _assert_pages_conserved(rs)
        finally:
            rs.close()

    def test_retire_hands_off_undispatched_inbox_to_survivor(self):
        """Scale-in must not strand queued-never-dispatched tickets behind
        the drain deadline: retire extracts them FIRST and the blocked
        caller wakes with a survivor's result (WFQ recharged, not
        double-counted)."""
        e0 = _engine()
        svc0 = PagedGenerationService(e0)
        svc1 = PagedGenerationService(_engine(base=e0))
        svc0.generate("retire handoff warm zero", max_new_tokens=2,
                      timeout_s=180)
        svc1.generate("retire handoff warm one", max_new_tokens=2,
                      timeout_s=180)
        rs = ReplicaSet([svc0, svc1], supervise=False)
        release = threading.Event()
        t1 = t2 = None
        try:
            wedged: dict = {}

            def call_wedged():
                try:
                    wedged["r"] = svc0.generate(
                        "wedged in flight", max_new_tokens=3,
                        temperature=0.0, timeout_s=60)
                except Exception as exc:  # noqa: BLE001
                    wedged["r"] = exc

            with faults.inject("paged.step", stall_event=release,
                               stall_s=30.0, times=1) as rule:
                t1 = threading.Thread(target=call_wedged)
                t1.start()
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and rule.stalled == 0:
                    time.sleep(0.005)
                assert rule.stalled == 1
                # a second ticket piles into the wedged inbox with the WFQ
                # metadata the router stamps (plus its caller-side charge)
                rs.tenants.admit(DEFAULT_TENANT, 8)
                queued: dict = {}

                def call_queued():
                    try:
                        queued["r"] = svc0.generate(
                            "queued behind the wedge", max_new_tokens=3,
                            temperature=0.0, timeout_s=60,
                            tenant=DEFAULT_TENANT, cost_tokens=8)
                    except Exception as exc:  # noqa: BLE001
                        queued["r"] = exc

                t2 = threading.Thread(target=call_queued)
                t2.start()
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and len(svc0._inbox) < 1:
                    time.sleep(0.005)
                # retire with a drain deadline the wedge will blow: the
                # queued ticket must move NOW, not after the deadline
                result = rs.retire(0, deadline_s=1.0)
                assert result["retired"] is True
                assert result["handed_off"] >= 1
                assert rs.stats()["handed_off"] >= 1
                t2.join(timeout=60)
                assert isinstance(queued["r"], PagedResult), queued["r"]
                assert queued["r"].finish_reason in ("stop", "length")
                release.set()
                t1.join(timeout=60)
            rs.tenants.release(DEFAULT_TENANT, 8)
        finally:
            release.set()
            for t in (t1, t2):
                if t is not None:
                    t.join(timeout=60)
            faults.reset()
            rs.close()

    def test_retire_last_serving_replica_refused_typed(self):
        e0 = _engine()
        rs = ReplicaSet([PagedGenerationService(e0)], supervise=False)
        try:
            with pytest.raises(ReplicaUnavailable) as exc:
                rs.retire(0)
            assert exc.value.details.get("reason") == "last_serving"
            assert exc.value.retryable is False
            # the refusal left the replica serving
            out = rs.generate("still serving", max_new_tokens=2,
                              temperature=0.0, timeout_s=180)
            assert isinstance(out, PagedResult)
        finally:
            rs.close()

    def test_injected_join_fault_leaves_set_unchanged(self):
        e0 = _engine()
        rs = ReplicaSet([PagedGenerationService(e0)], supervise=False)
        joiner = PagedGenerationService(_engine(base=e0))
        try:
            with faults.inject("replica.join",
                               error=RuntimeError("chaos: join flap"),
                               times=1):
                with pytest.raises(RuntimeError, match="join flap"):
                    rs.add_replica(joiner)
            # never half-joined: membership, capacity and health untouched
            assert rs.stats()["fleet"]["live_replicas"] == 1
            assert rs.tenants.capacity == joiner.max_queue
            # the set still serves, and the SAME joiner lands on retry
            assert rs.add_replica(joiner) == 1
            assert rs.stats()["fleet"]["live_replicas"] == 2
        finally:
            rs.close()

    def test_injected_retire_fault_leaves_replica_serving(self):
        e0 = _engine()
        rs = ReplicaSet([PagedGenerationService(e0),
                         PagedGenerationService(_engine(base=e0))],
                        supervise=False)
        try:
            with faults.inject("replica.retire",
                               error=RuntimeError("chaos: retire flap"),
                               times=1):
                with pytest.raises(RuntimeError, match="retire flap"):
                    rs.retire(0)
            # the fault fired before ANY transition: replica 0 never left
            # rotation and was never drained
            assert rs._health[0].state == HEALTH_HEALTHY
            assert rs.stats()["fleet"]["retired"] == 0
            out = rs.generate("retire flap survivor", max_new_tokens=2,
                              temperature=0.0, timeout_s=180)
            assert isinstance(out, PagedResult)
        finally:
            rs.close()

    def test_flap_storm_conserves_pages_and_leaks_nothing(self):
        """Churn chaos: joins and retires cycling under live traffic (the
        sanitizer is armed for this module) — every outcome typed, page
        pools conserved on live replicas, zero leaked pumps."""
        e0 = _engine()
        rs = ReplicaSet([PagedGenerationService(e0),
                         PagedGenerationService(_engine(base=e0))],
                        supervise=False)
        try:
            for cycle in range(3):
                idx = rs.add_replica(
                    PagedGenerationService(_engine(base=e0), max_queue=8))
                for i in range(2):
                    out = rs.generate(
                        f"flap storm c{cycle} r{i}", max_new_tokens=2,
                        temperature=0.0, timeout_s=180)
                    assert isinstance(out, PagedResult)
                result = rs.retire(idx, deadline_s=30.0)
                assert result["retired"] is True
                # the flap reuses ONE slot: joins never balloon the set
                assert rs.stats()["fleet"]["live_replicas"] == 2
            fleet = rs.stats()["fleet"]
            assert fleet["joined"] == 3 and fleet["retired"] == 3
            assert fleet["retire_drain_p95_s"] >= 0.0
            assert rs.stats()["pump_leaked"] == 0
            _assert_pages_conserved(rs)
        finally:
            rs.close()
        # retired engines idle-exit their pumps: no orphan decode threads
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and any(
                t.name == "paged-decode-pump" and t.is_alive()
                for t in threading.enumerate()):
            time.sleep(0.05)
        assert not any(t.name == "paged-decode-pump" and t.is_alive()
                       for t in threading.enumerate())


# ==========================================================================
# Autoscaler — the closed loop, on a synthetic clock

class TestAutoscaler:
    def _driven_set(self, monkeypatch, rs, drive):
        """Wrap fleet_load: REAL membership, synthetic saturation — the
        drill steers the policy without having to manufacture actual load
        on tiny engines."""
        orig = rs.fleet_load

        def fake():
            load = orig()
            load["busy"] = drive["busy"]
            load["backlog_fraction"] = drive["backlog"]
            for p in load["replicas"]:
                p["busy"] = drive["busy"]
            return load

        monkeypatch.setattr(rs, "fleet_load", fake)

    def test_closed_loop_scales_out_then_back_in(self, monkeypatch):
        """Acceptance: sustained busy duty scales a REAL replica out via
        the launcher seam; sustained idle retires it back to min — the
        whole loop driven through Autoscaler.step() with a synthetic
        clock (no cooldown sleeps)."""
        e0 = _engine()
        svc0 = PagedGenerationService(e0, max_queue=8)
        svc0.generate("autoscale warm", max_new_tokens=2, timeout_s=180)
        rs = ReplicaSet([svc0], supervise=False)
        launches: list = []

        def launcher():
            # a local launcher seam: the real socket one spawns a worker
            # that elastically joins via the registry; the drill adds the
            # replica synchronously so step() observes it immediately
            idx = rs.add_replica(
                PagedGenerationService(_engine(base=e0), max_queue=8))
            launches.append(idx)

        policy = AutoscalePolicy(min_replicas=1, max_replicas=2,
                                 window_s=1.0, out_cooldown_s=0.0,
                                 in_cooldown_s=0.0)
        scaler = Autoscaler(rs, policy, launcher=launcher)
        drive = {"busy": 0.95, "backlog": 0.8}
        self._driven_set(monkeypatch, rs, drive)
        try:
            # poll cadence must outpace window pruning: samples older than
            # window_s fall out, so the span only reaches the 80% coverage
            # gate when steps land well inside the window
            assert scaler.step(now=100.0) is None  # window warming
            assert scaler.step(now=100.3) is None
            assert scaler.step(now=100.6) is None
            assert scaler.step(now=100.9) == "out"
            assert launches == [1]
            assert rs.stats()["fleet"]["live_replicas"] == 2
            # hot at max: no further out, the saturation gauge arms instead
            for t in (101.2, 101.5, 101.8, 102.1):
                assert scaler.step(now=t) is None
            # load collapses: the most-idle replica retires back to min
            # (steps start once the hot samples have aged out of the window)
            drive.update(busy=0.0, backlog=0.0)
            assert scaler.step(now=103.5) is None
            assert scaler.step(now=103.8) is None
            assert scaler.step(now=104.1) is None
            assert scaler.step(now=104.4) == "in"
            assert rs.stats()["fleet"]["live_replicas"] == 1
            assert rs.stats()["fleet"]["retired"] == 1
            stats = scaler.stats()
            assert stats["scale_out"] == 1 and stats["scale_in"] == 1
            # at min and idle: the loop holds steady
            assert scaler.step(now=105.0) is None
        finally:
            scaler.close()
            rs.close()

    def test_scale_out_without_launcher_is_skipped_not_fatal(
            self, monkeypatch):
        e0 = _engine()
        rs = ReplicaSet([PagedGenerationService(e0, max_queue=8)],
                        supervise=False)
        scaler = Autoscaler(
            rs, AutoscalePolicy(min_replicas=1, max_replicas=4,
                                window_s=1.0, out_cooldown_s=0.0))
        drive = {"busy": 0.95, "backlog": 0.9}
        self._driven_set(monkeypatch, rs, drive)
        try:
            for i in range(5):
                assert scaler.step(now=200.0 + i * 0.3) is None
            stats = scaler.stats()
            assert stats["skipped"] >= 1 and stats["scale_out"] == 0
            assert rs.stats()["fleet"]["live_replicas"] == 1
        finally:
            scaler.close()
            rs.close()

    def test_pending_launch_counts_toward_max(self, monkeypatch):
        """A launched worker is invisible to fleet_load() until it
        compiles and registers — the in-flight launch must count toward
        max_replicas or the policy re-fires every cooldown and storms
        past the bound (seen live: max=2 fleet grew to 4 behind a ~20s
        join latency). The pending entry expires after launch_grace_s so
        a dead launch can't pin the fleet below max forever."""
        e0 = _engine()
        rs = ReplicaSet([PagedGenerationService(e0, max_queue=8)],
                        supervise=False)
        calls: list = []
        scaler = Autoscaler(
            rs, AutoscalePolicy(min_replicas=1, max_replicas=2,
                                window_s=1.0, out_cooldown_s=0.0),
            launcher=lambda: calls.append(1),  # slow join: never lands
            launch_grace_s=5.0)
        drive = {"busy": 0.95, "backlog": 0.9}
        self._driven_set(monkeypatch, rs, drive)
        try:
            for t in (300.0, 300.3, 300.6):
                assert scaler.step(now=t) is None  # window warming
            assert scaler.step(now=300.9) == "out"
            assert calls == [1]
            # still hot, zero cooldown, worker never joined: the pending
            # launch holds effective replicas at max — no second launch
            for t in (301.2, 301.5, 301.8, 302.1, 302.4):
                assert scaler.step(now=t) is None
            assert calls == [1]
            assert scaler.stats()["pending_launches"] == 1
            # grace expiry presumes the launch dead and frees the slot:
            # the next warm window may fire again
            for t in (306.0, 306.3, 306.6):
                assert scaler.step(now=t) is None
            assert scaler.step(now=306.9) == "out"
            assert calls == [1, 1]
            assert scaler.stats()["scale_out"] == 2
        finally:
            scaler.close()
            rs.close()

    def test_loop_thread_lifecycle(self):
        e0 = _engine()
        rs = ReplicaSet([PagedGenerationService(e0, max_queue=8)],
                        supervise=False)
        scaler = Autoscaler(
            rs, AutoscalePolicy(), poll_interval_s=0.05)
        try:
            scaler.start()
            scaler.start()  # idempotent
            assert any(t.name == "fleet-autoscaler" and t.is_alive()
                       for t in threading.enumerate())
            time.sleep(0.2)  # a few real polls: steady fleet, no decisions
            stats = scaler.stats()
            assert stats["scale_out"] == 0 and stats["scale_in"] == 0
        finally:
            scaler.close()
            rs.close()
        assert not any(t.name == "fleet-autoscaler" and t.is_alive()
                       for t in threading.enumerate())


# ==========================================================================
# worker_serve — concurrent redial: newest router connection wins

class _FakeEngine:
    page_size = 8
    max_slots = 2


class _FakeService:
    """Minimal duck-typed service for the worker_serve listener drill: the
    redial semantics live entirely in the accept loop, so the engine is
    dead weight here (the server only reads its shape for the ready
    frame)."""

    engine = _FakeEngine()
    broken = False
    closed = False
    tick_failure_count = 0
    pump_leaked_count = 0
    max_queue = 8
    default_timeout_s = 30.0
    default_deadline_s = 0.0
    retry_budget = 0
    tick_stall_budget_s = 0.0

    def heartbeat_age(self):
        return 0.0

    def backlog(self):
        return 0

    def projected_wait(self):
        return 0.0

    def duty_cycle(self):
        return {"idle": 1.0}

    def close(self):
        self.closed = True


_FAKE_SINGLETON = _FakeService()


def _fake_factory(**_kw):
    return _FAKE_SINGLETON


class TestWorkerServeRedial:
    def test_newer_router_connection_supersedes_typed(self, monkeypatch):
        """An advertised worker keeps accepting while a connection is
        live: the NEWEST handshake wins, the superseded link gets one
        typed final err frame, and the shared service carries over (no
        rebuild between connections)."""
        from sentio_tpu.runtime import worker as worker_mod

        monkeypatch.setattr(worker_mod, "_resolve_factory",
                            lambda path: _fake_factory)
        spec = worker_mod.WorkerSpec(
            auth_token="serve-token", status_interval_s=0.05,
            telemetry_interval_s=0.0)
        stop = threading.Event()
        bound: dict = {}
        ready = threading.Event()

        def on_bound(addr):
            bound["addr"] = addr
            ready.set()

        server = threading.Thread(
            target=worker_mod.worker_serve,
            args=("127.0.0.1", 0, spec, stop, on_bound),
            name="worker-serve-drill", daemon=True)
        server.start()
        t1 = t2 = None
        try:
            assert ready.wait(timeout=10)
            def recv_kind(t, kind, timeout_s=10.0):
                from sentio_tpu.runtime.transport import TransportError
                deadline = time.monotonic() + timeout_s
                while time.monotonic() < deadline:
                    try:
                        got = t.recv(timeout_s=timeout_s)
                    except TransportError:
                        return None  # link cut under us
                    if got is None:
                        return None
                    frame, _epoch = got
                    if frame[1] == kind:
                        return frame[2]
                return None

            t1 = dial(bound["addr"])
            ack1 = send_hello(t1, "serve-token", 0, os.getpid(), epoch=1,
                              timeout_s=5.0)
            assert int(ack1["epoch"]) == 1
            # the first link is live (ready + status frames flow) ...
            assert recv_kind(t1, "ready") is not None
            assert recv_kind(t1, "status") is not None
            # ... when a SECOND router dials in at a higher epoch
            t2 = dial(bound["addr"])
            ack2 = send_hello(t2, "serve-token", 0, os.getpid(), epoch=2,
                              timeout_s=5.0)
            assert int(ack2["epoch"]) == 2
            # the superseded link drains one typed final err, then dies
            superseded = recv_kind(t1, "err")
            assert superseded is not None, "no typed supersede frame"
            assert superseded["cls"] == "ReplicaUnavailable"
            assert "superseded" in superseded["message"]
            assert superseded["retryable"] is False
            # the new connection serves: the ready frame and status flow
            assert recv_kind(t2, "ready") is not None
            assert recv_kind(t2, "status") is not None
        finally:
            stop.set()
            for t in (t1, t2):
                if t is not None:
                    t.close()
            server.join(timeout=10)
        assert not server.is_alive()
        # the shared service survived the supersede and was closed ONCE,
        # by the listener teardown — not by the connection swap
        assert _FAKE_SINGLETON.closed is True
