"""Long-context serving end to end — the reference's hardest limit, beaten
visibly.

The reference truncates every prompt to ~2000 tokens before generation
(/root/reference/src/core/graph/nodes.py:296-338, factory.py:90 there) —
its context window is a config constant, not a capability. Here a 4K+
token prompt flows through the REAL serving path (paged KV pool, page
tables, fused-tick decode) untruncated, and the sp>1 mesh runs the same
prefill through ring attention (kernels/ring_attention.py) — the
long-context compute path that shards sequence over the ICI ring.
"""

from dataclasses import replace
from functools import partial

import numpy as np
import pytest

from sentio_tpu.models.llama import LlamaConfig, llama_forward
from sentio_tpu.runtime.paged import ContinuousBatchingEngine

pytestmark = [pytest.mark.slow, pytest.mark.mesh]


def long_cfg(max_len: int = 8192) -> LlamaConfig:
    return replace(LlamaConfig.tiny(), max_len=max_len)


def make_prompt(n_chars: int) -> str:
    # repetitive-but-not-periodic text; ByteTokenizer ~ 1 token/char
    words = ["pallas", "mesh", "ring", "paged", "tick", "fuse", "shard",
             "scan", "hbm", "mxu"]
    out = []
    i = 0
    while sum(len(w) + 1 for w in out) < n_chars:
        out.append(words[(i * i + i // 7) % len(words)])
        i += 1
    return " ".join(out)


class TestLongPromptServing:
    def test_4k_prompt_untruncated_through_paged_engine(self):
        cfg = long_cfg()
        eng = ContinuousBatchingEngine(
            model_config=cfg, max_slots=2, page_size=32,
            max_pages_per_seq=160,  # window 5120 tokens
            num_pages=1 + 180, ignore_eos=True,
        )
        prompt = make_prompt(4300)
        [res] = eng.run_all([prompt], max_new_tokens=8)
        assert res.prompt_tokens > 4096, (
            f"prompt truncated to {res.prompt_tokens} — the reference's 2K "
            "ceiling is the thing this engine exists to beat"
        )
        assert len(res.tokens) == 8 and res.finish_reason == "length"

    def test_page_size_invariance_at_4k(self):
        """The same long prompt through different page layouts must emit
        identical greedy tokens — paging is memory layout, not model
        behavior, at any context length."""
        cfg = long_cfg()
        prompt = make_prompt(4300)
        outs = []
        for page_size, mpps in ((32, 160), (64, 80)):
            eng = ContinuousBatchingEngine(
                model_config=cfg, max_slots=2, page_size=page_size,
                max_pages_per_seq=mpps, num_pages=1 + 2 * mpps,
                ignore_eos=True, rng_seed=0,
            )
            [res] = eng.run_all([prompt], max_new_tokens=8)
            outs.append(res.tokens)
        assert outs[0] == outs[1]

    def test_long_and_short_coexist_in_one_pool(self):
        """A 4K-token sequence and a 40-token sequence share the pool and
        decode in the same fused ticks — the fragmentation-free coexistence
        the paged design buys (runtime/paged.py module docstring)."""
        cfg = long_cfg()
        eng = ContinuousBatchingEngine(
            model_config=cfg, max_slots=2, page_size=32,
            max_pages_per_seq=160, num_pages=1 + 180, ignore_eos=True,
        )
        long_p, short_p = make_prompt(4300), "short question about paging"
        results = eng.run_all([long_p, short_p], max_new_tokens=8)
        assert results[0].prompt_tokens > 4096
        assert results[1].prompt_tokens < 64
        assert all(len(r.tokens) == 8 for r in results)


class TestRingPrefillOnMesh:
    def test_sp_mesh_ring_prefill_matches_single_device(self):
        """Prefill of a 2K+ prompt under an sp=2 (x tp=2, dp=2) mesh runs
        ring attention inside the paged engine's prefill (via
        make_mesh_attn_fn) and must emit the same greedy tokens as the
        plain single-program engine."""
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.kernels import make_mesh_attn_fn
        from sentio_tpu.models.llama import init_llama
        from sentio_tpu.parallel.mesh import build_mesh
        from sentio_tpu.parallel.sharding import LLAMA_TP_RULES, shard_params

        cfg = long_cfg(max_len=4096)
        prompt = make_prompt(2100)
        params = init_llama(jax.random.PRNGKey(0), cfg)

        plain = ContinuousBatchingEngine(
            model_config=cfg, params=params, max_slots=2, page_size=32,
            max_pages_per_seq=80, num_pages=1 + 100, ignore_eos=True,
        )
        [want] = plain.run_all([prompt], max_new_tokens=8)
        assert want.prompt_tokens > 2048

        mesh = build_mesh(MeshConfig(dp_size=2, sp_size=2, tp_size=2))
        sharded = shard_params(init_llama(jax.random.PRNGKey(0), cfg), mesh,
                               LLAMA_TP_RULES)
        ring = ContinuousBatchingEngine(
            model_config=cfg, params=sharded, mesh=mesh,
            forward_fn=partial(llama_forward,
                               attn_fn=make_mesh_attn_fn(mesh, causal=True)),
            max_slots=2, page_size=32, max_pages_per_seq=80,
            num_pages=1 + 100, ignore_eos=True,
        )
        [got] = ring.run_all([prompt], max_new_tokens=8)
        assert got.tokens == want.tokens, (
            "sp-mesh ring prefill diverged from the single-program engine"
        )


class TestChunkedPrefill:
    """Chunked prefill (prefill_chunk): long prompts admit one page-aligned
    segment per tick, so live decodes never stall for a whole 4-8K prefill.
    Correctness bar: greedy tokens identical to whole-prompt admission."""

    def test_greedy_parity_with_whole_prompt_admission(self):
        import jax

        from sentio_tpu.models.llama import init_llama

        cfg = long_cfg(max_len=4096)
        params = init_llama(jax.random.PRNGKey(0), cfg)
        prompt = make_prompt(1500)
        whole = ContinuousBatchingEngine(
            model_config=cfg, params=params, max_slots=2, page_size=32,
            max_pages_per_seq=64, num_pages=1 + 100, ignore_eos=True,
        )
        [want] = whole.run_all([prompt], max_new_tokens=8)
        chunked = ContinuousBatchingEngine(
            model_config=cfg, params=params, max_slots=2, page_size=32,
            max_pages_per_seq=64, num_pages=1 + 100, ignore_eos=True,
            prefill_chunk=512,
        )
        [got] = chunked.run_all([prompt], max_new_tokens=8)
        assert got.prompt_tokens == want.prompt_tokens > 1024
        assert got.tokens == want.tokens

    def test_segments_interleave_with_decode(self):
        """While a long prompt prefills segment by segment, an already-
        decoding request keeps emitting every tick — the stall a monolithic
        prefill would impose is the thing this feature removes."""
        cfg = long_cfg(max_len=4096)
        eng = ContinuousBatchingEngine(
            model_config=cfg, max_slots=2, page_size=32,
            max_pages_per_seq=64, num_pages=1 + 120, ignore_eos=True,
            prefill_chunk=512, steps_per_tick=4,
        )
        short = eng.submit("short chatty request", max_new_tokens=40)
        eng.step()
        long_rid = eng.submit(make_prompt(1500), max_new_tokens=4)
        progress = []
        done = {}
        for _ in range(30):
            for r in eng.step():
                done[r.request_id] = r
            slot = next(s for s in eng.slots if s.request_id == short) \
                if short not in done else None
            long_slot = next((s for s in eng.slots
                              if s.request_id == long_rid and s.active), None)
            if slot is not None and long_slot is not None \
                    and long_slot.prefill_todo is not None:
                progress.append(len(slot.emitted))
            if short in done and long_rid in done:
                break
        assert short in done and long_rid in done
        # the short request's emitted count GREW across ticks in which the
        # long prompt was still mid-prefill
        assert len(progress) >= 2 and progress[-1] > progress[0], progress

    def test_chunked_prefill_with_shared_prefix(self):
        """Chunking composes with the shared-prefix cache: the prior for
        segment K covers prefix pages + own segments, token-identically."""
        import jax

        from sentio_tpu.models.llama import init_llama

        cfg = long_cfg(max_len=4096)
        params = init_llama(jax.random.PRNGKey(0), cfg)
        header = "System: be terse. Cite sources. Answer from context only. "
        prompt = header + make_prompt(1200)

        def build(**kw):
            return ContinuousBatchingEngine(
                model_config=cfg, params=params, max_slots=2, page_size=32,
                max_pages_per_seq=64, num_pages=1 + 100, ignore_eos=True, **kw,
            )

        plain = build()
        [want] = plain.run_all([prompt], max_new_tokens=8)
        both = build(prefill_chunk=512)
        assert both.warm_prefix(header) > 0
        [got] = both.run_all([prompt], max_new_tokens=8)
        assert got.tokens == want.tokens
        assert both.prefix_hits == 1


    def test_segment_compile_variants_bounded(self):
        """Prior-table widths bucket to powers of two, so a long prompt's
        segment prefills compile O(log window) XLA variants — not one fresh
        program per (prior, width) pair, which at 8K/PREFILL_CHUNK=1024
        meant O(window/chunk) compiles stalling the serving thread."""
        cfg = long_cfg(max_len=4096)
        eng = ContinuousBatchingEngine(
            model_config=cfg, max_slots=2, page_size=32,
            max_pages_per_seq=64, num_pages=1 + 70, ignore_eos=True,
            prefill_chunk=128,
        )
        [res] = eng.run_all([make_prompt(1980)], max_new_tokens=4)
        assert len(res.tokens) == 4
        n_segments = -(-res.prompt_tokens // 128)
        assert n_segments >= 15
        # distinct traces of the shared prior-prefill program: one per
        # (suffix-width bucket, pow2 prior-page bucket, do_sample) combo —
        # {0,4,8,16,32,64} priors x final-segment sampling, NOT one per
        # segment
        n_variants = eng._prior_prefill_scatter._cache_size()
        assert n_variants <= 8, (
            f"{n_variants} compile variants for {n_segments} segments — "
            "prior bucketing is not bounding recompilation"
        )

    def test_chunked_prefill_int8_kv(self):
        """Chunking composes with int8 KV pages: segment K's prior primes
        from quantized pages via dequantize — greedy tokens must match
        whole-prompt int8 admission (same quantization noise both sides)."""
        import jax

        from sentio_tpu.models.llama import init_llama

        cfg = long_cfg(max_len=4096)
        params = init_llama(jax.random.PRNGKey(0), cfg)
        prompt = make_prompt(1500)

        def build(**kw):
            return ContinuousBatchingEngine(
                model_config=cfg, params=params, max_slots=2, page_size=32,
                max_pages_per_seq=64, num_pages=1 + 100, ignore_eos=True,
                kv_quant="int8", **kw,
            )

        [want] = build().run_all([prompt], max_new_tokens=8)
        [got] = build(prefill_chunk=512).run_all([prompt], max_new_tokens=8)
        assert got.prompt_tokens == want.prompt_tokens > 1024
        assert got.tokens == want.tokens


    def test_oldest_prefilling_slot_advances_first(self):
        """Segment scheduling is oldest-submit-first, not slot-index-first:
        a newer long prompt landing in a LOWER slot index must not starve
        an older one already mid-prefill in a higher slot."""
        cfg = long_cfg(max_len=4096)
        eng = ContinuousBatchingEngine(
            model_config=cfg, max_slots=2, page_size=32,
            max_pages_per_seq=64, num_pages=1 + 120, ignore_eos=True,
            prefill_chunk=512,
        )
        eng.submit("short", max_new_tokens=4)          # -> slot 0, retires fast
        rid_a = eng.submit(make_prompt(1500), max_new_tokens=4)  # -> slot 1
        eng.step()   # short decodes+retires; A advances one segment
        rid_b = eng.submit(make_prompt(1500), max_new_tokens=4)  # -> slot 0 (newer)
        eng.step()   # ONE segment dispatched: must be A's (older), not B's
        slot_a = next(s for s in eng.slots if s.request_id == rid_a)
        slot_b = next(s for s in eng.slots if s.request_id == rid_b)
        assert slot_a.prefill_done >= 1024 or slot_a.prefill_todo is None
        assert slot_b.prefill_done == 0 and slot_b.prefill_todo is not None

    def test_bad_chunk_size_rejected(self):
        with pytest.raises(ValueError, match="multiple of page_size"):
            ContinuousBatchingEngine(
                model_config=long_cfg(), page_size=32, prefill_chunk=100,
            )
