"""infra/tracing.py — previously dead code, now load-bearing (ISSUE 12):
mock-span fallback when OTel is absent, the single `enabled` hot-path
guard, profile_step's exception path, trace_function sync+async, the
set_tracing reset seam, the windowed profiler's single-flight guard, and
the graph-executor node-span wiring."""

import asyncio
import sys
import threading
from contextlib import contextmanager

import pytest

from sentio_tpu.config import ObservabilityConfig
from sentio_tpu.infra.tracing import (
    MockSpan,
    TracingManager,
    get_tracing,
    profile_window,
    set_tracing,
    trace_function,
)


@pytest.fixture(autouse=True)
def _reset_tracing():
    """Every test starts and ends with a clean singleton — the set_tracing
    reset seam the module exposes for exactly this purpose."""
    set_tracing(None)
    yield
    set_tracing(None)


class RecordingManager:
    """Duck-typed manager capturing span/profile_step calls — what the
    executor and pump wiring tests assert against."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.spans: list[tuple[str, dict]] = []
        self.steps: list[tuple[str, int]] = []
        self._lock = threading.Lock()

    @contextmanager
    def span(self, name, **attrs):
        with self._lock:
            self.spans.append((name, attrs))
        yield MockSpan()

    @contextmanager
    def profile_step(self, name, step=0):
        with self._lock:
            self.steps.append((name, step))
        yield


class TestMockFallback:
    def test_disabled_by_default(self):
        mgr = TracingManager(ObservabilityConfig())
        assert mgr.enabled is False
        with mgr.span("anything", a=1) as span:
            # the mock span accepts the full OTel surface
            assert span.set_attribute("k", "v") is span
            span.record_exception(ValueError("x"))
            span.set_status("ok")

    def test_otel_absent_is_noop_and_disabled(self, monkeypatch):
        """tracing_enabled=True but no opentelemetry installed: setup
        degrades to the mock path AND the hot-path guard stays False —
        serving code pays nothing to feed a mock."""
        monkeypatch.setitem(sys.modules, "opentelemetry", None)
        mgr = TracingManager(
            ObservabilityConfig(tracing_enabled=True))
        assert mgr.enabled is False
        ran = []
        with mgr.span("n") as span:
            ran.append(span)
        assert isinstance(ran[0], MockSpan)

    def test_enabled_with_real_otel(self):
        # the base image ships only opentelemetry-api; the SDK (and thus a
        # real tracer) is a deploy-time install — skip, don't fake it
        pytest.importorskip("opentelemetry.sdk")
        mgr = TracingManager(ObservabilityConfig(tracing_enabled=True))
        assert mgr.enabled is True
        with mgr.span("real", request_id="r1") as span:
            assert span is not None
        mgr.shutdown()


class TestProfileStep:
    def test_profile_step_wraps_body(self):
        mgr = TracingManager(ObservabilityConfig())
        ran = []
        with mgr.profile_step("tick", step=7):
            ran.append(True)
        assert ran == [True]

    def test_profile_step_exception_path(self, monkeypatch):
        """A broken StepTraceAnnotation (e.g. profiler unsupported on the
        backend) must degrade to the plain span, never fail the tick."""
        import jax

        class Boom:
            def __init__(self, *a, **k):
                raise RuntimeError("no profiler here")

        monkeypatch.setattr(jax.profiler, "StepTraceAnnotation", Boom)
        mgr = TracingManager(ObservabilityConfig())
        ran = []
        with mgr.profile_step("tick", step=1):
            ran.append(True)
        assert ran == [True]

    def test_profile_step_body_exception_propagates_unmangled(self):
        """An exception from the TRACED BODY (a failed device tick) must
        surface as itself: the pump's crash containment and the chaos
        drills key off the original type. The old broad except around the
        yield replaced it with contextlib's 'generator didn't stop after
        throw()' RuntimeError."""
        mgr = TracingManager(ObservabilityConfig())
        with pytest.raises(ValueError, match="tick blew up"):
            with mgr.profile_step("tick", step=2):
                raise ValueError("tick blew up")

    def test_profile_step_body_exception_with_broken_annotation(
            self, monkeypatch):
        import jax

        class ExitBoom:
            def __init__(self, *a, **k):
                pass

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                raise RuntimeError("exit failed")

        monkeypatch.setattr(jax.profiler, "StepTraceAnnotation", ExitBoom)
        mgr = TracingManager(ObservabilityConfig())
        # a broken annotation EXIT must neither mask the body's exception
        # nor raise its own
        with pytest.raises(ValueError, match="original"):
            with mgr.profile_step("tick", step=3):
                raise ValueError("original")
        ran = []
        with mgr.profile_step("tick", step=4):
            ran.append(True)
        assert ran == [True]


class TestTraceFunction:
    def test_sync(self):
        mgr = RecordingManager()
        set_tracing(mgr)

        @trace_function("my.sync")
        def add(a, b):
            return a + b

        assert add(2, 3) == 5
        assert mgr.spans[0][0] == "my.sync"

    def test_async(self):
        mgr = RecordingManager()
        set_tracing(mgr)

        @trace_function("my.async")
        async def mul(a, b):
            return a * b

        assert asyncio.run(mul(2, 3)) == 6
        assert mgr.spans[0][0] == "my.async"

    def test_default_name_and_explicit_manager(self):
        mgr = RecordingManager()

        @trace_function(manager=mgr)
        def named():
            return 1

        assert named() == 1
        assert named.__name__ == "named"
        assert "named" in mgr.spans[0][0]

    def test_set_tracing_reset(self):
        mgr = RecordingManager()
        set_tracing(mgr)
        assert get_tracing() is mgr
        set_tracing(None)
        fresh = get_tracing()
        assert fresh is not mgr
        assert isinstance(fresh, TracingManager)


class TestProfileWindow:
    def test_window_runs_and_writes(self, tmp_path):
        out = profile_window(0.01, str(tmp_path))
        assert out["started"] is True
        assert out["log_dir"] == str(tmp_path)

    def test_single_flight(self, tmp_path, monkeypatch):
        """The jax profiler is process-global: a second concurrent window
        is refused (409 at the endpoint), not interleaved. Deterministic:
        pin the busy flag directly instead of racing thread scheduling."""
        import sentio_tpu.infra.tracing as tracing_mod

        monkeypatch.setattr(tracing_mod, "_profile_active", True)
        refused = profile_window(0.01, str(tmp_path))
        assert refused["started"] is False
        assert "already active" in refused["error"]
        # releasing the flag restores normal operation
        monkeypatch.setattr(tracing_mod, "_profile_active", False)
        assert profile_window(0.01, str(tmp_path))["started"] is True


class TestExecutorSpans:
    def _graph(self):
        from sentio_tpu.graph.executor import END, GraphBuilder

        def a(state):
            return {"metadata": {"a_ran": True}}

        def b(state):
            return {"metadata": {"replica_id": 1}}

        return (
            GraphBuilder()
            .add_node("alpha", a)
            .add_node("beta", b)
            .add_edge("alpha", "beta")
            .add_edge("beta", END)
            .set_entry("alpha")
            .compile()
        )

    def test_node_spans_with_request_id(self):
        mgr = RecordingManager()
        set_tracing(mgr)
        graph = self._graph()
        state = graph.invoke({"metadata": {"query_id": "req-42"}})
        assert state["metadata"]["a_ran"] is True
        names = [n for n, _ in mgr.spans]
        assert names == ["graph.alpha", "graph.beta"]
        for _, attrs in mgr.spans:
            assert attrs["request_id"] == "req-42"
        # replica_id stamped by an upstream node rides later spans
        assert mgr.spans[0][1]["replica_id"] == -1

    def test_tracing_off_no_spans(self):
        mgr = RecordingManager(enabled=False)
        set_tracing(mgr)
        graph = self._graph()
        graph.invoke({"metadata": {"query_id": "req-43"}})
        assert mgr.spans == []

    def test_detached_node_span(self):
        from sentio_tpu.graph.executor import (
            END,
            GraphBuilder,
            wait_detached,
        )

        mgr = RecordingManager()
        set_tracing(mgr)
        done = threading.Event()

        def audit(state):
            done.set()
            return None

        graph = (
            GraphBuilder()
            .add_node("audit", audit, detached=True)
            .add_edge("audit", END)
            .set_entry("audit")
            .compile()
        )
        graph.invoke({"metadata": {"query_id": "req-44"}})
        assert wait_detached(timeout_s=10)
        assert done.wait(1)
        names = [n for n, _ in mgr.spans]
        assert "graph.audit" in names
        attrs = dict(mgr.spans)["graph.audit"]
        assert attrs["detached"] is True
        assert attrs["request_id"] == "req-44"


class TestPumpProfileStep:
    def test_tick_step_annotation_when_enabled(self):
        """With tracing enabled the pump wraps every engine tick in
        profile_step (step = tick number) so XLA device traces line up
        with flight ticks; with tracing off (the default elsewhere in this
        suite) the pump never touches the manager."""
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine
        from sentio_tpu.runtime.service import PagedGenerationService

        mgr = RecordingManager()
        set_tracing(mgr)
        eng = ContinuousBatchingEngine(
            max_slots=2, page_size=16, max_pages_per_seq=4,
            steps_per_tick=4, max_tick_steps=4,
        )
        svc = PagedGenerationService(eng)
        try:
            result = svc.generate("hello", max_new_tokens=4)
            assert result.tokens is not None
        finally:
            svc.close()
        assert mgr.steps, "no profile_step annotations recorded"
        names = {n for n, _ in mgr.steps}
        assert names == {"decode_tick"}
        steps = [s for _, s in mgr.steps]
        assert steps == sorted(steps)  # step numbers are the tick sequence
