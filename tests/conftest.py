"""Test harness configuration.

Force JAX onto the host CPU platform with 8 virtual devices BEFORE jax is
imported anywhere — this is how multi-chip sharding (dp/tp/sp meshes,
collectives) is exercised on a single host with no TPU attached, mirroring
the reference's mock-backend test strategy (SURVEY.md §4) at the device
level.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# the axon TPU plugin self-registers from sitecustomize when this is set,
# overriding JAX_PLATFORMS — tests must run on the virtual CPU mesh
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# sitecustomize imports jax at interpreter startup (before this file runs),
# locking JAX_PLATFORMS=axon from the ambient env — config.update still wins
# as long as no backend has initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from sentio_tpu.config import Settings, set_settings  # noqa: E402

# Suites exercising the paged engine / radix cache / decode service run with
# the runtime sanitizer armed (analysis/sanitizer.py): engine entry points
# assert the single-driver-thread contract, annotated locks record
# ownership, and every tick verifies page-pool conservation + radix
# refcounts. A regression in those invariants fails HERE, on the tick that
# introduced it, instead of as a pool-exhaustion heisenbug later.
_SANITIZED_MODULES = {
    "test_chaos",
    "test_elastic",
    "test_paged",
    "test_paged_sched",
    "test_paged_spec",
    "test_phases",
    "test_prefix_cache",
    "test_replica",
    "test_service",
    "test_sanitize",
}


@pytest.fixture(scope="module", autouse=True)
def _sanitize_engine_suites(request):
    # module-scoped (not function-scoped): autouse fixtures instantiate
    # before other fixtures of the same scope, so the env var is set before
    # any module-scoped engine fixture constructs its engine — a
    # function-scoped monkeypatch would arm the sanitizer AFTER those
    # engines were already built with _san=None
    module = getattr(request, "module", None)
    if module is None or module.__name__ not in _SANITIZED_MODULES:
        yield
        return
    prior = os.environ.get("SENTIO_SANITIZE")
    os.environ["SENTIO_SANITIZE"] = "1"
    yield
    if prior is None:
        os.environ.pop("SENTIO_SANITIZE", None)
    else:
        os.environ["SENTIO_SANITIZE"] = prior


@pytest.fixture()
def settings():
    """A fresh default Settings tree pinned as the singleton for the test."""
    s = Settings()
    set_settings(s)
    yield s
    set_settings(None)


@pytest.fixture()
def docs():
    from sentio_tpu.models.document import Document

    corpus = [
        ("d1", "The quick brown fox jumps over the lazy dog."),
        ("d2", "TPUs accelerate matrix multiplication with a systolic array."),
        ("d3", "JAX composes function transformations like jit grad and vmap."),
        ("d4", "The dog sleeps while the fox runs through the forest."),
        ("d5", "Retrieval augmented generation combines search with language models."),
        ("d6", "BM25 is a ranking function used by search engines for scoring."),
        ("d7", "Flash attention tiles the softmax computation to save memory bandwidth."),
        ("d8", "A lazy dog and a quick fox are common in typing exercises."),
    ]
    return [Document(text=t, id=i, metadata={"source": f"{i}.txt"}) for i, t in corpus]
