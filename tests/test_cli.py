"""CLI surface: trace (studio-equivalent execution dump) and convert
(HF checkpoint import), driven through main() with fake model providers."""

from __future__ import annotations

import json

import pytest

from sentio_tpu.cli import main
from sentio_tpu.config import (
    EmbedderConfig,
    GeneratorConfig,
    RerankConfig,
    Settings,
    set_settings,
)


@pytest.fixture()
def fake_settings():
    s = Settings(
        embedder=EmbedderConfig(provider="hash", dim=32),
        generator=GeneratorConfig(provider="echo", use_verifier=False, max_new_tokens=16),
        rerank=RerankConfig(enabled=False),
    )
    set_settings(s)
    yield s
    set_settings(None)


class TestTrace:
    def test_trace_dumps_execution(self, fake_settings, tmp_path, capsys):
        doc = tmp_path / "doc.txt"
        doc.write_text("TPUs pair a systolic MXU with HBM for fast matmul.")
        rc = main(["trace", "what is an MXU?", "--ingest", str(tmp_path), "--documents"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["graph_path"][0] == "retrieve"
        assert "generate" in out["graph_path"]
        assert out["num_retrieved"] >= 1
        assert out["node_timings_ms"]
        assert out["selected_documents"]
        assert out["answer"]

    def test_trace_empty_index_degrades(self, fake_settings, capsys):
        rc = main(["trace", "anything"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["num_retrieved"] == 0

    def test_trace_chrome_export(self, fake_settings, tmp_path, capsys):
        """--chrome dumps the whole flight timeline as a Chrome/Perfetto
        trace next to the normal JSON dump."""
        doc = tmp_path / "doc.txt"
        doc.write_text("TPUs pair a systolic MXU with HBM for fast matmul.")
        out_path = tmp_path / "trace.json"
        rc = main(["trace", "what is an MXU?", "--ingest", str(tmp_path),
                   "--chrome", str(out_path)])
        assert rc == 0
        json.loads(capsys.readouterr().out)  # normal dump still intact
        trace = json.loads(out_path.read_text())
        assert "traceEvents" in trace
        names = {e["name"] for e in trace["traceEvents"]}
        # the echo provider never touches the paged engine, so there may
        # be no ticks — but the request span must be on the timeline
        assert any(n.startswith("request ") for n in names)


class TestConvert:
    def test_convert_llama_dir_round_trip(self, fake_settings, tmp_path, capsys):
        transformers = pytest.importorskip("transformers")
        torch = pytest.importorskip("torch")

        cfg = transformers.LlamaConfig(
            vocab_size=64, hidden_size=16, intermediate_size=32,
            num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=1,
            max_position_embeddings=32,
        )
        torch.manual_seed(0)
        src = tmp_path / "hf"
        transformers.LlamaForCausalLM(cfg).save_pretrained(src)
        dst = tmp_path / "ckpt"
        rc = main(["convert", "llama", str(src), str(dst), "--dtype", "float32"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["config"]["dim"] == 16

        from sentio_tpu.runtime.checkpoint import load_pytree

        params, meta = load_pytree(dst)
        assert meta["family"] == "llama"
        assert params["embed_tokens"]["embedding"].shape == (64, 16)


class TestInfo:
    def test_info_runs(self, fake_settings, capsys):
        assert main(["info"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert "devices" in out and out["devices"]
