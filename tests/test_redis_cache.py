"""Redis L2 cache: the in-tree RESP2 client against an in-process fake
redis server (asyncio), plus degradation when the server is down/broken —
the reference's redis-down-→-memory-only behavior (cache_manager.py:77-84
there), here actually exercised over a socket instead of mocked."""

from __future__ import annotations

import asyncio
import json

import pytest

from sentio_tpu.config import CacheConfig
from sentio_tpu.infra.caching import CacheManager
from sentio_tpu.infra.redis_cache import RedisL2Cache, _encode_command


class FakeRedis:
    """Tiny RESP2 server: PING / AUTH / SELECT / GET / SET PX / DEL."""

    def __init__(self):
        self.store: dict[bytes, bytes] = {}
        self.commands: list[list[bytes]] = []
        self.server = None
        self.port = None
        self._writers: list = []

    async def start(self):
        self.server = await asyncio.start_server(self._handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]

    async def stop(self):
        self.server.close()
        # 3.12 wait_closed() blocks until handler connections end — drop them
        for w in self._writers:
            try:
                w.close()
            except Exception:  # noqa: BLE001
                pass
        await self.server.wait_closed()

    async def _handle(self, reader, writer):
        self._writers.append(writer)
        try:
            while True:
                line = (await reader.readuntil(b"\r\n"))[:-2]
                if not line.startswith(b"*"):
                    break
                n = int(line[1:])
                args = []
                for _ in range(n):
                    hdr = (await reader.readuntil(b"\r\n"))[:-2]
                    size = int(hdr[1:])
                    data = await reader.readexactly(size + 2)
                    args.append(data[:-2])
                self.commands.append(args)
                writer.write(self._dispatch(args))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    def _dispatch(self, args):
        cmd = args[0].upper()
        if cmd in (b"PING",):
            return b"+PONG\r\n"
        if cmd in (b"AUTH", b"SELECT"):
            return b"+OK\r\n"
        if cmd == b"SET":  # SET key val PX ms
            self.store[args[1]] = args[2]
            return b"+OK\r\n"
        if cmd == b"GET":
            val = self.store.get(args[1])
            if val is None:
                return b"$-1\r\n"
            return b"$%d\r\n%s\r\n" % (len(val), val)
        if cmd == b"DEL":
            existed = args[1] in self.store
            self.store.pop(args[1], None)
            return b":%d\r\n" % int(existed)
        return b"-ERR unknown\r\n"


@pytest.fixture()
def fake_redis():
    srv = FakeRedis()
    loop = asyncio.new_event_loop()
    loop.run_until_complete(srv.start())
    yield srv, loop
    loop.run_until_complete(srv.stop())
    loop.close()


class TestRESPClient:
    def test_encode_command(self):
        assert _encode_command("GET", "k") == b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n"

    def test_set_get_delete_round_trip(self, fake_redis):
        srv, loop = fake_redis
        cache = RedisL2Cache(url=f"redis://127.0.0.1:{srv.port}/0")

        async def flow():
            assert await cache.ping() is True
            await cache.set("q1", {"answer": 42}, ttl_s=10.0)
            assert await cache.get("q1") == {"answer": 42}
            await cache.delete("q1")
            assert await cache.get("q1") is None

        loop.run_until_complete(flow())
        # TTL reached the wire as PX milliseconds, keys carried the prefix
        sets = [c for c in srv.commands if c[0] == b"SET"]
        assert sets[0][1] == b"sentio:q1"
        assert sets[0][3] == b"PX" and sets[0][4] == b"10000"

    def test_down_server_degrades_to_miss(self):
        cache = RedisL2Cache(url="redis://127.0.0.1:1/0", timeout_s=0.3)

        async def flow():
            assert await cache.get("k") is None
            await cache.set("k", "v", 5.0)  # must not raise
            assert await cache.ping() is False

        asyncio.new_event_loop().run_until_complete(flow())

    def test_corrupt_json_is_a_miss(self, fake_redis):
        srv, loop = fake_redis
        cache = RedisL2Cache(url=f"redis://127.0.0.1:{srv.port}/0")
        srv.store[b"sentio:bad"] = b"{not json"

        async def flow():
            assert await cache.get("bad") is None

        loop.run_until_complete(flow())

    def test_reconnects_after_server_restart(self, fake_redis):
        srv, loop = fake_redis
        cache = RedisL2Cache(url=f"redis://127.0.0.1:{srv.port}/0")

        async def flow():
            await cache.set("a", 1, 5.0)
            await srv.stop()
            assert await cache.get("a") is None  # degraded, no raise
            srv2 = FakeRedis()
            await srv2.start()
            cache.port = srv2.port  # same client, new endpoint
            assert await cache.ping() is True
            await srv2.stop()

        loop.run_until_complete(flow())


class TestManagerIntegration:
    def test_multi_tier_promotes_l2_hit_to_l1(self, fake_redis):
        srv, loop = fake_redis
        cfg = CacheConfig(backend="multi_tier",
                          redis_url=f"redis://127.0.0.1:{srv.port}/0")
        mgr = CacheManager(config=cfg)
        srv.store[b"sentio:warm"] = json.dumps("from-l2").encode()

        async def flow():
            assert await mgr.aget("warm") == "from-l2"

        loop.run_until_complete(flow())
        assert mgr.l1.get("warm") == "from-l2"  # promoted

    def test_multi_tier_with_no_redis_still_serves_l1(self):
        cfg = CacheConfig(backend="multi_tier", redis_url="redis://127.0.0.1:1/0")
        mgr = CacheManager(config=cfg)
        mgr.set("k", "v")
        assert mgr.get("k") == "v"

        async def flow():
            await mgr.aset("k2", "v2")
            assert await mgr.aget("k2") == "v2"

        asyncio.new_event_loop().run_until_complete(flow())
