import numpy as np
import pytest

from sentio_tpu.config import GeneratorConfig
from sentio_tpu.models.document import Document
from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.ops.generator import (
    EchoProvider,
    LLMGenerator,
    TpuProvider,
    create_generator,
    get_provider,
)
from sentio_tpu.ops.prompts import PromptBuilder
from sentio_tpu.ops.reply_extractor import extract_json_block
from sentio_tpu.ops.verifier import AnswerVerifier, VerifyResult
from sentio_tpu.runtime.engine import GeneratorEngine


@pytest.fixture(scope="module")
def engine():
    return GeneratorEngine(
        config=GeneratorConfig(provider="tpu", model_preset="tiny", max_new_tokens=16),
        model_config=LlamaConfig.tiny(),
    )


DOCS = [
    Document(text="The MXU is a systolic array.", id="a", metadata={"score": 0.9, "source": "tpu.md"}),
    Document(text="JAX uses XLA.", id="b", metadata={"score": 0.5, "source": "jax.md"}),
]


class TestEngine:
    def test_generate_batched(self, engine):
        results = engine.generate(["Hello there", "Another prompt"], max_new_tokens=8)
        assert len(results) == 2
        for r in results:
            assert r.finish_reason in ("stop", "length")
            assert len(r.tokens) <= 8
            assert r.prompt_tokens > 0

    def test_greedy_deterministic(self, engine):
        a = engine.generate(["determinism test"], max_new_tokens=8, temperature=0.0)[0]
        b = engine.generate(["determinism test"], max_new_tokens=8, temperature=0.0)[0]
        assert a.tokens == b.tokens

    def test_stream_matches_generate(self, engine):
        prompt = "stream equivalence"
        bulk = engine.generate([prompt], max_new_tokens=8, temperature=0.0)[0]
        streamed = "".join(engine.stream(prompt, max_new_tokens=8, temperature=0.0))
        assert streamed == bulk.text

    def test_temperature_sampling_varies(self, engine):
        outs = {
            tuple(engine.generate(["vary me"], max_new_tokens=8, temperature=1.5)[0].tokens)
            for _ in range(4)
        }
        assert len(outs) > 1  # astronomically unlikely to all collide

    def test_device_stats(self, engine):
        stats = engine.device_stats()
        assert stats["platform"] == "cpu"
        assert stats["n_devices"] == 8
        assert stats["model"]["layers"] == 2


class TestSampling:
    def test_greedy_vs_temp(self):
        import jax
        import jax.numpy as jnp

        from sentio_tpu.runtime.sampling import sample_tokens

        logits = jnp.asarray([[1.0, 5.0, 2.0]])
        rng = jax.random.PRNGKey(0)
        assert int(sample_tokens(logits, rng, 0.0)[0][0]) == 1
        # top_k=1 forces argmax even at high temperature
        assert int(sample_tokens(logits, rng, 10.0, top_k=1)[0][0]) == 1

    def test_top_p_restricts_support(self):
        import jax
        import jax.numpy as jnp

        from sentio_tpu.runtime.sampling import sample_tokens

        logits = jnp.asarray([[10.0, 0.0, -10.0, -10.0]])
        picks = {
            int(sample_tokens(logits, jax.random.PRNGKey(i), 2.0, top_p=0.5)[0][0])
            for i in range(20)
        }
        assert picks == {0}

    def test_logprob_is_chosen_tokens_raw_log_softmax(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from sentio_tpu.runtime.sampling import sample_tokens

        logits = jnp.asarray([[1.0, 5.0, 2.0]])
        rng = jax.random.PRNGKey(0)
        tok, lp = sample_tokens(logits, rng, 0.0)
        expect = jax.nn.log_softmax(logits, axis=-1)[0, int(tok[0])]
        assert np.isclose(float(lp[0]), float(expect), atol=1e-6)
        assert float(lp[0]) < 0.0
        # the logprob reports the UNSCALED distribution: high temperature
        # with top_k=1 still picks argmax, and the logprob must match the
        # raw log-softmax, not the temperature-flattened one
        tok_t, lp_t = sample_tokens(logits, rng, 10.0, top_k=1)
        assert int(tok_t[0]) == int(tok[0])
        assert np.isclose(float(lp_t[0]), float(expect), atol=1e-6)


class TestPrompts:
    def test_fallback_templates_when_no_dir(self, tmp_path):
        pb = PromptBuilder(prompts_dir=str(tmp_path / "missing"))
        text = pb.build("retrieve", instruction="I", context="C", query="Q")
        assert "C" in text and "Q" in text

    def test_file_templates_cached(self, tmp_path):
        (tmp_path / "retrieve.md").write_text("CUSTOM {query}")
        pb = PromptBuilder(prompts_dir=str(tmp_path))
        assert pb.build("retrieve", query="hi") == "CUSTOM hi"
        (tmp_path / "retrieve.md").write_text("CHANGED {query}")
        assert pb.build("retrieve", query="hi") == "CUSTOM hi"  # cached
        PromptBuilder.clear_cache()

    def test_braces_in_context_safe(self, tmp_path):
        pb = PromptBuilder(prompts_dir=str(tmp_path / "missing"))
        out = pb.build("retrieve", context='{"weird": "json {braces}"}', query="q")
        assert '{"weird": "json {braces}"}' in out


class TestGenerator:
    def test_context_numbering_and_scores(self):
        gen = LLMGenerator(provider=EchoProvider(), config=GeneratorConfig())
        ctx = gen.prepare_context(DOCS)
        assert "[1] Source: tpu.md (score 0.900)" in ctx
        assert "[2] Source: jax.md" in ctx
        assert gen.prepare_context([]) == "(no context documents)"

    def test_echo_provider_quotes_top_source(self):
        gen = LLMGenerator(provider=EchoProvider(), config=GeneratorConfig())
        answer = gen.generate("what is the MXU?", DOCS)
        assert "[1]" in answer

    def test_stream_concat_equals_chat(self):
        gen = LLMGenerator(provider=EchoProvider(), config=GeneratorConfig())
        full = gen.generate("q", DOCS)
        streamed = "".join(gen.stream("q", DOCS))
        assert streamed == full

    def test_temperature_modes(self):
        cfg = GeneratorConfig()
        assert cfg.temperature("fast") == 0.0
        assert cfg.temperature("balanced") == 0.3
        assert cfg.temperature("quality") == 0.2
        assert cfg.temperature("creative") == 0.7
        assert cfg.temperature("bogus") == 0.3

    def test_tpu_provider_end_to_end(self, engine):
        gen = LLMGenerator(
            provider=TpuProvider(engine=engine),
            config=GeneratorConfig(max_new_tokens=8),
        )
        out = gen.generate("tiny question", DOCS, mode="fast")
        assert isinstance(out, str)

    def test_registry(self):
        assert isinstance(get_provider("echo"), EchoProvider)
        with pytest.raises(ValueError):
            get_provider("nope")

    def test_create_generator_falls_back_without_engine(self, settings):
        gen = create_generator(settings)
        assert isinstance(gen.provider, EchoProvider)


class TestReplyExtractor:
    def test_plain_json(self):
        r = extract_json_block('{"verdict": "pass"}')
        assert r.ok and r.payload["verdict"] == "pass"

    def test_fenced_json(self):
        r = extract_json_block('Sure!\n```json\n{"a": 1}\n```\nthanks')
        assert r.ok and r.payload == {"a": 1}

    def test_embedded_brace_span(self):
        r = extract_json_block('The audit says {"verdict": "warn", "notes": []} overall.')
        assert r.ok and r.payload["verdict"] == "warn"

    def test_nested_and_string_braces(self):
        r = extract_json_block('x {"outer": {"inner": "has } brace"}} y')
        assert r.ok and r.payload["outer"]["inner"] == "has } brace"

    def test_trailing_comma_relaxed(self):
        r = extract_json_block('{"a": 1, "b": [1, 2,],}')
        assert r.ok and r.payload["b"] == [1, 2]

    def test_garbage_returns_error(self):
        r = extract_json_block("no json here at all")
        assert not r.ok and r.error
        assert not extract_json_block("").ok


class TestVerifier:
    def _verifier(self, reply):
        class CannedProvider:
            name = "canned"

            def chat(self, prompt, max_new_tokens, temperature):
                assert temperature == 0.0  # audit runs at temp 0
                return reply

            def stream(self, *a, **k):
                yield reply

        gen = LLMGenerator(provider=CannedProvider(), config=GeneratorConfig())
        return AnswerVerifier(generator=gen, config=GeneratorConfig())

    def test_pass_verdict(self):
        v = self._verifier('{"verdict": "pass", "citations_ok": true, "notes": []}')
        result = v.verify("q", "answer", DOCS)
        assert result.verdict == "pass" and result.citations_ok

    def test_fail_with_revision(self):
        v = self._verifier(
            '{"verdict": "fail", "citations_ok": false, "notes": ["wrong"], '
            '"revised_answer": "better answer"}'
        )
        result = v.verify("q", "bad answer", DOCS)
        assert result.verdict == "fail"
        assert result.revised_answer == "better answer"

    def test_unparseable_degrades_to_warn(self):
        v = self._verifier("I refuse to emit JSON")
        result = v.verify("q", "a", DOCS)
        assert result.verdict == "warn"
        assert result.notes

    def test_invalid_verdict_normalized(self):
        v = self._verifier('{"verdict": "AMAZING", "notes": "single string"}')
        result = v.verify("q", "a", DOCS)
        assert result.verdict == "warn"
        assert result.notes == ["single string"]

    def test_provider_exception_never_raises(self):
        class BoomProvider:
            name = "boom"

            def chat(self, *a, **k):
                raise RuntimeError("device lost")

            def stream(self, *a, **k):
                raise RuntimeError("device lost")

        gen = LLMGenerator(provider=BoomProvider(), config=GeneratorConfig())
        v = AnswerVerifier(generator=gen, config=GeneratorConfig())
        result = v.verify("q", "a", DOCS)
        assert result.verdict == "warn"
        assert "device lost" in result.notes[0]

    def test_notes_capped_at_8(self):
        v = self._verifier(
            '{"verdict": "warn", "notes": ' + str([f"n{i}" for i in range(20)]).replace("'", '"') + "}"
        )
        assert len(v.verify("q", "a", DOCS).notes) == 8


class TestReviewRegressions:
    def test_generate_more_prompts_than_max_batch(self, engine):
        """>max batch bucket prompts must chunk, not crash on negative pad."""
        prompts = [f"prompt number {i}" for i in range(18)]
        results = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
        assert len(results) == 18
        # chunking must not change per-prompt results
        solo = engine.generate([prompts[17]], max_new_tokens=4, temperature=0.0)[0]
        assert results[17].tokens == solo.tokens

    def test_single_quoted_json_verifier_reply(self):
        r = extract_json_block("{'verdict': 'fail', 'citations_ok': false, 'notes': ['x']}")
        assert r.ok
        assert r.payload["verdict"] == "fail"
        assert r.payload["citations_ok"] is False

    def test_prompt_value_containing_placeholder_not_reexpanded(self, tmp_path):
        pb = PromptBuilder(prompts_dir=str(tmp_path / "missing"))
        out = pb.build("verify", instruction="answer quoting {context} literally",
                       context="SOURCES", query="q")
        assert "answer quoting {context} literally" in out
        assert out.count("SOURCES") == 1

    def test_stable_steps_buckets_headroom_clamp(self, engine):
        # requested counts round UP to a STEP_BUCKET (generate truncates the
        # over-run host-side) so the fused-scan variant space stays the
        # bounded set the compile manifest commits to
        assert engine._stable_steps(100, 1000) == 128
        assert engine._stable_steps(16, 1000) == 16  # bucket values pass through
        assert engine._stable_steps(1000, 700) == 512  # clamped -> bucket floor
        assert engine._stable_steps(1000, 1) == 1
        # above the top bucket, bucket_size returns n itself — the clamp
        # keeps such requests on-manifest instead of one-program-per-value
        top = max(engine.STEP_BUCKETS)
        assert engine._stable_steps(top + 999, top * 2) == top


def test_relaxed_parse_preserves_true_inside_strings():
    r = extract_json_block("{'verdict': 'fail', 'revised_answer': 'the claim is true'}")
    assert r.ok
    assert r.payload["revised_answer"] == "the claim is true"


def test_per_call_max_new_tokens_respected(engine):
    short = engine.generate(["count up"], max_new_tokens=4, temperature=0.0)[0]
    longer = engine.generate(["count up"], max_new_tokens=24, temperature=0.0)[0]
    assert len(short.tokens) <= 4
    assert len(longer.tokens) > 4 or longer.finish_reason == "stop"
