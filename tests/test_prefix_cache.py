"""Shared-prefix KV caching (runtime/paged.py register_prefix): matching
requests reuse the prefix pages read-only and prefill only their suffix;
generation must match the no-prefix engine."""

import numpy as np
import pytest

from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.runtime.paged import ContinuousBatchingEngine

pytestmark = pytest.mark.slow


HEADER = "You are a careful assistant. Cite sources. Answer concisely. "


def make_engine(**kw):
    return ContinuousBatchingEngine(
        model_config=LlamaConfig.tiny(), max_slots=4, page_size=16,
        max_pages_per_seq=8, steps_per_tick=4, ignore_eos=True, **kw,
    )


class TestRegistration:
    def test_register_returns_page_aligned_count(self):
        eng = make_engine()
        n = eng.register_prefix(HEADER)
        assert n > 0 and n % eng.page_size == 0
        # ByteTokenizer ~1 token/char (+BOS)
        assert n <= len(HEADER) + 1

    def test_short_prefix_not_cached(self):
        eng = make_engine()
        assert eng.register_prefix("hi") == 0
        assert eng._prefix is None

    def test_reregister_frees_old_pages(self):
        eng = make_engine()
        base = eng.allocator.free_pages
        eng.register_prefix(HEADER)
        held = base - eng.allocator.free_pages
        assert held > 0
        eng.register_prefix(HEADER + "Extra instruction text here, longer. ")
        held2 = base - eng.allocator.free_pages
        assert held2 >= held  # old pages freed, new ones allocated

    def test_short_reregistration_frees_old_pages(self):
        # a too-short re-registration must still release the old prefix
        eng = make_engine()
        base = eng.allocator.free_pages
        eng.register_prefix(HEADER)
        assert eng.allocator.free_pages < base
        assert eng.register_prefix("hi") == 0
        assert eng.allocator.free_pages == base  # nothing leaked


class TestPrefixServing:
    def test_matches_no_prefix_engine(self):
        prompts = [
            HEADER + "What is a systolic array?",
            HEADER + "Explain BM25 briefly.",
        ]
        plain = make_engine().run_all(prompts, max_new_tokens=8, temperature=0.0)

        eng = make_engine()
        n = eng.register_prefix(HEADER)
        assert n > 0
        cached = eng.run_all(prompts, max_new_tokens=8, temperature=0.0)

        assert [r.tokens for r in cached] == [r.tokens for r in plain]
        assert [r.prompt_tokens for r in cached] == [r.prompt_tokens for r in plain]

    def test_prefix_pages_survive_retire_and_are_reused(self):
        eng = make_engine()
        eng.register_prefix(HEADER)
        after_register = eng.allocator.free_pages
        eng.run_all([HEADER + "first question"], max_new_tokens=6, temperature=0.0)
        # per-request pages freed on retire, prefix pages still held
        assert eng.allocator.free_pages == after_register
        # second request reuses the same prefix pages
        out = eng.run_all([HEADER + "second question"], max_new_tokens=6,
                          temperature=0.0)
        assert out[0].finish_reason in ("stop", "length")
        assert eng.allocator.free_pages == after_register

    def test_non_matching_prompts_unaffected(self):
        prompts = ["totally different prompt with no header at all"]
        plain = make_engine().run_all(prompts, max_new_tokens=8, temperature=0.0)
        eng = make_engine()
        eng.register_prefix(HEADER)
        got = eng.run_all(prompts, max_new_tokens=8, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in plain]

    def test_exact_prefix_only_prompt_takes_normal_path(self):
        """A prompt whose tokens EQUAL the shared span (no suffix) must use
        the normal prefill — the suffix path would prefill zero tokens."""
        eng = make_engine()
        n = eng.register_prefix(HEADER)
        # reconstruct a prompt that tokenizes to exactly the shared tokens:
        # ByteTokenizer is byte-level, so n shared tokens = BOS + n-1 bytes
        prompt_exact = HEADER[: n - 1]
        toks = eng.tokenizer.encode(prompt_exact, add_bos=True)
        assert toks == eng._prefix["tokens"]  # the boundary case for real
        out = eng.run_all([prompt_exact], max_new_tokens=4, temperature=0.0)
        ref = make_engine().run_all([prompt_exact], max_new_tokens=4,
                                    temperature=0.0)
        assert out[0].tokens == ref[0].tokens

    def test_mixed_batch_prefix_and_plain(self):
        prompts = [
            HEADER + "cached question",
            "uncached question entirely",
        ]
        plain = make_engine().run_all(prompts, max_new_tokens=6, temperature=0.0)
        eng = make_engine()
        eng.register_prefix(HEADER)
        got = eng.run_all(prompts, max_new_tokens=6, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in plain]

    def test_int8_pool_prefix_cache(self):
        prompts = [HEADER + "int8 plus prefix cache"]
        eng = make_engine(kv_quant="int8")
        eng.register_prefix(HEADER)
        got = eng.run_all(prompts, max_new_tokens=6, temperature=0.0)
        ref = make_engine(kv_quant="int8").run_all(
            prompts, max_new_tokens=6, temperature=0.0
        )
        # int8 priming dequantizes the prefix once; first token must agree
        assert got[0].tokens[0] == ref[0].tokens[0]
