"""Radix prefix cache (runtime/radix.py + runtime/paged.py): automatic
multi-prefix KV reuse. Admission longest-prefix-matches every prompt against
a token-id radix tree over page-aligned KV page runs, reuses matched pages
read-only, prefills only the unmatched suffix, and inserts the new span back
— no registration step. Pins: match/insert/split mechanics, refcount
pinning vs LRU eviction, token-exact serving vs cold prefill, the
second-request prefill reduction, cross-node generate→verify reuse, and
PREFIX_CACHE=0 parity."""

from dataclasses import replace

import pytest

from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.runtime.paged import ContinuousBatchingEngine, PageAllocator
from sentio_tpu.runtime.radix import RadixPrefixCache

HEADER = "You are a careful assistant. Cite sources. Answer concisely. "


def make_engine(**kw):
    kw.setdefault("model_config", LlamaConfig.tiny())
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_pages_per_seq", 8)
    kw.setdefault("steps_per_tick", 4)
    kw.setdefault("ignore_eos", True)
    return ContinuousBatchingEngine(**kw)


# ---------------------------------------------------------------- tree unit
# Pure-host radix tree mechanics against a real PageAllocator — no device
# work, so these run in tier-1 (not slow-marked).


def toks(*pages):
    """Flatten page-sized token groups into one list."""
    out = []
    for p in pages:
        out.extend(p)
    return out


PG = 4  # unit-test page size


def make_tree(num_pages=64):
    alloc = PageAllocator(num_pages)
    return RadixPrefixCache(PG, alloc), alloc


class TestRadixTree:
    def test_insert_then_full_match(self):
        tree, alloc = make_tree()
        span = list(range(8))  # 2 pages
        pages = alloc.alloc(2)
        node, donated = tree.insert(span, 0, pages)
        assert donated == pages and node is not None
        n, got, deepest = tree.match(span)
        assert n == 8 and got == pages and deepest is node

    def test_partial_match_is_page_aligned(self):
        tree, alloc = make_tree()
        pages = alloc.alloc(2)
        tree.insert(toks([1, 2, 3, 4], [5, 6, 7, 8]), 0, pages)
        # diverges inside the second page: only the first page matches
        n, got, node = tree.match(toks([1, 2, 3, 4], [5, 6, 99, 99]))
        assert n == 4 and got == pages[:1] and node is not None
        # diverges inside the first page: nothing matches
        n, got, node = tree.match([1, 2, 99, 99])
        assert n == 0 and got == [] and node is None

    def test_divergent_insert_splits_edge(self):
        tree, alloc = make_tree()
        a_pages = alloc.alloc(3)
        a = toks([1] * PG, [2] * PG, [3] * PG)
        tree.insert(a, 0, a_pages)
        # b shares page 1, then diverges — the 3-page edge must split
        b = toks([1] * PG, [9] * PG)
        b_pages = alloc.alloc(2)
        _, donated = tree.insert(b, 0, b_pages)
        # only b's second page is new; its first page span was already cached
        assert donated == b_pages[1:]
        assert tree.node_count == 3  # split upper + lower + b's tail
        n, got, _ = tree.match(a)
        assert n == 12 and got == a_pages
        n, got, _ = tree.match(b)
        assert n == 8 and got == [a_pages[0], b_pages[1]]

    def test_match_ignores_trailing_partial_page(self):
        tree, alloc = make_tree()
        pages = alloc.alloc(1)
        tree.insert([1, 2, 3, 4], 0, pages)
        n, got, _ = tree.match([1, 2, 3, 4, 5, 6])  # 1.5 pages of query
        assert n == 4 and got == pages

    def test_pin_blocks_eviction_refcount_invariant(self):
        tree, alloc = make_tree()
        pages = alloc.alloc(2)
        node, _ = tree.insert(toks([1] * PG, [2] * PG), 0, pages)
        tree.lock(node)
        assert tree.evict(10) == 0  # pinned chain: nothing to free
        assert tree.pages_held == 2
        tree.unlock(node)
        assert tree.evict(10) == 2  # unpinned: fully reclaimed
        assert tree.pages_held == 0
        assert alloc.free_pages == alloc.num_pages - 1

    def test_partial_pin_evicts_only_unpinned_tail(self):
        tree, alloc = make_tree()
        a_pages = alloc.alloc(1)
        upper, _ = tree.insert([1] * PG, 0, a_pages)
        b_pages = alloc.alloc(1)
        deep, _ = tree.insert(toks([1] * PG, [2] * PG), PG, b_pages)
        tree.lock(upper)  # pin only the head page's chain
        assert tree.evict(10) == 1  # the deep tail is unpinned
        n, got, _ = tree.match(toks([1] * PG, [2] * PG))
        assert n == PG and got == a_pages  # head survived
        tree.unlock(upper)

    def test_lru_eviction_order(self):
        tree, alloc = make_tree()
        old_pages = alloc.alloc(1)
        tree.insert([1] * PG, 0, old_pages)
        new_pages = alloc.alloc(1)
        tree.insert([2] * PG, 0, new_pages)
        tree.match([1] * PG)  # refresh the older leaf
        assert tree.evict(1) == 1
        # the untouched leaf ([2]*PG) went first
        n, _, _ = tree.match([2] * PG)
        assert n == 0
        n, _, _ = tree.match([1] * PG)
        assert n == PG

    def test_refcount_underflow_asserts(self):
        tree, alloc = make_tree()
        node, _ = tree.insert([1] * PG, 0, alloc.alloc(1))
        with pytest.raises(AssertionError, match="underflow"):
            tree.unlock(node)

    def test_duplicate_insert_donates_nothing(self):
        tree, alloc = make_tree()
        span = toks([1] * PG, [2] * PG)
        first = alloc.alloc(2)
        tree.insert(span, 0, first)
        second = alloc.alloc(2)
        node, donated = tree.insert(span, 0, second)
        assert donated == []  # caller keeps ownership; tree kept `first`
        assert tree.pages_held == 2
        _, got, _ = tree.match(span)
        assert got == first

    def test_split_preserves_chain_refcounts(self):
        tree, alloc = make_tree()
        pages = alloc.alloc(2)
        node, _ = tree.insert(toks([1] * PG, [2] * PG), 0, pages)
        tree.lock(node)
        # a divergent insert splits the pinned edge after page 1
        tree.insert(toks([1] * PG, [7] * PG), 0, alloc.alloc(2))
        assert tree.evict(10) <= 1  # pinned pages still unreclaimable
        _, got, _ = tree.match(toks([1] * PG, [2] * PG))
        assert got == pages  # the pinned span is intact
        tree.unlock(node)  # symmetric through the split chain — no assert

    def test_clear_returns_all_pages(self):
        tree, alloc = make_tree()
        base = alloc.free_pages
        tree.insert(toks([1] * PG, [2] * PG), 0, alloc.alloc(2))
        tree.insert([3] * PG, 0, alloc.alloc(1))
        tree.clear()
        assert alloc.free_pages == base
        assert tree.empty and tree.pages_held == 0


# ------------------------------------------------------------- engine (jax)

pytestmark_engine = pytest.mark.slow


@pytest.mark.slow
class TestPrefixServing:
    def test_warm_second_request_matches_cold(self):
        prompts = [
            HEADER + "What is a systolic array?",
            HEADER + "Explain BM25 briefly.",
        ]
        cold = make_engine(prefix_cache=False).run_all(
            prompts, max_new_tokens=8, temperature=0.0)
        eng = make_engine()
        # sequential runs so the second request matches the first's span
        warm = [eng.run_all([p], max_new_tokens=8, temperature=0.0)[0]
                for p in prompts]
        assert [r.tokens for r in warm] == [r.tokens for r in cold]
        assert [r.prompt_tokens for r in warm] == [r.prompt_tokens for r in cold]
        # request 1 seeded the cache; request 2 skipped the shared head
        assert warm[0].prefix_hit_tokens == 0
        assert warm[1].prefix_hit_tokens > 0
        assert (warm[1].prefill_tokens + warm[1].prefix_hit_tokens
                == warm[1].prompt_tokens)

    def test_second_request_prefill_reduced_by_shared_length(self):
        eng = make_engine()
        q1 = HEADER + "first question here?"
        q2 = HEADER + "second question, different tail."
        [r1] = eng.run_all([q1], max_new_tokens=4, temperature=0.0)
        before = eng.prefill_tokens_total
        [r2] = eng.run_all([q2], max_new_tokens=4, temperature=0.0)
        # the shared span is the page-aligned common token prefix (BOS +
        # HEADER bytes for the byte tokenizer)
        expected_shared = ((1 + len(HEADER)) // eng.page_size) * eng.page_size
        assert r2.prefix_hit_tokens == expected_shared
        assert r2.prefill_tokens == r2.prompt_tokens - expected_shared
        # the ENGINE did less admission work, not just the bookkeeping
        assert eng.prefill_tokens_total - before == r2.prefill_tokens
        assert eng.stats()["prefix_hit_token_ratio"] > 0.0

    def test_cache_learns_without_warming_across_batch(self):
        # one run_all with 3 same-head prompts: the first seeds, and any
        # admitted AFTER its insert reuse the head (same-batch admissions
        # legitimately miss — the span isn't written yet)
        eng = make_engine()
        prompts = [HEADER + f"question {i}?" for i in range(3)]
        for p in prompts:
            eng.run_all([p], max_new_tokens=2, temperature=0.0)
        assert eng.prefix_hits == 2
        assert eng.prefix_misses == 0

    def test_int8_pool_radix_reuse_matches_cold(self):
        """KV_QUANT=int8 parametrization of the radix path under the
        sanitizer (conftest arms it for this module): the quantized
        dict-repr pool serves shared prefix pages read-only exactly like
        plain arrays — same tokens as a cache-disabled int8 engine, with
        conservation/refcounts checked every tick."""
        prompts = [
            HEADER + "What is a systolic array?",
            HEADER + "Explain BM25 briefly.",
        ]
        cold = make_engine(prefix_cache=False, kv_quant="int8").run_all(
            prompts, max_new_tokens=8, temperature=0.0)
        eng = make_engine(kv_quant="int8")
        warm = [eng.run_all([p], max_new_tokens=8, temperature=0.0)[0]
                for p in prompts]
        assert [r.tokens for r in warm] == [r.tokens for r in cold]
        assert warm[1].prefix_hit_tokens > 0
        assert eng.stats()["prefix_hit_token_ratio"] > 0.0

    def test_non_matching_prompt_unaffected(self):
        prompts = ["totally different prompt with no shared head at all"]
        plain = make_engine(prefix_cache=False).run_all(
            prompts, max_new_tokens=8, temperature=0.0)
        eng = make_engine()
        eng.warm_prefix(HEADER)
        got = eng.run_all(prompts, max_new_tokens=8, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in plain]
        assert got[0].prefix_hit_tokens == 0

    def test_exact_prefix_only_prompt_still_prefills_one_token(self):
        """A prompt whose tokens EQUAL a cached span must clamp the match
        so at least one suffix token prefills (the first sampled token
        comes from the last prompt logit)."""
        eng = make_engine()
        n = eng.warm_prefix(HEADER)
        assert n > 0
        prompt_exact = HEADER[: n - 1]  # BOS + n-1 bytes == n cached tokens
        out = eng.run_all([prompt_exact], max_new_tokens=4, temperature=0.0)
        ref = make_engine(prefix_cache=False).run_all(
            [prompt_exact], max_new_tokens=4, temperature=0.0)
        assert out[0].tokens == ref[0].tokens
        assert out[0].prefill_tokens >= 1

    def test_mixed_batch_hit_and_cold(self):
        eng = make_engine()
        eng.warm_prefix(HEADER)
        prompts = [HEADER + "cached question", "uncached question entirely"]
        plain = make_engine(prefix_cache=False).run_all(
            prompts, max_new_tokens=6, temperature=0.0)
        got = eng.run_all(prompts, max_new_tokens=6, temperature=0.0)
        assert [r.tokens for r in got] == [r.tokens for r in plain]

    def test_int8_pool_composes(self):
        prompts = [HEADER + "int8 plus radix cache"]
        eng = make_engine(kv_quant="int8")
        eng.warm_prefix(HEADER)
        got = eng.run_all(prompts, max_new_tokens=6, temperature=0.0)
        ref = make_engine(kv_quant="int8", prefix_cache=False).run_all(
            prompts, max_new_tokens=6, temperature=0.0)
        # int8 priming dequantizes the prefix once; first token must agree
        assert got[0].tokens[0] == ref[0].tokens[0]

    def test_disabled_engine_stats_and_pool_idle(self):
        eng = make_engine(prefix_cache=False)
        eng.run_all([HEADER + "q"], max_new_tokens=4, temperature=0.0)
        s = eng.stats()
        assert "prefix_cache_pages" not in s
        # no cache: every page returns to the pool at retire
        assert s["free_pages"] == s["total_pages"] - 1


@pytest.mark.slow
class TestPagePoolSafety:
    def live_pages(self, eng):
        out = set()
        for i, slot in enumerate(eng.slots):
            if slot.active:
                blocks = (slot.shared_tokens // eng.page_size) + len(slot.pages)
                out.update(int(p) for p in eng._page_table[i, :blocks] if p)
        return out

    def radix_pages(self, eng):
        out = set()
        stack = list(eng._radix.root.children.values())
        while stack:
            node = stack.pop()
            out.update(node.pages)
            stack.extend(node.children.values())
        return out

    def test_refcount_invariant_under_load(self):
        """Across a staggered multi-request run: the allocator free list,
        live slot tables, and radix-held pages never overlap — eviction can
        never free a page a live page table references."""
        eng = make_engine(num_pages=1 + 24, max_slots=3)
        prompts = [HEADER + f"safety question {i}?" for i in range(6)]
        for p in prompts:
            eng.submit(p, max_new_tokens=4)
        while eng.has_work:
            eng.step()
            free = set(eng.allocator._free)
            live = self.live_pages(eng)
            held = self.radix_pages(eng)
            assert not free & live, "freed page still in a live page table"
            assert not free & held, "freed page still owned by the cache"
        # idle: everything is either free or retained by the cache
        s = eng.stats()
        assert s["free_pages"] + s["prefix_cache_pages"] == s["total_pages"] - 1

    def test_eviction_under_pool_exhaustion(self):
        """Distinct prompts overflow a small pool: LRU leaves must be
        evicted to admit new work, and serving must stay correct."""
        # each ~43-token prompt needs 3 pages at admission and donates 2
        # full pages to the cache, so a 10-page pool hits pressure by the
        # fifth admission (held 8, free 2, need 3)
        eng = make_engine(num_pages=1 + 10, max_slots=2, max_pages_per_seq=6)
        for i in range(6):
            [r] = eng.run_all([f"prompt number {i} with its own distinct text"],
                              max_new_tokens=4, temperature=0.0)
            assert r.finish_reason in ("stop", "length")
        assert eng._radix.evicted_pages > 0
        s = eng.stats()
        assert s["free_pages"] + s["prefix_cache_pages"] == s["total_pages"] - 1

    def test_pinned_prefix_survives_eviction_pressure(self):
        """A slot decoding against matched pages pins them: pool pressure
        from a concurrent admission must evict OTHER leaves, never the
        pinned chain (and never corrupt the pinned request's output)."""
        eng = make_engine(num_pages=1 + 16, max_slots=2, max_pages_per_seq=6)
        ref_eng = make_engine(num_pages=1 + 16, max_slots=2,
                              max_pages_per_seq=6, prefix_cache=False)
        [want] = ref_eng.run_all([HEADER + "pinned?"], max_new_tokens=8,
                                 temperature=0.0)
        eng.run_all([HEADER + "seed"], max_new_tokens=2, temperature=0.0)
        rid = eng.submit(HEADER + "pinned?", max_new_tokens=8)
        eng.step()  # admit: matches + pins the HEADER span
        # pressure: distinct prompts that need the pool while rid decodes
        eng.submit("filler alpha with plenty of distinct bytes", max_new_tokens=2)
        eng.submit("filler beta, also made of different bytes!", max_new_tokens=2)
        done = {}
        while eng.has_work:
            for r in eng.step():
                done[r.request_id] = r
        assert done[rid].tokens == want.tokens


@pytest.mark.slow
class TestCrossNodeReuse:
    def test_generate_then_verify_reuses_prompt_head(self):
        """The acceptance path: within one /chat-shaped request, the verify
        prompt embeds the generate prompt verbatim — its admission must be
        served the whole generate-prompt span from the radix cache, visible
        per-admission in the flight recorder."""
        from sentio_tpu.config import GeneratorConfig
        from sentio_tpu.infra.flight import FlightRecorder, set_flight_recorder
        from sentio_tpu.models.document import Document
        from sentio_tpu.ops.generator import LLMGenerator, TpuProvider
        from sentio_tpu.ops.verifier import AnswerVerifier
        from sentio_tpu.runtime.service import PagedGenerationService

        recorder = FlightRecorder()
        set_flight_recorder(recorder)
        try:
            cfg = replace(LlamaConfig.tiny(), max_len=2048)
            eng = make_engine(model_config=cfg, max_slots=2, page_size=32,
                              max_pages_per_seq=48, num_pages=1 + 120)
            service = PagedGenerationService(eng)
            gen_cfg = GeneratorConfig(provider="tpu", max_new_tokens=8,
                                      verifier_max_tokens=8)
            generator = LLMGenerator(
                provider=TpuProvider(service=service), config=gen_cfg)
            verifier = AnswerVerifier(generator=generator, config=gen_cfg)
            docs = [Document(text="Systolic arrays pump operands through a "
                                  "grid of MACs.",
                             metadata={"source": "notes.md", "score": 0.9})]
            query = "What is a systolic array?"

            answer = generator.generate(query, docs, temperature=0.0,
                                        request_id="chat-1")
            verifier.verify(query, answer, docs, request_id="chat-1")

            record = recorder.get("chat-1")
            admissions = record["engine"]["admissions"]
            assert len(admissions) == 2, admissions
            gen_adm, ver_adm = admissions
            # the verify admission reused the generate prompt head: its
            # prefix-hit span covers every full page of the generate prompt
            assert ver_adm["prefix_hit_tokens"] > 0
            gen_prompt_tokens = gen_adm["prompt_tokens"]
            expected = (gen_prompt_tokens // eng.page_size) * eng.page_size
            assert ver_adm["prefix_hit_tokens"] >= expected
            assert ver_adm["prefill_tokens"] == (
                ver_adm["prompt_tokens"] - ver_adm["prefix_hit_tokens"])
            service.close()
        finally:
            set_flight_recorder(None)

    def test_two_warm_chat_requests_second_skips_shared_head(self):
        """Acceptance: with two same-system-prompt requests through the
        serving facade, the second request's admitted prefill token count
        (flight recorder) drops by the shared-prefix length."""
        from sentio_tpu.infra.flight import FlightRecorder, set_flight_recorder
        from sentio_tpu.runtime.service import PagedGenerationService

        recorder = FlightRecorder()
        set_flight_recorder(recorder)
        try:
            eng = make_engine(max_slots=2)
            service = PagedGenerationService(eng)
            service.generate(HEADER + "warmup question?", max_new_tokens=4,
                             request_id="warm-1")
            service.generate(HEADER + "second question!", max_new_tokens=4,
                             request_id="warm-2")
            first = recorder.get("warm-1")["engine"]["admissions"][0]
            second = recorder.get("warm-2")["engine"]["admissions"][0]
            shared = ((1 + len(HEADER)) // eng.page_size) * eng.page_size
            assert first["prefix_hit_tokens"] == 0
            assert first["prefill_tokens"] == first["prompt_tokens"]
            assert second["prefix_hit_tokens"] == shared
            assert second["prefill_tokens"] == second["prompt_tokens"] - shared
            # per-tick telemetry carries the matched-token counts too
            hit_total = sum(t.get("prefix_hit_tokens", 0)
                            for t in recorder.timeline())
            assert hit_total == shared
            service.close()
        finally:
            set_flight_recorder(None)
