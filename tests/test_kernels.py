"""Kernel correctness: Pallas flash attention (interpret mode on the CPU
test mesh) and ring attention (real ppermute collectives over the virtual
8-device mesh) against the XLA reference attention."""

import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.config import MeshConfig
from sentio_tpu.kernels.flash_attention import flash_attention
from sentio_tpu.kernels.ring_attention import ring_attention_sharded
from sentio_tpu.models.layers import attention, causal_mask
from sentio_tpu.parallel.mesh import build_mesh

pytestmark = [pytest.mark.slow, pytest.mark.mesh]


def make_qkv(b, t, h, d, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32) for _ in range(3)
    )


class TestFlashAttention:
    def test_causal_matches_reference(self):
        q, k, v = make_qkv(2, 96, 4, 32)
        ref = attention(q, k, v, causal_mask(96), jnp.float32)
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_non_divisible_length_padded(self):
        # 50 does not divide by the 32-blocks; padding must not leak
        q, k, v = make_qkv(1, 50, 2, 16, seed=1)
        ref = attention(q, k, v, causal_mask(50), jnp.float32)
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_varlen_rows(self):
        q, k, v = make_qkv(2, 64, 2, 16, seed=2)
        lens = jnp.array([40, 64], jnp.int32)
        pad = jnp.arange(64)[None, :] < lens[:, None]
        ref = attention(q, k, v, causal_mask(64) & pad[:, None, None, :], jnp.float32)
        out = flash_attention(q, k, v, lens, causal=True, block_q=32, block_k=32, interpret=True)
        valid = np.asarray(pad)[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(out) * valid, np.asarray(ref) * valid, atol=2e-5
        )

    def test_non_causal(self):
        q, k, v = make_qkv(1, 64, 2, 16, seed=3)
        ref = attention(q, k, v, None, jnp.float32)
        out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_cross_attention_shapes(self):
        # S != T (query block against a longer cache window)
        q, _, _ = make_qkv(1, 32, 2, 16, seed=4)
        _, k, v = make_qkv(1, 96, 2, 16, seed=5)
        ref = attention(q, k, v, None, jnp.float32)
        out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestRingAttention:
    @pytest.fixture()
    def mesh(self):
        return build_mesh(MeshConfig(dp_size=2, sp_size=4, tp_size=1))

    def test_causal_matches_reference(self, mesh):
        q, k, v = make_qkv(4, 64, 4, 32, seed=6)
        ref = attention(q, k, v, causal_mask(64), jnp.float32)
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_full_matches_reference(self, mesh):
        q, k, v = make_qkv(2, 32, 2, 16, seed=7)
        ref = attention(q, k, v, None, jnp.float32)
        out = ring_attention_sharded(q, k, v, mesh, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_rejects_indivisible_sequence(self, mesh):
        q, k, v = make_qkv(2, 30, 2, 16)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention_sharded(q, k, v, mesh)

    def test_sp8_full_ring(self):
        mesh = build_mesh(MeshConfig(dp_size=1, sp_size=8, tp_size=1))
        q, k, v = make_qkv(1, 128, 2, 16, seed=8)
        ref = attention(q, k, v, causal_mask(128), jnp.float32)
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestLlamaKernelIntegration:
    def test_forward_with_flash_matches_xla(self):
        import jax

        from sentio_tpu.kernels import flash_attn_fn
        from sentio_tpu.models.llama import LlamaConfig, init_llama, llama_forward

        cfg = LlamaConfig.tiny()
        params = init_llama(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(np.random.default_rng(9).integers(1, 500, (2, 48)), jnp.int32)
        mask = jnp.ones((2, 48), bool)

        ref, _ = llama_forward(params, cfg, ids, pad_mask=mask)
        out, _ = llama_forward(params, cfg, ids, pad_mask=mask, attn_fn=flash_attn_fn)
        # the model runs in bf16 — blockwise vs monolithic softmax reorders
        # accumulation, so compare at bf16 resolution + next-token agreement
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.15, rtol=0.1)
        # random init → near-uniform logits with frequent ties, so a few
        # argmax flips from bf16 noise are expected; bound the rate
        agree = (np.argmax(np.asarray(out), -1) == np.argmax(np.asarray(ref), -1)).mean()
        assert agree > 0.95, f"next-token argmax agreement {agree}"


class TestMeshAttnFn:
    """make_mesh_attn_fn: kernels running INSIDE shard_map over the mesh —
    heads on tp, sequence-ring over sp — must match XLA attention."""

    def _masked_ref(self, q, k, v, kv_lens=None, causal=True):
        t = q.shape[1]
        mask = causal_mask(t) if causal else jnp.ones((t, t), bool)[None, None]
        if kv_lens is not None:
            key_ok = jnp.arange(t)[None, :] < kv_lens[:, None]
            mask = mask & key_ok[:, None, None, :]
        return attention(q, k, v, mask, jnp.float32)

    def test_tp_sharded_flash_matches_xla(self):
        from sentio_tpu.kernels import make_mesh_attn_fn

        mesh = build_mesh(MeshConfig(dp_size=4, sp_size=1, tp_size=2))
        fn = make_mesh_attn_fn(mesh, causal=True)
        q, k, v = make_qkv(4, 32, 4, 16, seed=11)
        out = fn(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._masked_ref(q, k, v)),
            atol=2e-2, rtol=2e-2,
        )

    def test_sp_ring_matches_xla(self):
        from sentio_tpu.kernels import make_mesh_attn_fn

        mesh = build_mesh(MeshConfig(dp_size=2, sp_size=2, tp_size=2))
        fn = make_mesh_attn_fn(mesh, causal=True)
        q, k, v = make_qkv(2, 32, 4, 16, seed=12)
        out = fn(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._masked_ref(q, k, v)), atol=2e-4
        )

    def test_ring_respects_kv_lens(self):
        from sentio_tpu.kernels import make_mesh_attn_fn

        mesh = build_mesh(MeshConfig(dp_size=2, sp_size=2, tp_size=2))
        fn = make_mesh_attn_fn(mesh, causal=True)
        q, k, v = make_qkv(2, 32, 4, 16, seed=13)
        lens = jnp.asarray([20, 9], jnp.int32)
        out = fn(q, k, v, lens)
        ref = self._masked_ref(q, k, v, kv_lens=lens)
        # compare only valid query rows (padding queries attend nothing real)
        for b in range(2):
            n = int(lens[b])
            np.testing.assert_allclose(
                np.asarray(out)[b, :n], np.asarray(ref)[b, :n], atol=2e-4
            )

    def test_indivisible_heads_raise(self):
        from sentio_tpu.kernels import make_mesh_attn_fn

        mesh = build_mesh(MeshConfig(dp_size=1, sp_size=2, tp_size=4))
        fn = make_mesh_attn_fn(mesh, causal=True)
        q, k, v = make_qkv(2, 32, 6, 16)
        with pytest.raises(ValueError, match="heads"):
            fn(q, k, v)

    def test_encoder_kernel_matches_xla(self):
        from sentio_tpu.kernels import encoder_attn_fn

        q, k, v = make_qkv(3, 24, 2, 16, seed=14)
        lens = jnp.asarray([24, 10, 1], jnp.int32)
        out = encoder_attn_fn(q, k, v, lens)
        ref = self._masked_ref(q, k, v, kv_lens=lens, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2, rtol=2e-2)

    def test_encoder_forward_kernel_path_matches(self):
        import jax

        from sentio_tpu.kernels import encoder_attn_fn
        from sentio_tpu.models.transformer import (
            EncoderConfig, encoder_forward, init_encoder,
        )

        cfg = EncoderConfig.tiny()
        params = init_encoder(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(5)
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 24)), jnp.int32)
        mask = jnp.asarray([[True] * 24, [True] * 10 + [False] * 14])
        ref = encoder_forward(params, cfg, ids, mask)
        out = encoder_forward(params, cfg, ids, mask, attn_fn=encoder_attn_fn)
        # compare real-token positions only
        np.testing.assert_allclose(
            np.asarray(out)[0], np.asarray(ref)[0], atol=5e-2, rtol=5e-2
        )
        np.testing.assert_allclose(
            np.asarray(out)[1, :10], np.asarray(ref)[1, :10], atol=5e-2, rtol=5e-2
        )
