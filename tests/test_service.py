"""Continuous-batching service + cross-thread coalescing (runtime/service.py,
parallel/batcher.ThreadBatcher, embedder query coalescing).

The round-1 gap these close: the paged engine and the batcher existed but
nothing in the serving path used them. The bar here: concurrent callers on
worker threads actually SHARE device batches — staggered requests share
decode ticks, concurrent single-query embeds share one padded forward.
"""

import threading
import time

import numpy as np
import pytest

from sentio_tpu.config import EmbedderConfig, GeneratorConfig
from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.parallel.batcher import BatcherClosed, ThreadBatcher
from sentio_tpu.runtime.engine import GeneratorEngine
from sentio_tpu.runtime.paged import ContinuousBatchingEngine
from sentio_tpu.runtime.service import (
    GenerationTimeout,
    PagedGenerationService,
    ReplicaUnavailable,
)

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def contiguous():
    return GeneratorEngine(
        config=GeneratorConfig(provider="tpu", model_preset="tiny", max_new_tokens=16),
        model_config=LlamaConfig.tiny(),
        rng_seed=0,
    )


@pytest.fixture()
def service(contiguous):
    engine = ContinuousBatchingEngine(
        model_config=contiguous.model_config,
        params=contiguous.params,
        tokenizer=contiguous.tokenizer,
        max_slots=4,
        page_size=16,
        max_pages_per_seq=8,
    )
    svc = PagedGenerationService(engine)
    yield svc
    svc.close()


class TestThreadBatcher:
    def test_batches_concurrent_submits(self):
        calls: list[list[int]] = []

        def process(items):
            calls.append(list(items))
            return [i * 10 for i in items]

        batcher = ThreadBatcher(process, max_size=8, deadline_ms=50.0)
        results = {}

        def worker(i):
            results[i] = batcher.submit(i)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {i: i * 10 for i in range(6)}
        # 6 items arriving within one 50 ms window must not take 6 batches
        assert batcher.stats.batches < 6
        assert batcher.stats.snapshot()["avg_occupancy"] > 1.0 / 8.0
        batcher.close()

    def test_failing_batch_fails_only_its_callers(self):
        def process(items):
            if "bad" in items:
                raise RuntimeError("boom")
            return [i.upper() for i in items]

        batcher = ThreadBatcher(process, max_size=1, deadline_ms=0.0)
        with pytest.raises(RuntimeError, match="boom"):
            batcher.submit("bad")
        assert batcher.submit("ok") == "OK"  # batcher survived
        batcher.close()

    def test_closed_batcher_raises(self):
        batcher = ThreadBatcher(lambda items: items, max_size=2)
        batcher.close()
        with pytest.raises(BatcherClosed):
            batcher.submit(1)

    def test_wrong_result_count_raises(self):
        batcher = ThreadBatcher(lambda items: [], max_size=1, deadline_ms=0.0)
        with pytest.raises(RuntimeError, match="returned 0 results"):
            batcher.submit("x")
        batcher.close()


class TestPagedGenerationService:
    def test_single_request_matches_engine(self, service, contiguous):
        prompt = "service equivalence check"
        want = contiguous.generate([prompt], max_new_tokens=12, temperature=0.0)[0]
        got = service.generate(prompt, max_new_tokens=12, temperature=0.0)
        assert got.tokens == want.tokens
        assert got.finish_reason in ("stop", "length")

    def test_int8_engine_service_roundtrip_with_top_k(self, contiguous):
        """KV_QUANT=int8 parametrization of the service path under the
        sanitizer: the pump drives a quantized dict-repr pool through
        admit/decode/retire, and per-request top_k rides the ticket into
        the fused tick (traced — no per-k recompile)."""
        engine = ContinuousBatchingEngine(
            model_config=contiguous.model_config,
            params=contiguous.params,
            tokenizer=contiguous.tokenizer,
            max_slots=4,
            page_size=16,
            max_pages_per_seq=8,
            kv_quant="int8",
        )
        svc = PagedGenerationService(engine)
        try:
            want = contiguous.generate(
                ["int8 service check"], max_new_tokens=8, temperature=0.0)[0]
            got = svc.generate("int8 service check", max_new_tokens=8,
                               temperature=0.0)
            # greedy int8 usually tracks bf16 on the tiny model; require a
            # valid completion plus first-token agreement (least noise)
            assert got.finish_reason in ("stop", "length")
            if want.tokens and got.tokens:
                assert got.tokens[0] == want.tokens[0]
            hot = svc.generate("sampled int8 request", max_new_tokens=6,
                               temperature=0.8, top_k=4)
            assert hot.finish_reason in ("stop", "length")
            assert engine.stats()["kv_quant"] == "int8"
        finally:
            svc.close()

    def test_staggered_requests_share_decode_ticks(self, service):
        """Request B arrives while A is mid-decode; continuous batching must
        run them in the same fused step (max_active_slots >= 2) and both
        must complete."""
        results = {}

        def call(name, prompt, max_new):
            results[name] = service.generate(prompt, max_new_tokens=max_new, temperature=0.0)

        a = threading.Thread(target=call, args=("a", "first long running request", 64))
        # NB: prompt chosen to not greedy-sample EOS as its very first token
        # (random-init weights) — that would retire B at admission
        b = threading.Thread(target=call, args=("b", "hello world from request two", 8))
        # hold the inbox mutex while both submit threads start: both requests
        # are enqueued before the first admission tick can run, so they must
        # share decode ticks (B would otherwise race A's whole generation)
        with service._mutex:
            a.start()
            b.start()
            time.sleep(0.2)
        a.join(timeout=120)
        b.join(timeout=120)
        assert "a" in results and "b" in results
        stats = service.stats()
        assert stats["completed"] >= 2
        assert stats["max_active_slots"] >= 2, (
            f"requests never shared a decode tick: {stats}"
        )

    def test_many_concurrent_requests(self, service):
        n = 6  # > max_slots=4: forces queueing + slot reuse
        out = {}

        def call(i):
            out[i] = service.generate(f"prompt number {i}", max_new_tokens=6, temperature=0.0)

        threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert len(out) == n
        assert all(r.finish_reason in ("stop", "length") for r in out.values())
        # all pages reclaimed after the burst — free, or retained by the
        # radix prefix cache (minus the reserved scratch page)
        s = service.stats()
        assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
            == s["total_pages"] - 1

    def test_tick_failure_fails_waiters_and_recovers(self, contiguous):
        """A failing decode tick must (a) fail the in-flight waiters with
        finish_reason='error' and (b) reset the engine so the NEXT request
        works — a transient device error must not poison the pool forever."""
        engine = ContinuousBatchingEngine(
            model_config=contiguous.model_config,
            params=contiguous.params,
            tokenizer=contiguous.tokenizer,
            max_slots=2,
            page_size=16,
            max_pages_per_seq=4,
        )
        svc = PagedGenerationService(engine)
        original_step = engine.step

        def boom():
            raise RuntimeError("injected device failure")

        engine.step = boom
        try:
            failed = svc.generate("doomed request", max_new_tokens=4)
            assert failed.finish_reason == "error"
        finally:
            engine.step = original_step
        # engine was reset by the pump; a new request must succeed
        ok = svc.generate("hello world from request two", max_new_tokens=4)
        assert ok.finish_reason in ("stop", "length")
        s = svc.stats()
        assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
            == s["total_pages"] - 1
        svc.close()

    def test_closed_service_rejects(self, contiguous):
        engine = ContinuousBatchingEngine(
            model_config=contiguous.model_config,
            params=contiguous.params,
            tokenizer=contiguous.tokenizer,
            max_slots=2,
            page_size=16,
            max_pages_per_seq=4,
        )
        svc = PagedGenerationService(engine)
        svc.close()
        # typed 503 (ReplicaUnavailable) — closed/broken admissions carry a
        # Retry-After instead of the old untyped RuntimeError → 500
        with pytest.raises(ReplicaUnavailable, match="closed") as exc_info:
            svc.generate("x")
        assert exc_info.value.status == 503
        assert exc_info.value.details["retry_after_s"] > 0


class TestRobustness:
    """Deadline propagation, crash-requeue budget, and drain ordering —
    the request-lifecycle robustness surface over the paged pump."""

    def _engine(self, contiguous, **kw):
        kw.setdefault("max_slots", 2)
        kw.setdefault("page_size", 16)
        kw.setdefault("max_pages_per_seq", 8)
        kw.setdefault("steps_per_tick", 1)
        return ContinuousBatchingEngine(
            model_config=contiguous.model_config, params=contiguous.params,
            tokenizer=contiguous.tokenizer, **kw,
        )

    def test_deadline_cancels_mid_decode(self, contiguous):
        from sentio_tpu.infra.exceptions import DeadlineExceededError

        svc = PagedGenerationService(self._engine(contiguous))
        try:
            with pytest.raises(DeadlineExceededError):
                svc.generate("expire me mid decode", max_new_tokens=400,
                             deadline_s=0.3)
            # the cancelled slot's pages are reclaimed, not stranded
            deadline = time.time() + 30
            while time.time() < deadline:
                s = svc.stats()
                if s["active_slots"] == 0 and s["free_pages"] \
                        + s.get("prefix_cache_pages", 0) == s["total_pages"] - 1:
                    break
                time.sleep(0.05)
            s = svc.stats()
            assert s["active_slots"] == 0, s
            assert s["expired"] >= 1, s
        finally:
            svc.close()

    def test_timeout_completion_race_returns_result(self, contiguous):
        """event.wait timing out while the pump completes the very same
        ticket must return the finished result, not raise + cancel it."""
        svc = PagedGenerationService(self._engine(contiguous))
        try:
            # warm so the next generate is fast relative to the timeout
            svc.generate("warm the compile path", max_new_tokens=2)
            # a timeout the generation usually BEATS: across repetitions the
            # wait/complete race window is crossed both ways; either way the
            # caller must never see a timeout for work that finished
            for i in range(5):
                try:
                    out = svc.generate(f"race window probe {i}",
                                       max_new_tokens=2, timeout_s=0.05)
                    assert out.finish_reason in ("stop", "length")
                except GenerationTimeout:
                    pass  # genuinely unfinished: acceptable, just not both
        finally:
            svc.close()

    def test_crash_requeue_budget_recovers_single_failure(self, contiguous):
        engine = self._engine(contiguous)
        svc = PagedGenerationService(engine, retry_budget=1)
        original_step = engine.step
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient device fault")
            return original_step()

        engine.step = flaky
        try:
            out = svc.generate("survives one bad tick", max_new_tokens=4)
            assert out.finish_reason in ("stop", "length")
            stats = svc.stats()
            assert stats["requeued"] == 1, stats
            assert stats["tick_failures"] == 1, stats
        finally:
            engine.step = original_step
            svc.close()

    def test_queue_full_sheds_with_retry_after(self, contiguous):
        from sentio_tpu.infra.exceptions import ServiceOverloaded

        svc = PagedGenerationService(self._engine(contiguous), max_queue=0)
        try:
            with pytest.raises(ServiceOverloaded) as exc_info:
                svc.generate("no room at the inn", max_new_tokens=2)
            assert exc_info.value.status == 429
            assert "retry_after_s" in exc_info.value.details
            assert svc.stats()["shed"] == 1
        finally:
            svc.close()

    def test_drain_then_close_ordering(self, contiguous):
        """drain() must (1) flip to draining, (2) wait out in-flight work,
        (3) close — a submit observed after drain returns must fail closed,
        and the drained flag must be visible in stats while draining."""
        from sentio_tpu.infra.exceptions import ServiceOverloaded

        svc = PagedGenerationService(self._engine(contiguous))
        result = {}

        def call():
            result["r"] = svc.generate("drain waits for me", max_new_tokens=100,
                                       temperature=0.0, timeout_s=120)

        t = threading.Thread(target=call)
        t.start()
        deadline = time.time() + 30
        while time.time() < deadline and svc.stats()["active_slots"] == 0:
            time.sleep(0.01)
        out = svc.drain(deadline_s=60.0)
        t.join(timeout=120)
        assert out["drained"] is True
        assert result["r"].finish_reason in ("stop", "length")
        with pytest.raises((ReplicaUnavailable, ServiceOverloaded)):
            svc.generate("too late")

    def test_drain_deadline_bounds_wedged_pump_join(self, contiguous):
        """ISSUE 10 satellite: drain() must honor its deadline against a
        pump wedged inside a device dispatch — the final pump join derives
        from the drain deadline's remainder (not the old hardcoded 10s),
        the wedged pump is counted leaked exactly once, and a second
        close() neither re-joins nor double-counts."""
        from sentio_tpu.infra import faults

        svc = PagedGenerationService(self._engine(contiguous))
        release = threading.Event()
        rule = faults.FaultRule(stall_event=release, stall_s=60.0, times=1)
        faults.arm("paged.step", rule)
        try:
            result: dict = {}

            def call():
                try:
                    result["r"] = svc.generate("wedge me", max_new_tokens=4,
                                               timeout_s=60)
                except Exception as exc:  # noqa: BLE001
                    result["r"] = exc

            t = threading.Thread(target=call, daemon=True)
            t.start()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and rule.stalled == 0:
                time.sleep(0.005)
            assert rule.stalled == 1, "pump never wedged"
            t0 = time.monotonic()
            out = svc.drain(deadline_s=1.5)
            elapsed = time.monotonic() - t0
            # deadline honored: drain window + the (deadline-derived) join,
            # nowhere near the old hardcoded 10s join on top
            assert elapsed < 6.0, f"drain took {elapsed:.1f}s against a 1.5s deadline"
            assert out["drained"] is False and out["abandoned"] >= 1
            assert svc.stats()["pump_leaked"] == 1
            # second close: counted and logged once, not re-joined
            t0 = time.monotonic()
            svc.close()
            assert time.monotonic() - t0 < 1.0, "close() re-joined the leaked pump"
            assert svc.stats()["pump_leaked"] == 1
            # unwedge and let the abandoned pump die cleanly (it sees the
            # closed latch, fails its waiters, exits) — the leak count
            # keeps its history
            release.set()
            t.join(timeout=60)
            assert result, "wedged caller never reached a terminal outcome"
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and any(
                th.name == "paged-decode-pump" and th.is_alive()
                for th in threading.enumerate()
            ):
                time.sleep(0.05)
            assert svc.stats()["pump_leaked"] == 1
        finally:
            faults.disarm("paged.step")
            release.set()

    def test_leaked_pump_surfaces_in_stats(self, contiguous):
        """A pump that outlives close()'s join shows up as pump_leaked
        instead of being silently dropped."""
        svc = PagedGenerationService(self._engine(contiguous))
        release = threading.Event()
        started = threading.Event()

        class StuckPump:
            name = "paged-decode-pump"
            daemon = True

            def join(self, timeout=None):
                started.set()

            def is_alive(self):
                return not release.is_set()

        with svc._mutex:
            svc._pump = StuckPump()
        svc.close()
        assert started.is_set()
        assert svc.stats()["pump_leaked"] == 1
        release.set()


class TestEmbedderCoalescing:
    def test_concurrent_queries_share_batches(self):
        from sentio_tpu.ops.embedder import TpuEmbedder

        emb = TpuEmbedder(
            EmbedderConfig(
                provider="tpu", model_preset="tiny", coalesce=True,
                coalesce_deadline_ms=50.0, coalesce_max=8, cache_size=0,
            )
        )
        # warm the compile so all threads hit a fast path inside the window
        emb.embed_device(["warmup query"])
        texts = [f"coalesced query {i}" for i in range(6)]
        out = {}

        def worker(t):
            out[t] = np.asarray(emb.embed_device([t]))

        threads = [threading.Thread(target=worker, args=(t,)) for t in texts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = emb.get_stats()["coalescer"]
        assert stats["items"] >= 6
        assert stats["batches"] < stats["items"], f"no coalescing happened: {stats}"
        # coalesced vectors must equal the direct batch path
        direct = np.asarray(emb._embed_device_batch(texts))
        for i, t in enumerate(texts):
            np.testing.assert_allclose(out[t][0], direct[i], rtol=2e-2, atol=2e-2)

    def test_multi_text_calls_bypass_coalescer(self):
        from sentio_tpu.ops.embedder import TpuEmbedder

        emb = TpuEmbedder(EmbedderConfig(provider="tpu", model_preset="tiny", coalesce=True))
        out = emb.embed_device(["a b c", "d e f"])
        assert out.shape == (2, emb.dimension)
        assert emb._query_batcher.stats.batches == 0
        emb.close()


class TestCancellation:
    def test_timeout_cancels_engine_request(self, contiguous):
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine
        from sentio_tpu.runtime.service import GenerationTimeout, PagedGenerationService

        eng = ContinuousBatchingEngine(
            model_config=contiguous.model_config, params=contiguous.params,
            tokenizer=contiguous.tokenizer, max_slots=2, page_size=16,
            max_pages_per_seq=8, steps_per_tick=1,
        )
        svc = PagedGenerationService(eng)
        try:
            with pytest.raises(GenerationTimeout):
                svc.generate("slow request", max_new_tokens=100, timeout_s=0.05)
            # the pump must reclaim the abandoned slot's pages
            deadline = time.time() + 30
            while time.time() < deadline:
                s = svc.stats()
                if s["free_pages"] + s.get("prefix_cache_pages", 0) \
                        == s["total_pages"] - 1 and s["active_slots"] == 0:
                    break
                time.sleep(0.05)
            s = svc.stats()
            assert s["active_slots"] == 0, s
            assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
                == s["total_pages"] - 1, s
        finally:
            svc.close()

    def test_abandoned_stream_cancels(self, contiguous):
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine
        from sentio_tpu.runtime.service import PagedGenerationService

        eng = ContinuousBatchingEngine(
            model_config=contiguous.model_config, params=contiguous.params,
            tokenizer=contiguous.tokenizer, max_slots=2, page_size=16,
            max_pages_per_seq=8, steps_per_tick=1,
        )
        svc = PagedGenerationService(eng)
        try:
            it = svc.generate_stream("stream to abandon", max_new_tokens=200)
            next(it)  # consume a first chunk so decode is mid-flight
            it.close()  # consumer disconnects
            deadline = time.time() + 30
            while time.time() < deadline:
                s = svc.stats()
                if s["active_slots"] == 0 and s["queued_inbox"] == 0:
                    break
                time.sleep(0.05)
            s = svc.stats()
            assert s["active_slots"] == 0, s
            assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
                == s["total_pages"] - 1, s
        finally:
            svc.close()


class TestPipelinedService:
    def test_concurrent_requests_through_depth2_engine(self, contiguous):
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine
        from sentio_tpu.runtime.service import PagedGenerationService

        eng = ContinuousBatchingEngine(
            model_config=contiguous.model_config, params=contiguous.params,
            tokenizer=contiguous.tokenizer, max_slots=4, page_size=16,
            max_pages_per_seq=8, steps_per_tick=4, max_tick_steps=8,
            pipeline_depth=2,
        )
        svc = PagedGenerationService(eng)
        try:
            out = {}

            def call(i):
                out[i] = svc.generate(f"pipelined service {i}", max_new_tokens=10,
                                      temperature=0.0)

            threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert len(out) == 6
            refs = {
                i: contiguous.generate([f"pipelined service {i}"],
                                       max_new_tokens=10, temperature=0.0)[0]
                for i in range(6)
            }
            for i in range(6):
                assert out[i].tokens == refs[i].tokens
            s = svc.stats()
            assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
                == s["total_pages"] - 1
        finally:
            svc.close()

    def test_streaming_through_depth2_engine(self, contiguous):
        from sentio_tpu.runtime.paged import ContinuousBatchingEngine
        from sentio_tpu.runtime.service import PagedGenerationService

        eng = ContinuousBatchingEngine(
            model_config=contiguous.model_config, params=contiguous.params,
            tokenizer=contiguous.tokenizer, max_slots=2, page_size=16,
            max_pages_per_seq=8, steps_per_tick=4, pipeline_depth=2,
        )
        svc = PagedGenerationService(eng)
        try:
            want = contiguous.generate(["stream depth two"], max_new_tokens=12,
                                       temperature=0.0)[0]
            got = "".join(svc.generate_stream("stream depth two",
                                              max_new_tokens=12, temperature=0.0))
            assert got == want.text
        finally:
            svc.close()
