"""Speculative decoding inside the paged engine (runtime/paged_spec.py).

Correctness bar: greedy rows are BIT-EXACT against the plain paged engine
(float32 configs — bf16 argmax ties flip between the dense-verify and
paged-decode float paths on degenerate random-init models, which is a
precision artifact, not a logic difference). Sampled rows reuse
accept_and_correct, whose marginal-exactness is proven empirically in
tests/test_speculative.py.
"""

from dataclasses import replace

import pytest

from sentio_tpu.models.llama import LlamaConfig, init_llama
from sentio_tpu.runtime.paged import ContinuousBatchingEngine

pytestmark = pytest.mark.slow


def f32_cfg():
    return replace(LlamaConfig.tiny(), dtype="float32")


def draft_cfg(cfg):
    return replace(
        LlamaConfig(vocab_size=cfg.vocab_size, dim=32, n_layers=1, n_heads=2,
                    n_kv_heads=2, mlp_dim=64, max_len=cfg.max_len),
        dtype="float32",
    )


@pytest.fixture(scope="module")
def stack():
    import jax

    cfg = f32_cfg()
    params = init_llama(jax.random.PRNGKey(0), cfg)
    dcfg = draft_cfg(cfg)
    dparams = init_llama(jax.random.PRNGKey(7), dcfg)
    return cfg, params, dcfg, dparams


def make(cfg, params, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_pages_per_seq", 8)
    return ContinuousBatchingEngine(model_config=cfg, params=params, **kw)


PROMPTS = ["speculate on this prompt", "another about mxu arrays",
           "third request", "and a fourth"]


class TestGreedyParity:
    def test_weak_draft_bit_exact(self, stack):
        cfg, params, dcfg, dparams = stack
        want = make(cfg, params, ignore_eos=True).run_all(PROMPTS, max_new_tokens=24)
        got = make(cfg, params, ignore_eos=True, draft_params=dparams,
                   draft_config=dcfg, spec_k=4).run_all(PROMPTS, max_new_tokens=24)
        assert [w.tokens for w in want] == [g.tokens for g in got]

    def test_perfect_draft_bit_exact(self, stack):
        cfg, params, _, _ = stack
        want = make(cfg, params, ignore_eos=True).run_all(PROMPTS, max_new_tokens=24)
        eng = make(cfg, params, ignore_eos=True, draft_params=params,
                   draft_config=cfg, spec_k=4)
        got = eng.run_all(PROMPTS, max_new_tokens=24)
        assert [w.tokens for w in want] == [g.tokens for g in got]
        # a perfect draft accepts ~everything: well above 1 token/verify
        # (tick-boundary budget caps keep it below the k+1 ceiling)
        stats = eng.stats()
        assert stats["spec_tokens_per_verify"] > 2.0, stats

    def test_eos_semantics_match(self, stack):
        """With EOS honored, spec must stop each row exactly where the
        plain engine does (same tokens, same finish reasons)."""
        cfg, params, dcfg, dparams = stack
        want = make(cfg, params).run_all(PROMPTS, max_new_tokens=24)
        got = make(cfg, params, draft_params=dparams, draft_config=dcfg,
                   spec_k=4).run_all(PROMPTS, max_new_tokens=24)
        assert [(w.tokens, w.finish_reason) for w in want] == \
               [(g.tokens, g.finish_reason) for g in got]

    def test_continuous_batching_waves(self, stack):
        """Requests joining and leaving across ticks (more requests than
        slots) keep greedy parity — speculation composes with the
        continuous-batching lifecycle, not just a single batch."""
        cfg, params, dcfg, dparams = stack
        prompts = [f"wave request number {i} about pallas" for i in range(10)]
        lens = [8 + (i * 5) % 20 for i in range(10)]

        def run(eng):
            rids = [eng.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]
            done = {}
            while eng.has_work:
                for r in eng.step():
                    done[r.request_id] = r
            return [done[r].tokens for r in rids]

        want = run(make(cfg, params, max_slots=3, ignore_eos=True))
        got = run(make(cfg, params, max_slots=3, ignore_eos=True,
                       draft_params=dparams, draft_config=dcfg, spec_k=3))
        assert want == got


class TestCompositions:
    def test_prefix_cache_composes(self, stack):
        cfg, params, dcfg, dparams = stack
        header = "System header: be terse and cite. "
        prompts = [header + q for q in ("what is a mesh?", "why bfloat16?")]
        want = make(cfg, params, ignore_eos=True).run_all(prompts, max_new_tokens=16)
        spec = make(cfg, params, ignore_eos=True, draft_params=dparams,
                    draft_config=dcfg, spec_k=4)
        assert spec.warm_prefix(header) > 0
        got = spec.run_all(prompts, max_new_tokens=16)
        assert [w.tokens for w in want] == [g.tokens for g in got]
        assert spec.prefix_hits == 2

    def test_int8_kv_composes(self, stack):
        """Spec gathers quantized pages through dequantize and re-quantizes
        on scatter-back (idempotent absmax scales). Outputs are NOT
        bit-compared to the plain int8 engine: within a tick the verify
        attends the current rounds' KV at full precision while the plain
        engine reads every step through int8 — spec output differs within
        quantization noise (and is at least as close to the unquantized
        model). The invariants: the compose path runs, budgets hold, and
        the first token (identical prefill path both sides) matches."""
        cfg, params, dcfg, dparams = stack
        want = make(cfg, params, ignore_eos=True,
                    kv_quant="int8").run_all(PROMPTS[:2], max_new_tokens=16)
        got = make(cfg, params, ignore_eos=True, kv_quant="int8",
                   draft_params=dparams, draft_config=dcfg,
                   spec_k=4).run_all(PROMPTS[:2], max_new_tokens=16)
        for w, g in zip(want, got):
            assert len(g.tokens) == 16
            assert g.tokens[0] == w.tokens[0]

    def test_long_prompt_bucket_exceeding_draft_window(self, stack):
        """Draft-cache overrun regression: with max_pages_per_seq=6 the
        per-row window is 96 tokens, and a ~70-token prompt buckets its
        prefill width to 128 — before the clamp, draft prefill's
        ``.at[:, rows_idx, :width].set`` overhung the 96-wide draft cache
        axis and failed at trace time, killing the tick thread."""
        cfg, params, dcfg, dparams = stack
        prompt = "overrun " * 9  # 72 bytes + BOS → width bucket 128 > 96
        want = make(cfg, params, ignore_eos=True, max_pages_per_seq=6) \
            .run_all([prompt], max_new_tokens=4)
        eng = make(cfg, params, ignore_eos=True, max_pages_per_seq=6,
                   draft_params=dparams, draft_config=dcfg, spec_k=4)
        got = eng.run_all([prompt], max_new_tokens=4)
        assert [w.tokens for w in want] == [g.tokens for g in got]
        assert got[0].finish_reason in ("stop", "length")

    def test_sampled_and_mixed_batch_complete(self, stack):
        """Sampled rows (rejection sampling) and greedy rows serve in the
        same tick; per-call outputs are rng-path-dependent so only the
        contract is asserted (length, budget) — marginal exactness of the
        accept rule is proven in tests/test_speculative.py."""
        cfg, params, dcfg, dparams = stack
        eng = make(cfg, params, ignore_eos=True, draft_params=dparams,
                   draft_config=dcfg, spec_k=4)
        rids = [eng.submit(PROMPTS[i], max_new_tokens=12,
                           temperature=0.0 if i % 2 else 0.8)
                for i in range(4)]
        done = {}
        while eng.has_work:
            for r in eng.step():
                done[r.request_id] = r
        assert all(len(done[r].tokens) == 12 for r in rids)


class TestValidation:
    def test_vocab_mismatch_raises(self, stack):
        cfg, params, dcfg, dparams = stack
        bad = replace(dcfg, vocab_size=cfg.vocab_size * 2)
        with pytest.raises(ValueError, match="vocab"):
            make(cfg, params, draft_params=dparams, draft_config=bad)

    def test_chunked_prefill_conflict_raises(self, stack):
        cfg, params, dcfg, dparams = stack
        with pytest.raises(ValueError, match="mutually exclusive"):
            make(cfg, params, draft_params=dparams, draft_config=dcfg,
                 prefill_chunk=16)

    def test_draft_without_config_raises(self, stack):
        cfg, params, _, dparams = stack
        with pytest.raises(ValueError, match="draft_config"):
            make(cfg, params, draft_params=dparams)


class TestServingIntegration:
    def test_draft_checkpoint_activates_paged_spec(self, stack, tmp_path):
        """LLM_DRAFT_CHECKPOINT + USE_PAGED_KV=1 (the default deployment)
        now speculates in the paged service — the round-4 dead-knob gap,
        closed through the real DI container."""
        from sentio_tpu.config import (
            EmbedderConfig, GeneratorConfig, RerankConfig, Settings,
        )
        from sentio_tpu.runtime.checkpoint import save_pytree
        from sentio_tpu.serve.dependencies import DependencyContainer

        _cfg, _params, dcfg, dparams = stack
        from dataclasses import asdict

        ck = tmp_path / "draft-ck"
        save_pytree(ck, dparams,
                    meta={"family": "llama", "config": asdict(dcfg)})

        settings = Settings(
            embedder=EmbedderConfig(provider="hash", dim=32),
            rerank=RerankConfig(enabled=False),
            generator=GeneratorConfig(
                provider="tpu", model_preset="tiny", use_verifier=False,
                max_new_tokens=12, use_paged_decode=True, kv_page_size=16,
                kv_max_pages_per_seq=8, max_batch_size=2,
                draft_checkpoint_path=str(ck), speculative_k=3,
                prefix_cache=False,
            ),
        )
        # mesh=None mirrors the real single-chip deployment (the test env's
        # 8 virtual CPU devices would otherwise build a dp mesh, and paged
        # speculation doesn't support meshes yet)
        container = DependencyContainer(settings=settings, mesh=None)
        service = container.generation_service
        assert service is not None
        eng = service.engine
        assert eng.draft_params is not None and eng.spec_k == 3
        try:
            out = service.generate("one request through the spec path",
                                   max_new_tokens=10, temperature=0.0)
            assert len(out.tokens) == 10 or out.finish_reason == "stop"
        finally:
            service.close()
