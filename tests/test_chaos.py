"""Chaos drill: the paged serving path under faults-plus-load (tier 1).

SURVEY §5 prescribes fault-injection-driven resilience; this is the drill
that exercises it end to end: probabilistic decode-tick faults armed while
concurrent generate/stream callers hammer the service. The contract under
chaos (the contract vLLM-class systems must keep, Kwon et al., SOSP '23):

* every caller reaches a TERMINAL outcome — a result, a typed shed/deadline
  error, or a budgeted error result; nobody hangs;
* a tick failure with a successful ``engine.reset()`` requeues innocent
  waiters (per-ticket retry budget) instead of failing all of them;
* page-pool conservation holds throughout (conftest arms SENTIO_SANITIZE=1
  for this module, so every tick self-checks);
* no pump or waiter threads leak.

Engines here are tiny (default LlamaConfig.tiny) so the drill runs in the
quick tier — the point is scheduler/recovery logic, not model quality.
"""

import threading
import time

import pytest

from sentio_tpu.infra import faults
from sentio_tpu.infra.exceptions import (
    DeadlineExceededError,
    ReplicaUnavailable,
    SentioError,
    ServiceOverloaded,
)
from sentio_tpu.runtime.paged import ContinuousBatchingEngine, PagedResult
from sentio_tpu.runtime.service import PagedGenerationService


@pytest.fixture(scope="module")
def engine():
    # ONE engine for the module: each engine instance owns fresh jit
    # wrappers, so more engines = more XLA compiles in the quick tier
    return ContinuousBatchingEngine(
        max_slots=4, page_size=8, max_pages_per_seq=4, steps_per_tick=2,
    )


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.reset()


def _assert_pages_conserved(svc):
    s = svc.stats()
    assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
        == s["total_pages"] - 1, s


def _catch(fn, **kwargs):
    """Run ``fn`` and return its result OR the exception it raised — for
    threads whose outcome (either way) the test asserts on afterwards."""
    try:
        return fn(**kwargs)
    except Exception as exc:  # noqa: BLE001 — the test inspects the type
        return exc


def _build_parallel(build_fn, n=2, timeout_s=360.0):
    """Construct ``n`` replicas CONCURRENTLY. Each ProcessReplica
    constructor blocks through a full spawn + jax init + handshake
    (~10 s on CPU); building the drills' 2-worker sets serially doubles
    that startup wall time for no isolation benefit."""
    out: dict = {}
    errs: dict = {}

    def run(i):
        try:
            out[i] = build_fn(i)
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            errs[i] = exc

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s)
    if errs:
        for built in out.values():  # don't leak the siblings that DID spawn
            try:
                built.close()
            except Exception:  # noqa: BLE001 — already failing
                pass
        raise next(iter(errs.values()))
    assert len(out) == n, "replica construction timed out"
    return [out[i] for i in range(n)]


def _assert_no_pump_threads(timeout_s: float = 15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        pumps = [t for t in threading.enumerate()
                 if t.name.startswith(("paged-decode-pump",
                                       "replica-supervisor",
                                       "replica-rebuild"))
                 and t.is_alive()]
        if not pumps:
            return
        time.sleep(0.05)
    raise AssertionError(f"leaked pump/supervisor threads: {pumps}")


class TestChaosDrill:
    def test_mixed_load_under_probabilistic_tick_faults(self, engine):
        """≥8 concurrent mixed generate/stream callers while every decode
        tick fails with probability 0.25: all callers terminate, the pool
        conserves, the service still works afterwards, nothing leaks."""
        svc = PagedGenerationService(engine, retry_budget=2)
        outcomes: dict[str, object] = {}

        def call_generate(i):
            try:
                outcomes[f"g{i}"] = svc.generate(
                    f"chaos generate load {i}", max_new_tokens=6,
                    temperature=0.0, timeout_s=120,
                )
            except Exception as exc:  # noqa: BLE001 — any typed error is terminal
                outcomes[f"g{i}"] = exc

        def call_stream(i):
            try:
                outcomes[f"s{i}"] = "".join(svc.generate_stream(
                    f"chaos stream load {i}", max_new_tokens=6,
                    temperature=0.0, timeout_s=120,
                ))
            except Exception as exc:  # noqa: BLE001
                outcomes[f"s{i}"] = exc

        with faults.inject("paged.step", error=RuntimeError("chaos tick"),
                           probability=0.25, seed=1234) as rule:
            threads = (
                [threading.Thread(target=call_generate, args=(i,)) for i in range(5)]
                + [threading.Thread(target=call_stream, args=(i,)) for i in range(4)]
            )
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert not any(t.is_alive() for t in threads), (
                "caller thread hung under chaos"
            )
        assert rule.fired >= 1, "drill never actually injected a fault"
        # EVERY caller reached a terminal outcome
        assert len(outcomes) == 9
        for name, out in outcomes.items():
            assert isinstance(out, (PagedResult, str, Exception)), (name, out)
            if isinstance(out, PagedResult):
                assert out.finish_reason in ("stop", "length", "error"), (name, out)
        # the service survived: a post-chaos request works end to end
        ok = svc.generate("post chaos sanity", max_new_tokens=4, timeout_s=120)
        assert ok.finish_reason in ("stop", "length")
        _assert_pages_conserved(svc)
        svc.close()
        _assert_no_pump_threads()

    def test_tick_failure_requeues_innocent_waiters(self, engine):
        """One failed tick + successful reset: BOTH in-flight waiters are
        requeued and complete normally — the pre-fix behavior failed every
        waiter via _fail_all_locked even after a clean reset."""
        svc = PagedGenerationService(engine, retry_budget=1)
        results = {}

        def call(i):
            results[i] = svc.generate(
                f"innocent waiter number {i} with padding", max_new_tokens=6,
                temperature=0.0, timeout_s=120,
            )

        with faults.inject("paged.step", error=RuntimeError("one bad tick"),
                           times=1) as rule:
            threads = [threading.Thread(target=call, args=(i,)) for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        assert rule.fired == 1
        assert len(results) == 2
        for i, res in results.items():
            assert res.finish_reason in ("stop", "length"), (i, res)
        stats = svc.stats()
        assert stats["requeued"] >= 1, stats
        assert stats["tick_failures"] == 1, stats
        _assert_pages_conserved(svc)
        svc.close()

    def test_exhausted_budget_fails_only_that_ticket(self, engine):
        """A stream that already delivered tokens cannot be resubmitted
        (restart would duplicate output) — after a tick failure it gets the
        error, while a queued generate is requeued and succeeds.

        Determinism: phase 1 arms a delay-only rule (every tick sleeps, so
        the short stream cannot outrun the test), phase 2 swaps in the
        one-shot error once BOTH requests are observably in flight."""
        svc = PagedGenerationService(engine, retry_budget=1)
        stream_err: list = []
        stream_text: list[str] = []
        faults.arm("paged.step", faults.FaultRule(delay_s=0.1))

        def consume():
            try:
                for piece in svc.generate_stream(
                    "s",  # short prompt: maximum decode room in the window
                    max_new_tokens=200, temperature=0.0, timeout_s=120,
                ):
                    stream_text.append(piece)
            except Exception as exc:  # noqa: BLE001
                stream_err.append(exc)

        streamer = threading.Thread(target=consume)
        streamer.start()
        # wait until real tokens flowed to the stream consumer
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not stream_text:
            time.sleep(0.005)
        assert stream_text, "stream produced nothing to be mid-flight with"
        gen_result: dict = {}

        def call():
            gen_result["r"] = svc.generate(
                "innocent generate behind the doomed stream",
                max_new_tokens=4, temperature=0.0, timeout_s=120,
            )

        t = threading.Thread(target=call)
        t.start()
        # both requests visible to the service before the fault arms
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            s = svc.stats()
            if s["active_slots"] + s["queued"] + s["queued_inbox"] >= 2:
                break
            time.sleep(0.005)
        faults.arm("paged.step", faults.FaultRule(
            error=RuntimeError("boom"), times=1))
        t.join(timeout=120)
        streamer.join(timeout=120)
        faults.disarm("paged.step")
        assert not streamer.is_alive()
        # the delivered-tokens stream is the casualty...
        assert stream_err, "mid-flight stream should have been failed"
        # ...while the resubmittable generate survived the same tick failure
        assert gen_result["r"].finish_reason in ("stop", "length")
        _assert_pages_conserved(svc)
        svc.close()

    def test_replica_kill_drill_failover_and_rebuild(self):
        """ISSUE 8 acceptance drill (sanitizer armed for this module): one
        of 2 replicas is killed mid-traffic — a decode tick fails AND its
        ``engine.reset()`` is forced to fail, so the replica latches broken
        — under ≥8 concurrent mixed generate/stream callers. The contract:

        * every caller terminates with a TYPED outcome (a result, text, or
          a SentioError — never a bare RuntimeError);
        * the surviving replica keeps serving during the outage;
        * the supervisor quarantines the corpse, rebuilds it in place from
          the shared weights, and the REBUILT replica serves a request
          before the test ends;
        * page pools conserve on both sides and no pump/supervisor threads
          leak."""
        from sentio_tpu.runtime.replica import HEALTH_HEALTHY, ReplicaSet

        e0 = ContinuousBatchingEngine(
            max_slots=2, page_size=8, max_pages_per_seq=4, steps_per_tick=2,
        )
        e1 = ContinuousBatchingEngine(
            params=e0.params, tokenizer=e0.tokenizer,
            max_slots=2, page_size=8, max_pages_per_seq=4, steps_per_tick=2,
        )
        svc0 = PagedGenerationService(e0, retry_budget=1)
        svc1 = PagedGenerationService(e1, retry_budget=1)
        # pre-compile both engines so the drill's traffic exercises the
        # failure machinery instead of waiting out XLA compiles
        svc0.generate("drill warm zero", max_new_tokens=2, timeout_s=180)
        svc1.generate("drill warm one", max_new_tokens=2, timeout_s=180)
        rs = ReplicaSet(
            [svc0, svc1],
            probe_interval_s=0.05, quarantine_backoff_s=0.1,
            breaker_tick_failures=2, failover_budget=2,
        )
        outcomes: dict[str, object] = {}

        def call_generate(i):
            try:
                outcomes[f"g{i}"] = rs.generate(
                    f"replica drill generate {i}", max_new_tokens=6,
                    temperature=0.0, timeout_s=120,
                )
            except Exception as exc:  # noqa: BLE001 — typed errors terminal
                outcomes[f"g{i}"] = exc

        def call_stream(i):
            try:
                outcomes[f"s{i}"] = "".join(rs.generate_stream(
                    f"replica drill stream {i}", max_new_tokens=6,
                    temperature=0.0, timeout_s=120,
                ))
            except Exception as exc:  # noqa: BLE001
                outcomes[f"s{i}"] = exc

        try:
            # armed BEFORE traffic: whichever replica ticks first dies with
            # an unrecoverable reset (deterministically exactly one kill)
            faults.arm("paged.step", faults.FaultRule(
                error=RuntimeError("drill: replica kill"), times=1))
            faults.arm("engine.reset", faults.FaultRule(
                error=RuntimeError("drill: reset denied"), times=1))
            threads = (
                [threading.Thread(target=call_generate, args=(i,))
                 for i in range(5)]
                + [threading.Thread(target=call_stream, args=(i,))
                   for i in range(4)]
            )
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert not any(t.is_alive() for t in threads), (
                "caller thread hung across the replica kill"
            )
            faults.reset()
            # exactly one replica latched broken
            dead = [i for i, svc in enumerate((svc0, svc1)) if svc.broken]
            assert len(dead) == 1, f"expected one broken replica, got {dead}"
            # EVERY caller terminated with a typed outcome; the survivor
            # absorbed failed-over load (successes exist despite the kill)
            assert len(outcomes) == 9
            successes = 0
            for name, out in outcomes.items():
                if isinstance(out, Exception):
                    assert isinstance(out, SentioError), (
                        f"{name}: untyped {type(out).__name__}: {out}"
                    )
                else:
                    assert isinstance(out, (PagedResult, str)), (name, out)
                    if isinstance(out, PagedResult):
                        assert out.finish_reason in ("stop", "length"), (
                            name, out,
                        )
                    successes += 1
            assert successes >= 1, (
                f"survivor never served during the outage: {outcomes}"
            )
            # the supervisor rebuilds the corpse in place and the set
            # returns to full health
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if rs.health_summary()["status"] == "healthy":
                    break
                time.sleep(0.05)
            summary = rs.health_summary()
            assert summary["status"] == "healthy", summary
            assert summary["replicas"][dead[0]]["rebuilds"] == 1, summary
            # the REBUILT replica itself serves (not just the survivor):
            # route directly at the fresh service occupying the dead slot
            rebuilt = rs._services[dead[0]]
            assert rebuilt is not (svc0, svc1)[dead[0]]
            ok = rebuilt.generate("rebuilt replica serves again",
                                  max_new_tokens=3, timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
            # ... and through the router too
            ok2 = rs.generate("post drill routed sanity", max_new_tokens=3,
                              timeout_s=120)
            assert ok2.finish_reason in ("stop", "length")
            # health transitions were evented to the flight recorder
            from sentio_tpu.infra.flight import get_flight_recorder

            events = [t for t in get_flight_recorder().timeline()
                      if t.get("event") == "replica_health"]
            seen = {(e["state_from"], e["state_to"]) for e in events}
            assert ("HEALTHY", "QUARANTINED") in seen, seen
            assert ("QUARANTINED", "REBUILDING") in seen, seen
            assert ("REBUILDING", "HEALTHY") in seen, seen
            # page-pool conservation on BOTH sides of the kill (sanitizer
            # checked every tick; this is the end-state audit)
            for s in rs.stats()["replicas"]:
                assert s["free_pages"] + s.get("prefix_cache_pages", 0) \
                    == s["total_pages"] - 1, s
            assert rs.stats()["health"]["replicas"][dead[0]]["state"] \
                == HEALTH_HEALTHY
        finally:
            faults.reset()
            rs.close()
        _assert_no_pump_threads()

    def test_replica_stall_drill_watchdog_handoff_and_rebuild(self):
        """ISSUE 10 acceptance drill (sanitizer armed for this module): one
        of 2 replicas is WEDGED mid-traffic — its next decode tick blocks
        inside a stall fault, raising nothing, exactly like a hung device
        dispatch. The contract:

        * the watchdog quarantines the stalled replica within 2x its
          ``TICK_STALL_BUDGET_S`` (no exception required — heartbeat age
          with pending work is the whole signal);
        * the wedged replica's never-dispatched INBOX tickets are handed
          off directly to the survivor and complete there WITHOUT their
          callers observing any failure (failover budget untouched);
        * its admitted ticket fails typed and fails over (one failover);
        * every caller outcome is typed, pages conserve on the surviving
          replica, the abandoned pump is accounted in ``stats()``
          (pump_leaked survives the rebuild swap via carryover), and the
          rebuilt replica serves again."""
        from sentio_tpu.runtime.replica import HEALTH_HEALTHY, ReplicaSet

        # generous budget: a LEGITIMATE tick on the survivor may include a
        # multi-second cold XLA compile (a new prefill width/row variant
        # for the adopted tickets) and must never read as a stall
        budget_s = 5.0
        e0 = ContinuousBatchingEngine(
            max_slots=2, page_size=8, max_pages_per_seq=4, num_pages=65,
            steps_per_tick=2,
        )
        e1 = ContinuousBatchingEngine(
            params=e0.params, tokenizer=e0.tokenizer,
            max_slots=2, page_size=8, max_pages_per_seq=4, num_pages=65,
            steps_per_tick=2,
        )
        svc0 = PagedGenerationService(e0, retry_budget=1,
                                      tick_stall_budget_s=budget_s)
        svc1 = PagedGenerationService(e1, retry_budget=1,
                                      tick_stall_budget_s=budget_s)
        # pre-compile + seed a distinct radix session per replica: after
        # the wedge, follow-ups on the wedged replica's session prefix
        # route to it by affinity and pile into its (never-drained) inbox
        sessions = ["session zero affinity head spanning pages easily ",
                    "session one affinity head spanning pages easily "]
        svc0.generate(sessions[0] + "seed", max_new_tokens=2, timeout_s=180)
        svc1.generate(sessions[1] + "seed", max_new_tokens=2, timeout_s=180)
        rs = ReplicaSet(
            [svc0, svc1],
            probe_interval_s=0.05, quarantine_backoff_s=0.1,
            rebuild_drain_s=0.3, failover_budget=2,
        )
        release = threading.Event()
        outcomes: dict[str, object] = {}

        def call(tag, prompt):
            try:
                outcomes[tag] = rs.generate(prompt, max_new_tokens=4,
                                            temperature=0.0, timeout_s=120)
            except Exception as exc:  # noqa: BLE001 — typed errors terminal
                outcomes[tag] = exc
        try:
            # one-shot wedge: the next decode tick anywhere blocks until
            # release (120s worst-case cap); both pumps are idle, so the
            # single request below deterministically picks the victim
            rule = faults.FaultRule(stall_event=release, stall_s=120.0,
                                    times=1)
            faults.arm("paged.step", rule)
            t_a = threading.Thread(target=call,
                                   args=("admitted", "cold wedge probe"))
            t_a.start()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and rule.stalled == 0:
                time.sleep(0.005)
            assert rule.stalled == 1, "no pump ever wedged"
            t_wedge = time.monotonic()
            dead = max(range(2), key=lambda i: (svc0, svc1)[i].backlog())
            wedged_svc = (svc0, svc1)[dead]
            assert wedged_svc.backlog() >= 1
            # inbox load for the wedged replica, routed there by affinity
            inbox_callers = []
            for k in range(2):
                t = threading.Thread(
                    target=call,
                    args=(f"inbox{k}", sessions[dead] + f"turn {k}"))
                t.start()
                inbox_callers.append(t)
            deadline = time.monotonic() + min(budget_s * 0.8, 2.0)
            while time.monotonic() < deadline and wedged_svc.backlog() < 3:
                time.sleep(0.005)
            assert wedged_svc.backlog() >= 3, (
                "inbox tickets did not land on the wedged replica before "
                "detection"
            )
            # the watchdog quarantines on heartbeat age alone, within
            # 2x the stall budget of the wedge
            deadline = time.monotonic() + 3 * budget_s
            quarantined_at = None
            while time.monotonic() < deadline:
                state = rs.health_summary()["replicas"][dead]["state"]
                if state != HEALTH_HEALTHY:
                    quarantined_at = time.monotonic()
                    break
                time.sleep(0.01)
            assert quarantined_at is not None, "watchdog never fired"
            assert quarantined_at - t_wedge <= 2 * budget_s, (
                f"detection took {quarantined_at - t_wedge:.2f}s "
                f"(budget {budget_s}s)"
            )
            t_a.join(timeout=120)
            for t in inbox_callers:
                t.join(timeout=120)
            assert not t_a.is_alive() and not any(
                t.is_alive() for t in inbox_callers), (
                "caller thread hung across the stall"
            )
            # every caller terminated typed; the inbox tickets completed on
            # the SURVIVOR without their callers failing over
            assert len(outcomes) == 3
            for name, out in outcomes.items():
                if isinstance(out, Exception):
                    assert isinstance(out, SentioError), (
                        f"{name}: untyped {type(out).__name__}: {out}")
                else:
                    assert out.finish_reason in ("stop", "length"), (name, out)
            for k in range(2):
                assert isinstance(outcomes[f"inbox{k}"], PagedResult), (
                    f"handed-off ticket inbox{k} did not complete: "
                    f"{outcomes[f'inbox{k}']}"
                )
            stats = rs.stats()
            assert stats["handed_off"] == 2, stats["handed_off"]
            assert stats["stall_quarantines"] == 1
            # only the ADMITTED ticket's caller spent failover budget; the
            # handed-off tickets moved without touching it
            assert stats["failovers"] <= 1, stats["failovers"]
            # the supervisor abandons the wedged engine and rebuilds the
            # slot in place; the abandoned pump is ACCOUNTED even though
            # its service incarnation left rotation
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if rs.health_summary()["status"] == "healthy":
                    break
                time.sleep(0.05)
            summary = rs.health_summary()
            assert summary["status"] == "healthy", summary
            assert summary["replicas"][dead]["rebuilds"] == 1, summary
            assert rs.stats()["pump_leaked"] >= 1, (
                "abandoned wedged pump vanished from stats"
            )
            # pages conserve on the surviving replica (sanitizer checked
            # every tick; this is the end-state audit) and the REBUILT
            # replica serves again
            survivor_stats = rs.stats()["replicas"][1 - dead]
            assert survivor_stats["free_pages"] \
                + survivor_stats.get("prefix_cache_pages", 0) \
                == survivor_stats["total_pages"] - 1, survivor_stats
            rebuilt = rs._services[dead]
            assert rebuilt is not wedged_svc
            ok = rebuilt.generate("rebuilt after stall", max_new_tokens=3,
                                  timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
            ok2 = rs.generate("post stall routed sanity", max_new_tokens=3,
                              timeout_s=120)
            assert ok2.finish_reason in ("stop", "length")
            # the stall was evented for operators
            from sentio_tpu.infra.flight import get_flight_recorder

            events = get_flight_recorder().timeline()
            assert any(e.get("event") == "pump_stall" for e in events)
            assert any(e.get("event") == "inbox_handoff"
                       and e.get("handed_off") == 2 for e in events)
        finally:
            release.set()  # unwedge the abandoned pump so it can exit
            faults.reset()
            rs.close()
        _assert_no_pump_threads()

    def test_process_replica_sigkill_drill(self):
        """ISSUE 13 acceptance drill: one of 2 PROCESS-mode replicas takes
        a real ``SIGKILL`` mid-traffic — no exception raised in any Python
        frame, the worker process is simply gone. The contract:

        * every caller terminates with a TYPED outcome (in-flight RPCs
          against the corpse fail ReplicaUnavailable and fail over);
        * the survivor keeps serving during the outage;
        * the supervisor detects the corpse from the OUTSIDE (broken pipe /
          ``proc.is_alive()``), quarantines, and rebuilds by RESPAWNING the
          process; the respawned worker serves before the test ends;
        * detection and recovery land within budget;
        * zero orphan worker processes at teardown."""
        import dataclasses
        import multiprocessing

        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.models.tokenizer import ByteTokenizer
        from sentio_tpu.runtime.replica import ReplicaSet
        from sentio_tpu.runtime.worker import ProcessReplica, WorkerSpec

        cfg = LlamaConfig.tiny()
        spec = WorkerSpec(factory_kwargs=dict(
            model_config=dataclasses.asdict(cfg),
            engine_kwargs=dict(max_slots=2, page_size=8, max_pages_per_seq=4,
                               steps_per_tick=2),
            service_kwargs=dict(retry_budget=1),
        ))
        tok = ByteTokenizer(cfg.vocab_size)
        p0, p1 = _build_parallel(lambda i: ProcessReplica(
            spec, tok, replica_id=i, build_timeout_s=300.0))
        # pre-compile both workers (concurrently — separate processes) so
        # the drill's traffic exercises the failure machinery instead of
        # waiting out XLA compiles
        _build_parallel(lambda i: [p0, p1][i].generate(
            f"drill warm {i}", max_new_tokens=2, timeout_s=180))
        rs = ReplicaSet(
            [p0, p1],
            probe_interval_s=0.05, quarantine_backoff_s=0.1,
            failover_budget=2, rebuild_drain_s=0.5,
        )
        outcomes: dict[str, object] = {}
        stop_traffic = threading.Event()

        def call_generate(i):
            try:
                outcomes[f"g{i}"] = rs.generate(
                    f"sigkill drill generate {i}", max_new_tokens=8,
                    temperature=0.0, timeout_s=120,
                )
            except Exception as exc:  # noqa: BLE001 — typed errors terminal
                outcomes[f"g{i}"] = exc

        def call_stream(i):
            try:
                outcomes[f"s{i}"] = "".join(rs.generate_stream(
                    f"sigkill drill stream {i}", max_new_tokens=8,
                    temperature=0.0, timeout_s=120,
                ))
            except Exception as exc:  # noqa: BLE001
                outcomes[f"s{i}"] = exc

        try:
            threads = (
                [threading.Thread(target=call_generate, args=(i,))
                 for i in range(5)]
                + [threading.Thread(target=call_stream, args=(i,))
                   for i in range(3)]
            )
            for t in threads:
                t.start()
            # the kill lands while traffic is in flight (workers decode for
            # several ticks at 8 tokens / 2 steps-per-tick)
            time.sleep(0.1)
            t_kill = time.monotonic()
            p1.kill()  # real SIGKILL: no handlers run, no frames unwind
            # detection: the supervisor (or a failing caller) must move the
            # corpse out of HEALTHY from the OUTSIDE
            t_detect = None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if rs.health_summary()["replicas"][1]["state"] != "HEALTHY":
                    t_detect = time.monotonic()
                    break
                time.sleep(0.01)
            assert t_detect is not None, "corpse never left HEALTHY"
            assert t_detect - t_kill <= 15.0, (
                f"detection took {t_detect - t_kill:.1f}s"
            )
            for t in threads:
                t.join(timeout=180)
            assert not any(t.is_alive() for t in threads), (
                "caller thread hung across the worker SIGKILL"
            )
            # EVERY caller terminated with a typed outcome; the survivor
            # absorbed failed-over load
            assert len(outcomes) == 8
            successes = 0
            for name, out in outcomes.items():
                if isinstance(out, Exception):
                    assert isinstance(out, SentioError), (
                        f"{name}: untyped {type(out).__name__}: {out}"
                    )
                else:
                    assert isinstance(out, (PagedResult, str)), (name, out)
                    if isinstance(out, PagedResult):
                        assert out.finish_reason in ("stop", "length"), (
                            name, out,
                        )
                    successes += 1
            assert successes >= 1, (
                f"survivor never served during the outage: {outcomes}"
            )
            # the supervisor RESPAWNS the dead worker process and the set
            # returns to full health within budget
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if rs.health_summary()["status"] == "healthy":
                    break
                time.sleep(0.05)
            summary = rs.health_summary()
            assert summary["status"] == "healthy", summary
            assert summary["replicas"][1]["rebuilds"] == 1, summary
            rebuilt = rs._services[1]
            assert rebuilt is not p1, "slot was not respawned"
            assert rebuilt.pid != p1.pid, "respawn reused the corpse's pid?"
            ok = rebuilt.generate("respawned replica serves again",
                                  max_new_tokens=3, timeout_s=180)
            assert ok.finish_reason in ("stop", "length")
            ok2 = rs.generate("post sigkill routed sanity", max_new_tokens=3,
                              timeout_s=120)
            assert ok2.finish_reason in ("stop", "length")
        finally:
            stop_traffic.set()
            rs.close()
        # zero orphan worker processes at teardown: close() reaps
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and multiprocessing.active_children():
            time.sleep(0.05)
        assert multiprocessing.active_children() == [], (
            "orphan replica worker processes leaked"
        )
        _assert_no_pump_threads()

    def test_warmup_stall_quarantined_by_budget(self):
        """ISSUE 13 satellite: a wedge DURING warmup. WARMING is
        watchdog-exempt (cold compiles legitimately dwarf any stall
        budget), so pre-budget this hang was only caught by caller
        timeouts — the spawn/rebuild path just sat there. With
        ``WARMUP_BUDGET_S`` the exemption EXPIRES: the watchdog
        quarantines the replica (typed, supervisor-visible) and the
        blocked warmup caller gets the typed abandonment error."""
        from sentio_tpu.runtime.replica import (
            HEALTH_HEALTHY,
            HEALTH_QUARANTINED,
            ReplicaSet,
        )

        eng = ContinuousBatchingEngine(
            max_slots=2, page_size=8, max_pages_per_seq=4, steps_per_tick=2,
        )
        budget_s = 2.0
        svc = PagedGenerationService(eng, tick_stall_budget_s=budget_s,
                                     warmup_budget_s=budget_s)
        rs = ReplicaSet([svc], supervise=False)
        release = threading.Event()
        warm_outcome: list = []
        rule = faults.FaultRule(stall_event=release, stall_s=120.0, times=1)
        faults.arm("paged.step", rule)
        try:
            warmer = threading.Thread(
                target=lambda: warm_outcome.append(
                    _catch(svc.warmup, max_new_tokens=2)),
            )
            warmer.start()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and rule.stalled == 0:
                time.sleep(0.005)
            assert rule.stalled == 1, "warmup never wedged"
            t_wedge = time.monotonic()
            # inside the budget the stand-down holds: warming is exempt
            rs._supervise_once()
            assert rs.health_summary()["replicas"][0]["state"] \
                == HEALTH_HEALTHY
            # past the budget the exemption expires and the watchdog fires
            deadline = time.monotonic() + 6 * budget_s
            state = HEALTH_HEALTHY
            while time.monotonic() < deadline:
                rs._supervise_once()
                state = rs.health_summary()["replicas"][0]["state"]
                if state != HEALTH_HEALTHY:
                    break
                time.sleep(0.05)
            assert state in (HEALTH_QUARANTINED, "REBUILDING"), (
                "watchdog never fired on the stalled warmup"
            )
            assert time.monotonic() - t_wedge <= 4 * budget_s, (
                "stalled-warmup detection exceeded 2x budget + slack"
            )
            assert rs.health_summary()["replicas"][0].get("reason", "") \
                .startswith("pump stalled"), rs.health_summary()
            # the blocked warmup caller wakes with the TYPED abandonment
            # error instead of hanging out its generate timeouts
            warmer.join(timeout=60)
            assert not warmer.is_alive(), "warmup still hung post-quarantine"
            assert isinstance(warm_outcome[0], ReplicaUnavailable), (
                warm_outcome
            )
            assert rs.stats()["stall_quarantines"] == 1
        finally:
            release.set()  # unwedge the abandoned pump so it can exit
            faults.reset()
            rs.close()
        _assert_no_pump_threads()

    def test_admission_shed_and_deadline_at_submit(self, engine):
        """Typed sheds: a full queue answers 429-style ServiceOverloaded
        with a retry hint; an already-expired deadline is a typed
        DeadlineExceededError. Neither touches the engine."""
        svc = PagedGenerationService(engine, max_queue=0)
        with pytest.raises(ServiceOverloaded) as exc_info:
            svc.generate("cannot even queue", max_new_tokens=2)
        assert exc_info.value.status == 429
        assert exc_info.value.details["retry_after_s"] >= 0
        with pytest.raises(ServiceOverloaded):
            svc.check_admission()  # pre-commit probe sheds identically
        svc2 = PagedGenerationService(engine)
        with pytest.raises(DeadlineExceededError):
            svc2.generate("expired before submit", max_new_tokens=2,
                          deadline_ts=time.perf_counter() - 0.5)
        stats = svc2.stats()
        assert stats["shed"] >= 1
        svc.close()
        svc2.close()

    def test_drain_sheds_new_work_and_finishes_in_flight(self, engine):
        """drain(): in-flight decode completes, concurrent submits shed with
        503/draining, and the service ends closed."""
        svc = PagedGenerationService(engine)
        result: dict = {}

        def call():
            # long enough (24 ticks at 2 steps/tick) that the drain below
            # provably starts while this is mid-decode; the old 150-token
            # budget bought ~40 extra seconds of tiny-model decode without
            # widening any assertion
            result["r"] = svc.generate(
                "long generation that must finish during drain",
                max_new_tokens=48, temperature=0.0, timeout_s=120,
            )

        t = threading.Thread(target=call)
        t.start()
        # let the pump admit it before draining
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and svc.stats()["active_slots"] == 0:
            time.sleep(0.01)
        drain_out: dict = {}

        def drain():
            drain_out.update(svc.drain(deadline_s=60.0))

        d = threading.Thread(target=drain)
        d.start()
        # shed while draining: a submit racing the drain gets a typed 503
        shed = None
        probe_deadline = time.monotonic() + 60
        while time.monotonic() < probe_deadline:
            try:
                svc.generate("late arrival", max_new_tokens=2, timeout_s=30)
            except ServiceOverloaded as exc:
                shed = exc
                break
            except ReplicaUnavailable:
                break  # drain already closed the service — also typed
            time.sleep(0.005)
        t.join(timeout=120)
        d.join(timeout=120)
        assert result["r"].finish_reason in ("stop", "length")
        assert drain_out.get("drained") is True, drain_out
        if shed is not None:
            assert shed.status == 503
        with pytest.raises(ReplicaUnavailable, match="closed"):
            svc.generate("after drain-close")
        _assert_no_pump_threads()


class TestResumableStreams:
    """ISSUE 14 acceptance drills: a stream that already DELIVERED tokens
    survives its replica's death by replay-prefill — the delivered prefix
    re-admits on a survivor as a prior context suffix, decode continues
    from the splice point, and the client sees one uninterrupted stream
    whose output is token-identical to a run that never saw a fault."""

    PROMPT = "resumable stream drill with a reasonably long prompt body"

    def test_midstream_death_resumes_token_exact_thread_mode(self):
        """Injected mid-stream death (thread mode): one of 2 replicas
        fails a decode tick AFTER delivering at least one chunk of a live
        stream. The stream must complete with output byte-identical to the
        no-fault greedy run (zero duplicated, zero missing tokens), emit
        the ``stream_resumed`` flight event, count into stats, and leave
        the survivor's page pool conserved (sanitizer armed throughout)."""
        from sentio_tpu.runtime.replica import ReplicaSet

        e0 = ContinuousBatchingEngine(
            max_slots=2, page_size=8, max_pages_per_seq=4, steps_per_tick=2,
        )
        e1 = ContinuousBatchingEngine(
            params=e0.params, tokenizer=e0.tokenizer,
            max_slots=2, page_size=8, max_pages_per_seq=4, steps_per_tick=2,
        )
        svc0 = PagedGenerationService(e0, retry_budget=1)
        svc1 = PagedGenerationService(e1, retry_budget=1)
        svc1.generate("drill warm one", max_new_tokens=2, timeout_s=180)
        # the no-fault reference ALSO warms svc0's radix with the full
        # prompt, so the drill stream deterministically routes to svc0
        # (prefix affinity) — the replica the fault will kill
        expected = svc0.generate(self.PROMPT, max_new_tokens=16,
                                 temperature=0.0, timeout_s=180)
        assert len(expected.tokens) >= 4, "drill needs a multi-chunk answer"
        rs = ReplicaSet([svc0, svc1], supervise=False, failover_budget=1)
        try:
            # armed BEFORE the stream starts: tick 1 delivers a chunk
            # (skip=1), tick 2 dies — at least one token is ALWAYS
            # delivered before the death, no consumer-timing race. The
            # reset succeeds, so this is a pure mid-stream casualty (the
            # service requeues fresh work but can never restart a
            # delivered-token stream itself).
            faults.arm("paged.step", faults.FaultRule(
                error=RuntimeError("drill: midstream death"),
                times=1, skip=1))
            stats_out: dict = {}
            pieces = list(rs.generate_stream(
                self.PROMPT, max_new_tokens=16, temperature=0.0,
                timeout_s=120, stats_out=stats_out,
            ))
            faults.reset()
            # token-exact vs the no-fault run: zero duplicated, zero
            # missing tokens, one uninterrupted stream
            assert "".join(pieces) == expected.text
            assert stats_out.get("resumed") == 1, stats_out
            assert stats_out.get("replayed_tokens", 0) >= 1, stats_out
            assert stats_out.get("tokens") == len(expected.tokens), stats_out
            stats = rs.stats()
            assert stats["stream_resumes"] == 1
            assert stats["resume_replayed_tokens"] >= 1
            assert stats["resume_exhausted"] == 0
            # the resume was evented for operators
            from sentio_tpu.infra.flight import get_flight_recorder

            events = [t for t in get_flight_recorder().timeline()
                      if t.get("event") == "stream_resumed"]
            assert events, "stream_resumed flight event missing"
            assert events[-1]["replica_from"] == 0
            assert events[-1]["replica_to"] == 1
            assert events[-1]["replayed_tokens"] >= 1
            # pages conserve on the survivor (and on the reset victim)
            _assert_pages_conserved(svc1)
            _assert_pages_conserved(svc0)
            # the survivor still serves routed traffic afterwards
            ok = rs.generate("post resume routed sanity", max_new_tokens=3,
                             temperature=0.0, timeout_s=120)
            assert ok.finish_reason in ("stop", "length")
        finally:
            faults.reset()
            rs.close()
        _assert_no_pump_threads()

    def test_midstream_sigkill_resumes_token_exact_process_mode(self):
        """ISSUE 14 process-mode drill: a REAL ``SIGKILL`` lands between
        delivered stream chunks (the ``worker.stream_chunk`` injection
        point, armed in-worker over the RPC fault surface, composes a
        stall — the determinism window — with ``kill_process``). The
        contract:

        * the stream completes token-identical to a no-fault greedy run
          (the resume replays the delivered prefix on the survivor);
        * the dead worker's never-answered SHADOWED tickets hand off to
          the survivor and complete WITHOUT spending caller failover
          budget (``handed_off`` > 0 — thread-mode handoff parity);
        * the supervisor respawns the worker; zero orphans at teardown."""
        import dataclasses
        import multiprocessing

        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.models.tokenizer import ByteTokenizer
        from sentio_tpu.runtime.replica import ReplicaSet
        from sentio_tpu.runtime.worker import ProcessReplica, WorkerSpec

        cfg = LlamaConfig.tiny()
        spec = WorkerSpec(factory_kwargs=dict(
            model_config=dataclasses.asdict(cfg),
            engine_kwargs=dict(max_slots=2, page_size=8, max_pages_per_seq=4,
                               steps_per_tick=2),
            service_kwargs=dict(retry_budget=1),
        ))
        tok = ByteTokenizer(cfg.vocab_size)
        p0, p1 = _build_parallel(lambda i: ProcessReplica(
            spec, tok, replica_id=i, build_timeout_s=300.0))
        # no-fault reference from the survivor (seeded inits are identical
        # across workers — pinned by test_worker's parity suite); p0's
        # radix is primed DEEPER than p1's reference insert so prefix
        # affinity deterministically routes the drill stream to p0.
        # Independent workers: both (compile-heavy) warms run concurrently
        ref_out = _build_parallel(lambda i: (
            p1.generate(self.PROMPT, max_new_tokens=16, temperature=0.0,
                        timeout_s=180)
            if i else
            p0.generate(self.PROMPT, max_new_tokens=2, temperature=0.0,
                        timeout_s=180)))
        expected = ref_out[1]
        assert len(expected.tokens) >= 4
        rs = ReplicaSet(
            [p0, p1],
            probe_interval_s=0.05, quarantine_backoff_s=0.1,
            failover_budget=1, rebuild_drain_s=0.5,
        )
        probe_results: dict = {}

        def probe(i):
            try:
                probe_results[i] = p0.generate(
                    f"handoff probe {i}", max_new_tokens=24, timeout_s=120)
            except Exception as exc:  # noqa: BLE001 — asserted below
                probe_results[i] = exc

        try:
            # between delivered chunks: wedge 3s (the window the test uses
            # to queue handoff probes), then a REAL SIGKILL — no handler
            # runs, no frame unwinds
            p0.inject_fault("worker.stream_chunk", stall_s=3.0,
                            kill_process=True, times=1)
            stats_out: dict = {}
            it = rs.generate_stream(self.PROMPT, max_new_tokens=16,
                                    temperature=0.0, timeout_s=120,
                                    stats_out=stats_out)
            pieces = [next(it)]  # chunk 1 delivered; chunk 2 arms the fault
            # inside the stall window: wedge p0's pump so the probes cannot
            # complete before the kill, then queue them (they register in
            # the router-side shadow)
            p0.inject_fault("paged.step", stall_s=30.0, times=1)
            time.sleep(0.1)
            threads = [threading.Thread(target=probe, args=(i,), daemon=True)
                       for i in range(2)]
            for t in threads:
                t.start()
            time.sleep(0.3)
            for piece in it:
                pieces.append(piece)
            # token-exact across a real SIGKILL
            assert "".join(pieces) == expected.text
            assert stats_out.get("resumed") == 1, stats_out
            for t in threads:
                t.join(timeout=120)
            assert not any(t.is_alive() for t in threads), (
                "handoff probe hung across the SIGKILL"
            )
            # the shadowed probes completed on the survivor via handoff —
            # typed results, no failover budget spent
            for i, out in probe_results.items():
                assert isinstance(out, PagedResult), (i, out)
                assert out.finish_reason in ("stop", "length"), (i, out)
                assert out.replica_id == 1, (i, out)
            stats = rs.stats()
            assert stats["handed_off"] >= 2, stats["handed_off"]
            assert stats["stream_resumes"] >= 1
            assert stats["resume_replayed_tokens"] >= 1
            # the supervisor respawns the corpse and the set heals
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if rs.health_summary()["status"] == "healthy":
                    break
                time.sleep(0.05)
            summary = rs.health_summary()
            assert summary["status"] == "healthy", summary
            ok = rs.generate("post sigkill routed sanity", max_new_tokens=3,
                             temperature=0.0, timeout_s=120)
            assert ok.finish_reason in ("stop", "length")
        finally:
            rs.close()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and multiprocessing.active_children():
            time.sleep(0.05)
        assert multiprocessing.active_children() == [], (
            "orphan replica worker processes leaked"
        )
        _assert_no_pump_threads()

    def test_half_open_partition_drill_socket_transport(self):
        """ISSUE 15 acceptance drill: a HALF-OPEN network partition of 1
        of 2 SOCKET-transport workers mid-traffic — router reads from the
        victim stall (no EOF, no error; its process stays alive and keeps
        decoding) while writes still land. The contract:

        * the partition is DETECTED from status-frame staleness alone
          (transport-liveness contract) and the victim is quarantined
          typed within budget;
        * a delivered-token stream in flight RESUMES token-exact on the
          survivor (same machinery as replica death — partitions ride the
          HEALTHY→QUARANTINED path unchanged);
        * the victim's shadowed never-answered tickets hand off to the
          survivor without spending caller failover budget;
        * the partitioned worker re-registers at a HIGHER incarnation
          epoch (heal: same process — its engine and radix survive), and
          every pre-partition frame it sent — buffered status frames AND
          the answers it kept computing for handed-off work — is dropped
          by the epoch fence (stale_frames > 0): a healed worker can
          never resurrect dead tickets or double-deliver stream chunks;
        * zero orphan processes/threads at teardown."""
        import dataclasses
        import multiprocessing

        from sentio_tpu.models.llama import LlamaConfig
        from sentio_tpu.models.tokenizer import ByteTokenizer
        from sentio_tpu.runtime.replica import ReplicaSet, WorkerRegistry
        from sentio_tpu.runtime.worker import ProcessReplica, WorkerSpec

        cfg = LlamaConfig.tiny()
        registry = WorkerRegistry("partition-drill", slots=2)
        spec = WorkerSpec(
            factory_kwargs=dict(
                model_config=dataclasses.asdict(cfg),
                engine_kwargs=dict(max_slots=2, page_size=8,
                                   max_pages_per_seq=4, steps_per_tick=2),
                service_kwargs=dict(retry_budget=1),
            ),
            auth_token="partition-drill", status_interval_s=0.05,
            reconnect=True, reconnect_backoff_s=0.2,
            router_silence_timeout_s=0.8,
        )
        tok = ByteTokenizer(cfg.vocab_size)
        kw = dict(build_timeout_s=300.0, transport_mode="socket",
                  registry=registry, partition_timeout_s=1.0,
                  ping_interval_s=0.2, heal_grace_s=15.0)
        # fresh collector for the drill: the zero-double-count assertion
        # below is an EQUALITY against the worker's cumulative registry,
        # which needs merge baselines that start at zero
        from sentio_tpu.infra.metrics import (MetricsCollector, get_metrics,
                                              set_metrics)

        old_collector = get_metrics()
        metrics = MetricsCollector()
        set_metrics(metrics)
        p0, p1 = _build_parallel(lambda i: ProcessReplica(
            spec, tok, replica_id=i, **kw))
        old_pid, old_epoch = p0.pid, p0.epoch
        # no-fault greedy reference from the survivor (seeded inits are
        # identical across workers — pinned by the parity suites) and the
        # VICTIM's radix primed so prefix affinity routes the drill
        # stream onto the replica that will be partitioned — concurrent
        # warms, the workers are independent processes
        ref_out = _build_parallel(lambda i: (
            p1.generate(self.PROMPT, max_new_tokens=16, temperature=0.0,
                        timeout_s=180)
            if i else
            p0.generate(self.PROMPT, max_new_tokens=2, temperature=0.0,
                        timeout_s=180)))
        expected = ref_out[1]
        assert len(expected.tokens) >= 4
        rs = ReplicaSet(
            [p0, p1],
            probe_interval_s=0.05, quarantine_backoff_s=0.1,
            failover_budget=1, rebuild_drain_s=0.5,
        )
        release = threading.Event()
        probe_results: dict = {}
        t_state: dict = {"armed": None, "detect": None}

        def probe(i):
            try:
                probe_results[i] = p0.generate(
                    f"partition handoff probe {i}", max_new_tokens=12,
                    timeout_s=120)
            except Exception as exc:  # noqa: BLE001 — asserted below
                probe_results[i] = exc

        def watch_detection():
            while t_state["detect"] is None:
                if t_state["armed"] is not None:
                    state = rs.health_summary()["replicas"][0]["state"]
                    if state != "HEALTHY":
                        t_state["detect"] = time.monotonic()
                        return
                time.sleep(0.01)

        watcher = threading.Thread(target=watch_detection, daemon=True)
        watcher.start()
        try:
            # a PRE-partition telemetry frame must merge at the victim's
            # original epoch — the fence assertions after heal need a
            # baseline that the stale buffer could plausibly double-count
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and \
                    metrics.worker_telemetry_epoch(0) != old_epoch:
                time.sleep(0.05)
            assert metrics.worker_telemetry_epoch(0) == old_epoch, (
                "no pre-partition telemetry frame merged")
            stats_out: dict = {}
            it = rs.generate_stream(self.PROMPT, max_new_tokens=16,
                                    temperature=0.0, timeout_s=120,
                                    stats_out=stats_out)
            pieces = [next(it)]  # ≥1 chunk DELIVERED before the partition
            # half-open partition: the router's reads from p0 wedge (its
            # frames buffer unread); router→worker writes keep succeeding
            faults.arm("transport.recv.r0", faults.FaultRule(
                stall_event=release, stall_s=120.0, times=1))
            t_state["armed"] = time.monotonic()
            # probes launched INTO the partition: their request frames
            # reach the live worker (writes work), its answers never come
            # back (reads stall) — they stay router-side shadowed until
            # the quarantine hands them to the survivor
            threads = [threading.Thread(target=probe, args=(i,),
                                        daemon=True) for i in range(2)]
            for t in threads:
                t.start()
            # the stream blocks at the partition, gets the typed death,
            # and resumes on the survivor — one uninterrupted iterator
            for piece in it:
                pieces.append(piece)
            assert "".join(pieces) == expected.text
            assert stats_out.get("resumed") == 1, stats_out
            assert stats_out.get("replayed_tokens", 0) >= 1, stats_out
            # detection came from staleness, within budget
            watcher.join(timeout=30)
            assert t_state["detect"] is not None, "partition never detected"
            assert t_state["detect"] - t_state["armed"] <= 5.0
            for t in threads:
                t.join(timeout=120)
            assert not any(t.is_alive() for t in threads), (
                "probe hung across the partition")
            for i, out in probe_results.items():
                assert isinstance(out, PagedResult), (i, out)
                assert out.finish_reason in ("stop", "length"), (i, out)
                assert out.replica_id == 1, (i, out)
            stats = rs.stats()
            assert stats["handed_off"] >= 2, stats["handed_off"]
            assert stats["stream_resumes"] >= 1
            # HEAL: the live partitioned worker re-registers at a higher
            # epoch — same process, fresh incarnation
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if rs.health_summary()["status"] == "healthy":
                    break
                time.sleep(0.05)
            summary = rs.health_summary()
            assert summary["status"] == "healthy", summary
            healed = rs._services[0]
            assert healed.epoch > old_epoch, "reconnect must bump the epoch"
            assert healed.pid == old_pid, (
                "expected HEAL (same process re-registered), got a respawn")
            # release the wedged read: the old connection drains its
            # buffered pre-partition frames straight into the epoch fence
            release.set()
            deadline = time.monotonic() + 30
            while registry.stale_frames(0) == 0 and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            assert registry.stale_frames(0) > 0, (
                "pre-partition frames were not stale-dropped")
            # ISSUE 16: telemetry continuity across the heal. The healed
            # incarnation's frames merge (the fence advances to its epoch)
            # and the age gauge snaps back from its partition climb
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and \
                    metrics.worker_telemetry_epoch(0) != healed.epoch:
                time.sleep(0.05)
            assert metrics.worker_telemetry_epoch(0) == healed.epoch, (
                "healed worker's telemetry never merged")
            assert healed.telemetry_age() is not None
            age = metrics.memory.gauges.get("worker_telemetry_age('0',)")
            assert age is not None and age < 10.0, (
                f"telemetry age gauge never recovered: {age}")
            # ZERO double count: the worker process survived the heal, so
            # its cumulative registry is one monotone series — the router's
            # merged total must EQUAL the last accepted cumulative. Any
            # pre-partition frame slipping past the fence would telescope
            # the deltas to MORE than the cumulative. (Retry around the
            # 1 Hz cadence: a frame landing between the two reads moves
            # both sides.)
            for _ in range(20):
                snap = (healed._telemetry or {}).get("series") or {}
                counts = snap.get("histo_count") or {}
                phase_keys = [k for k in counts
                              if k.startswith("tick_phase(")]
                totals_match = bool(phase_keys)
                for key in phase_keys:
                    phase = key[len("tick_phase('"):-len("',)")]
                    merged = metrics.memory.counters.get(
                        f"worker_tick_phase_ticks{('0', phase)}", 0.0)
                    if merged != counts[key]:
                        totals_match = False
                        break
                if totals_match and \
                        (healed._telemetry or {}).get("series") is snap:
                    break
                time.sleep(0.3)
            assert totals_match, (
                "router totals drifted from the worker's cumulative "
                "registry — pre-partition telemetry double-counted")
            # the healed set serves routed traffic
            ok = rs.generate("post partition routed sanity",
                             max_new_tokens=3, temperature=0.0,
                             timeout_s=120)
            assert ok.finish_reason in ("stop", "length")
        finally:
            release.set()
            faults.reset()
            rs.close()
            registry.close()
            set_metrics(old_collector)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and multiprocessing.active_children():
            time.sleep(0.05)
        assert multiprocessing.active_children() == [], (
            "orphan replica worker processes leaked"
        )
        _assert_no_pump_threads()
