"""Vector store registry + Qdrant REST adapter against an in-memory fake
Qdrant served through httpx.MockTransport — the reference's mock-client
test pattern (test_jina_embeddings.py there injects a mock httpx client);
here the fake implements enough of the REST surface (collection bootstrap,
upsert, count, scroll, search, batch search, delete) to check behavior,
including ranking parity with the in-tree TPU index on the same vectors.
"""

from __future__ import annotations

import json

import httpx
import numpy as np
import pytest

from sentio_tpu.models.document import Document
from sentio_tpu.ops.dense_index import TpuDenseIndex
from sentio_tpu.ops.vector_store import (
    QdrantVectorStore,
    VectorStoreError,
    get_vector_store,
)


class FakeQdrant:
    """Minimal in-memory Qdrant REST double with exact cosine scoring."""

    def __init__(self):
        self.collections: dict[str, dict] = {}

    def handler(self, request: httpx.Request) -> httpx.Response:
        path = request.url.path
        body = json.loads(request.content) if request.content else {}
        parts = [p for p in path.split("/") if p]
        if parts == ["collections"]:
            return self._ok({"collections": [{"name": n} for n in self.collections]})
        name = parts[1]
        if len(parts) == 2:
            if request.method == "GET":
                if name not in self.collections:
                    return httpx.Response(404, json={"status": {"error": "not found"}})
                return self._ok({"status": "green"})
            if request.method == "PUT":
                self.collections[name] = {"points": {}, "dim": body["vectors"]["size"]}
                return self._ok(True)
            if request.method == "DELETE":
                self.collections.pop(name, None)
                return self._ok(True)
        col = self.collections.get(name)
        if col is None:
            return httpx.Response(404, json={"status": {"error": "no collection"}})
        op = parts[-1]
        if op == "points" and request.method == "PUT":
            for pt in body["points"]:
                col["points"][pt["id"]] = pt
            return self._ok({"status": "completed"})
        if op == "points" and request.method == "POST":  # retrieve by ids
            return self._ok([
                {"id": pid, "payload": None} for pid in body["ids"] if pid in col["points"]
            ])
        if op == "count":
            return self._ok({"count": len(col["points"])})
        if op == "delete":
            for pid in body["points"]:
                col["points"].pop(pid, None)
            return self._ok({"status": "completed"})
        if op == "scroll":
            ids = sorted(col["points"])
            start = 0 if "offset" not in body else ids.index(body["offset"])
            page = ids[start : start + body["limit"]]
            nxt = ids[start + body["limit"]] if start + body["limit"] < len(ids) else None
            return self._ok({
                "points": [
                    {"id": pid, "payload": col["points"][pid]["payload"]} for pid in page
                ],
                "next_page_offset": nxt,
            })
        if op == "search":
            return self._ok(self._search(col, body))
        if op == "batch":  # .../points/search/batch
            return self._ok([self._search(col, s) for s in body["searches"]])
        return httpx.Response(400, json={"status": {"error": f"unhandled {path}"}})

    def _search(self, col, body):
        q = np.asarray(body["vector"], np.float32)
        qn = q / max(np.linalg.norm(q), 1e-9)
        scored = []
        for pid, pt in col["points"].items():
            v = np.asarray(pt["vector"], np.float32)
            vn = v / max(np.linalg.norm(v), 1e-9)
            scored.append((float(qn @ vn), pid))
        scored.sort(key=lambda t: (-t[0], t[1]))
        return [
            {"id": pid, "score": s, "payload": col["points"][pid]["payload"]}
            for s, pid in scored[: body["limit"]]
        ]

    @staticmethod
    def _ok(result):
        return httpx.Response(200, json={"status": "ok", "result": result})


@pytest.fixture()
def fake():
    return FakeQdrant()


@pytest.fixture()
def store(fake):
    return QdrantVectorStore(
        dim=8, collection="test", transport=httpx.MockTransport(fake.handler)
    )


def mk_docs_vecs(n=6, dim=8, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.standard_normal((n, dim)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    docs = [Document(text=f"doc {i}", id=f"d{i}", metadata={"i": i}) for i in range(n)]
    return docs, vecs


class TestQdrantAdapter:
    def test_add_count_search(self, store):
        docs, vecs = mk_docs_vecs()
        store.add(docs, vecs)
        assert store.size == 6
        hits = store.search(vecs[2], top_k=3)
        assert hits[0][0].id == "d2"
        assert hits[0][1] == pytest.approx(1.0, abs=1e-5)

    def test_upsert_same_id_overwrites(self, store):
        docs, vecs = mk_docs_vecs()
        store.add(docs, vecs)
        store.add([Document(text="updated", id="d0", metadata={})], vecs[:1])
        assert store.size == 6
        hits = store.search(vecs[0], top_k=1)
        assert hits[0][0].text == "updated"

    def test_delete(self, store):
        docs, vecs = mk_docs_vecs()
        store.add(docs, vecs)
        assert store.delete(["d1", "d3", "missing"]) == 2
        assert store.size == 4

    def test_documents_scroll_pagination(self, store):
        docs, vecs = mk_docs_vecs(n=600)  # > one 256-point scroll page
        store.add(docs, vecs)
        got = store.documents()
        assert len(got) == 600
        assert {d.id for d in got} == {d.id for d in docs}

    def test_retrieve_contract(self, store):
        docs, vecs = mk_docs_vecs()
        store.add(docs, vecs)
        out = store.retrieve(vecs[4], top_k=2)
        assert out[0].id == "d4"
        assert out[0].metadata["retriever"] == "qdrant"
        assert "score" in out[0].metadata

    def test_clear_drops_collection(self, store):
        docs, vecs = mk_docs_vecs()
        store.add(docs, vecs)
        store.clear()
        assert store.size == 0  # re-bootstraps empty

    def test_batch_search(self, store):
        docs, vecs = mk_docs_vecs()
        store.add(docs, vecs)
        batches = store.search_batch(vecs[:3], top_k=2)
        assert [b[0][0].id for b in batches] == ["d0", "d1", "d2"]

    def test_shape_mismatch_raises(self, store):
        docs, vecs = mk_docs_vecs()
        with pytest.raises(VectorStoreError):
            store.add(docs, vecs[:, :4])

    def test_unreachable_raises_store_error(self):
        def down(request):
            raise httpx.ConnectError("connection refused")

        s = QdrantVectorStore(dim=8, transport=httpx.MockTransport(down))
        with pytest.raises(VectorStoreError):
            s.search(np.zeros(8, np.float32))

    def test_payload_text_fallback(self, fake, store):
        """Payloads written by other tools use 'content' etc. — the adapter
        applies the reference's multi-key fallback (dense.py:76-104 there)."""
        store.add([Document(text="x", id="seed", metadata={})],
                  np.ones((1, 8), np.float32))
        pid = next(iter(fake.collections["test"]["points"]))
        fake.collections["test"]["points"][pid]["payload"] = {
            "content": "alt content", "doc_id": "seed", "extra": 1
        }
        hits = store.search(np.ones(8, np.float32), top_k=1)
        assert hits[0][0].text == "alt content"


class TestRankingParityWithTpuIndex:
    def test_same_ranking_as_dense_index(self, store):
        docs, vecs = mk_docs_vecs(n=40, seed=3)
        store.add(docs, vecs)
        tpu = TpuDenseIndex(dim=8, dtype="float32")
        tpu.add(docs, vecs)
        rng = np.random.default_rng(9)
        for _ in range(5):
            q = rng.standard_normal(8).astype(np.float32)
            a = [d.id for d, _ in store.search(q, top_k=5)]
            b = [d.id for d, _ in tpu.search(q, top_k=5)]
            assert a == b


class TestRegistry:
    def test_tpu_default(self):
        idx = get_vector_store("tpu", dim=16)
        assert isinstance(idx, TpuDenseIndex)

    def test_qdrant_entry(self):
        s = get_vector_store("qdrant", dim=16, url="http://example:6333",
                             transport=httpx.MockTransport(FakeQdrant().handler))
        assert isinstance(s, QdrantVectorStore)

    def test_unknown_raises(self):
        with pytest.raises(VectorStoreError):
            get_vector_store("hnswlib", dim=16)

    def test_container_respects_index_backend(self, settings):
        from sentio_tpu.config import EmbedderConfig
        from sentio_tpu.serve.dependencies import DependencyContainer

        settings.embedder = EmbedderConfig(provider="hash", dim=8)
        settings.retrieval.index_backend = "qdrant"
        c = DependencyContainer(settings=settings)
        assert isinstance(c.dense_index, QdrantVectorStore)


class TestIngestorRoutesThroughRegistry:
    def test_ingestor_uses_qdrant_backend(self, settings, fake, monkeypatch):
        """cli ingest with INDEX_BACKEND=qdrant must write to the external
        store the serving pods read, not a process-private index."""
        from sentio_tpu.config import EmbedderConfig
        from sentio_tpu.ops import vector_store as vs
        from sentio_tpu.ops.ingest import DocumentIngestor

        settings.embedder = EmbedderConfig(provider="hash", dim=8)
        settings.retrieval.index_backend = "qdrant"

        orig = vs.QdrantVectorStore

        def patched(*args, **kwargs):
            kwargs["transport"] = httpx.MockTransport(fake.handler)
            return orig(*args, **kwargs)

        monkeypatch.setattr(vs, "QdrantVectorStore", patched)
        ing = DocumentIngestor(settings=settings)
        assert isinstance(ing.dense_index, orig)
        stats = ing.ingest_document("TPUs multiply matrices.", metadata={})
        assert stats.chunks_stored >= 1
        assert fake.collections["sentio"]["points"]


class TestPooledResilience:
    """Reference parity: pooled clients + per-op breaker/retry + health loop
    (async_qdrant_store.py:50-266 there)."""

    def test_pool_round_robins_clients(self, fake):
        s = QdrantVectorStore(dim=8, collection="t",
                              transport=httpx.MockTransport(fake.handler),
                              pool_size=3)
        seen = [s._next_client() for _ in range(6)]
        assert len({id(c) for c in seen}) == 3
        assert [id(c) for c in seen[:3]] == [id(c) for c in seen[3:]]

    def test_transient_5xx_retries_then_succeeds(self, fake):
        calls = {"n": 0}

        def flaky(request):
            calls["n"] += 1
            if calls["n"] <= 2:
                return httpx.Response(503, text="overloaded")
            return fake.handler(request)

        s = QdrantVectorStore(dim=8, collection="t",
                              transport=httpx.MockTransport(flaky))
        out = s._request("GET", "/collections")
        assert out["status"] == "ok"
        assert calls["n"] == 3  # two 503s retried, third attempt succeeded

    def test_4xx_does_not_retry(self, fake):
        calls = {"n": 0}

        def bad(request):
            calls["n"] += 1
            return httpx.Response(422, text="bad request")

        s = QdrantVectorStore(dim=8, collection="t",
                              transport=httpx.MockTransport(bad))
        with pytest.raises(VectorStoreError):
            s._request("GET", "/collections")
        assert calls["n"] == 1

    def test_breaker_opens_and_fails_fast(self):
        calls = {"n": 0}

        def down(request):
            calls["n"] += 1
            raise httpx.ConnectError("refused")

        from sentio_tpu.infra.resilience import RetryPolicy
        from sentio_tpu.ops.vector_store import TransientStoreError

        s = QdrantVectorStore(
            dim=8, collection="breaker-t",
            transport=httpx.MockTransport(down),
            retry=RetryPolicy(max_attempts=1, retry_on=(TransientStoreError,)),
        )
        for _ in range(5):  # failure_threshold consecutive failures
            with pytest.raises(VectorStoreError):
                s._request("GET", "/collections")
        n_before = calls["n"]
        with pytest.raises(VectorStoreError, match="unavailable"):
            s._request("GET", "/collections")
        assert calls["n"] == n_before  # rejected by the breaker, no wire call

    def test_health_loop_caches_and_recovers(self, fake):
        state = {"up": False}

        def flapping(request):
            if not state["up"]:
                raise httpx.ConnectError("down")
            return fake.handler(request)

        import time as _t

        s = QdrantVectorStore(dim=8, collection="t",
                              transport=httpx.MockTransport(flapping),
                              health_interval_s=0.05)
        s.health()  # public surface starts the loop
        _t.sleep(0.2)
        assert s.health() is False
        state["up"] = True
        _t.sleep(0.2)
        assert s.health() is True
        s.close()
        assert s._health_thread is None

    def test_concurrent_searches_all_succeed(self, fake):
        import concurrent.futures

        s = QdrantVectorStore(dim=8, collection="t",
                              transport=httpx.MockTransport(fake.handler),
                              pool_size=4)
        docs, vecs = mk_docs_vecs(n=12)
        s.add(docs, vecs)
        with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
            futs = [ex.submit(s.search, vecs[i % 12], 3) for i in range(32)]
            results = [f.result() for f in futs]
        assert all(len(r) == 3 for r in results)
        assert {r[0][0].id for r in results} <= {d.id for d in docs}

    def test_4xx_storm_does_not_open_breaker(self, fake):
        calls = {"n": 0}

        def mixed(request):
            calls["n"] += 1
            if request.url.path == "/collections":
                return fake.handler(request)
            return httpx.Response(422, text="bad filter")

        s = QdrantVectorStore(dim=8, collection="breaker-4xx",
                              transport=httpx.MockTransport(mixed))
        for _ in range(8):  # past failure_threshold — must NOT open
            with pytest.raises(VectorStoreError):
                s._request("POST", "/collections/breaker-4xx/points/search", {})
        out = s._request("GET", "/collections")  # healthy op still flows
        assert out["status"] == "ok"
