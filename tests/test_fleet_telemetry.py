"""Fleet telemetry plane (ISSUE 16): cumulative-on-the-wire worker series
merged into router fleet families under ``{replica}`` labels, the
(pid, epoch) merge fence (HEAL keeps baselines, RESPAWN resets them, a
stale epoch's buffered frame is DROPPED — never double-counted), the
NTP-style ClockSync estimator, fleet Chrome-trace re-basing, the
``/debug/flight`` stitch helper, and the ``TELEMETRY_INTERVAL_S=0``
byte-parity contract on a ``_WorkerServer`` over a fake transport.

Everything here is process-free: real collectors, real server threads,
fake transports — the spawned-worker integration rides tests/test_worker.py
and the chaos drill in tests/test_chaos.py.
"""

import os
import queue
import threading
import time

import pytest

from sentio_tpu.infra.chrome_trace import _FLEET_PID_BASE, build_fleet_trace
from sentio_tpu.infra.flight import FlightRecorder
from sentio_tpu.infra.metrics import (
    MAX_WORKER_SERIES_PER_REPLICA,
    MetricsCollector,
    set_metrics,
)
from sentio_tpu.infra.phases import TICK_PHASES, sum_phase_totals
from sentio_tpu.runtime.transport import ClockSync, TransportError
from sentio_tpu.runtime.worker import (
    _F_PONG,
    _F_READY,
    _F_STATUS,
    _F_TELEMETRY,
    _TELEMETRY_STAT_KEYS,
    ProcessReplica,
    WorkerSpec,
    _WorkerServer,
)


def _ctr(mc: MetricsCollector, name: str, *labels) -> float:
    return mc.memory.counters.get(f"{name}{tuple(labels)}", 0.0)


def _series(ticks: float, device_wait_s: float, device_wait_n: float) -> dict:
    """A hand-built ``export_worker_series`` snapshot: one plain counter +
    one tick-phase histogram series, both CUMULATIVE."""
    key = "tick_phase('device_wait',)"
    return {
        "counters": {"ticks()": ticks},
        "histo_sum": {key: device_wait_s},
        "histo_count": {key: device_wait_n},
    }


class TestMergeWorkerSeries:
    def test_cumulative_frames_difference_into_deltas(self):
        rc = MetricsCollector()
        res = rc.merge_worker_series(0, _series(5.0, 1.0, 4.0),
                                     epoch=1, pid=111)
        assert res["accepted"] and res["merged"] == 2
        assert _ctr(rc, "worker_events", "0", "ticks") == 5.0
        assert _ctr(rc, "worker_tick_phase_seconds", "0", "device_wait") == 1.0
        assert _ctr(rc, "worker_tick_phase_ticks", "0", "device_wait") == 4.0
        # the next frame carries the GROWN cumulative; only the delta lands
        rc.merge_worker_series(0, _series(8.0, 1.5, 6.0), epoch=1, pid=111)
        assert _ctr(rc, "worker_events", "0", "ticks") == 8.0
        assert _ctr(rc, "worker_tick_phase_seconds", "0",
                    "device_wait") == pytest.approx(1.5)
        assert _ctr(rc, "worker_tick_phase_ticks", "0", "device_wait") == 6.0

    def test_dropped_frame_is_lossless(self):
        # cumulative-on-the-wire: skipping an intermediate frame changes
        # nothing — the next frame carries everything
        rc = MetricsCollector()
        rc.merge_worker_series(0, _series(5.0, 1.0, 4.0), epoch=1, pid=111)
        # frame with ticks=8 lost in transit; ticks=13 arrives
        rc.merge_worker_series(0, _series(13.0, 2.0, 9.0), epoch=1, pid=111)
        assert _ctr(rc, "worker_events", "0", "ticks") == 13.0
        assert _ctr(rc, "worker_tick_phase_ticks", "0", "device_wait") == 9.0

    def test_stale_epoch_frame_dropped_whole(self):
        rc = MetricsCollector()
        rc.merge_worker_series(0, _series(5.0, 1.0, 4.0), epoch=2, pid=111)
        # a healed worker's pre-partition buffer drains late: epoch 1
        res = rc.merge_worker_series(0, _series(9.0, 3.0, 8.0),
                                     epoch=1, pid=111)
        assert not res["accepted"] and res["merged"] == 0
        assert _ctr(rc, "worker_events", "0", "ticks") == 5.0
        assert _ctr(rc, "worker_telemetry_dropped", "0", "stale_epoch") == 1.0
        assert rc.worker_telemetry_epoch(0) == 2

    def test_heal_same_pid_keeps_baselines_no_double_count(self):
        rc = MetricsCollector()
        rc.merge_worker_series(0, _series(5.0, 1.0, 4.0), epoch=1, pid=111)
        # HEAL: same process, higher epoch — its registry never reset, so
        # the merged total must equal the last cumulative, not 5 + 8
        rc.merge_worker_series(0, _series(8.0, 1.5, 6.0), epoch=3, pid=111)
        assert _ctr(rc, "worker_events", "0", "ticks") == 8.0
        assert rc.worker_telemetry_epoch(0) == 3

    def test_respawn_pid_change_resets_baselines(self):
        rc = MetricsCollector()
        rc.merge_worker_series(0, _series(10.0, 2.0, 7.0), epoch=1, pid=111)
        # RESPAWN: fresh process restarts its registry from zero — its
        # first cumulative IS the first delta
        rc.merge_worker_series(0, _series(4.0, 0.5, 2.0), epoch=2, pid=222)
        assert _ctr(rc, "worker_events", "0", "ticks") == 14.0
        assert _ctr(rc, "worker_tick_phase_ticks", "0", "device_wait") == 9.0

    def test_regressing_cumulative_clamps_to_zero(self):
        rc = MetricsCollector()
        rc.merge_worker_series(0, _series(10.0, 2.0, 7.0), epoch=1, pid=111)
        rc.merge_worker_series(0, _series(3.0, 2.0, 7.0), epoch=1, pid=111)
        assert _ctr(rc, "worker_events", "0", "ticks") == 10.0
        # the regressed value becomes the new baseline; growth resumes
        rc.merge_worker_series(0, _series(5.0, 2.0, 7.0), epoch=1, pid=111)
        assert _ctr(rc, "worker_events", "0", "ticks") == 12.0

    def test_cardinality_guard_refuses_new_series_past_cap(self):
        rc = MetricsCollector()
        cap = 2 * MAX_WORKER_SERIES_PER_REPLICA
        flood = {"counters": {f"k{i}()": 1.0 for i in range(cap + 5)}}
        res = rc.merge_worker_series(0, flood, epoch=1, pid=111)
        assert res["accepted"] and res["merged"] == cap
        assert _ctr(rc, "worker_telemetry_dropped", "0", "cardinality") == 5.0
        # KNOWN series keep merging under the cap — only new ones refused
        grown = {"counters": {"k0()": 3.0}}
        rc.merge_worker_series(0, grown, epoch=1, pid=111)
        assert _ctr(rc, "worker_events", "0", "k0") == 3.0

    def test_malformed_key_dropped_not_fatal(self):
        rc = MetricsCollector()
        res = rc.merge_worker_series(
            0, {"counters": {"bad(((": 9.0, "ticks()": 2.0}},
            epoch=1, pid=111)
        assert res["accepted"] and res["merged"] == 1
        assert _ctr(rc, "worker_events", "0", "ticks") == 2.0
        assert _ctr(rc, "worker_telemetry_dropped", "0", "malformed") == 1.0

    def test_known_label_structures_keep_their_labels(self):
        # verify/xla_compiles have bounded label sets — they keep label
        # structure instead of flattening into the one `series` label
        rc = MetricsCollector()
        rc.merge_worker_series(0, {"counters": {
            "verify('sync', 'pass')": 3.0,
            "xla_compiles('decode',)": 2.0,
        }}, epoch=1, pid=111)
        assert _ctr(rc, "worker_verify", "0", "sync", "pass") == 3.0
        assert _ctr(rc, "worker_compiles", "0", "decode") == 2.0

    def test_telemetry_age_gauge(self):
        rc = MetricsCollector()
        rc.record_telemetry_age(1, 12.5)
        assert rc.memory.gauges["worker_telemetry_age('1',)"] == 12.5
        rc.record_telemetry_age(1, 0.0)
        assert rc.memory.gauges["worker_telemetry_age('1',)"] == 0.0


# the frozen /metrics manifest (satellite 3): the fleet families a
# process/socket-mode router must expose once worker telemetry merges —
# renaming any of these breaks dashboards and the monitoring.yaml rules
FLEET_SERIES_MANIFEST = (
    "sentio_tpu_worker_tick_phase_seconds_total",
    "sentio_tpu_worker_tick_phase_ticks_total",
    "sentio_tpu_worker_verify_total",
    "sentio_tpu_worker_compiles_total",
    "sentio_tpu_worker_telemetry_age_seconds",
    "sentio_tpu_replica_stat",
)


class TestSeriesManifestParity:
    @pytest.fixture()
    def pair(self):
        """(worker-side collector, router collector): the worker records
        through the SAME record_* API thread mode uses, the router merges
        its exported snapshot — parity by construction."""
        pytest.importorskip("prometheus_client")
        wc = MetricsCollector()
        wc.record_tick_phases({p: 0.001 for p in TICK_PHASES})
        wc.record_verify("sync", "pass")
        wc.record_compiles("decode")
        rc = MetricsCollector()
        res = rc.merge_worker_series(0, wc.export_worker_series(),
                                     epoch=1, pid=42)
        assert res["accepted"]
        rc.record_telemetry_age(0, 0.0)
        rc.set_replica_stat(0, "pool_hbm_bytes", 2048.0)
        return wc, rc

    def test_fleet_manifest_present_with_replica_label(self, pair):
        _, rc = pair
        text = rc.export_prometheus().decode()
        for family in FLEET_SERIES_MANIFEST:
            lines = [ln for ln in text.splitlines()
                     if ln.startswith(family + "{")]
            assert lines, f"{family} missing from /metrics"
            assert any('replica="0"' in ln for ln in lines), (
                f"{family} lost its replica label:\n" + "\n".join(lines))
        # every tick phase appears — the phase label set is the full
        # bounded TICK_PHASES vocabulary, same as thread mode's histogram
        for phase in TICK_PHASES:
            assert f'phase="{phase}"' in text

    def test_pool_bytes_rides_the_same_gauge_as_thread_mode(self, pair):
        # thread mode publishes pool occupancy via set_replica_stat; the
        # telemetry ingest calls the SAME method — the exported sample is
        # byte-identical across replica modes
        _, rc = pair
        tc = MetricsCollector()
        tc.set_replica_stat(0, "pool_hbm_bytes", 2048.0)
        want = [ln for ln in tc.export_prometheus().decode().splitlines()
                if ln.startswith("sentio_tpu_replica_stat{")]
        got = [ln for ln in rc.export_prometheus().decode().splitlines()
               if ln.startswith("sentio_tpu_replica_stat{")]
        assert want and want == got

    def test_worker_side_names_unchanged(self, pair):
        # the worker's own registry keeps the native (un-prefixed) names —
        # the fleet view is a ROUTER rename, not a worker one
        wc, _ = pair
        text = wc.export_prometheus().decode()
        assert "sentio_tpu_tick_phase_seconds" in text
        assert "sentio_tpu_verify_total" in text
        assert "sentio_tpu_xla_compiles_total" in text


class TestClockSync:
    def test_empty_estimator_returns_none(self):
        assert ClockSync().estimate() is None

    def test_offset_and_rtt_from_one_exchange(self):
        cs = ClockSync()
        cs.add_sample(10.0, 10.010, 100.0)
        est = cs.estimate()
        assert est["rtt_s"] == pytest.approx(0.010)
        assert est["offset_s"] == pytest.approx(100.0 - 10.005)
        assert est["uncertainty_s"] == pytest.approx(0.005)
        assert est["samples"] == 1

    def test_min_rtt_sample_wins(self):
        # Cristian's algorithm: the fastest exchange has the tightest bound
        cs = ClockSync()
        cs.add_sample(10.0, 10.010, 100.0)
        cs.add_sample(20.0, 20.002, 110.0)
        est = cs.estimate()
        assert est["rtt_s"] == pytest.approx(0.002)
        assert est["offset_s"] == pytest.approx(110.0 - 20.001)
        assert est["uncertainty_s"] == pytest.approx(0.001)
        assert est["samples"] == 2

    def test_negative_rtt_clamped(self):
        cs = ClockSync()
        cs.add_sample(5.0, 4.9, 50.0)  # clock jitter: t_rx < t_tx
        est = cs.estimate()
        assert est["rtt_s"] == 0.0 and est["uncertainty_s"] == 0.0
        assert est["offset_s"] == pytest.approx(45.0)

    def test_window_evicts_old_samples(self):
        cs = ClockSync(window=2)
        cs.add_sample(1.0, 1.001, 10.0)   # best rtt, but will be evicted
        cs.add_sample(2.0, 2.020, 20.0)
        cs.add_sample(3.0, 3.010, 30.0)
        est = cs.estimate()
        assert est["rtt_s"] == pytest.approx(0.010)
        assert est["samples"] == 3


class TestPhaseAndFlightHelpers:
    def test_sum_phase_totals_folds_rows(self):
        rows = [
            {"phase_seconds": {"device_wait": 1.0, "other": 0.5},
             "duty_elapsed_s": 2.0},
            {"phase_seconds": {"device_wait": 0.25}, "duty_elapsed_s": 1.0},
            {"worker_dead": 1},  # dead worker's fallback row: contributes 0
        ]
        totals, elapsed = sum_phase_totals(rows)
        assert totals == {"device_wait": 1.25, "other": 0.5}
        assert elapsed == pytest.approx(3.0)

    def test_flight_origin_and_highwater(self):
        rec = FlightRecorder(max_ticks=4, max_requests=2)
        assert isinstance(rec.origin(), float)
        for i in range(6):
            rec.record_tick(tick=i, pump_ms=1.0)
        rec.start_request("a")
        rec.start_request("b")
        rec.start_request("c")  # evicts oldest finished/active per policy
        hw = rec.highwater()
        assert hw["ticks_recorded"] == 6
        assert hw["ticks_retained"] == 4  # ring bounded
        assert hw["requests_retained"] <= 2
        # the cadence frame ships ONLY these bounded marks
        assert set(hw) == {"ticks_recorded", "ticks_retained",
                           "requests_retained", "requests_dropped"}


class TestFleetTrace:
    def _workers(self, uncertainty=0.0005):
        worker_tick = {"tick": 7, "t_s": 2.0, "pump_ms": 10.0,
                       "phase_ms": {"other": 10.0}, "replica": 0}
        worker_record = {
            "request_id": "w1", "t_start_s": 1.5, "latency_ms": 100.0,
            "engine": {"replica_id": 0, "t_submit_s": 1.6, "tokens": 4},
        }
        return [{"replica": 1, "epoch": 2, "shift_s": 3.0,
                 "uncertainty_s": uncertainty,
                 "ticks": [worker_tick], "records": [worker_record]}]

    def test_worker_lane_pid_name_and_rebase(self):
        router_tick = {"tick": 1, "t_s": 1.0, "pump_ms": 4.0, "replica": 0}
        trace = build_fleet_trace(self._workers(),
                                  router_ticks=[router_tick])
        events = trace["traceEvents"]
        pid = _FLEET_PID_BASE * 2 + 2  # replica 1, epoch 2
        names = {e["pid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert names[pid] == "worker 1 epoch 2 (clock ±0.5ms)"
        assert names[0] == "replica 0"  # router lane untouched
        ticks = {e["name"]: e for e in events if e.get("ph") == "X"}
        # worker tick re-based: ends at t_s + shift, starts pump_ms earlier
        assert ticks["tick 7"]["pid"] == pid
        assert ticks["tick 7"]["ts"] == pytest.approx((5.0 - 0.010) * 1e6)
        assert ticks["tick 1"]["ts"] == pytest.approx((1.0 - 0.004) * 1e6)
        # worker request span shifted onto the router timeline too
        req = ticks["request w1"]
        assert req["pid"] == pid
        assert req["ts"] == pytest.approx(4.5 * 1e6)

    def test_unaligned_clock_is_stated_not_guessed(self):
        trace = build_fleet_trace(self._workers(uncertainty=None))
        names = [e["args"]["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert "worker 1 epoch 2 (clock unaligned)" in names

    def test_incarnations_get_separate_lanes(self):
        tick = {"tick": 1, "t_s": 1.0, "pump_ms": 1.0, "replica": 0}
        workers = [
            {"replica": 0, "epoch": 1, "shift_s": 0.0,
             "uncertainty_s": 0.0, "ticks": [dict(tick)], "records": []},
            {"replica": 0, "epoch": 2, "shift_s": 0.0,
             "uncertainty_s": 0.0, "ticks": [dict(tick)], "records": []},
        ]
        trace = build_fleet_trace(workers)
        pids = {e["pid"] for e in trace["traceEvents"]}
        assert _FLEET_PID_BASE + 1 in pids and _FLEET_PID_BASE + 2 in pids


class TestReplicaClockIngest:
    def _bare(self) -> ProcessReplica:
        pr = object.__new__(ProcessReplica)
        pr.replica_id = 0
        pr.epoch = 1
        pr._telemetry = {}
        pr._telemetry_ts = 0.0
        pr._worker_origin_s = None
        pr._clock = ClockSync()
        return pr

    def test_flight_shift_math(self):
        pr = self._bare()
        assert pr.flight_shift_s(5.0) == (0.0, None)  # origin unknown
        pr._worker_origin_s = 100.0
        shift, bound = pr.flight_shift_s(5.0)
        assert shift == pytest.approx(95.0) and bound is None  # offset≈0
        pr._clock.add_sample(10.0, 10.002, 52.001)  # offset = 42.0 exactly
        shift, bound = pr.flight_shift_s(5.0)
        assert shift == pytest.approx(100.0 - 42.0 - 5.0)
        assert bound == pytest.approx(0.001)

    def test_ingest_pong_feeds_estimator(self):
        pr = self._bare()
        t0 = time.perf_counter()
        pr._ingest_pong({"t_tx": t0 - 0.002, "t_worker": t0,
                         "origin_s": 7.5})
        assert pr._worker_origin_s == 7.5
        assert pr.clock_sync() is not None
        pr._ingest_pong({})  # malformed pong: ignored, not fatal
        assert pr.clock_sync()["samples"] == 1

    def test_ingest_telemetry_merges_and_fences(self):
        pr = self._bare()
        fresh = MetricsCollector()
        set_metrics(fresh)
        try:
            payload = {"series": _series(5.0, 1.0, 4.0), "pid": 111,
                       "origin_s": 7.5,
                       "stats": {"pool_hbm_bytes": 2048.0, "free_pages": 60}}
            pr._ingest_telemetry(payload, epoch=2)
            assert _ctr(fresh, "worker_events", "0", "ticks") == 5.0
            assert pr.telemetry_age() is not None
            assert pr.telemetry_age() < 5.0
            assert pr._worker_origin_s == 7.5
            assert fresh.memory.gauges["worker_telemetry_age('0',)"] == 0.0
            assert fresh.memory.gauges[
                "replica_0_pool_hbm_bytes()"] == 2048.0
            # a stale-epoch frame neither merges nor refreshes the cache
            ts_before = pr._telemetry_ts
            pr._ingest_telemetry(
                {"series": _series(9.0, 2.0, 8.0), "pid": 111}, epoch=1)
            assert _ctr(fresh, "worker_events", "0", "ticks") == 5.0
            assert pr._telemetry_ts == ts_before
            assert _ctr(fresh, "worker_telemetry_dropped", "0",
                        "stale_epoch") == 1.0
        finally:
            set_metrics(None)


class _FakeTransport:
    """In-process stand-in for a pipe/socket transport: ``send`` collects
    frames, ``recv`` drains a queue (``(frame, epoch)`` tuples), a sentinel
    raises ``TransportError`` like a router hangup would."""

    _CLOSE = object()

    def __init__(self):
        self.sent: list = []
        self._q: queue.Queue = queue.Queue()

    def send(self, frame) -> None:
        self.sent.append(frame)

    def recv(self, timeout_s=None):
        try:
            item = self._q.get(timeout=timeout_s)
        except queue.Empty:
            return None
        if item is self._CLOSE:
            raise TransportError("router hung up")
        return item, 0

    def push(self, frame) -> None:
        self._q.put((0, *frame) if len(frame) == 2 else frame)

    def kinds(self) -> list:
        return [f[1] for f in list(self.sent)]


class _StubEngine:
    page_size = 8
    max_slots = 2


class _StubService:
    engine = _StubEngine()
    max_queue = 4
    default_timeout_s = 1.0
    default_deadline_s = 1.0
    retry_budget = 0
    tick_stall_budget_s = 0.0
    broken = False
    closed = False
    tick_failure_count = 0
    pump_leaked_count = 0

    def heartbeat_age(self):
        return 0.0

    def backlog(self):
        return 0

    def projected_wait(self):
        return 0.0

    def duty_cycle(self):
        return {"host": 0.0, "device": 0.0, "idle": 1.0}

    def stats(self):
        return {"phase_seconds": {"other": 0.1}, "duty_elapsed_s": 0.2,
                "duty_cycle": self.duty_cycle(), "queued": 0,
                "internal_debug_blob": object()}  # NOT a telemetry key


def _run_server(telemetry_interval_s: float, status_interval_s: float = 30.0):
    spec = WorkerSpec(factory_kwargs={},
                      status_interval_s=status_interval_s,
                      telemetry_interval_s=telemetry_interval_s)
    transport = _FakeTransport()
    server = _WorkerServer(transport, spec, svc=_StubService())
    thread = threading.Thread(target=server.run, daemon=True)
    thread.start()
    deadline = time.perf_counter() + 5.0
    while _F_READY not in transport.kinds():
        assert time.perf_counter() < deadline, "worker never sent ready"
        time.sleep(0.01)
    return server, transport, thread


def _shutdown(transport: _FakeTransport, thread: threading.Thread) -> None:
    transport.push((0, "__shutdown__", {}))
    thread.join(timeout=5.0)
    assert not thread.is_alive()


class TestWorkerServerTelemetryPlane:
    def test_interval_zero_is_byte_identical(self):
        """TELEMETRY_INTERVAL_S=0 parity: no telemetry thread, no pong for
        a bare ping — the wire carries exactly the pre-telemetry frames."""
        server, transport, thread = _run_server(telemetry_interval_s=0.0)
        try:
            transport.push((0, "__ping__", {}))  # bare: telemetry off
            time.sleep(0.5)
            kinds = transport.kinds()
            assert _F_TELEMETRY not in kinds
            assert _F_PONG not in kinds
            assert [k for k in kinds if k not in (_F_STATUS,)] == [_F_READY]
            assert not any(t.name == "worker-telemetry"
                           for t in threading.enumerate())
        finally:
            _shutdown(transport, thread)
        assert server.outcome == "shutdown"

    def test_interval_on_ships_frames_and_pongs(self):
        server, transport, thread = _run_server(telemetry_interval_s=0.05)
        try:
            deadline = time.perf_counter() + 5.0
            while _F_TELEMETRY not in transport.kinds():
                assert time.perf_counter() < deadline, "no telemetry frame"
                time.sleep(0.01)
            frame = next(f for f in list(transport.sent)
                         if f[1] == _F_TELEMETRY)
            req_id, _, payload = frame
            assert req_id == 0  # unsolicited
            assert set(payload["series"]) == {"counters", "histo_count",
                                              "histo_sum"}
            # stats ship ONLY the bounded subset — never arbitrary keys
            assert set(payload["stats"]) <= set(_TELEMETRY_STAT_KEYS)
            assert payload["stats"]["phase_seconds"] == {"other": 0.1}
            assert set(payload["flight"]) == {
                "ticks_recorded", "ticks_retained", "requests_retained",
                "requests_dropped"}
            assert payload["pid"] == os.getpid()
            assert isinstance(payload["origin_s"], float)
            assert isinstance(payload["t_worker"], float)
            # a stamped ping gets a pong echoing the transmit stamp
            transport.push((0, "__ping__", {"t_tx": 123.25}))
            while _F_PONG not in transport.kinds():
                assert time.perf_counter() < deadline, "no pong"
                time.sleep(0.01)
            pong = next(f for f in list(transport.sent) if f[1] == _F_PONG)
            assert pong[2]["t_tx"] == 123.25
            assert pong[2]["pid"] == os.getpid()
            assert isinstance(pong[2]["t_worker"], float)
        finally:
            _shutdown(transport, thread)

    def test_link_loss_ends_incarnation(self):
        server, transport, thread = _run_server(telemetry_interval_s=0.0)
        transport._q.put(_FakeTransport._CLOSE)
        thread.join(timeout=5.0)
        assert not thread.is_alive()
        assert server.outcome == "link_lost"


class TestStitchFlightRecord:
    def _router_record(self) -> dict:
        return {"request_id": "r1", "t_start_s": 10.0, "latency_ms": 50.0,
                "engine": {"queue_depth": 1}}

    def _worker_record(self) -> dict:
        return {
            "request_id": "r1",
            "engine": {"t_submit_s": 1.0, "tokens": 5, "replica_id": 0},
            "ticks": [{"tick": 3, "t_s": 2.0, "pump_ms": 4.0,
                       "phase_ms": {"device_wait": 3.0, "other": 1.0}}],
            "ticks_truncated": True,
        }

    class _Svc:
        def __init__(self, record=None, fail=False, replica_id=0,
                     shift=(3.0, 0.0005)):
            self.replica_id = replica_id
            self.epoch = 2
            self._record = record
            self._fail = fail
            self._shift = shift

        def fetch_flight(self, request_id=None, last=None, timeout_s=5.0):
            if self._fail:
                raise RuntimeError("worker gone")
            return {"record": self._record, "replica": self.replica_id,
                    "epoch": self.epoch}

        def flight_shift_s(self, router_origin_s):
            return self._shift

    class _Container:
        def __init__(self, service):
            self._service = service

        def peek(self, name):
            return self._service

    def _stitch(self, services, record):
        pytest.importorskip("aiohttp")
        from sentio_tpu.serve.app import _stitch_flight_record

        class _ReplicaSet:
            _services = services

        return _stitch_flight_record(self._Container(_ReplicaSet()),
                                     "r1", record)

    def test_thread_mode_is_local(self):
        pytest.importorskip("aiohttp")
        from sentio_tpu.serve.app import _stitch_flight_record

        record = self._router_record()
        out = _stitch_flight_record(self._Container(object()), "r1", record)
        assert out["engine_window"] == "local"
        assert "replicas_unavailable" not in out

    def test_stitched_record_rebases_and_conserves(self):
        out = self._stitch([self._Svc(record=self._worker_record())],
                           self._router_record())
        assert out["engine_window"] == "stitched"
        assert out["engine_replica"] == 0 and out["engine_epoch"] == 2
        # worker truth merged IN, router-only fields kept
        assert out["engine"]["tokens"] == 5
        assert out["engine"]["queue_depth"] == 1
        assert out["engine"]["t_submit_s"] == pytest.approx(4.0)  # +shift
        assert out["ticks"][0]["t_s"] == pytest.approx(5.0)
        assert out["ticks_truncated"] is True
        assert out["clock_uncertainty_s"] == pytest.approx(0.0005)
        # per-tick phase conservation survives the re-base (tier-1 gate:
        # the shift moves timestamps, never durations)
        for tick in out["ticks"]:
            assert sum(tick["phase_ms"].values()) == pytest.approx(
                tick["pump_ms"], rel=0.05, abs=0.5)

    def test_dead_worker_named_not_silent(self):
        out = self._stitch(
            [self._Svc(fail=True, replica_id=0),
             self._Svc(record=self._worker_record(), replica_id=1)],
            self._router_record())
        assert out["engine_window"] == "stitched"
        assert out["replicas_unavailable"] == [
            {"replica": 0, "error": "RuntimeError"}]

    def test_no_owner_is_remote(self):
        out = self._stitch([self._Svc(record=None)], self._router_record())
        assert out["engine_window"] == "remote"
        assert "ticks" not in out
