"""Checkpoint/resume subsystem: atomic writes, bf16 round-trip, retention,
corruption fallback, and sharded restore onto the virtual 8-device mesh."""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentio_tpu.runtime.checkpoint import (
    CheckpointError,
    CheckpointManager,
    load_pytree,
    save_pytree,
)


@pytest.fixture()
def tree():
    return {
        "dense": {
            "kernel": np.arange(12, dtype=np.float32).reshape(3, 4),
            "bias": np.zeros(4, np.float32),
        },
        "steps": np.int64(7),
        "stack": [np.ones(2, np.float32), np.full(2, 3.0, np.float32)],
    }


class TestSaveLoad:
    def test_round_trip(self, tmp_path, tree):
        save_pytree(tmp_path / "ck", tree, meta={"note": "hello"})
        got, meta = load_pytree(tmp_path / "ck")
        assert meta == {"note": "hello"}
        np.testing.assert_array_equal(got["dense"]["kernel"], tree["dense"]["kernel"])
        assert got["steps"] == 7
        assert isinstance(got["stack"], list) and len(got["stack"]) == 2
        np.testing.assert_array_equal(got["stack"][1], tree["stack"][1])

    def test_bfloat16_round_trip(self, tmp_path):
        arr = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8)), jnp.bfloat16)
        save_pytree(tmp_path / "ck", {"w": arr})
        got, _ = load_pytree(tmp_path / "ck")
        assert str(got["w"].dtype) == "bfloat16"
        np.testing.assert_array_equal(
            np.asarray(got["w"]).view(np.uint16), np.asarray(arr).view(np.uint16)
        )

    def test_device_arrays_pulled_to_host(self, tmp_path):
        save_pytree(tmp_path / "ck", {"x": jnp.arange(5)})
        got, _ = load_pytree(tmp_path / "ck")
        np.testing.assert_array_equal(got["x"], np.arange(5))

    def test_missing_checkpoint_raises(self, tmp_path):
        with pytest.raises(CheckpointError):
            load_pytree(tmp_path / "nope")

    def test_overwrite_is_atomic_replace(self, tmp_path, tree):
        save_pytree(tmp_path / "ck", tree)
        save_pytree(tmp_path / "ck", {"only": np.ones(1, np.float32)})
        got, _ = load_pytree(tmp_path / "ck")
        assert list(got) == ["only"]

    def test_no_pickle_on_load(self, tmp_path, tree):
        # manifest-declared arrays load with allow_pickle=False; object leaves
        # are refused at save time
        with pytest.raises(CheckpointError):
            save_pytree(tmp_path / "ck", {"bad": np.array([object()])})


class TestShardedRestore:
    def test_restore_into_named_sharding(self, tmp_path):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.array(jax.devices()[:8]).reshape(8)
        mesh = Mesh(devs, ("tp",))
        w = np.random.default_rng(1).standard_normal((16, 32)).astype(np.float32)
        save_pytree(tmp_path / "ck", {"w": w})
        sh = {"w": NamedSharding(mesh, P(None, "tp"))}
        got, _ = load_pytree(tmp_path / "ck", shardings=sh)
        assert got["w"].sharding == sh["w"]
        np.testing.assert_array_equal(np.asarray(got["w"]), w)


class TestManager:
    def test_save_restore_latest(self, tmp_path, tree):
        mgr = CheckpointManager(tmp_path, keep=3)
        mgr.save(1, {"params": tree}, meta={"step": 1})
        mgr.save(5, {"params": tree, "extra": {"x": np.ones(2, np.float32)}})
        assert mgr.all_steps() == [1, 5]
        step, trees, _ = mgr.restore()
        assert step == 5 and set(trees) == {"params", "extra"}

    def test_retention_gc(self, tmp_path):
        mgr = CheckpointManager(tmp_path, keep=2)
        for s in range(5):
            mgr.save(s, {"t": {"x": np.full(1, s, np.float32)}})
        assert mgr.all_steps() == [3, 4]

    def test_corrupt_newest_falls_back(self, tmp_path, tree):
        mgr = CheckpointManager(tmp_path, keep=5)
        mgr.save(1, {"params": tree})
        mgr.save(2, {"params": tree})
        # corrupt step 2's manifest
        mf = tmp_path / "step_00000002" / "params" / "manifest.json"
        mf.write_text("{not json")
        step, trees, _ = mgr.restore()
        assert step == 1

    def test_incomplete_step_invisible(self, tmp_path, tree):
        mgr = CheckpointManager(tmp_path, keep=5)
        mgr.save(1, {"params": tree})
        # simulate a crashed save: directory without .complete marker
        (tmp_path / "step_00000009").mkdir()
        assert mgr.all_steps() == [1]
        assert mgr.latest_step() == 1

    def test_restore_empty_raises(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        with pytest.raises(CheckpointError):
            mgr.restore()

    def test_model_params_round_trip(self, tmp_path):
        from sentio_tpu.models.llama import LlamaConfig, init_llama

        cfg = LlamaConfig.tiny()
        params = init_llama(jax.random.PRNGKey(0), cfg)
        mgr = CheckpointManager(tmp_path)
        mgr.save(0, {"params": params}, meta={"config": cfg.__dict__})
        step, trees, metas = mgr.restore()
        assert metas["params"]["config"]["dim"] == cfg.dim
        got, want = trees["params"], params
        for path in (["embed_tokens", "embedding"], ["layers_0", "attn", "wq", "kernel"]):
            g, w = got, want
            for k in path:
                g, w = g[k], w[k]
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


class TestReviewRegressions:
    def test_truncated_npz_falls_back(self, tmp_path, tree):
        """Power loss can truncate arrays.npz → zipfile.BadZipFile must fall
        back to the previous step, not abort restore."""
        mgr = CheckpointManager(tmp_path, keep=5)
        mgr.save(1, {"params": tree})
        mgr.save(2, {"params": tree})
        npz = tmp_path / "step_00000002" / "params" / "arrays.npz"
        npz.write_bytes(npz.read_bytes()[: npz.stat().st_size // 2])
        step, _, _ = mgr.restore()
        assert step == 1

    def test_tuple_round_trips_as_tuple(self, tmp_path):
        """Optax states are tuple pytrees — a list on restore changes the
        treedef and breaks shardings= application."""
        t = {"opt": (np.ones(2, np.float32), {"mu": np.zeros(3, np.float32)})}
        save_pytree(tmp_path / "ck", t)
        got, _ = load_pytree(tmp_path / "ck")
        assert isinstance(got["opt"], tuple)
        assert isinstance(got["opt"][1], dict)
        jax.tree.map(lambda a, b: None, t, got)  # same treedef

    def test_non_string_dict_key_rejected(self, tmp_path):
        with pytest.raises(CheckpointError):
            save_pytree(tmp_path / "ck", {3: np.ones(1, np.float32)})

    def test_overwrite_crash_window_leaves_a_checkpoint(self, tmp_path, tree):
        """The old dir is renamed aside (atomic) before the new one replaces
        it — at no point is the destination absent."""
        save_pytree(tmp_path / "ck", tree)
        save_pytree(tmp_path / "ck", tree)  # exercise the swap path
        got, _ = load_pytree(tmp_path / "ck")
        assert "dense" in got
        assert not list(tmp_path.glob(".old-*")) and not list(tmp_path.glob(".tmp-*"))

    def test_manager_sweeps_stale_tmp(self, tmp_path):
        (tmp_path / ".tmp-step-dead").mkdir(parents=True)
        (tmp_path / ".old-step_00000001-123").mkdir(parents=True)
        CheckpointManager(tmp_path)
        assert not list(tmp_path.glob(".tmp-*")) and not list(tmp_path.glob(".old-*"))

    def test_restore_returns_per_tree_metas(self, tmp_path, tree):
        """A step assembled from separate save_pytree calls keeps each
        tree's own meta — the manager must not collapse them to one."""
        from sentio_tpu.runtime.checkpoint import save_pytree as sp

        step_dir = tmp_path / "step_00000003"
        sp(step_dir / "params", tree, meta={"config": {"dim": 64}})
        sp(step_dir / "zindex", {"x": np.ones(1, np.float32)}, meta={"rows": 1})
        (step_dir / ".complete").write_text("1")
        mgr = CheckpointManager(tmp_path)
        _, trees, metas = mgr.restore()
        assert metas["params"]["config"]["dim"] == 64
        assert metas["zindex"]["rows"] == 1
