# sentio-tpu serving image.
#
# Parity with the reference's Dockerfile (python slim, non-root, curl
# healthcheck, single server process) re-based for TPU hosts: the image is
# built FROM a JAX TPU base so libtpu and the TPU runtime are present, and
# the server binds the host's TPU devices (run with --privileged or the TPU
# device plugin on GKE). CPU-only dev: build with
#   docker build --build-arg BASE=python:3.12-slim .
# and the server falls back to jax[cpu] semantics (JAX_PLATFORMS=cpu).

ARG BASE=us-docker.pkg.dev/ml-images/jax/jax-tpu:latest
FROM ${BASE}

WORKDIR /app

# the JAX TPU base ships the jax stack; slim/CPU bases need the runtime deps
COPY requirements.txt ./
RUN python -c "import jax, aiohttp, httpx, einops, optax" 2>/dev/null \
    || pip install --no-cache-dir -r requirements.txt

COPY sentio_tpu/ sentio_tpu/
COPY prompts/ prompts/
COPY bench.py ./

# the C++ BM25 core builds on first use when a toolchain exists; bake it at
# image build time so runtime containers need no compiler
RUN python -c "from sentio_tpu import native; native.load_bm25()" || true

RUN useradd --create-home --uid 10001 sentio \
    && chown -R sentio:sentio /app
USER sentio

ENV PYTHONUNBUFFERED=1 \
    SENTIO_HOST=0.0.0.0 \
    SENTIO_PORT=8000

EXPOSE 8000
HEALTHCHECK --interval=30s --timeout=5s --start-period=120s --retries=3 \
    CMD python -c "import urllib.request,os; urllib.request.urlopen(f'http://127.0.0.1:{os.environ.get(\"SENTIO_PORT\",8000)}/health', timeout=4)"

CMD ["python", "-m", "sentio_tpu.cli", "serve"]
