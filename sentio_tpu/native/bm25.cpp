// Native BM25 scoring core — the host-side hot loop of sparse retrieval.
//
// The reference delegates million-doc sparse retrieval to Lucene via
// Pyserini (/root/reference/src/core/retrievers/sparse.py:206-276, a JVM
// dependency); this is the equivalent native backend for the TPU VM host,
// scoring a CSR postings index (built by sentio_tpu/ops/bm25.py, which owns
// tokenization and vocab so Python and native scores agree bit-for-bit on
// the same inputs).
//
// The index arrays are BORROWED from numpy (zero-copy): the Python wrapper
// keeps them alive for the handle's lifetime. C ABI throughout — consumed
// via ctypes, no pybind11.
//
// Scoring math (mirrors BM25Index.scores):
//   contrib = idf[t] * (tf * (k1 + 1) / (tf + norm[doc]) + delta)
// accumulated over query-term occurrences; norm[d] = k1*(1-b+b*dl/avgdl)
// is precomputed Python-side.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

struct SBm25 {
  int32_t n_docs;
  int32_t n_terms;
  const int64_t* term_offsets;  // [n_terms + 1]
  const int32_t* post_docs;     // [nnz]
  const float* post_tfs;        // [nnz]
  const float* idf;             // [n_terms]
  const float* norm;            // [n_docs]
  float k1;
  float delta;
};

void* sbm25_create(int32_t n_docs, int32_t n_terms, const int64_t* term_offsets,
                   const int32_t* post_docs, const float* post_tfs,
                   const float* idf, const float* norm, float k1, float delta) {
  auto* h = new SBm25();
  h->n_docs = n_docs;
  h->n_terms = n_terms;
  h->term_offsets = term_offsets;
  h->post_docs = post_docs;
  h->post_tfs = post_tfs;
  h->idf = idf;
  h->norm = norm;
  h->k1 = k1;
  h->delta = delta;
  return h;
}

void sbm25_destroy(void* handle) { delete static_cast<SBm25*>(handle); }

// Accumulate scores for one query (term ids WITH repeats, matching the
// Python np.add.at semantics) into a zeroed [n_docs] accumulator, recording
// touched docs. The handle is READ-ONLY here — all scratch is caller-owned,
// so any number of threads may score against one handle concurrently.
static void score_into(const SBm25* h, const int32_t* qids, int32_t n_q,
                       float* acc, std::vector<int32_t>* touched) {
  const float k1p1 = h->k1 + 1.0f;
  for (int32_t qi = 0; qi < n_q; ++qi) {
    const int32_t t = qids[qi];
    if (t < 0 || t >= h->n_terms) continue;
    const int64_t start = h->term_offsets[t];
    const int64_t end = h->term_offsets[t + 1];
    const float idf_t = h->idf[t];
    for (int64_t p = start; p < end; ++p) {
      const int32_t d = h->post_docs[p];
      const float tf = h->post_tfs[p];
      const float contrib = idf_t * (tf * k1p1 / (tf + h->norm[d]) + h->delta);
      if (touched != nullptr && acc[d] == 0.0f) touched->push_back(d);
      acc[d] += contrib;
    }
  }
}

// Dense score vector over the whole corpus (parity/fusion path). ``out`` is
// the accumulator itself — no handle scratch, no lock needed.
void sbm25_scores(void* handle, const int32_t* qids, int32_t n_q, float* out) {
  const auto* h = static_cast<const SBm25*>(handle);
  std::memset(out, 0, sizeof(float) * static_cast<size_t>(h->n_docs));
  score_into(h, qids, n_q, out, nullptr);
}

// Top-k by score (descending, ties broken by ascending doc id for
// determinism). Only docs with score > 0 are returned. Returns the count
// written into out_idx/out_scores (<= top_k). Scratch is a thread_local
// accumulator cleared via the touched list after each query — short
// queries never pay an O(n_docs) memset, and per-thread scratch keeps
// concurrent searches against one handle lock-free.
int32_t sbm25_search(void* handle, const int32_t* qids, int32_t n_q,
                     int32_t top_k, int32_t* out_idx, float* out_scores) {
  const auto* h = static_cast<const SBm25*>(handle);
  thread_local std::vector<float> acc;
  const auto need = static_cast<size_t>(h->n_docs);
  if (acc.size() < need) {
    acc.resize(need, 0.0f);
  } else if (acc.size() > 4 * need && acc.size() > (1u << 20)) {
    // corpus shrank a lot (rebuild/handle swap): release the excess rather
    // than pinning peak-corpus scratch per thread forever
    std::vector<float>(need, 0.0f).swap(acc);
  }
  std::vector<int32_t> docs;
  docs.reserve(1024);
  score_into(h, qids, n_q, acc.data(), &docs);

  // ``docs`` may hold duplicates (a zero contrib leaves acc at 0, so the
  // same doc can be pushed again); drop exact duplicates. Top-k selection
  // happens IN PLACE but never truncates — the full list doubles as the
  // touched set that restores acc's all-zero invariant at the end. (No
  // exception guard: the only caller is ctypes, where a C++ exception
  // escaping the C ABI terminates the process anyway.)
  std::sort(docs.begin(), docs.end());
  docs.erase(std::unique(docs.begin(), docs.end()), docs.end());

  const auto cmp = [&acc](int32_t a, int32_t b) {
    const float sa = acc[a], sb = acc[b];
    if (sa != sb) return sa > sb;
    return a < b;
  };
  const size_t k = std::min(static_cast<size_t>(top_k), docs.size());
  if (k > 0 && k < docs.size()) {
    std::nth_element(docs.begin(), docs.begin() + static_cast<int64_t>(k) - 1,
                     docs.end(), cmp);
  }
  std::sort(docs.begin(), docs.begin() + static_cast<int64_t>(k), cmp);

  int32_t written = 0;
  for (size_t i = 0; i < k; ++i) {
    const int32_t d = docs[i];
    if (acc[d] <= 0.0f) break;
    out_idx[written] = d;
    out_scores[written] = acc[d];
    ++written;
  }
  // restore the all-zero invariant for the next query on this thread
  for (const int32_t d : docs) acc[d] = 0.0f;
  return written;
}

int32_t sbm25_version() { return 1; }

}  // extern "C"
