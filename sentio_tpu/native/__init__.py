"""Native (C++) host-side components, consumed via ctypes.

The reference leans on external native compute for its host-side hot loops
— Lucene/JVM BM25 through Pyserini (/root/reference/src/core/retrievers/
sparse.py:206-276) and Qdrant's Rust HNSW server. Here the native layer is
in-tree C++ built with the system toolchain on first use; every native
component has a pure-Python/numpy fallback so the framework never *requires*
a compiler at runtime.

``load_bm25()`` returns the ctypes library handle for the BM25 scoring core
(building it if needed) or None when unavailable.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

logger = logging.getLogger(__name__)

_SRC_DIR = Path(__file__).parent
_LOCK = threading.Lock()
_CACHE: dict[str, Optional[ctypes.CDLL]] = {}


def _build(name: str) -> Optional[Path]:
    src = _SRC_DIR / f"{name}.cpp"
    out = _SRC_DIR / f"lib{name}.so"
    if out.exists() and out.stat().st_mtime >= src.stat().st_mtime:
        return out
    # compile to a per-process temp name and os.replace into place: the
    # in-process _LOCK cannot serialize concurrent *processes* (multiple
    # server workers / pytest-xdist on a fresh checkout), and dlopen on a
    # half-written .so fails hard
    tmp = _SRC_DIR / f".lib{name}.{os.getpid()}.so"
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           str(src), "-o", str(tmp)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            # -march=native can fail on exotic hosts; retry portable
            proc = subprocess.run([c for c in cmd if c != "-march=native"],
                                  capture_output=True, text=True, timeout=120)
            if proc.returncode != 0:
                logger.warning("native %s build failed:\n%s", name, proc.stderr[-2000:])
                return None
        os.replace(tmp, out)
    except (OSError, subprocess.TimeoutExpired) as exc:
        logger.warning("native %s build skipped: %s", name, exc)
        return None
    finally:
        tmp.unlink(missing_ok=True)
    return out


def _load(name: str) -> Optional[ctypes.CDLL]:
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        lib: Optional[ctypes.CDLL] = None
        path = _build(name)
        if path is not None:
            try:
                lib = ctypes.CDLL(str(path))
            except OSError as exc:
                logger.warning("native %s load failed: %s", name, exc)
        _CACHE[name] = lib
        return lib


def load_bm25() -> Optional[ctypes.CDLL]:
    """The BM25 scoring core (native/bm25.cpp), with argtypes configured."""
    lib = _load("bm25")
    if lib is None or getattr(lib, "_sbm25_configured", False):
        return lib
    c = ctypes
    i32p, i64p, f32p = (c.POINTER(c.c_int32), c.POINTER(c.c_int64), c.POINTER(c.c_float))
    lib.sbm25_create.restype = c.c_void_p
    lib.sbm25_create.argtypes = [c.c_int32, c.c_int32, i64p, i32p, f32p, f32p,
                                 f32p, c.c_float, c.c_float]
    lib.sbm25_destroy.argtypes = [c.c_void_p]
    lib.sbm25_scores.argtypes = [c.c_void_p, i32p, c.c_int32, f32p]
    lib.sbm25_search.restype = c.c_int32
    lib.sbm25_search.argtypes = [c.c_void_p, i32p, c.c_int32, c.c_int32, i32p, f32p]
    lib.sbm25_version.restype = c.c_int32
    lib._sbm25_configured = True
    return lib
