"""sentio-tpu: a TPU-native retrieval-augmented generation framework.

A brand-new framework with the capability surface of the reference RAG
service (hybrid dense+BM25 retrieval with rrf/weighted_rrf/comb_sum fusion,
scorer plugins, cross-encoder reranking, citation-grounded generation, LLM
self-verification, ingestion/chunking, resilience ladder, caching, auth,
observability) — re-designed TPU-first: every model runs in-process on a JAX
device mesh (Flax bi-encoder, cross-encoder, Llama-class decoder with paged
KV), requests are coalesced into data-parallel batches over ICI, and the
dense index is an exact sharded matmul+top-k in HBM.

This top-level module stays import-light: importing :mod:`sentio_tpu` must not
pull in JAX (CLI startup, host-only tooling). Heavy subsystems live under
``sentio_tpu.models`` / ``sentio_tpu.parallel`` / ``sentio_tpu.kernels``.
"""

__version__ = "0.1.0"

__all__ = ["__version__"]
