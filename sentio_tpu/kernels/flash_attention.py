"""Pallas flash attention for TPU: blockwise online-softmax, O(T) memory.

The reference never runs attention at all — its models are remote HTTP
services (SURVEY.md §0). In this framework attention is the dominant FLOP
consumer of the generator/verifier (models/llama.py) and the encoders, so
the prefill/scoring path gets a proper TPU kernel:

* grid ``(B*H, T/block_q, S/block_k)``; the k dimension is sequential
  ("arbitrary"), carrying running max ``m``, normalizer ``l`` and the
  accumulator in fp32 VMEM scratch across k-blocks — the classic
  flash-attention recurrence, never materializing the [T, S] score matrix;
* q·kᵀ and p·v land on the MXU in the input dtype (bf16) with fp32
  accumulation (``preferred_element_type``);
* causal block skipping: k-blocks strictly above the diagonal are masked
  wholesale (their contribution is exp(-inf)=0) — and the per-element mask
  handles the diagonal blocks;
* variable-length rows via ``kv_lens`` [B]: key positions ≥ len score -inf
  (the prefill padding mask).

On CPU (tests, dev) the same kernel runs in Pallas interpret mode;
:func:`attention_auto` picks kernel vs. the XLA fallback by platform and
problem size.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(np.finfo(np.float32).min)

__all__ = ["flash_attention", "attention_auto"]


def _flash_kernel(
    lens_ref,  # [B*H] int32, scalar-prefetched whole into SMEM
    q_ref,     # [block_q, d]
    k_ref,     # [block_k, d]
    v_ref,     # [block_k, d]
    o_ref,     # [block_q, d]
    m_ref,     # [block_q, 1] scratch fp32
    l_ref,     # [block_q, 1] scratch fp32
    acc_ref,   # [block_q, d] scratch fp32
    *,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # block-level causal skip: the whole k-block is in the future
    run = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(run)
    def _block():
        q = q_ref[:]
        k = k_ref[:]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [bq, bk]

        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = k_pos < lens_ref[bh]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
        p = jnp.exp(jnp.where(m_new > NEG_INF / 2, s - m_new, NEG_INF))
        alpha = jnp.exp(jnp.where(m_new > NEG_INF / 2, m_prev - m_new, 0.0))

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        l = l_ref[:]
        o_ref[:] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def _pad_to(x: jax.Array, size: int, axis: int) -> jax.Array:
    pad = size - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: Optional[jax.Array] = None,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """q [B,T,H,D], k/v [B,S,H,D] (kv heads already expanded) → [B,T,H,D].

    ``kv_lens`` [B] int32 limits each row's attendable keys (padding).
    Head dim is padded to a lane multiple (128) for the MXU; T/S pad to the
    block sizes. All padding is sliced away on return.
    """
    b, t, h, d = q.shape
    s = k.shape[1]
    sm_scale = 1.0 / float(np.sqrt(d))
    if kv_lens is None:
        kv_lens = jnp.full((b,), s, jnp.int32)

    block_q_eff = min(block_q, max(t, 16))
    block_k_eff = min(block_k, max(s, 16))
    t_pad = int(np.ceil(t / block_q_eff)) * block_q_eff
    s_pad = int(np.ceil(s / block_k_eff)) * block_k_eff
    d_pad = max(int(np.ceil(d / 128)) * 128, d) if not interpret else d

    # [B,T,H,D] → [B*H, T, D] rows of independent attention problems
    def to_rows(x, length):
        x = _pad_to(_pad_to(x, length, 1), d_pad, 3)
        return x.transpose(0, 2, 1, 3).reshape(b * h, length, d_pad)

    qr, kr, vr = to_rows(q, t_pad), to_rows(k, s_pad), to_rows(v, s_pad)
    lens_rows = jnp.repeat(kv_lens.astype(jnp.int32), h)  # [B*H]

    grid = (b * h, t_pad // block_q_eff, s_pad // block_k_eff)
    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        sm_scale=sm_scale,
        block_q=block_q_eff,
        block_k=block_k_eff,
    )
    # lens rides as a scalar-prefetch operand: the whole [B*H] vector lands
    # in SMEM before the kernel body runs (TPU lowering rejects rank-1
    # SMEM *blocks* that aren't whole-array or 128-multiples — observed as
    # a lowering error on real chips; interpret mode on CPU accepted it)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q_eff, d_pad), lambda bh, qi, ki, lens: (bh, qi, 0)),
            pl.BlockSpec((None, block_k_eff, d_pad), lambda bh, qi, ki, lens: (bh, ki, 0)),
            pl.BlockSpec((None, block_k_eff, d_pad), lambda bh, qi, ki, lens: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q_eff, d_pad), lambda bh, qi, ki, lens: (bh, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q_eff, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q_eff, 1), jnp.float32),   # normalizer l
            pltpu.VMEM((block_q_eff, d_pad), jnp.float32),  # output accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, t_pad, d_pad), q.dtype),
        interpret=interpret,
    )(lens_rows, qr, kr, vr)

    out = out.reshape(b, h, t_pad, d_pad).transpose(0, 2, 1, 3)
    return out[:, :t, :, :d]


def attention_auto(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask=None,
    *,
    causal: bool = True,
    kv_lens: Optional[jax.Array] = None,
    dtype=jnp.bfloat16,
    min_seq_for_kernel: int = 256,
):
    """Pick the Pallas kernel on TPU for long sequences, XLA elsewhere."""
    from sentio_tpu.models.layers import attention as xla_attention

    platform = q.devices().pop().platform if hasattr(q, "devices") else "cpu"
    t, s = q.shape[1], k.shape[1]
    if platform == "tpu" and t >= min_seq_for_kernel and mask is None:
        return flash_attention(q, k, v, kv_lens, causal=causal)
    return xla_attention(q, k, v, mask, dtype)
