"""Pallas/TPU kernels: flash attention, ring (sequence-parallel) attention.

Every kernel has an XLA fallback (models/layers.py:attention) so the whole
framework runs on CPU; the kernels take over on TPU where the problem size
pays for them. ``flash_attn_fn`` is the adapter signature models accept
(``llama_forward(..., attn_fn=...)``): (q, k, v, kv_lens) → [B, T, H, D]
with causal semantics.
"""

from __future__ import annotations

import jax

from sentio_tpu.kernels.flash_attention import attention_auto, flash_attention
from sentio_tpu.kernels.ring_attention import ring_attention, ring_attention_sharded

__all__ = [
    "flash_attention",
    "attention_auto",
    "ring_attention",
    "ring_attention_sharded",
    "flash_attn_fn",
    "encoder_attn_fn",
    "make_ring_attn_fn",
    "make_mesh_attn_fn",
    "default_attn_fn",
    "default_encoder_attn_fn",
]


def flash_attn_fn(q, k, v, kv_lens=None):
    """Causal flash attention adapter for ``llama_forward(attn_fn=...)``."""
    interpret = jax.default_backend() != "tpu"
    return flash_attention(q, k, v, kv_lens, causal=True, interpret=interpret)


def encoder_attn_fn(q, k, v, kv_lens=None):
    """Bidirectional flash adapter for encoder forwards: right-padded keys
    are masked by ``kv_lens``, no causal constraint."""
    interpret = jax.default_backend() != "tpu"
    return flash_attention(q, k, v, kv_lens, causal=False, interpret=interpret)


def make_ring_attn_fn(axis_name: str):
    """Ring-attention adapter for use INSIDE shard_map over ``axis_name``
    (sequence axis). kv_lens masks right-padding by global key position."""

    def fn(q, k, v, kv_lens=None):
        return ring_attention(q, k, v, kv_lens, axis_name=axis_name, causal=True)

    return fn


def make_mesh_attn_fn(mesh, causal: bool = True):
    """Kernel attention that runs INSIDE shard_map over the mesh — the
    sharded replacement for the old "no kernels under a mesh" gate:

    * heads shard over ``tp`` (matching the Megatron column sharding of
      wq/wk/wv, so no resharding at the kernel boundary);
    * with sp > 1 the sequence shards over ``sp`` and the inner kernel is
      the ppermute ring (long-context path); otherwise each shard runs
      flash attention on its local heads;
    * batch shards over ``dp`` when divisible, else replicates (serving
      batches are small; training batches always divide).

    Returns an ``attn_fn(q, k, v, kv_lens)`` for multi-token causal blocks
    (prefill / training); encoders pass ``causal=False`` (sp must be 1).
    """
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from sentio_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP

    sp = mesh.shape[AXIS_SP]
    tp = mesh.shape[AXIS_TP]
    dp = mesh.shape[AXIS_DP]
    if sp > 1 and not causal:
        raise ValueError("sequence-parallel ring attention is causal-only")
    interpret = jax.default_backend() != "tpu"

    def fn(q, k, v, kv_lens=None):
        b, t, h, _ = q.shape
        if h % tp != 0 or t % sp != 0:
            # indivisible shapes fall back to XLA attention upstream
            raise ValueError(f"heads {h} % tp {tp} or seq {t} % sp {sp} != 0")
        batch_axis = AXIS_DP if (dp > 1 and b % dp == 0) else None
        spec = P(batch_axis, AXIS_SP if sp > 1 else None,
                 AXIS_TP if tp > 1 else None, None)
        lens_spec = P(batch_axis)
        if kv_lens is None:
            kv_lens = jnp.full((b,), t, jnp.int32)

        if sp > 1:
            def inner(q, k, v, lens):
                return ring_attention(q, k, v, lens, axis_name=AXIS_SP,
                                      causal=True)
        else:
            def inner(q, k, v, lens):
                return flash_attention(q, k, v, lens, causal=causal,
                                       interpret=interpret)

        return shard_map(
            inner, mesh=mesh,
            in_specs=(spec, spec, spec, lens_spec),
            out_specs=spec, check_rep=False,
        )(q, k, v, kv_lens)

    return fn


def default_attn_fn():
    """Flash on TPU, None (XLA fallback) elsewhere."""
    if jax.default_backend() == "tpu":
        return flash_attn_fn
    return None


def default_encoder_attn_fn():
    """Bidirectional flash on TPU, None (XLA fallback) elsewhere."""
    if jax.default_backend() == "tpu":
        return encoder_attn_fn
    return None


def select_encoder_attn_fn(mesh, n_heads: int):
    """THE policy for encoder attention kernels (embedder + cross-encoder —
    one definition so the sites cannot drift): no mesh → plain flash on TPU;
    mesh on TPU with sp == 1 and heads divisible by tp → flash inside
    shard_map; anything else → None (XLA attention under GSPMD)."""
    from sentio_tpu.parallel.mesh import AXIS_SP, AXIS_TP

    if mesh is None:
        return default_encoder_attn_fn()
    if (jax.default_backend() == "tpu" and mesh.shape[AXIS_SP] == 1
            and n_heads % mesh.shape[AXIS_TP] == 0):
        return make_mesh_attn_fn(mesh, causal=False)
    return None
