"""Pallas/TPU kernels: flash attention, ring (sequence-parallel) attention.

Every kernel has an XLA fallback (models/layers.py:attention) so the whole
framework runs on CPU; the kernels take over on TPU where the problem size
pays for them. ``flash_attn_fn`` is the adapter signature models accept
(``llama_forward(..., attn_fn=...)``): (q, k, v, kv_lens) → [B, T, H, D]
with causal semantics.
"""

from __future__ import annotations

import jax

from sentio_tpu.kernels.flash_attention import attention_auto, flash_attention
from sentio_tpu.kernels.ring_attention import ring_attention, ring_attention_sharded

__all__ = [
    "flash_attention",
    "attention_auto",
    "ring_attention",
    "ring_attention_sharded",
    "flash_attn_fn",
    "make_ring_attn_fn",
    "default_attn_fn",
]


def flash_attn_fn(q, k, v, kv_lens=None):
    """Causal flash attention adapter for ``llama_forward(attn_fn=...)``."""
    interpret = jax.default_backend() != "tpu"
    return flash_attention(q, k, v, kv_lens, causal=True, interpret=interpret)


def make_ring_attn_fn(axis_name: str):
    """Ring-attention adapter for use INSIDE shard_map over ``axis_name``
    (sequence axis). kv_lens unsupported: SP serves long, unpadded contexts."""

    def fn(q, k, v, kv_lens=None):
        if kv_lens is not None:
            raise ValueError("ring attention path expects unpadded sequences")
        return ring_attention(q, k, v, axis_name=axis_name, causal=True)

    return fn


def default_attn_fn():
    """Flash on TPU, None (XLA fallback) elsewhere."""
    if jax.default_backend() == "tpu":
        return flash_attn_fn
    return None
