"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context support the reference entirely lacks (it truncates context to
~2000 tokens, /root/reference/src/core/graph/nodes.py:296-338 there;
SURVEY.md §5 "long-context — absent"). Here sequences shard over the ``sp``
axis and attention runs as a ring: each device holds its local Q shard
permanently, while K/V shards rotate around the ring via
``jax.lax.ppermute`` (XLA lowers it to ICI send/recv on TPU). After
``sp`` steps every Q block has seen every K/V block, with O(T/sp) activation
memory per device and compute/communication overlap left to XLA's scheduler.

Numerical form: the flash-attention online-softmax recurrence carried
ACROSS ring steps — running max ``m``, normalizer ``l``, fp32 accumulator —
so the result is exactly softmax(QKᵀ)V regardless of arrival order.

Causality with a sharded sequence: chunk ``c`` (its global offset =
src_index · T_local) is fully visible to later chunks, causal-masked on the
diagonal chunk, and fully masked for earlier chunks (contributes
exp(-inf) = 0 but still rides the ring to keep the permute schedule static).

``ring_attention`` is the shard_map-internal function (use inside your own
shard_map with axis ``sp``); :func:`ring_attention_sharded` wraps it for
standalone [B, T, H, D] arrays on a mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sentio_tpu.parallel.mesh import AXIS_DP, AXIS_SP

NEG_INF = float(np.finfo(np.float32).min)

__all__ = ["ring_attention", "ring_attention_sharded"]


def _chunk_attend(q, k, v, q_offset, k_offset, causal: bool, sm_scale: float,
                  kv_lens=None):
    """Scores of local q [B,T,H,D] against one k/v chunk, with the global
    causal mask derived from the two chunk offsets. ``kv_lens`` [B] masks
    keys at global positions >= the row's true length (right-padded
    batches). Returns the raw masked score matrix [B,H,T,S] in fp32; the
    online-softmax recurrence over chunks lives in the caller's ring step."""
    s = jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    sk = k.shape[1]
    k_pos = k_offset + jnp.arange(sk)[None, :]
    if causal:
        t = q.shape[1]
        q_pos = q_offset + jnp.arange(t)[:, None]
        s = jnp.where((k_pos <= q_pos)[None, None, :, :], s, NEG_INF)
    if kv_lens is not None:
        valid = k_pos[0][None, :] < kv_lens[:, None]  # [B, S]
        s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    return s


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: Optional[jax.Array] = None,
    *,
    axis_name: str = AXIS_SP,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Inside shard_map: q/k/v are the LOCAL sequence shards [B, T_loc, H, D]
    (kv heads already expanded to H); ``kv_lens`` [B] (replicated) masks
    right-padding by GLOBAL key position. Returns the local output shard."""
    b, t_loc, h, d = q.shape
    sp = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = sm_scale if sm_scale is not None else 1.0 / float(np.sqrt(d))
    perm = [(i, (i + 1) % sp) for i in range(sp)]  # send k/v to the right

    q32 = q.astype(jnp.float32)
    m0 = jnp.full((b, h, t_loc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_loc, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, t_loc, d), jnp.float32)

    def step(carry, step_idx):
        k_chunk, v_chunk, m, l, acc = carry
        # the chunk we hold at step i originated on device (my_idx - i) % sp
        src_idx = (my_idx - step_idx) % sp
        s = _chunk_attend(
            q32, k_chunk.astype(jnp.float32), v_chunk.astype(jnp.float32),
            q_offset=my_idx * t_loc, k_offset=src_idx * t_loc,
            causal=causal, sm_scale=scale, kv_lens=kv_lens,
        )
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        safe = m_new > NEG_INF / 2
        p = jnp.exp(jnp.where(safe, s - m_new, NEG_INF))
        alpha = jnp.exp(jnp.where(safe, m - m_new, 0.0))
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bhts,bshd->bhtd", p, v_chunk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha + pv
        # rotate k/v around the ring (last rotation returns them home; XLA
        # overlaps it with the next step's compute where profitable)
        k_next = jax.lax.ppermute(k_chunk, axis_name, perm)
        v_next = jax.lax.ppermute(v_chunk, axis_name, perm)
        return (k_next, v_next, m_new, l, acc), None

    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(sp)
    )
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, T_loc, H, D]


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    sp_axis: str = AXIS_SP,
    batch_axes: tuple[str, ...] = (AXIS_DP,),
) -> jax.Array:
    """Standalone entry: global [B, T, H, D] arrays, batch over dp, sequence
    over sp. T must divide by the sp axis size."""
    from jax.experimental.shard_map import shard_map

    t = q.shape[1]
    sp = mesh.shape[sp_axis]
    if t % sp != 0:
        raise ValueError(f"sequence length {t} not divisible by sp={sp}")
    batch_spec = batch_axes[0] if len(batch_axes) == 1 else batch_axes
    spec = P(batch_spec, sp_axis, None, None)

    fn = shard_map(
        functools.partial(ring_attention, axis_name=sp_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    q = jax.device_put(q, NamedSharding(mesh, spec))
    k = jax.device_put(k, NamedSharding(mesh, spec))
    v = jax.device_put(v, NamedSharding(mesh, spec))
    return fn(q, k, v)
