"""Pallas paged attention (decode): attention over a page-table KV cache.

The continuous-batching engine (runtime/paged.py) stores KV in a pool of
fixed-size pages; at decode each row attends over its own scattered page
list. The XLA fallback gathers pages into a contiguous window first — an
HBM round-trip proportional to the whole window. This kernel instead walks
the page table directly:

* the page table and row lengths ride **scalar prefetch**
  (``pltpu.PrefetchScalarGridSpec``), so the BlockSpec index_map picks the
  *physical* page to DMA for grid step (row b, logical block i) —
  ``page_table[b, i]`` — and only pages the row actually owns ever leave
  HBM;
* grid ``(B, NB)`` with the page axis sequential, carrying the classic
  online-softmax (m, l, acc) recurrence in fp32 VMEM scratch;
* GQA stays folded: q is viewed [Hkv, rep, D] and both dots batch over the
  kv-head axis, so pages are never expanded to query heads;
* pages past a row's length are skipped wholesale (``pl.when``), the
  current page masks per-position (key pos ≤ len — the new token's KV was
  scattered at index ``len`` before the call).

Two kernel variants share the grid/recurrence:

* **bf16 pages** (``paged_attention``) — K/V page blocks DMA as-is;
* **int8 pages** (``paged_attention_quant``) — the BlockSpecs DMA int8
  page blocks PLUS their fp16 per-vector scales through the same
  scalar-prefetch index_map, and dequantization happens in-register in
  VMEM: q·(s·K) folds as (q·K)·s on the kv-head-batched score dot, and
  p·(s·V) as (p·s)·V on the value dot, so quantized pages never
  round-trip through a dense bf16 gather in HBM. Page reads shrink to
  ~half the bytes of bf16 — the point of quantizing a bandwidth-bound
  decode.

Runs in interpret mode on CPU (tests); on TPU it is the decode fast path
once windows are long enough to beat the fused XLA gather.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(np.finfo(np.float32).min)

# jax renamed TPUCompilerParams -> CompilerParams across the versions this
# repo spans (CPU test env vs the axon TPU image); accept either
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

__all__ = ["paged_attention", "paged_attention_quant", "make_paged_attn_impl"]


def _paged_kernel(
    pt_ref,    # [B, NB] int32 scalar-prefetch — page table
    lens_ref,  # [B] int32 scalar-prefetch — current token index per row
    q_ref,     # [Hkv, rep, D]
    k_ref,     # [page, Hkv, D] — the physical page chosen by index_map
    v_ref,     # [page, Hkv, D]
    o_ref,     # [Hkv, rep, D]
    m_ref,     # [Hkv, rep, 1] fp32 scratch
    l_ref,     # [Hkv, rep, 1] fp32 scratch
    acc_ref,   # [Hkv, rep, D] fp32 scratch
    *,
    page: int,
    sm_scale: float,
):
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    cur = lens_ref[b]  # the new token sits at absolute index ``cur``

    @pl.when(i * page <= cur)
    def _block():
        q = q_ref[:]  # [Hkv, rep, D]
        # [page, Hkv, D] → [Hkv, page, D]: Mosaic's tpu.matmul requires the
        # batch dims of both operands at the SAME index ("batch dims must be
        # equal" compile error on real chips otherwise; interpret mode on CPU
        # accepted the mismatched layout)
        k = k_ref[:].swapaxes(0, 1)
        # s[g, r, p] = q[g, r, :] · k[g, p, :]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * sm_scale  # [Hkv, rep, page]

        pos = i * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(pos <= cur, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        p = jnp.exp(jnp.where(m_new > NEG_INF / 2, s - m_new, NEG_INF))
        alpha = jnp.exp(jnp.where(m_new > NEG_INF / 2, m_prev - m_new, 0.0))

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=2, keepdims=True)
        v = v_ref[:].swapaxes(0, 1)  # [Hkv, page, D], same batch-dim rule
        # acc[g, r, :] += p[g, r, :] @ v[g, :, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(i == pl.num_programs(1) - 1)
    def _finalize():
        l = l_ref[:]
        o_ref[:] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention(
    q: jax.Array,           # [B, H, D] — one decode token per row
    k_pages: jax.Array,     # [P, page, Hkv, D] — one layer's page pool
    v_pages: jax.Array,     # [P, page, Hkv, D]
    page_table: jax.Array,  # [B, NB] int32 physical page ids
    lens: jax.Array,        # [B] int32 — index of the current token
    *,
    interpret: bool = False,
) -> jax.Array:
    """Decode attention over the paged pool → [B, H, D]."""
    b, h, d = q.shape
    _, page, hkv, _ = k_pages.shape
    rep = h // hkv
    nb = page_table.shape[1]
    sm_scale = 1.0 / float(np.sqrt(d))

    # [B, H, D] → [B, Hkv, rep, D]: group query heads under their kv head
    q4 = q.reshape(b, hkv, rep, d)

    kernel = functools.partial(_paged_kernel, page=page, sm_scale=sm_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((None, hkv, rep, d), lambda bb, i, pt, ln: (bb, 0, 0, 0)),
            pl.BlockSpec((None, page, hkv, d), lambda bb, i, pt, ln: (pt[bb, i], 0, 0, 0)),
            pl.BlockSpec((None, page, hkv, d), lambda bb, i, pt, ln: (pt[bb, i], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, hkv, rep, d), lambda bb, i, pt, ln: (bb, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, rep, 1), jnp.float32),
            pltpu.VMEM((hkv, rep, 1), jnp.float32),
            pltpu.VMEM((hkv, rep, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lens.astype(jnp.int32), q4, k_pages, v_pages)
    return out.reshape(b, h, d)


def _paged_kernel_quant(
    pt_ref,    # [B, NB] int32 scalar-prefetch — page table
    lens_ref,  # [B] int32 scalar-prefetch — current token index per row
    q_ref,     # [Hkv, rep, D]
    kq_ref,    # [page, Hkv, D] int8 — the physical page chosen by index_map
    ks_ref,    # [page, Hkv] f16 — per-vector absmax scales for that page
    vq_ref,    # [page, Hkv, D] int8
    vs_ref,    # [page, Hkv] f16
    o_ref,     # [Hkv, rep, D]
    m_ref,     # [Hkv, rep, 1] fp32 scratch
    l_ref,     # [Hkv, rep, 1] fp32 scratch
    acc_ref,   # [Hkv, rep, D] fp32 scratch
    *,
    page: int,
    sm_scale: float,
):
    """Online-softmax over int8 pages, dequantized in-register.

    The scale never expands to [page, D]: q·(s_p·K_p) == (q·K_p)·s_p per key
    vector, so the score dot runs on the raw int8 block (cast to f32 on the
    VPU) and the scalar scale multiplies the [Hkv, rep, page] score tile.
    Same fold on the value side: p·(s·V) == (p·s)·V."""
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    cur = lens_ref[b]  # the new token sits at absolute index ``cur``

    @pl.when(i * page <= cur)
    def _block():
        q = q_ref[:].astype(jnp.float32)  # [Hkv, rep, D]
        # [page, Hkv, ...] → [Hkv, page, ...]: batch dims of both matmul
        # operands must sit at the SAME index (see _paged_kernel)
        k = kq_ref[:].swapaxes(0, 1).astype(jnp.float32)   # [Hkv, page, D]
        ks = ks_ref[:].swapaxes(0, 1).astype(jnp.float32)  # [Hkv, page]
        # s[g, r, p] = (q[g, r, :] · kq[g, p, :]) * ks[g, p] — the (q·K)·s
        # fold: one scalar multiply per score instead of page*D dequants
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * ks[:, None, :] * sm_scale  # [Hkv, rep, page]

        pos = i * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(pos <= cur, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        p = jnp.exp(jnp.where(m_new > NEG_INF / 2, s - m_new, NEG_INF))
        alpha = jnp.exp(jnp.where(m_new > NEG_INF / 2, m_prev - m_new, 0.0))

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=2, keepdims=True)
        v = vq_ref[:].swapaxes(0, 1).astype(jnp.float32)   # [Hkv, page, D]
        vs = vs_ref[:].swapaxes(0, 1).astype(jnp.float32)  # [Hkv, page]
        # acc[g, r, :] += (p[g, r, :] * vs[g, :]) @ vq[g, :, :] — the (p·s)·V
        # fold on the value dot
        pv = p * vs[:, None, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pv, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(i == pl.num_programs(1) - 1)
    def _finalize():
        l = l_ref[:]
        o_ref[:] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_quant(
    q: jax.Array,           # [B, H, D] — one decode token per row
    k_pages_q: jax.Array,   # [P, page, Hkv, D] int8 — one layer's page pool
    k_scales: jax.Array,    # [P, page, Hkv] f16 per-vector absmax scales
    v_pages_q: jax.Array,   # [P, page, Hkv, D] int8
    v_scales: jax.Array,    # [P, page, Hkv] f16
    page_table: jax.Array,  # [B, NB] int32 physical page ids
    lens: jax.Array,        # [B] int32 — index of the current token
    *,
    interpret: bool = False,
) -> jax.Array:
    """Decode attention over the int8-quantized paged pool → [B, H, D].

    Same grid/scalar-prefetch walk as :func:`paged_attention`; the int8
    payload and its scale pages DMA per grid step and dequantize in VMEM.
    """
    b, h, d = q.shape
    _, page, hkv, _ = k_pages_q.shape
    rep = h // hkv
    nb = page_table.shape[1]
    sm_scale = 1.0 / float(np.sqrt(d))

    q4 = q.reshape(b, hkv, rep, d)

    kernel = functools.partial(_paged_kernel_quant, page=page, sm_scale=sm_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((None, hkv, rep, d), lambda bb, i, pt, ln: (bb, 0, 0, 0)),
            pl.BlockSpec((None, page, hkv, d), lambda bb, i, pt, ln: (pt[bb, i], 0, 0, 0)),
            pl.BlockSpec((None, page, hkv), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
            pl.BlockSpec((None, page, hkv, d), lambda bb, i, pt, ln: (pt[bb, i], 0, 0, 0)),
            pl.BlockSpec((None, page, hkv), lambda bb, i, pt, ln: (pt[bb, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, hkv, rep, d), lambda bb, i, pt, ln: (bb, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, rep, 1), jnp.float32),
            pltpu.VMEM((hkv, rep, 1), jnp.float32),
            pltpu.VMEM((hkv, rep, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32), lens.astype(jnp.int32),
        q4, k_pages_q, k_scales, v_pages_q, v_scales,
    )
    return out.reshape(b, h, d)


def make_paged_attn_impl(interpret: bool | None = None):
    """Adapter with the ``paged_decode_forward(attn_impl=...)`` signature:
    (q [B,1,H,D], k_pages_l, v_pages_l, page_table, lens, n_rep) → [B,1,H,D].

    Representation-aware: a plain array routes to the bf16 kernel, a
    ``{"q", "s"}`` pytree (the ``kv_quant="int8"`` pool layer from
    ``runtime.paged._layer_pages``) routes to the int8 kernel — so one
    engine attn seam serves both pool representations.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def impl(q, k_pages_l, v_pages_l, page_table, lens, n_rep):
        if isinstance(k_pages_l, dict):
            out = paged_attention_quant(
                q[:, 0], k_pages_l["q"], k_pages_l["s"],
                v_pages_l["q"], v_pages_l["s"],
                page_table, lens, interpret=interpret,
            )
        else:
            out = paged_attention(
                q[:, 0], k_pages_l, v_pages_l, page_table, lens,
                interpret=interpret,
            )
        return out[:, None]

    return impl
