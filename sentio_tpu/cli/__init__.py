"""Command-line interface: ingest / serve / bench / info.

Parity with /root/reference/src/cli/ (Typer app with ``ingest``/``api``/
``ui``/``run``/``studio`` sub-apps, __init__.py:17-23 there) on stdlib
argparse — Typer isn't in the base image, and the UI is served by the API
process itself (GET /), so ``serve`` covers the reference's ``api`` + ``ui``
+ ``run`` trio. ``python -m sentio_tpu.cli <cmd>``.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["main"]


def _cmd_ingest(args: argparse.Namespace) -> int:
    from sentio_tpu.config import get_settings
    from sentio_tpu.ops.ingest import DocumentIngestor

    settings = get_settings()
    ingestor = DocumentIngestor(settings=settings)
    stats = ingestor.ingest_path(args.path, recursive=not args.no_recursive)
    if args.save:
        ingestor.dense_index.save(args.save)
        print(f"index saved to {args.save}", file=sys.stderr)
    print(json.dumps(stats.to_dict()))
    return 0 if not stats.errors else 1


def _cmd_serve(args: argparse.Namespace) -> int:
    from sentio_tpu.config import get_settings
    from sentio_tpu.serve.app import run_server

    settings = get_settings()
    if args.host:
        settings.serve.host = args.host
    if args.port:
        settings.serve.port = args.port
    if args.index:
        settings.retrieval.index_path = args.index
    run_server(settings)
    return 0


def _cmd_bench(args: argparse.Namespace) -> int:
    import os
    import runpy
    from pathlib import Path

    if args.fast:
        os.environ["BENCH_FAST"] = "1"
    bench = Path(__file__).resolve().parents[2] / "bench.py"
    runpy.run_path(str(bench), run_name="__main__")
    return 0


def _cmd_info(args: argparse.Namespace) -> int:
    import jax

    import sentio_tpu
    from sentio_tpu.config import get_settings

    settings = get_settings()
    devices = jax.devices()
    print(json.dumps({
        "version": sentio_tpu.__version__,
        "devices": [{"platform": d.platform, "kind": d.device_kind} for d in devices],
        "retrieval": settings.retrieval.strategy,
        "generator": settings.generator.model_preset,
        "mesh": {
            "dp": settings.mesh.dp_size,
            "tp": settings.mesh.tp_size,
            "sp": settings.mesh.sp_size,
        },
    }, indent=2))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="sentio-tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    p_ingest = sub.add_parser("ingest", help="ingest a file or directory into the index")
    p_ingest.add_argument("path")
    p_ingest.add_argument("--no-recursive", action="store_true")
    p_ingest.add_argument("--save", default="", help="persist the dense index to this path")
    p_ingest.set_defaults(fn=_cmd_ingest)

    p_serve = sub.add_parser("serve", help="run the API server (UI at /)")
    p_serve.add_argument("--host", default="")
    p_serve.add_argument("--port", type=int, default=0)
    p_serve.add_argument("--index", default="", help="load a persisted dense index (from ingest --save)")
    p_serve.set_defaults(fn=_cmd_serve)

    p_bench = sub.add_parser("bench", help="run the end-to-end benchmark")
    p_bench.add_argument("--fast", action="store_true")
    p_bench.set_defaults(fn=_cmd_bench)

    p_info = sub.add_parser("info", help="print version/device/config info")
    p_info.set_defaults(fn=_cmd_info)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
