"""Command-line interface: ingest / serve / bench / info / trace / convert /
lint / audit / check.

Parity with /root/reference/src/cli/ (Typer app with ``ingest``/``api``/
``ui``/``run``/``studio`` sub-apps, __init__.py:17-23 there) on stdlib
argparse — Typer isn't in the base image, and the UI is served by the API
process itself (GET /), so ``serve`` covers the reference's ``api`` + ``ui``
+ ``run`` trio. ``trace`` is the studio equivalent (the reference launches
LangGraph Studio, cli/studio.py there): it runs one query through the graph
and dumps the full node-by-node execution trace as JSON. ``convert``
imports public HF checkpoints into framework checkpoints (models/convert.py).
``python -m sentio_tpu.cli <cmd>``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["main"]


def _cmd_ingest(args: argparse.Namespace) -> int:
    from sentio_tpu.config import get_settings
    from sentio_tpu.ops.ingest import DocumentIngestor

    settings = get_settings()
    ingestor = DocumentIngestor(settings=settings)
    stats = ingestor.ingest_path(args.path, recursive=not args.no_recursive)
    if args.save:
        ingestor.dense_index.save(args.save)
        print(f"index saved to {args.save}", file=sys.stderr)
    print(json.dumps(stats.to_dict()))
    return 0 if not stats.errors else 1


def _cmd_serve(args: argparse.Namespace) -> int:
    from sentio_tpu.config import get_settings
    from sentio_tpu.serve.app import run_server

    settings = get_settings()
    if args.host:
        settings.serve.host = args.host
    if args.port:
        settings.serve.port = args.port
    if args.index:
        settings.retrieval.index_path = args.index
    run_server(settings)
    return 0


def _cmd_bench(args: argparse.Namespace) -> int:
    import os
    import runpy
    from pathlib import Path

    if args.fast:
        os.environ["BENCH_FAST"] = "1"
    bench = Path(__file__).resolve().parents[2] / "bench.py"
    runpy.run_path(str(bench), run_name="__main__")
    return 0


def _cmd_trace(args: argparse.Namespace) -> int:
    """Run one query through the full graph and dump the execution trace —
    the offline equivalent of the reference's LangGraph Studio inspection
    (cli/studio.py + langgraph.json there) — joined with the request's
    FLIGHT RECORD: with the paged decode path active, the dump includes the
    engine's tick timeline for this request (batch occupancy, queue depth,
    prefill/decode token split, page-pool levels) plus TTFT/TPOT."""
    import uuid

    from sentio_tpu.config import get_settings
    from sentio_tpu.graph.state import create_initial_state
    from sentio_tpu.infra.flight import get_flight_recorder
    from sentio_tpu.serve.dependencies import DependencyContainer

    settings = get_settings()
    if args.index:
        settings.retrieval.index_path = args.index
    container = DependencyContainer(settings=settings)
    if args.ingest:
        container.ingestor.ingest_path(args.ingest)
    query_id = f"trace-{uuid.uuid4().hex[:8]}"
    state = container.graph.invoke(
        create_initial_state(
            args.query, metadata={"mode": args.mode, "query_id": query_id}
        )
    )
    # async/gated verification: the graph returns before the detached
    # audit lands — join it so the one-shot trace prints the verdict the
    # flight record ends up with (the serving path never waits like this)
    if state["metadata"].get("verify_pending"):
        from sentio_tpu.graph.executor import wait_detached

        wait_detached()
    trace = {
        "query": args.query,
        "request_id": query_id,
        "graph_path": state["metadata"].get("graph_path"),
        "node_timings_ms": state["metadata"].get("node_timings_ms"),
        "num_retrieved": len(state.get("retrieved_documents") or []),
        "num_reranked": len(state.get("reranked_documents") or []),
        "num_selected": len(state.get("selected_documents") or []),
        "answer": state.get("response"),
        # verify verdict (or typed skipped_confident) as the graph saw it;
        # the per-request verify record — mode, confidence, verdict
        # latency, skip reason — rides trace["flight"]["verify"] below
        "evaluation": state.get("evaluation") or None,
        "metadata": {
            k: v for k, v in state["metadata"].items()
            if k not in ("graph_path", "node_timings_ms")
        },
    }
    flight = get_flight_recorder().get(query_id)
    if flight is not None:
        # the graph-state copies above stay authoritative; the flight view
        # adds what only the engine pump saw (ticks, TTFT/TPOT)
        trace["flight"] = {
            k: v for k, v in flight.items()
            if k not in ("node_timings_ms", "graph_path", "request_id")
        }
    if args.chrome:
        # the WHOLE flight timeline (every tick with its phase split, every
        # request span, verify verdicts) as a Chrome/Perfetto trace — open
        # the file in ui.perfetto.dev. --fleet additionally pulls every
        # worker replica's flight buffer and lays the fleet out on ONE
        # clock-aligned timeline (one lane per worker incarnation)
        from sentio_tpu.infra.chrome_trace import flight_to_chrome

        chrome = _fleet_trace(container) if args.fleet else None
        if chrome is None:
            if args.fleet:
                print("--fleet: no worker replicas (thread mode?) — "
                      "falling back to the local timeline", file=sys.stderr)
            chrome = flight_to_chrome()
        with open(args.chrome, "w") as fh:
            json.dump(chrome, fh)
        print(f"chrome trace written to {args.chrome} "
              f"(open in ui.perfetto.dev)", file=sys.stderr)
    if args.documents:
        trace["selected_documents"] = [
            {"id": d.id, "text": d.text[:200], "metadata": d.metadata}
            for d in (state.get("selected_documents") or [])
        ]
    print(json.dumps(trace, indent=2, default=str))
    return 0


def _fleet_trace(container):
    """Fetch every worker replica's flight buffer (ticks + records) over
    the ``fetch_flight`` RPC and lay the fleet out on one clock-aligned
    Chrome trace: router request lanes on top, one synthetic process row
    per worker INCARNATION below, worker timestamps re-based onto the
    router's perf_counter timeline with the ClockSync offset (the lane
    name carries the ± uncertainty bound). Returns None when no worker
    replicas exist (thread mode) — the caller falls back to the local
    single-recorder export.

    DEAD and RETIRED incarnations stay on the timeline: their lanes
    render from the router's cached last telemetry frame, with the
    status suffixed to the lane name — churn reads as history instead
    of a silently missing row."""
    from sentio_tpu.infra.chrome_trace import build_fleet_trace
    from sentio_tpu.infra.flight import get_flight_recorder

    service = container.peek("generation_service")
    members = list(getattr(service, "_services", None) or ())
    healths = list(getattr(service, "_health", None) or ())
    fetchable = [svc for svc in members
                 if callable(getattr(svc, "fetch_flight", None))]
    if not fetchable:
        return None
    recorder = get_flight_recorder()
    router_origin = recorder.origin()
    workers = []
    for idx, svc in enumerate(members):
        if not callable(getattr(svc, "fetch_flight", None)):
            continue
        state = (getattr(healths[idx], "state", "")
                 if idx < len(healths) else "")
        if state in ("RETIRING", "RETIRED"):
            workers.append(svc.cached_flight_lane(router_origin, "retired"))
            continue
        try:
            reply = svc.fetch_flight()
        except Exception as exc:  # noqa: BLE001 — dead worker: cached lane
            print(f"--fleet: replica {getattr(svc, 'replica_id', '?')} "
                  f"unavailable ({type(exc).__name__}) — rendering lane "
                  f"from cached telemetry", file=sys.stderr)
            if callable(getattr(svc, "cached_flight_lane", None)):
                workers.append(
                    svc.cached_flight_lane(router_origin, "dead"))
            continue
        shift, bound = svc.flight_shift_s(router_origin)
        workers.append({
            "replica": reply.get("replica"),
            "epoch": reply.get("epoch") or 0,
            "shift_s": shift,
            "uncertainty_s": bound,
            "ticks": reply.get("ticks") or [],
            "records": reply.get("records") or [],
        })
    return build_fleet_trace(workers, router_ticks=recorder.timeline(),
                             router_records=recorder.records())


def _cmd_convert(args: argparse.Namespace) -> int:
    """Import a local HF checkpoint directory into a framework checkpoint
    (runtime/checkpoint.py format) ready for serve --restore."""
    from sentio_tpu.models import convert as C
    from sentio_tpu.runtime.checkpoint import save_pytree

    if args.family == "llama":
        params, cfg = C.load_llama_dir(args.src, dtype=args.dtype)
    elif args.family == "moe":
        params, cfg = C.load_moe_dir(args.src, dtype=args.dtype)
    elif args.family == "encoder":
        params, cfg = C.load_encoder_dir(args.src, dtype=args.dtype)
    elif args.family == "cross-encoder":
        params, cfg = C.load_encoder_dir(args.src, dtype=args.dtype, cross_encoder=True)
    else:  # pragma: no cover - argparse choices guard this
        raise ValueError(args.family)
    save_pytree(args.dst, params, meta={"family": args.family, "config": cfg.__dict__})
    print(json.dumps({"family": args.family, "dst": args.dst, "config": cfg.__dict__}))
    return 0


def _cmd_eval(args: argparse.Namespace) -> int:
    """Run the BASELINE.md measurement matrix (five configs + the measured
    reference-architecture baseline) and write EVAL.json."""
    from sentio_tpu.eval.runner import run_eval

    payload = run_eval(
        scale=args.scale,
        n_docs=args.docs,
        n_queries=args.queries,
        concurrency=args.concurrency,
        new_tokens=args.new_tokens,
        rtt_ms=args.rtt_ms,
        seed=args.seed,
        skip_baseline=args.skip_baseline,
        configs={c.strip() for c in args.configs.split(",") if c.strip()} or None
        if args.configs else None,
        encoder_checkpoint=args.encoder_checkpoint,
        kv_quant=args.kv_quant,
    )
    text = json.dumps(payload, indent=2)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    print(text)
    return 0


def _cmd_train_encoder(args: argparse.Namespace) -> int:
    """Train the bi-encoder in-tree (eval/train_encoder.py) and save a
    ``load_model``-compatible checkpoint for EMBEDDER_CHECKPOINT /
    ``eval --encoder-checkpoint``."""
    from sentio_tpu.eval.train_encoder import TrainConfig, eval_recall, train_encoder
    from sentio_tpu.models.transformer import EncoderConfig

    enc_cfg = EncoderConfig(
        vocab_size=512, dim=args.dim, n_layers=args.layers,
        n_heads=max(args.dim // 64, 2), mlp_dim=args.dim * 4, max_len=512,
    )
    params, enc_cfg, history = train_encoder(
        enc_cfg=enc_cfg,
        train_cfg=TrainConfig(steps=args.steps, batch=args.batch, lr=args.lr),
        out_path=args.out,
        seed=args.seed,
    )
    payload = {"checkpoint": args.out, "history": history}
    if args.eval_recall:
        payload["recall_at_10"] = round(eval_recall(params, enc_cfg), 3)
    print(json.dumps(payload))
    return 0


def _cmd_lint(args: argparse.Namespace) -> int:
    """Run the static analyzer (analysis/) over the source tree against the
    committed baseline: retrace hazards at jit sites, lock discipline from
    guarded-by annotations, wall-clock and exception hygiene. Exit 1 on any
    finding not in the baseline."""
    from sentio_tpu.analysis.runner import main as lint_main

    forwarded = list(args.paths)
    if args.baseline:
        forwarded += ["--baseline", args.baseline]
    if args.update_baseline:
        forwarded.append("--update-baseline")
    if args.json:
        forwarded.append("--json")
    if args.lock_graph:
        forwarded.append("--lock-graph")
    if args.failures:
        forwarded.append("--failures")
    if args.boundary_graph:
        forwarded.append("--boundary-graph")
    if args.sarif:
        forwarded += ["--sarif", args.sarif]
    return lint_main(forwarded)


def _cmd_audit(args: argparse.Namespace) -> int:
    """AOT-lower every registered jit family on a tiny CPU config and gate
    compile variants / donation aliasing / sharding / static HBM against
    the committed analysis/compile_manifest.json. Exit 1 on regressions."""
    from sentio_tpu.analysis.audit.runner import main as audit_main

    forwarded: list[str] = []
    if args.manifest:
        forwarded += ["--manifest", args.manifest]
    if args.update_manifest:
        forwarded.append("--update-manifest")
    if args.json:
        forwarded.append("--json")
    if args.no_mesh:
        forwarded.append("--no-mesh")
    return audit_main(forwarded)


def _cmd_check(args: argparse.Namespace) -> int:
    """The one-stop static gate: ``sentio lint`` (AST analysis vs baseline)
    then ``sentio audit`` (compile manifest). Exit non-zero when either
    fails; both always run so one invocation reports everything. With
    ``--json`` the two results nest under ONE parseable envelope."""
    if not args.json:
        from sentio_tpu.analysis.audit.runner import main as audit_main
        from sentio_tpu.analysis.runner import main as lint_main

        lint_rc = lint_main([])
        audit_rc = audit_main([])
        return lint_rc or audit_rc

    from sentio_tpu.analysis.audit.runner import _pin_platform, run_audit
    from sentio_tpu.analysis.runner import run_gate

    lint = run_gate()
    _pin_platform()
    audit = run_audit()
    ok = lint.ok and audit.ok
    print(json.dumps({
        "ok": ok,
        "lint": {
            "ok": lint.ok,
            "new": [dict(f.to_json(), line=f.line) for f in lint.new],
            "baselined": [dict(f.to_json(), line=f.line)
                          for f in lint.matched],
            "stale": lint.stale,
        },
        "audit": {
            "ok": audit.ok,
            "families": len(audit.report["families"]),
            "variants": audit.variant_count(),
            "regressions": audit.diff.regressions,
            "stale": audit.diff.stale,
        },
    }, indent=1))
    return 0 if ok else 1


def _cmd_info(args: argparse.Namespace) -> int:
    import jax

    import sentio_tpu
    from sentio_tpu.config import get_settings

    settings = get_settings()
    devices = jax.devices()
    print(json.dumps({
        "version": sentio_tpu.__version__,
        "devices": [{"platform": d.platform, "kind": d.device_kind} for d in devices],
        "retrieval": settings.retrieval.strategy,
        "generator": settings.generator.model_preset,
        "mesh": {
            "dp": settings.mesh.dp_size,
            "tp": settings.mesh.tp_size,
            "sp": settings.mesh.sp_size,
        },
    }, indent=2))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="sentio-tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    p_ingest = sub.add_parser("ingest", help="ingest a file or directory into the index")
    p_ingest.add_argument("path")
    p_ingest.add_argument("--no-recursive", action="store_true")
    p_ingest.add_argument("--save", default="", help="persist the dense index to this path")
    p_ingest.set_defaults(fn=_cmd_ingest)

    p_serve = sub.add_parser("serve", help="run the API server (UI at /)")
    p_serve.add_argument("--host", default="")
    p_serve.add_argument("--port", type=int, default=0)
    p_serve.add_argument("--index", default="", help="load a persisted dense index (from ingest --save)")
    p_serve.set_defaults(fn=_cmd_serve)

    p_bench = sub.add_parser("bench", help="run the end-to-end benchmark")
    p_bench.add_argument("--fast", action="store_true")
    p_bench.set_defaults(fn=_cmd_bench)

    p_trace = sub.add_parser("trace", help="run one query and dump the graph execution trace")
    p_trace.add_argument("query")
    p_trace.add_argument("--ingest", default="", help="ingest this path first")
    p_trace.add_argument("--index", default="", help="load a persisted dense index")
    p_trace.add_argument("--mode", default="balanced",
                         choices=["fast", "balanced", "quality", "creative"])
    p_trace.add_argument("--documents", action="store_true",
                         help="include selected document previews")
    p_trace.add_argument("--chrome", default="", metavar="OUT_JSON",
                         help="also dump the full flight timeline as a "
                              "Chrome/Perfetto trace (ui.perfetto.dev)")
    p_trace.add_argument("--fleet", action="store_true",
                         help="with --chrome: fetch every worker replica's "
                              "flight buffer and emit ONE clock-aligned "
                              "fleet trace (a lane per worker incarnation)")
    p_trace.set_defaults(fn=_cmd_trace)

    p_conv = sub.add_parser("convert", help="convert a local HF checkpoint dir")
    p_conv.add_argument("family", choices=["llama", "moe", "encoder", "cross-encoder"])
    p_conv.add_argument("src", help="HF checkpoint directory (config.json + weights)")
    p_conv.add_argument("dst", help="output framework checkpoint directory")
    p_conv.add_argument("--dtype", default="bfloat16")
    p_conv.set_defaults(fn=_cmd_convert)

    p_eval = sub.add_parser(
        "eval", help="run the BASELINE measurement matrix; write EVAL.json"
    )
    p_eval.add_argument("--scale", default="bench", choices=["tiny", "bench"])
    p_eval.add_argument("--docs", type=int, default=1024)
    p_eval.add_argument("--queries", type=int, default=64)
    p_eval.add_argument("--concurrency", type=int, default=8)
    p_eval.add_argument("--new-tokens", type=int, default=48)
    p_eval.add_argument("--rtt-ms", type=float, default=0.0,
                        help="inject per-hop RTT into the loopback baseline APIs")
    p_eval.add_argument("--seed", type=int, default=0)
    p_eval.add_argument("--skip-baseline", action="store_true")
    p_eval.add_argument("--configs", default="",
                        help="comma list: sparse_api,dense,hybrid_rerank,full_paged,batched")
    p_eval.add_argument("--out", default="", help="also write the JSON here")
    p_eval.add_argument("--kv-quant", default=os.environ.get("KV_QUANT", "none"),
                        choices=["none", "int8"],
                        help="KV page quantization for the paged configs "
                             "(the quality-gate measurement knob)")
    p_eval.add_argument("--encoder-checkpoint", default="",
                        help="trained bi-encoder checkpoint for the dense leg "
                             "(see `train-encoder`)")
    p_eval.set_defaults(fn=_cmd_eval)

    p_tr = sub.add_parser(
        "train-encoder",
        help="contrastively train the bi-encoder on the synthetic bundle "
             "(dense retrieval with zero egress)",
    )
    p_tr.add_argument("out", help="checkpoint output directory")
    p_tr.add_argument("--steps", type=int, default=600)
    p_tr.add_argument("--batch", type=int, default=64)
    p_tr.add_argument("--lr", type=float, default=3e-4)
    p_tr.add_argument("--dim", type=int, default=256)
    p_tr.add_argument("--layers", type=int, default=4)
    p_tr.add_argument("--seed", type=int, default=0)
    p_tr.add_argument("--eval-recall", action="store_true",
                      help="measure recall@10 on the eval bundle (seed 0) "
                           "after training")
    p_tr.set_defaults(fn=_cmd_train_encoder)

    p_lint = sub.add_parser(
        "lint",
        help="static analysis: retrace / lock-discipline / clock / "
             "exception hazards vs the committed baseline",
    )
    p_lint.add_argument("paths", nargs="*",
                        help="files or directories (default: sentio_tpu/)")
    p_lint.add_argument("--baseline", default="",
                        help="baseline JSON (default: analysis/baseline.json)")
    p_lint.add_argument("--update-baseline", action="store_true",
                        help="re-record the baseline from current findings")
    p_lint.add_argument("--json", action="store_true",
                        help="machine-readable output")
    p_lint.add_argument("--lock-graph", action="store_true",
                        dest="lock_graph",
                        help="dump the static lock-order digraph as JSON "
                             "(exit 1 if it has cycles)")
    p_lint.add_argument("--failures", action="store_true",
                        help="report only the failure-surface rules "
                             "(boundary escapes, typed rethrow, swallows, "
                             "codec / frame contracts)")
    p_lint.add_argument("--boundary-graph", action="store_true",
                        dest="boundary_graph",
                        help="dump the failure-surface graph (boundaries "
                             "with reachable escapes, frame channels) as "
                             "JSON")
    p_lint.add_argument("--sarif", metavar="PATH", default="",
                        help="also write the gate result as SARIF 2.1.0")
    p_lint.set_defaults(fn=_cmd_lint)

    p_audit = sub.add_parser(
        "audit",
        help="compile-manifest audit: AOT-lower every jit family and gate "
             "variants/donation/sharding/HBM vs the committed manifest",
    )
    p_audit.add_argument("--manifest", default="",
                         help="manifest JSON (default: "
                              "analysis/compile_manifest.json)")
    p_audit.add_argument("--update-manifest", action="store_true",
                         help="re-record the manifest from the current audit")
    p_audit.add_argument("--json", action="store_true",
                         help="machine-readable output")
    p_audit.add_argument("--no-mesh", action="store_true",
                         help="skip the 2-device sharding section")
    p_audit.set_defaults(fn=_cmd_audit)

    p_check = sub.add_parser(
        "check", help="run `sentio lint` and `sentio audit` as one gate"
    )
    p_check.add_argument("--json", action="store_true",
                         help="machine-readable output")
    p_check.set_defaults(fn=_cmd_check)

    p_info = sub.add_parser("info", help="print version/device/config info")
    p_info.set_defaults(fn=_cmd_info)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
