from sentio_tpu.cli import main

raise SystemExit(main())
