"""Pipeline parallelism: GPipe-style microbatched execution over ``pp``.

The reference scales horizontally with stateless pods (SURVEY.md §2.12) and
has no concept of model partitioning; here layer-stage pipelining is a
first-class mesh axis. Design (TPU-idiomatic, per the scaling-book recipe):

* The decoder's layers are stacked ([L, ...] leaves, models/llama.py
  ``stack_layer_params``) and the leading layer dim is sharded over the
  ``pp`` mesh axis — each pp rank holds a contiguous stage of L/S layers.
* Execution runs under ``jax.shard_map`` **manual over pp only**
  (``axis_names={"pp"}``): activations hop stage-to-stage with one
  ``lax.ppermute`` per schedule step, while dp/sp/tp sharding of the
  activations and of each stage's weights stays in XLA's hands (partial
  auto mode), so pipeline composes with tensor parallelism without manual
  psums here.
* The schedule is GPipe: M microbatches drain through S stages in
  M + S - 1 steps (bubble fraction (S-1)/(M+S-1)); each rank scans its
  local layer stack with ``lax.scan``. Backward is ``jax.grad`` through the
  whole thing — ppermute/scan/where all have transpose rules, so no manual
  backward schedule is needed (1F1B is a later optimization, not a
  correctness requirement).

Everything outside the layer stack — embedding, final norm, LM head, loss —
runs outside the shard_map under ordinary jit, replicated over pp.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sentio_tpu.models import layers as L
from sentio_tpu.models.llama import LlamaConfig, block_forward
from sentio_tpu.parallel.mesh import AXIS_PP

Array = jax.Array


class PipelineError(Exception):
    pass


def stacked_param_shardings(stacked: dict, mesh: Mesh) -> dict:
    """NamedShardings for a ``stack_layer_params`` tree: embed/head/final
    norm replicated (they live outside the pipeline), stacked layers staged
    over pp on the leading (layer) dim with the per-layer Megatron tp layout
    (sharding.py LLAMA_TP_RULES) on the inner dims — one source of truth for
    the tp layout, with AXIS_PP prepended here."""
    from sentio_tpu.parallel.sharding import LLAMA_TP_RULES, path_str, spec_for

    def layer_leaf(path, leaf):
        # per-layer spec for the trailing dims, pp prepended for the stack dim
        inner = spec_for(path_str(path), LLAMA_TP_RULES, leaf.ndim - 1)
        entries = (AXIS_PP,) + tuple(inner)
        # axes absent from this mesh degrade to replication
        entries = tuple(a if a in mesh.axis_names else None for a in entries)
        return NamedSharding(mesh, P(*entries))

    return {
        "embed_tokens": jax.tree.map(lambda _: NamedSharding(mesh, P()), stacked["embed_tokens"]),
        "lm_head": jax.tree.map(lambda _: NamedSharding(mesh, P()), stacked["lm_head"]),
        "final_norm": jax.tree.map(lambda _: NamedSharding(mesh, P()), stacked["final_norm"]),
        "layers": jax.tree_util.tree_map_with_path(layer_leaf, stacked["layers"]),
    }


def shard_stacked_params(stacked: dict, mesh: Mesh) -> dict:
    n_stages = mesh.shape[AXIS_PP]
    n_layers = jax.tree.leaves(stacked["layers"])[0].shape[0]
    if n_layers % n_stages != 0:
        raise PipelineError(f"{n_layers} layers not divisible by pp={n_stages}")
    return jax.device_put(stacked, stacked_param_shardings(stacked, mesh))


def _stage_apply(local_layers: Any, cfg: LlamaConfig, x: Array,
                 positions: Array, cos: Array, sin: Array,
                 pad_mask: Optional[Array]) -> Array:
    """Run this rank's layer stack over activations x [mb, T, D]. The
    residual stream stays float32 end to end (f32 x + bf16 block output
    promotes to f32), which matters twice: numerically it is the usual
    practice for deep residual streams, and structurally XLA's partitioner
    hard-crashes on bf16 scan carries / collectives inside a partial-auto
    manual region ("Invalid binary instruction opcode copy") — the f32
    carry sidesteps that while every matmul still runs in the model dtype
    inside block_forward."""

    def step(h, lp):
        return block_forward(lp, cfg, h, positions, cos, sin, pad_mask), None

    x, _ = lax.scan(step, x, local_layers)
    return x


def pipeline_apply(
    stacked_layers: Any,
    cfg: LlamaConfig,
    stream: Array,
    positions: Array,
    cos: Array,
    sin: Array,
    mesh: Mesh,
    pad_stream: Array,
) -> Array:
    """Push a microbatch stream [M, mb, T, D] through all layers, pipelined
    over the pp axis. ``pad_stream`` is [M, mb, T] validity masks. Returns
    the transformed stream with the same shape.

    The output stream materializes on the last stage and is broadcast to all
    pp ranks with one masked psum — the loss/head consumer is replicated over
    pp, so every rank needs it. (A production refinement keeps the head/loss
    inside the last stage and psums only the scalar; at framework scale the
    stream is microbatched activations, not logits, so the broadcast is
    M·mb·T·D bf16 — acceptable, and it keeps head sharding in auto mode.)

    The stream is float32 end to end — both across the shard_map boundary
    and as the carried/permuted residual inside (see _stage_apply): XLA's
    partial-manual partitioner hard-crashes ("Invalid binary instruction
    opcode copy") on bf16 values crossing into or carried within the manual
    region. Matmul compute inside each block still runs in the model dtype.
    """
    n_stages = mesh.shape[AXIS_PP]
    stream = stream.astype(jnp.float32)  # f32 residual stream (see _stage_apply)
    if n_stages == 1:
        # no stages → microbatching serves no purpose; run one merged batch
        m_, mb_, t_, d_ = stream.shape
        merged = stream.reshape(m_ * mb_, t_, d_)
        pos = jnp.broadcast_to(positions[:1], (m_ * mb_, t_))
        out = _stage_apply(stacked_layers, cfg, merged, pos, cos, sin,
                           pad_stream.reshape(m_ * mb_, t_))
        return out.reshape(m_, mb_, t_, d_)

    n_micro = stream.shape[0]
    n_steps = n_micro + n_stages - 1
    perm = [(j, j + 1) for j in range(n_stages - 1)]

    def per_rank(local_layers, local_stream, local_pads):
        rank = lax.axis_index(AXIS_PP)
        # shard_map hands each rank the full stream (replicated over pp);
        # local_layers is this rank's [L/S, ...] stage.

        def step(carry, t):
            prev_y, out = carry
            recv = lax.ppermute(prev_y, AXIS_PP, perm)
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            feed = lax.dynamic_index_in_dim(local_stream, feed_idx, 0, keepdims=False)
            x = jnp.where(rank == 0, feed, recv)
            # at step t, rank r is processing microbatch t - r (clamped over
            # the fill/drain bubbles) — pick that microbatch's pad mask
            own_idx = jnp.clip(t - rank, 0, n_micro - 1)
            pm = lax.dynamic_index_in_dim(local_pads, own_idx, 0, keepdims=False)
            y = _stage_apply(local_layers, cfg, x, positions, cos, sin, pm)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            valid = (t >= n_stages - 1) & (rank == n_stages - 1)
            cur = lax.dynamic_index_in_dim(out, out_idx, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, y, cur), out_idx, 0
            )
            return (y, out), None

        zero = jnp.zeros_like(local_stream[0])
        out0 = jnp.zeros_like(local_stream)
        (_, out), _ = lax.scan(step, (zero, out0), jnp.arange(n_steps))
        # only the last rank holds real outputs; broadcast across pp
        out = jnp.where(rank == n_stages - 1, out, jnp.zeros_like(out))
        return lax.psum(out, AXIS_PP)

    fn = jax.shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(P(AXIS_PP), P(), P()),
        out_specs=P(),
        axis_names={AXIS_PP},
        check_vma=False,
    )
    return fn(stacked_layers, stream, pad_stream)


def pipeline_loss(
    stacked: dict,
    cfg: LlamaConfig,
    ids: Array,
    mask: Array,
    mesh: Mesh,
    n_micro: int = 2,
) -> Array:
    """Mean next-token cross-entropy computed through the layer pipeline —
    the pp analogue of models/llama.py ``llama_loss``. ids/mask [B, T+1];
    B must divide into n_micro microbatches."""
    dt = cfg.jdtype
    inp, tgt = ids[:, :-1], ids[:, 1:]
    pm = mask[:, :-1]
    b, t = inp.shape
    if b % n_micro != 0:
        raise PipelineError(f"batch {b} not divisible by n_micro={n_micro}")
    mb = b // n_micro

    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (mb, t))
    cos, sin = L.rope_frequencies(cfg.head_dim, max(t, cfg.max_len), cfg.rope_theta)

    x = L.embed(stacked["embed_tokens"], inp, dt)            # [B, T, D]
    stream = x.reshape(n_micro, mb, t, cfg.dim)
    pad_stream = pm.reshape(n_micro, mb, t)

    out = pipeline_apply(stacked["layers"], cfg, stream, positions, cos, sin,
                         mesh, pad_stream)
    h = out.reshape(b, t, cfg.dim)
    h = L.rmsnorm(stacked["final_norm"], h, cfg.norm_eps)
    logits = L.dense(stacked["lm_head"], h, dt).astype(jnp.float32)

    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[:, :, None], axis=-1)[..., 0]
    weights = mask[:, 1:].astype(jnp.float32)
    return (nll * weights).sum() / jnp.maximum(weights.sum(), 1.0)
