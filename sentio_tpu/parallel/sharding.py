"""Sharding specs: regex partition rules → NamedSharding over the mesh.

This is the tensor-parallel half of the comm layer (SURVEY.md §2.12): weight
matrices get PartitionSpecs by parameter-path pattern, activations get batch
sharding over the data axes, and XLA inserts the all-reduces. Rules follow
the Megatron layout — attention QKV and MLP up/gate column-sharded (output
feature dim on ``tp``), attention out and MLP down row-sharded (input feature
dim on ``tp``) — so each transformer block needs exactly two psums.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sentio_tpu.parallel.mesh import AXIS_DCN, AXIS_DP, AXIS_EP, AXIS_TP

# (path regex, PartitionSpec). First match wins; unmatched params replicate.
# Param paths are "/"-joined pytree key paths, e.g. "layers_0/attn/wq/kernel".
Rules = Sequence[tuple[str, P]]

LLAMA_TP_RULES: Rules = (
    # embeddings: shard vocab dim (row) — logits psum'd at the head
    (r".*embed_tokens/embedding$", P(AXIS_TP, None)),
    (r".*lm_head/kernel$", P(None, AXIS_TP)),
    # attention: q/k/v column-parallel, o row-parallel
    (r".*attn/(wq|wk|wv)/kernel$", P(None, AXIS_TP)),
    (r".*attn/wo/kernel$", P(AXIS_TP, None)),
    # swiglu mlp: gate/up column-parallel, down row-parallel
    (r".*mlp/(w_gate|w_up)/kernel$", P(None, AXIS_TP)),
    (r".*mlp/w_down/kernel$", P(AXIS_TP, None)),
    # norms replicate
    (r".*norm.*", P()),
)

# MoE decoder: attention follows the Llama layout; expert-indexed weights
# shard experts over ``ep`` on the leading dim (expert parallelism — the
# dispatch/combine einsums become all_to_all-style collectives) and keep the
# Megatron column/row split on the per-expert matmul dims over ``tp``. The
# router is a tiny [d, E] projection — replicated.
MOE_EP_RULES: Rules = (
    (r".*moe/router/kernel$", P()),
    (r".*moe/(w_gate|w_up)$", P(AXIS_EP, None, AXIS_TP)),
    (r".*moe/w_down$", P(AXIS_EP, AXIS_TP, None)),
) + tuple(LLAMA_TP_RULES)

ENCODER_TP_RULES: Rules = (
    (r".*embed(_tokens|_positions)?/embedding$", P(None, None)),
    (r".*attn/(wq|wk|wv)/kernel$", P(None, AXIS_TP)),
    (r".*attn/wo/kernel$", P(AXIS_TP, None)),
    (r".*mlp/(w_gate|w_up|w_in)/kernel$", P(None, AXIS_TP)),
    (r".*mlp/(w_down|w_out)/kernel$", P(AXIS_TP, None)),
    (r".*", P()),
)


def path_str(path: tuple) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def spec_for(path: str, rules: Rules, ndim: int) -> P:
    """Resolve the PartitionSpec for one parameter path; pads/truncates the
    spec to the tensor rank so rules can be written for the common 2D case."""
    for pattern, spec in rules:
        if re.match(pattern, path):
            entries = tuple(spec)
            if len(entries) > ndim:
                entries = entries[-ndim:] if ndim > 0 else ()
            elif len(entries) < ndim:
                entries = (None,) * (ndim - len(entries)) + entries
            return P(*entries)
    return P()


def make_param_shardings(params: Any, mesh: Mesh, rules: Rules) -> Any:
    """Pytree of NamedShardings matching ``params``' structure."""

    def one(path, leaf):
        spec = spec_for(path_str(path), rules, getattr(leaf, "ndim", 0))
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params: Any, mesh: Mesh, rules: Rules) -> Any:
    """Place a host pytree onto the mesh according to the rules. This is the
    startup weight-load step (reference's lazy first-request init inverted —
    SURVEY.md §3.3)."""
    shardings = make_param_shardings(params, mesh, rules)
    return jax.device_put(params, shardings)


def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch) dim over all data axes, replicate the rest."""
    data = tuple(a for a in (AXIS_DCN, AXIS_DP) if mesh.shape[a] > 1)
    spec = P(data if data else None, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def describe_shardings(params: Any, mesh: Mesh, rules: Rules) -> dict[str, str]:
    """Human-readable {path: spec} map — surfaced by the health endpoint so
    operators can audit the layout without a debugger."""
    out: dict[str, str] = {}

    def one(path, leaf):
        p = path_str(path)
        out[p] = str(spec_for(p, rules, getattr(leaf, "ndim", 0)))
        return leaf

    jax.tree_util.tree_map_with_path(one, params)
    return out
