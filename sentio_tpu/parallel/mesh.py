"""Device mesh construction — the framework's "communication backend".

The reference scales with Kubernetes HPA over stateless pods and has no
NCCL/MPI layer (SURVEY.md §2.12). Here distribution is first-class: a
:class:`jax.sharding.Mesh` with named axes

* ``dp`` — data parallel (batch of coalesced requests) over ICI,
* ``pp`` — pipeline parallel (layer stages, ppermute activation handoff),
* ``ep`` — expert parallel (MoE expert sharding, all_to_all dispatch),
* ``sp`` — sequence/context parallel (ring attention) over ICI,
* ``tp`` — tensor parallel (model weight sharding) over ICI,

and an optional leading ``dcn`` data axis for multi-slice pods. All
collectives are XLA's (psum / all_gather / ppermute / all_to_all) — mesh
geometry and sharding specs are the entire comm layer; there is no socket
code to write.

Axis order matters on TPU: the innermost mesh dims map to the
torus-contiguous device order produced by ``mesh_utils.create_device_mesh``,
so tp (all-reduce heavy) is placed innermost to ride the fastest ICI links,
then sp (per-block ring hops), then ep (one all_to_all pair per MoE layer),
then pp (one point-to-point handoff per stage per microbatch — the least
bandwidth-hungry ICI axis), with dp/dcn outermost (gradient reductions only).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from sentio_tpu.config import MeshConfig

logger = logging.getLogger(__name__)

AXIS_DCN = "dcn"
AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_EP = "ep"
AXIS_SP = "sp"
AXIS_TP = "tp"

# canonical axis order, outermost → innermost
MESH_AXES = (AXIS_DCN, AXIS_DP, AXIS_PP, AXIS_EP, AXIS_SP, AXIS_TP)


class MeshError(Exception):
    pass


@dataclass(frozen=True)
class MeshSpec:
    """Resolved mesh geometry."""

    dcn: int
    dp: int
    pp: int
    ep: int
    sp: int
    tp: int

    @property
    def shape(self) -> tuple[int, int, int, int, int, int]:
        return (self.dcn, self.dp, self.pp, self.ep, self.sp, self.tp)

    @property
    def n_devices(self) -> int:
        return self.dcn * self.dp * self.pp * self.ep * self.sp * self.tp


def resolve_spec(config: MeshConfig, n_devices: int) -> MeshSpec:
    """Turn a (possibly partial) MeshConfig into concrete axis sizes.

    ``dp_size == 0`` means "absorb all remaining devices on the data axis" —
    the right default for a serving mesh where throughput scales with dp.
    """
    tp = max(1, config.tp_size)
    sp = max(1, config.sp_size)
    pp = max(1, config.pp_size)
    ep = max(1, config.ep_size)
    dcn = max(1, config.dcn_size)
    fixed = tp * sp * pp * ep * dcn
    if n_devices % fixed != 0:
        raise MeshError(
            f"{n_devices} devices not divisible by tp*sp*pp*ep*dcn={fixed} "
            f"(tp={tp}, sp={sp}, pp={pp}, ep={ep}, dcn={dcn})"
        )
    dp = config.dp_size if config.dp_size > 0 else n_devices // fixed
    spec = MeshSpec(dcn=dcn, dp=dp, pp=pp, ep=ep, sp=sp, tp=tp)
    if spec.n_devices != n_devices:
        raise MeshError(
            f"mesh {spec.shape} needs {spec.n_devices} devices, have {n_devices}"
        )
    return spec


def build_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global device mesh.

    Single-slice: ``mesh_utils.create_device_mesh`` lays devices out so that
    neighboring mesh coordinates are ICI neighbors. Multi-slice (dcn > 1):
    ``create_hybrid_device_mesh`` keeps the dcn axis across slices and every
    ICI axis within a slice, so tp/sp collectives never cross DCN.
    """
    config = config or MeshConfig()
    if devices is None:
        devices = jax.devices(config.backend) if config.backend else jax.devices()
    spec = resolve_spec(config, len(devices))

    if spec.dcn > 1:
        if not all(hasattr(d, "slice_index") for d in devices):
            # host-platform devices carry no slice topology — plain reshape
            # so multi-slice programs (dcn-axis shardings and the
            # collectives they imply) still compile+run on the virtual
            # mesh. Real pods take the hybrid path below, and its geometry
            # errors (slice count mismatch etc.) must stay LOUD: a silent
            # reshape there would route tp/sp collectives over DCN.
            dev_array = np.asarray(list(devices)).reshape(spec.shape)
        else:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=(1, spec.dp, spec.pp, spec.ep, spec.sp, spec.tp),
                dcn_mesh_shape=(spec.dcn, 1, 1, 1, 1, 1),
                devices=devices,
            )
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(spec.shape, devices=list(devices))
        except (ValueError, AssertionError):
            # host-platform or odd topologies: plain reshape is always valid
            dev_array = np.asarray(list(devices)).reshape(spec.shape)
    mesh = Mesh(dev_array, MESH_AXES)
    logger.info("mesh built: %s over %d %s devices", dict(zip(MESH_AXES, spec.shape)),
                spec.n_devices, dev_array.flat[0].platform)
    return mesh


def split_mesh_dp(mesh: Mesh, n: int) -> list[Mesh]:
    """Split ``mesh`` into ``n`` submeshes along the ``dp`` axis — one per
    data-parallel serving replica (runtime/replica.py). Each submesh keeps
    every other axis intact (tp/sp/pp/ep collectives stay inside a replica's
    device slice; no collective ever crosses replicas), so a replica engine
    built on a submesh shards its weights and KV pool exactly as it would on
    a whole mesh of that geometry. ``dp`` must divide evenly — a ragged
    split would give replicas different batch multiples and make routing
    load math meaningless."""
    if n <= 0:
        raise MeshError(f"cannot split a mesh into {n} replicas")
    if n == 1:
        return [mesh]
    dp = mesh.shape[AXIS_DP]
    if dp % n != 0:
        raise MeshError(
            f"mesh dp={dp} not divisible by {n} replicas — set MESH_DP to a "
            f"multiple of REPLICAS (or REPLICAS to a divisor of dp)"
        )
    axis = MESH_AXES.index(AXIS_DP)
    return [Mesh(chunk, MESH_AXES)
            for chunk in np.split(np.asarray(mesh.devices), n, axis=axis)]


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes a request batch is sharded over (all data-like axes)."""
    return tuple(a for a in (AXIS_DCN, AXIS_DP) if mesh.shape[a] > 1) or (AXIS_DP,)


def batch_multiple(mesh: Mesh) -> int:
    """Batches submitted to pjit'd fns must be a multiple of this."""
    return mesh.shape[AXIS_DCN] * mesh.shape[AXIS_DP]


_default_mesh: Optional[Mesh] = None


def get_mesh(config: Optional[MeshConfig] = None) -> Mesh:
    """Process-wide mesh singleton (built once at startup, like the
    reference's DI container owns its clients)."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = build_mesh(config)
    return _default_mesh


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh
