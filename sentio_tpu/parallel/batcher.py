"""Deadline-based request coalescing for TPU dispatch.

The reference's concurrency story is a connection pool to external services
(/root/reference/src/core/vector_store/async_qdrant_store.py:50-266). On TPU
the equivalent primitive is a *batcher*: concurrent requests (embed / rerank /
generate) are coalesced into one padded device batch so the MXU sees large
matmuls, with a deadline bound (default ~8 ms) so p50 latency doesn't pay for
occupancy. One compiled program per bucketed batch size; the batcher rounds
up to the bucket and the model side masks padding.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Generic, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

ProcessFn = Callable[[list[T]], Awaitable[Sequence[R]]]


class BatcherClosed(Exception):
    pass


@dataclass
class BatcherStats:
    batches: int = 0
    items: int = 0
    errors: int = 0
    occupancy_sum: float = 0.0
    wait_ms_sum: float = 0.0

    def snapshot(self) -> dict[str, float]:
        return {
            "batches": self.batches,
            "items": self.items,
            "errors": self.errors,
            "avg_occupancy": round(self.occupancy_sum / self.batches, 3) if self.batches else 0.0,
            "avg_wait_ms": round(self.wait_ms_sum / self.items, 3) if self.items else 0.0,
        }


@dataclass
class _Pending(Generic[T, R]):
    item: T
    future: "asyncio.Future[R]"
    enqueued_at: float = field(default_factory=time.perf_counter)


class Batcher(Generic[T, R]):
    """Coalesces awaited ``submit`` calls into batched ``process_fn`` calls.

    ``process_fn`` receives a list of items (1 <= n <= max_size) and must
    return one result per item, in order. A failing batch fails only the
    futures in that batch — the batcher itself stays up (circuit breaking
    happens a layer above, like the reference's resilience ladder).
    """

    def __init__(
        self,
        process_fn: ProcessFn,
        max_size: int = 8,
        deadline_ms: float = 8.0,
        name: str = "batcher",
    ) -> None:
        if max_size < 1:
            raise ValueError("max_size must be >= 1")
        self.process_fn = process_fn
        self.max_size = max_size
        self.deadline_s = max(deadline_ms, 0.0) / 1000.0
        self.name = name
        self.stats = BatcherStats()
        self._queue: asyncio.Queue[Optional[_Pending[T, R]]] = asyncio.Queue()
        self._worker: Optional[asyncio.Task] = None
        self._closed = False

    # ---------------------------------------------------------------- public

    async def submit(self, item: T) -> R:
        if self._closed:
            raise BatcherClosed(f"{self.name} is closed")
        self._ensure_worker()
        pending: _Pending[T, R] = _Pending(item, asyncio.get_running_loop().create_future())
        await self._queue.put(pending)
        return await pending.future

    async def close(self) -> None:
        self._closed = True
        if self._worker is not None:
            await self._queue.put(None)
            await self._worker
            self._worker = None

    # --------------------------------------------------------------- worker

    def _ensure_worker(self) -> None:
        if self._worker is None or self._worker.done():
            self._worker = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while True:
            head = await self._queue.get()
            if head is None:
                return
            batch = [head]
            deadline = time.perf_counter() + self.deadline_s
            while len(batch) < self.max_size:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = await asyncio.wait_for(self._queue.get(), timeout=remaining)
                except asyncio.TimeoutError:
                    break
                if nxt is None:
                    await self._dispatch(batch)
                    return
                batch.append(nxt)
            await self._dispatch(batch)

    async def _dispatch(self, batch: list[_Pending[T, R]]) -> None:
        now = time.perf_counter()
        self.stats.batches += 1
        self.stats.items += len(batch)
        self.stats.occupancy_sum += len(batch) / self.max_size
        self.stats.wait_ms_sum += sum((now - p.enqueued_at) * 1000.0 for p in batch)
        try:
            results = await self.process_fn([p.item for p in batch])
            if len(results) != len(batch):
                raise RuntimeError(
                    f"{self.name}: process_fn returned {len(results)} results "
                    f"for {len(batch)} items"
                )
            for pending, result in zip(batch, results):
                if not pending.future.done():
                    pending.future.set_result(result)
        except Exception as exc:  # noqa: BLE001 — fail the batch, not the batcher
            self.stats.errors += 1
            for pending in batch:
                if not pending.future.done():
                    pending.future.set_exception(exc)


def bucket_size(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (compile once per bucket, pad to it). When n
    exceeds every bucket the result is n itself — callers pad by
    ``bucket - n`` and that difference must never go negative; an exact-size
    compile is correct, just uncached."""
    for b in sorted(buckets):
        if n <= b:
            return b
    return n


def floor_bucket(n: int, buckets: Sequence[int]) -> int:
    """Largest bucket <= n (min(buckets) if none fit) — for quantities that
    must round DOWN, like decode step counts bounded by cache headroom."""
    best = min(buckets)
    for b in sorted(buckets):
        if b <= n:
            best = b
    return best
