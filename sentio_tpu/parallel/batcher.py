"""Deadline-based request coalescing for TPU dispatch.

The reference's concurrency story is a connection pool to external services
(/root/reference/src/core/vector_store/async_qdrant_store.py:50-266). On TPU
the equivalent primitive is a *batcher*: concurrent requests (embed / rerank /
generate) are coalesced into one padded device batch so the MXU sees large
matmuls, with a deadline bound (default ~8 ms) so p50 latency doesn't pay for
occupancy. One compiled program per bucketed batch size; the batcher rounds
up to the bucket and the model side masks padding.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Generic, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

ProcessFn = Callable[[list[T]], Awaitable[Sequence[R]]]


class BatcherClosed(Exception):
    pass


class BatcherTimeout(Exception):
    pass


@dataclass
class BatcherStats:
    batches: int = 0
    items: int = 0
    errors: int = 0
    occupancy_sum: float = 0.0
    wait_ms_sum: float = 0.0

    def snapshot(self) -> dict[str, float]:
        return {
            "batches": self.batches,
            "items": self.items,
            "errors": self.errors,
            "avg_occupancy": round(self.occupancy_sum / self.batches, 3) if self.batches else 0.0,
            "avg_wait_ms": round(self.wait_ms_sum / self.items, 3) if self.items else 0.0,
        }


@dataclass
class _Pending(Generic[T, R]):
    item: T
    future: "asyncio.Future[R]"
    enqueued_at: float = field(default_factory=time.perf_counter)


class Batcher(Generic[T, R]):
    """Coalesces awaited ``submit`` calls into batched ``process_fn`` calls.

    ``process_fn`` receives a list of items (1 <= n <= max_size) and must
    return one result per item, in order. A failing batch fails only the
    futures in that batch — the batcher itself stays up (circuit breaking
    happens a layer above, like the reference's resilience ladder).
    """

    def __init__(
        self,
        process_fn: ProcessFn,
        max_size: int = 8,
        deadline_ms: float = 8.0,
        name: str = "batcher",
    ) -> None:
        if max_size < 1:
            raise ValueError("max_size must be >= 1")
        self.process_fn = process_fn
        self.max_size = max_size
        self.deadline_s = max(deadline_ms, 0.0) / 1000.0
        self.name = name
        self.stats = BatcherStats()
        self._queue: asyncio.Queue[Optional[_Pending[T, R]]] = asyncio.Queue()
        self._worker: Optional[asyncio.Task] = None
        self._closed = False

    # ---------------------------------------------------------------- public

    async def submit(self, item: T) -> R:
        if self._closed:
            raise BatcherClosed(f"{self.name} is closed")
        self._ensure_worker()
        pending: _Pending[T, R] = _Pending(item, asyncio.get_running_loop().create_future())
        await self._queue.put(pending)
        return await pending.future

    async def close(self) -> None:
        self._closed = True
        if self._worker is not None:
            await self._queue.put(None)
            await self._worker
            self._worker = None

    # --------------------------------------------------------------- worker

    def _ensure_worker(self) -> None:
        if self._worker is None or self._worker.done():
            self._worker = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while True:
            head = await self._queue.get()
            if head is None:
                return
            batch = [head]
            deadline = time.perf_counter() + self.deadline_s
            while len(batch) < self.max_size:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = await asyncio.wait_for(self._queue.get(), timeout=remaining)
                except asyncio.TimeoutError:
                    break
                if nxt is None:
                    await self._dispatch(batch)
                    return
                batch.append(nxt)
            await self._dispatch(batch)

    async def _dispatch(self, batch: list[_Pending[T, R]]) -> None:
        now = time.perf_counter()
        self.stats.batches += 1
        self.stats.items += len(batch)
        self.stats.occupancy_sum += len(batch) / self.max_size
        self.stats.wait_ms_sum += sum((now - p.enqueued_at) * 1000.0 for p in batch)
        try:
            results = await self.process_fn([p.item for p in batch])
            if len(results) != len(batch):
                raise RuntimeError(
                    f"{self.name}: process_fn returned {len(results)} results "
                    f"for {len(batch)} items"
                )
            for pending, result in zip(batch, results):
                if not pending.future.done():
                    pending.future.set_result(result)
        except Exception as exc:  # noqa: BLE001 — fail the batch, not the batcher
            self.stats.errors += 1
            for pending in batch:
                if not pending.future.done():
                    pending.future.set_exception(exc)


class _SyncPending(Generic[T, R]):
    __slots__ = ("item", "event", "result", "error", "enqueued_at")

    def __init__(self, item: T) -> None:
        self.item = item
        self.event = threading.Event()
        self.result: Optional[R] = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.perf_counter()


class ThreadBatcher(Generic[T, R]):
    """Cross-THREAD deadline coalescer — the sync sibling of :class:`Batcher`.

    The serving pipeline runs synchronously on worker threads
    (``asyncio.to_thread`` per request, serve/handlers.py), so coalescing
    concurrent query embeddings / rerank scores into one padded device batch
    must happen below the event loop. ``submit`` blocks the calling thread
    until its result is ready; a single daemon dispatcher thread collects
    items for up to ``deadline_ms`` (or ``max_size``) and invokes the sync
    ``process_fn`` once per batch. Same contract as Batcher: one result per
    item, in order; a failing batch fails only its own callers.
    """

    def __init__(
        self,
        process_fn: Callable[[list[T]], Sequence[R]],
        max_size: int = 8,
        deadline_ms: float = 8.0,
        name: str = "thread-batcher",
        timeout_s: float = 120.0,
    ) -> None:
        if max_size < 1:
            raise ValueError("max_size must be >= 1")
        self.process_fn = process_fn
        self.max_size = max_size
        self.deadline_s = max(deadline_ms, 0.0) / 1000.0
        self.timeout_s = timeout_s
        self.name = name
        self.stats = BatcherStats()
        self._queue: deque[_SyncPending[T, R]] = deque()  # guarded-by: _cond
        self._cond = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._closed = False

    def submit(self, item: T) -> R:
        pending: _SyncPending[T, R] = _SyncPending(item)
        with self._cond:
            if self._closed:
                raise BatcherClosed(f"{self.name} is closed")
            self._queue.append(pending)
            self._ensure_worker()
            self._cond.notify_all()
        # bounded wait: a wedged process_fn (device stall, hung compile) must
        # surface as an error the resilience ladder can degrade on, not
        # deadlock every serving worker thread forever
        if not pending.event.wait(self.timeout_s):
            # mark abandoned so the dispatcher drops it instead of burning a
            # device batch on a result nobody is waiting for
            pending.error = BatcherTimeout(
                f"{self.name}: batch did not complete within {self.timeout_s:.0f}s"
            )
            pending.event.set()
            raise pending.error
        if pending.error is not None:
            raise pending.error
        return pending.result  # type: ignore[return-value]

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
            self._worker = None

    def _ensure_worker(self) -> None:  # _cond held
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(  # thread-role: batcher
                target=self._run, name=self.name, daemon=True
            )
            self._worker.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                deadline = time.perf_counter() + self.deadline_s
                while len(self._queue) < self.max_size and not self._closed:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch = []
                while self._queue and len(batch) < self.max_size:
                    pending = self._queue.popleft()
                    if not pending.event.is_set():  # skip timed-out waiters
                        batch.append(pending)
            if batch:
                self._dispatch(batch)

    def _dispatch(self, batch: list[_SyncPending[T, R]]) -> None:
        now = time.perf_counter()
        self.stats.batches += 1
        self.stats.items += len(batch)
        self.stats.occupancy_sum += len(batch) / self.max_size
        self.stats.wait_ms_sum += sum((now - p.enqueued_at) * 1000.0 for p in batch)
        try:
            results = self.process_fn([p.item for p in batch])
            if len(results) != len(batch):
                raise RuntimeError(
                    f"{self.name}: process_fn returned {len(results)} results "
                    f"for {len(batch)} items"
                )
            for pending, result in zip(batch, results):
                pending.result = result
                pending.event.set()
        except BaseException as exc:  # noqa: BLE001 — fail the batch, not the batcher
            self.stats.errors += 1
            for pending in batch:
                if not pending.event.is_set():
                    pending.error = exc
                    pending.event.set()
            # exiting exceptions must still exit: waiters are failed above,
            # but swallowing KeyboardInterrupt/SystemExit here would keep a
            # dying interpreter's worker thread spinning
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                raise


def bucket_size(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (compile once per bucket, pad to it). When n
    exceeds every bucket the result is n itself — callers pad by
    ``bucket - n`` and that difference must never go negative; an exact-size
    compile is correct, just uncached."""
    for b in sorted(buckets):
        if n <= b:
            return b
    return n


def floor_bucket(n: int, buckets: Sequence[int]) -> int:
    """Largest bucket <= n (min(buckets) if none fit) — for quantities that
    must round DOWN, like decode step counts bounded by cache headroom."""
    best = min(buckets)
    for b in sorted(buckets):
        if b <= n:
            best = b
    return best
