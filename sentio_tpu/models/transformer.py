"""Bidirectional transformer encoder (BERT/XLM-R family).

Backbone for both the bi-encoder embedder (replacing the reference's remote
Jina embeddings API, /root/reference/src/core/embeddings/providers/jina.py:33)
and the cross-encoder reranker (replacing api.jina.ai/v1/rerank,
jina_reranker.py:120-154). Post-LN residual blocks with learned positions and
token-type embeddings so weights of the public BERT/XLM-R/bge checkpoint
family convert directly (see models/convert.py).

Pure functions over an explicit param pytree; see models/layers.py for the
rationale. All shapes static; mask handles padding, so one compiled program
per (batch-bucket, seq-bucket).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from sentio_tpu.models import layers as L

Array = jax.Array


@dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 32_000
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    n_types: int = 2
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jdtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls) -> "EncoderConfig":
        """CPU-test scale (the deterministic 'fake backend' of SURVEY.md §4,
        but a real model with random weights rather than a mock)."""
        return cls(vocab_size=512, dim=64, n_layers=2, n_heads=2, mlp_dim=128, max_len=128)

    @classmethod
    def base(cls) -> "EncoderConfig":
        return cls(vocab_size=250_002, dim=1024, n_layers=24, n_heads=16, mlp_dim=4096, max_len=8192)


def init_encoder(rng: Array, cfg: EncoderConfig) -> dict:
    keys = iter(jax.random.split(rng, 4 + cfg.n_layers * 6))
    params: dict = {
        "embed_tokens": L.embed_init(next(keys), cfg.vocab_size, cfg.dim),
        "embed_positions": L.embed_init(next(keys), cfg.max_len, cfg.dim),
        "embed_types": L.embed_init(next(keys), cfg.n_types, cfg.dim),
        "embed_norm": L.layernorm_init(cfg.dim),
    }
    for i in range(cfg.n_layers):
        params[f"layers_{i}"] = {
            "attn": {
                "wq": L.dense_init(next(keys), cfg.dim, cfg.dim),
                "wk": L.dense_init(next(keys), cfg.dim, cfg.dim),
                "wv": L.dense_init(next(keys), cfg.dim, cfg.dim),
                "wo": L.dense_init(next(keys), cfg.dim, cfg.dim),
            },
            "attn_norm": L.layernorm_init(cfg.dim),
            "mlp": {
                "w_in": L.dense_init(next(keys), cfg.dim, cfg.mlp_dim),
                "w_out": L.dense_init(next(keys), cfg.mlp_dim, cfg.dim),
            },
            "mlp_norm": L.layernorm_init(cfg.dim),
        }
    return params


def encoder_forward(
    params: dict,
    cfg: EncoderConfig,
    ids: Array,
    mask: Array,
    type_ids: Optional[Array] = None,
    attn_fn=None,
) -> Array:
    """ids/mask: [B, T] (mask True = real token). Returns hidden [B, T, D].
    ``attn_fn`` (see sentio_tpu.kernels.encoder_attn_fn): bidirectional
    flash kernel taking (q, k, v, kv_lens); right-padded masks reduce to
    per-row lengths, so kernels see lengths instead of a [B,T] mask."""
    dt = cfg.jdtype
    b, t = ids.shape
    positions = jnp.arange(t)[None, :]
    x = (
        L.embed(params["embed_tokens"], ids, dt)
        + L.embed(params["embed_positions"], positions, dt)
    )
    if type_ids is not None:
        x = x + L.embed(params["embed_types"], type_ids, dt)
    x = L.layernorm(params["embed_norm"], x)

    attn_mask = (mask[:, None, None, :]).astype(bool)  # [B,1,1,T] keys masked
    kv_lens = mask.astype(jnp.int32).sum(axis=1) if attn_fn is not None else None
    for i in range(cfg.n_layers):
        lp = params[f"layers_{i}"]
        x = _block(lp, cfg, x, attn_mask, attn_fn, kv_lens)
    return x


def _block(lp: dict, cfg: EncoderConfig, x: Array, attn_mask: Array,
           attn_fn=None, kv_lens: Optional[Array] = None) -> Array:
    dt = cfg.jdtype
    b, t, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim

    q = L.dense(lp["attn"]["wq"], x, dt).reshape(b, t, h, hd)
    k = L.dense(lp["attn"]["wk"], x, dt).reshape(b, t, h, hd)
    v = L.dense(lp["attn"]["wv"], x, dt).reshape(b, t, h, hd)
    if attn_fn is not None:
        attn_out = attn_fn(q, k, v, kv_lens).reshape(b, t, d)
    else:
        attn_out = L.attention(q, k, v, attn_mask, dt).reshape(b, t, d)
    x = L.layernorm(lp["attn_norm"], x + L.dense(lp["attn"]["wo"], attn_out, dt))

    mlp = L.dense(lp["mlp"]["w_out"], jax.nn.gelu(L.dense(lp["mlp"]["w_in"], x, dt)), dt)
    return L.layernorm(lp["mlp_norm"], x + mlp)


def mean_pool(hidden: Array, mask: Array) -> Array:
    """Masked mean over tokens → L2-normalized embedding [B, D], float32."""
    m = mask.astype(jnp.float32)[:, :, None]
    summed = (hidden.astype(jnp.float32) * m).sum(axis=1)
    counts = jnp.maximum(m.sum(axis=1), 1.0)
    pooled = summed / counts
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def cls_pool(hidden: Array) -> Array:
    """First-token representation [B, D] (cross-encoder head input)."""
    return hidden[:, 0, :].astype(jnp.float32)
