"""Tokenizers: a reversible byte-level tokenizer, a word-hash tokenizer, and
an optional HuggingFace wrapper for real checkpoints.

The reference never tokenizes — its models are remote APIs and its token
budgeting approximates 4 chars/token (/root/reference/src/core/graph/
nodes.py:296-338). In-process models need the real thing:

* :class:`ByteTokenizer` — vocab = 256 bytes + specials, fully reversible.
  The test/dev tokenizer: tiny models trained/ran over bytes round-trip text
  exactly, so the whole generate→verify pipeline is drivable offline.
* :class:`WordHashTokenizer` — deterministic word→id hashing; the encoder
  fake-backend tokenizer (stable ids, no vocab file), mirroring the
  reference's hash-seeded mock embeddings pattern (jina.py:141-159 there).
* :class:`HFTokenizer` — wraps a local ``transformers`` tokenizer for real
  checkpoints (Llama-3, bge, XLM-R). Local files only; never downloads.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Protocol, Sequence

import numpy as np


class Tokenizer(Protocol):
    vocab_size: int
    pad_id: int
    bos_id: int
    eos_id: int
    cls_id: int
    sep_id: int

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...


def batch_encode(
    tokenizer: "Tokenizer",
    texts: Sequence[str],
    max_len: int,
    add_bos: bool = False,
    add_eos: bool = False,
    pad_to: Optional[int] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Encode + truncate + right-pad a batch. Returns (ids, mask) int32/bool
    arrays shaped [B, L] with L = pad_to or the longest row (<= max_len)."""
    rows = [tokenizer.encode(t, add_bos=add_bos, add_eos=add_eos)[:max_len] for t in texts]
    rows = [r if r else [tokenizer.pad_id] for r in rows]
    width = pad_to if pad_to is not None else max(len(r) for r in rows)
    width = max(min(width, max_len), 1)
    ids = np.full((len(rows), width), tokenizer.pad_id, dtype=np.int32)
    mask = np.zeros((len(rows), width), dtype=bool)
    for i, r in enumerate(rows):
        r = r[:width]
        ids[i, : len(r)] = r
        mask[i, : len(r)] = True
    return ids, mask


def batch_encode_pairs(
    tokenizer: "Tokenizer",
    pairs: Sequence[tuple[str, str]],
    max_len: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Cross-encoder input: [CLS] a [SEP] b [SEP] with type ids 0/1.
    The first segment keeps at most half the budget; the doc gets the rest."""
    ids = np.full((len(pairs), max_len), tokenizer.pad_id, dtype=np.int32)
    mask = np.zeros((len(pairs), max_len), dtype=bool)
    types = np.zeros((len(pairs), max_len), dtype=np.int32)
    for i, (a, b) in enumerate(pairs):
        a_ids = tokenizer.encode(a)[: max_len // 2 - 2]
        b_budget = max_len - len(a_ids) - 3
        b_ids = tokenizer.encode(b)[: max(b_budget, 0)]
        row = [tokenizer.cls_id] + a_ids + [tokenizer.sep_id] + b_ids + [tokenizer.sep_id]
        row = row[:max_len]
        ids[i, : len(row)] = row
        mask[i, : len(row)] = True
        boundary = min(len(a_ids) + 2, max_len)
        types[i, boundary : len(row)] = 1
    return ids, mask, types


@dataclass
class _SpecialIds:
    pad_id: int
    bos_id: int
    eos_id: int
    cls_id: int
    sep_id: int


class ByteTokenizer:
    """UTF-8 bytes + 5 specials. ``decode(encode(s)) == s`` for any string."""

    def __init__(self, vocab_size: int = 512) -> None:
        if vocab_size < 261:
            raise ValueError("ByteTokenizer needs vocab_size >= 261")
        self.vocab_size = vocab_size
        self.pad_id, self.bos_id, self.eos_id, self.cls_id, self.sep_id = range(256, 261)

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        """Bytes decode; specials are dropped, but UNUSED vocab slots (the
        MXU-alignment padding above the specials) render as the replacement
        char — a random-init model sampling them must yield visible output,
        not a silently empty string (which reads as 'no answer' downstream)."""
        out = bytearray()
        for i in ids:
            if 0 <= i < 256:
                out.append(i)
            elif i > self.sep_id:  # unused padded-vocab slot
                out.extend("�".encode())
        return out.decode("utf-8", errors="replace")


class WordHashTokenizer:
    """Stable word→id hash (md5, like the reference's deterministic mock
    embeddings). Irreversible; decode returns placeholder tokens."""

    def __init__(self, vocab_size: int = 512) -> None:
        if vocab_size < 16:
            raise ValueError("vocab too small")
        self.vocab_size = vocab_size
        self.pad_id, self.bos_id, self.eos_id, self.cls_id, self.sep_id = range(5)
        self._n_special = 8

    def _hash(self, word: str) -> int:
        h = int.from_bytes(hashlib.md5(word.encode()).digest()[:4], "little")
        return self._n_special + h % (self.vocab_size - self._n_special)

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> list[int]:
        ids = [self._hash(w) for w in text.lower().split()]
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return " ".join(f"<{i}>" for i in ids if i >= self._n_special)


class HFTokenizer:
    """Adapter over a local HuggingFace tokenizer directory. Import of
    ``transformers`` is deferred and the path must exist locally — this
    framework performs no network access for model assets."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer  # deferred heavy import

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = int(self._tok.vocab_size)
        ids = _SpecialIds(
            pad_id=self._tok.pad_token_id if self._tok.pad_token_id is not None else 0,
            bos_id=self._tok.bos_token_id if self._tok.bos_token_id is not None else 0,
            eos_id=self._tok.eos_token_id if self._tok.eos_token_id is not None else 0,
            cls_id=self._tok.cls_token_id if self._tok.cls_token_id is not None else 0,
            sep_id=self._tok.sep_token_id if self._tok.sep_token_id is not None else 0,
        )
        self.pad_id, self.bos_id, self.eos_id = ids.pad_id, ids.bos_id, ids.eos_id
        self.cls_id, self.sep_id = ids.cls_id, ids.sep_id

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode([i for i in ids], skip_special_tokens=True)


def get_tokenizer(kind: str, vocab_size: int = 512, path: str = "") -> Tokenizer:
    if kind == "byte":
        return ByteTokenizer(vocab_size)
    if kind == "hash":
        return WordHashTokenizer(vocab_size)
    if kind == "hf":
        return HFTokenizer(path)
    raise ValueError(f"unknown tokenizer kind {kind!r}")
