"""Core document model.

Capability parity with the reference's ``Document`` dataclass
(/root/reference/src/core/models/document.py:8-20): ``text`` + ``metadata`` +
auto-uuid ``id``. We additionally carry an optional host-side ``embedding``
(numpy array) because in this framework embeddings are produced in-process
(TPU forward pass) and flow through the ingest pipeline with the document
rather than living only in an external vector store.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Optional


def _new_id() -> str:
    return str(uuid.uuid4())


@dataclass
class Document:
    """A unit of retrievable text with metadata and optional embedding."""

    text: str
    metadata: dict[str, Any] = field(default_factory=dict)
    id: str = field(default_factory=_new_id)
    embedding: Optional[Any] = None  # numpy ndarray when present; never a jax array

    def __post_init__(self) -> None:
        if self.metadata is None:
            self.metadata = {}

    @property
    def content(self) -> str:
        """Text with the reference's content-normalization fallback.

        The reference tolerates documents whose text migrated into
        ``metadata['content']`` (nodes.py:76-79 there); we keep that contract
        so payloads from external stores round-trip.
        """
        if self.text:
            return self.text
        return str(self.metadata.get("content", "") or "")

    def score(self, default: float = 0.0) -> float:
        """Best-known relevance score from metadata."""
        for key in ("hybrid_score", "rerank_score", "score"):
            value = self.metadata.get(key)
            if value is not None:
                try:
                    return float(value)
                except (TypeError, ValueError):
                    continue
        return default

    def to_dict(self) -> dict[str, Any]:
        return {"id": self.id, "text": self.content, "metadata": dict(self.metadata)}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "Document":
        return cls(
            text=str(data.get("text", "") or ""),
            metadata=dict(data.get("metadata", {}) or {}),
            id=str(data.get("id") or _new_id()),
        )
