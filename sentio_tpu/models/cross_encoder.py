"""Cross-encoder relevance scorer (bge-reranker-base class).

TPU-native replacement for the reference's remote rerank API
(/root/reference/src/core/rerankers/jina_reranker.py:120-154): (query, doc)
pairs are tokenized as ``[CLS] q [SEP] d [SEP]`` with token types, run
through the shared bidirectional encoder, and the [CLS] state feeds a scalar
relevance head. Batched pairs → one forward pass → scores; the MXU sees one
big matmul stack instead of N HTTP calls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sentio_tpu.models import layers as L
from sentio_tpu.models.transformer import EncoderConfig, cls_pool, encoder_forward, init_encoder

Array = jax.Array


def init_cross_encoder(rng: Array, cfg: EncoderConfig) -> dict:
    enc_rng, head_rng = jax.random.split(rng)
    return {
        "encoder": init_encoder(enc_rng, cfg),
        "head": L.dense_init(head_rng, cfg.dim, 1),
    }


def cross_encoder_scores(
    params: dict,
    cfg: EncoderConfig,
    ids: Array,
    mask: Array,
    type_ids: Array,
    attn_fn=None,
) -> Array:
    """[B, T] pair encodings → [B] float32 relevance scores (unbounded;
    consumers sigmoid or rank directly — ranking only needs order).

    An optional ``pooler`` stage (dense + tanh over [CLS], present when
    converting RoBERTa/bge-class classification heads — models/convert.py)
    runs between pooling and the scalar head. ``attn_fn``: bidirectional
    flash kernel (see sentio_tpu.kernels), XLA attention when None."""
    hidden = encoder_forward(params["encoder"], cfg, ids, mask, type_ids,
                             attn_fn=attn_fn)
    pooled = cls_pool(hidden)
    if "pooler" in params:
        pooled = jnp.tanh(L.dense(params["pooler"], pooled, jnp.float32))
    scores = L.dense(params["head"], pooled, jnp.float32)
    return scores[:, 0].astype(jnp.float32)
