"""Mixture-of-Experts decoder: Llama geometry with routed SwiGLU experts.

The reference delegates all model compute to hosted APIs (SURVEY.md §0) and
has no model families of its own; this family exists so the framework's
generator seam can serve sparse models at the same per-token FLOP cost as a
much smaller dense model — the standard scale path on TPU pods.

Design (GShard/Switch-style, static shapes throughout — XLA-friendly):

* Each block keeps the Llama attention (reused from models/llama.py) and
  replaces the dense SwiGLU with ``n_experts`` SwiGLU experts plus a linear
  router. Top-``experts_per_token`` routing with renormalized gates.
* Dispatch/combine are one-hot einsums over a fixed per-expert capacity
  ``C = ceil(G·k/E · capacity_factor)`` — tokens over capacity are dropped
  (their residual stream passes through untouched), which keeps every shape
  static under jit.
* Expert parallelism is pure sharding: expert-indexed weights carry the
  ``ep`` mesh axis on their leading dim (MOE_EP_RULES in
  parallel/sharding.py), token activations stay on the data axes, and XLA
  lowers the dispatch/combine einsums to all_to_all-style collectives over
  ICI. No manual collectives here — mesh geometry is the comm layer.
* The router computes in float32 (softmax stability) and adds the Switch
  load-balance auxiliary loss so training keeps experts utilized.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from sentio_tpu.models import layers as L
from sentio_tpu.models.llama import Cache, LlamaConfig, _attn, init_cache  # noqa: F401

Array = jax.Array


@dataclass(frozen=True)
class MoeConfig(LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01

    @classmethod
    def tiny(cls) -> "MoeConfig":
        """CPU-test scale, byte-level vocab."""
        return cls(
            vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            mlp_dim=128, max_len=512, rope_theta=10_000.0,
            n_experts=4, experts_per_token=2,
        )


def init_moe(rng: Array, cfg: MoeConfig) -> dict:
    keys = iter(jax.random.split(rng, 2 + cfg.n_layers * 8))
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    params: dict = {
        "embed_tokens": L.embed_init(next(keys), cfg.vocab_size, cfg.dim),
        "lm_head": L.dense_init(next(keys), cfg.dim, cfg.vocab_size, with_bias=False),
        "final_norm": L.rmsnorm_init(cfg.dim),
    }

    def expert_stack(key, in_dim, out_dim):
        ws = [
            L.dense_init(k, in_dim, out_dim, with_bias=False)["kernel"]
            for k in jax.random.split(key, cfg.n_experts)
        ]
        return jnp.stack(ws)  # [E, in, out]

    for i in range(cfg.n_layers):
        params[f"layers_{i}"] = {
            "attn_norm": L.rmsnorm_init(cfg.dim),
            "attn": {
                "wq": L.dense_init(next(keys), cfg.dim, cfg.dim, with_bias=False),
                "wk": L.dense_init(next(keys), cfg.dim, kv_dim, with_bias=False),
                "wv": L.dense_init(next(keys), cfg.dim, kv_dim, with_bias=False),
                "wo": L.dense_init(next(keys), cfg.dim, cfg.dim, with_bias=False),
            },
            "mlp_norm": L.rmsnorm_init(cfg.dim),
            "moe": {
                "router": L.dense_init(next(keys), cfg.dim, cfg.n_experts, with_bias=False),
                "w_gate": expert_stack(next(keys), cfg.dim, cfg.mlp_dim),
                "w_up": expert_stack(next(keys), cfg.dim, cfg.mlp_dim),
                "w_down": expert_stack(next(keys), cfg.mlp_dim, cfg.dim),
            },
        }
    return params


def expert_capacity(cfg: MoeConfig, n_tokens: int) -> int:
    import math

    per_expert = n_tokens * cfg.experts_per_token / cfg.n_experts
    return max(1, math.ceil(per_expert * cfg.capacity_factor))


def route_topk(
    logits: Array, k: int, capacity: int, valid: Optional[Array] = None
) -> tuple[Array, Array, Array]:
    """GShard-style top-k dispatch with fixed capacity.

    logits [G, E] (float32) → (dispatch [G, E, C] bool, combine [G, E, C]
    float32, aux scalar). Tokens beyond an expert's capacity in choice-
    priority order are dropped (combine weight 0). Gates of the kept choices
    are renormalized over the *selected* experts. ``valid`` [G] bool masks
    padding tokens out entirely: they take no capacity slots and contribute
    nothing to the load-balance aux statistics.
    """
    g, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    vmask = (
        jnp.ones((g,), jnp.float32) if valid is None else valid.astype(jnp.float32)
    )

    remaining = probs
    counts = jnp.zeros((e,), jnp.float32)
    dispatch = jnp.zeros((g, e, capacity), bool)
    combine = jnp.zeros((g, e, capacity), jnp.float32)
    gate_total = jnp.zeros((g,), jnp.float32)

    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                  # [G]
        gate = jnp.take_along_axis(probs, idx[:, None], 1)[:, 0]
        # padding tokens choose nothing: zeroed one-hots take no buffer
        # positions and advance no expert counts
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32) * vmask[:, None]
        # position of each token within its chosen expert's buffer
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot) + counts[None, :]
        pos = (pos_in_expert * onehot).sum(-1)                # [G]
        keep = (pos < capacity) & (vmask > 0)
        pos_oh = jax.nn.one_hot(
            jnp.clip(pos, 0, capacity - 1).astype(jnp.int32), capacity,
            dtype=jnp.float32,
        )                                                     # [G, C]
        slot = onehot[:, :, None] * pos_oh[:, None, :]        # [G, E, C]
        slot = slot * keep[:, None, None]
        dispatch = dispatch | (slot > 0)
        combine = combine + slot * gate[:, None, None]
        gate_total = gate_total + gate * keep
        counts = counts + onehot.sum(0)
        remaining = remaining * (1.0 - onehot)

    # renormalize kept gates so each token's expert mix sums to 1
    combine = combine / jnp.maximum(gate_total[:, None, None], 1e-9)

    # Switch aux loss over REAL tokens only: E * sum_e (fraction ASSIGNED to
    # e, pre-drop — capacity clipping must not cap the imbalance signal) *
    # (mean router prob of e)
    n_valid = jnp.maximum(vmask.sum(), 1.0)
    frac = counts / jnp.maximum(counts.sum(), 1.0)
    mean_prob = (probs * vmask[:, None]).sum(0) / n_valid
    aux = (frac * mean_prob).sum() * e
    return dispatch, combine, aux


def moe_mlp(
    mp: dict, cfg: MoeConfig, x: Array, pad_mask: Optional[Array] = None
) -> tuple[Array, Array]:
    """Routed SwiGLU over x [B, T, D] → (out [B, T, D], aux loss scalar).
    ``pad_mask`` [B, T] keeps padding tokens from consuming expert capacity
    or skewing the load-balance statistics."""
    dt = cfg.jdtype
    b, t, d = x.shape
    flat = x.reshape(b * t, d)
    capacity = expert_capacity(cfg, b * t)

    logits = L.dense(mp["router"], flat, jnp.float32)          # [G, E] f32
    valid = None if pad_mask is None else pad_mask.reshape(b * t)
    dispatch, combine, aux = route_topk(
        logits, cfg.experts_per_token, capacity, valid
    )

    # dispatch tokens to per-expert buffers: [E, C, D]
    expert_in = jnp.einsum(
        "gec,gd->ecd", dispatch.astype(dt), flat.astype(dt)
    )
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, mp["w_gate"].astype(dt)))
    up = jnp.einsum("ecd,edf->ecf", expert_in, mp["w_up"].astype(dt))
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up, mp["w_down"].astype(dt))

    out = jnp.einsum("gec,ecd->gd", combine.astype(dt), expert_out)
    return out.reshape(b, t, d), aux


def moe_forward(
    params: dict,
    cfg: MoeConfig,
    ids: Array,
    positions: Optional[Array] = None,
    cache: Optional[Cache] = None,
    cache_index: Array | int = 0,
    pad_mask: Optional[Array] = None,
    attn_fn=None,
) -> tuple[Array, Optional[Cache], Array]:
    """ids [B, T] → (logits [B, T, vocab] f32, cache, total aux loss).

    Prefill/decode (cache + positions) semantics match models/llama.py
    ``llama_forward``, but the return adds a trailing router-aux scalar the
    training loss consumes — serving code that expects the two-tuple
    contract uses :func:`moe_serving_forward`, which drops it.
    """
    dt = cfg.jdtype
    b, t = ids.shape
    if cache is not None:
        cache = dict(cache)
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    rope_len = cache["k"].shape[2] if cache is not None else max(t, cfg.max_len)
    cos, sin = L.rope_frequencies(cfg.head_dim, rope_len, cfg.rope_theta)

    x = L.embed(params["embed_tokens"], ids, dt)
    aux_total = jnp.zeros((), jnp.float32)
    for i in range(cfg.n_layers):
        lp = params[f"layers_{i}"]
        attn_out, cache = _attn(
            lp["attn"], cfg, L.rmsnorm(lp["attn_norm"], x, cfg.norm_eps),
            positions, cos, sin, i, cache, cache_index, pad_mask, attn_fn,
        )
        x = x + attn_out
        moe_out, aux = moe_mlp(
            lp["moe"], cfg, L.rmsnorm(lp["mlp_norm"], x, cfg.norm_eps), pad_mask
        )
        x = x + moe_out
        aux_total = aux_total + aux
    x = L.rmsnorm(params["final_norm"], x, cfg.norm_eps)
    logits = L.dense(params["lm_head"], x, dt)
    return logits.astype(jnp.float32), cache, aux_total


def moe_serving_forward(
    params: dict,
    cfg: MoeConfig,
    ids: Array,
    positions: Optional[Array] = None,
    cache: Optional[Cache] = None,
    cache_index: Array | int = 0,
    pad_mask: Optional[Array] = None,
    attn_fn=None,
) -> tuple[Array, Optional[Cache]]:
    """Two-tuple adapter matching ``llama_forward``'s serving contract
    (runtime/engine.py, runtime/paged.py unpack ``logits, cache``); the
    router aux loss is a training-only signal and is dropped here."""
    logits, cache, _ = moe_forward(
        params, cfg, ids, positions, cache, cache_index, pad_mask, attn_fn
    )
    return logits, cache


def moe_loss(params: dict, cfg: MoeConfig, ids: Array, mask: Array) -> Array:
    """Next-token cross-entropy + router aux — the ep train-step objective."""
    logits, _, aux = moe_forward(params, cfg, ids[:, :-1], pad_mask=mask[:, :-1])
    targets = ids[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[:, :, None], axis=-1)[..., 0]
    weights = mask[:, 1:].astype(jnp.float32)
    ce = (nll * weights).sum() / jnp.maximum(weights.sum(), 1.0)
    return ce + cfg.router_aux_weight * aux
