"""Shared neural net primitives — pure-JAX functional style.

Every model in the framework (bi-encoder, cross-encoder, decoder LM) is an
explicit parameter pytree + pure apply functions. No module framework: param
paths are then stable and human-chosen, which is what the tensor-parallel
partition rules in :mod:`sentio_tpu.parallel.sharding` match on, and the KV
cache threads through calls as a plain pytree (jit/pjit-friendly, no mutable
state). Compute dtype is bfloat16 on TPU (MXU-native); params stay float32
and are cast at use.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array
PyTree = dict


def dense_init(rng: Array, in_dim: int, out_dim: int, with_bias: bool = True) -> PyTree:
    """Truncated-normal fan-in init, matching transformer practice."""
    std = 1.0 / np.sqrt(in_dim)
    kernel = jax.random.truncated_normal(rng, -2.0, 2.0, (in_dim, out_dim)) * std
    params = {"kernel": kernel.astype(jnp.float32)}
    if with_bias:
        params["bias"] = jnp.zeros((out_dim,), jnp.float32)
    return params


def dense(params: PyTree, x: Array, dtype: jnp.dtype = jnp.bfloat16) -> Array:
    y = x.astype(dtype) @ params["kernel"].astype(dtype)
    if "bias" in params:
        y = y + params["bias"].astype(dtype)
    return y


def embed_init(rng: Array, vocab: int, dim: int) -> PyTree:
    emb = jax.random.normal(rng, (vocab, dim)) * 0.02
    return {"embedding": emb.astype(jnp.float32)}


def embed(params: PyTree, ids: Array, dtype: jnp.dtype = jnp.bfloat16) -> Array:
    return params["embedding"].astype(dtype)[ids]


def layernorm_init(dim: int) -> PyTree:
    return {"scale": jnp.ones((dim,), jnp.float32), "bias": jnp.zeros((dim,), jnp.float32)}


def layernorm(params: PyTree, x: Array, eps: float = 1e-6) -> Array:
    # norm math in fp32 for stability, output back in input dtype
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * params["scale"] + params["bias"]
    return y.astype(x.dtype)


def rmsnorm_init(dim: int) -> PyTree:
    return {"scale": jnp.ones((dim,), jnp.float32)}


def rmsnorm(params: PyTree, x: Array, eps: float = 1e-5) -> Array:
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt((xf * xf).mean(-1, keepdims=True) + eps)
    return (y * params["scale"]).astype(x.dtype)


def rope_frequencies(head_dim: int, max_len: int, theta: float = 10_000.0) -> tuple[Array, Array]:
    """Precomputed cos/sin tables [max_len, head_dim//2], float32."""
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    t = np.arange(max_len)
    freqs = np.outer(t, inv_freq)
    return jnp.asarray(np.cos(freqs), jnp.float32), jnp.asarray(np.sin(freqs), jnp.float32)


def apply_rope(x: Array, positions: Array, cos: Array, sin: Array) -> Array:
    """Rotate q/k. x: [B, T, H, D]; positions: [B, T] absolute positions
    (explicit, so paged/continuation decode just passes offsets)."""
    c = cos[positions][:, :, None, :]  # [B, T, 1, D/2]
    s = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def attention(
    q: Array,
    k: Array,
    v: Array,
    mask: Optional[Array],
    dtype: jnp.dtype = jnp.bfloat16,
) -> Array:
    """Plain batched MHA core: q [B,T,H,D], k/v [B,S,H,D], mask broadcastable
    to [B,H,T,S] (True = attend). Softmax in fp32. The Pallas flash kernel in
    :mod:`sentio_tpu.kernels` replaces this on TPU for long sequences; this
    XLA form is the universal fallback and fuses well for moderate T."""
    head_dim = q.shape[-1]
    scale = 1.0 / np.sqrt(head_dim)
    logits = jnp.einsum("bthd,bshd->bhts", q.astype(dtype), k.astype(dtype))
    logits = logits.astype(jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", weights.astype(dtype), v.astype(dtype))
    return out


def repeat_kv(x: Array, n_rep: int) -> Array:
    """GQA: expand kv heads to match query heads. [B,S,Hkv,D] -> [B,S,Hkv*n,D]."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def causal_mask(t: int, s: Optional[int] = None, offset: int = 0) -> Array:
    """[1, 1, T, S] boolean causal mask; offset shifts query positions (decode
    with cache: query i attends keys <= offset + i)."""
    s = s if s is not None else t
    qi = jnp.arange(t)[:, None] + offset
    kj = jnp.arange(s)[None, :]
    return (kj <= qi)[None, None, :, :]
