"""Decoder-only LM (Llama-3 family): RMSNorm pre-norm, RoPE, GQA, SwiGLU.

The generator AND verifier of the pipeline — one set of weights serves both
(the reference made two HTTP calls to a hosted model per request:
/root/reference/src/core/llm/providers/openai.py:117, answer_verifier.py:47;
here both are forward passes on the same sharded params).

Pure functions over an explicit param pytree (see models/layers.py). The KV
cache is an explicit pytree threaded through calls, stacked over layers
([L, B, S, Hkv, D]) so one PartitionSpec shards every layer's cache: batch on
``dp``, kv-heads on ``tp``. Static shapes throughout: prefill pads to a
bucket, decode attends over the full cache window under a position mask —
one compiled program per (batch-bucket, cache-bucket).

Tensor-parallel layout is Megatron-style via the path rules in
parallel/sharding.py: wq/wk/wv/w_gate/w_up column-sharded, wo/w_down
row-sharded → two psums per block, inserted by XLA from the shardings.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from sentio_tpu.analysis.audit.registry import jit_family
from sentio_tpu.models import layers as L

Array = jax.Array
Cache = dict  # {"k": [L,B,S,Hkv,D], "v": [L,B,S,Hkv,D]}


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14_336
    max_len: int = 8192
    rope_theta: float = 500_000.0
    dtype: str = "bfloat16"
    norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jdtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """CPU-test scale; byte-level vocab (ByteTokenizer round-trips)."""
        return cls(
            vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            mlp_dim=128, max_len=512, rope_theta=10_000.0,
        )


def init_llama(rng: Array, cfg: LlamaConfig) -> dict:
    keys = iter(jax.random.split(rng, 2 + cfg.n_layers * 7))
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    params: dict = {
        "embed_tokens": L.embed_init(next(keys), cfg.vocab_size, cfg.dim),
        "lm_head": L.dense_init(next(keys), cfg.dim, cfg.vocab_size, with_bias=False),
        "final_norm": L.rmsnorm_init(cfg.dim),
    }
    for i in range(cfg.n_layers):
        params[f"layers_{i}"] = {
            "attn_norm": L.rmsnorm_init(cfg.dim),
            "attn": {
                "wq": L.dense_init(next(keys), cfg.dim, cfg.dim, with_bias=False),
                "wk": L.dense_init(next(keys), cfg.dim, kv_dim, with_bias=False),
                "wv": L.dense_init(next(keys), cfg.dim, kv_dim, with_bias=False),
                "wo": L.dense_init(next(keys), cfg.dim, cfg.dim, with_bias=False),
            },
            "mlp_norm": L.rmsnorm_init(cfg.dim),
            "mlp": {
                "w_gate": L.dense_init(next(keys), cfg.dim, cfg.mlp_dim, with_bias=False),
                "w_up": L.dense_init(next(keys), cfg.dim, cfg.mlp_dim, with_bias=False),
                "w_down": L.dense_init(next(keys), cfg.mlp_dim, cfg.dim, with_bias=False),
            },
        }
    return params


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> Cache:
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.jdtype), "v": jnp.zeros(shape, cfg.jdtype)}


def _write_cache(cache_layer: Array, kv: Array, index: Array | int) -> Array:
    """Write kv [B,T,H,D] into cache_layer [B,S,H,D] at seq offset ``index``
    (scalar) or per-row offsets (vector [B])."""
    idx = jnp.asarray(index)
    if idx.ndim == 0:
        return jax.lax.dynamic_update_slice(cache_layer, kv, (0, idx, 0, 0))
    return jax.vmap(
        lambda row_cache, row_kv, row_idx: jax.lax.dynamic_update_slice(
            row_cache, row_kv, (row_idx, 0, 0)
        )
    )(cache_layer, kv, idx)


def _attn(
    lp: dict,
    cfg: LlamaConfig,
    x: Array,
    positions: Array,
    cos: Array,
    sin: Array,
    layer: int,
    cache: Optional[Cache],
    cache_index: Array,
    pad_mask: Optional[Array],
    attn_fn=None,
) -> tuple[Array, Optional[Cache]]:
    dt = cfg.jdtype
    b, t, d = x.shape
    h, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    q = L.dense(lp["wq"], x, dt).reshape(b, t, h, hd)
    k = L.dense(lp["wk"], x, dt).reshape(b, t, hkv, hd)
    v = L.dense(lp["wv"], x, dt).reshape(b, t, hkv, hd)
    q = L.apply_rope(q, positions, cos, sin)
    k = L.apply_rope(k, positions, cos, sin)

    # kernels (flash/ring) apply for multi-token causal attention where the
    # query block starts at position 0 (prefill writes at slot 0, training has
    # no cache) — exactly when positions == arange(t); decode (t == 1) and
    # ragged offsets use the masked XLA path
    use_kernel = attn_fn is not None and t > 1

    if cache is not None:
        # write this step's k/v into the cache window at cache_index, which is
        # a scalar (aligned prefill) or [B] vector (ragged decode: coalesced
        # sequences of different lengths each write at their own slot)
        k_cache = _write_cache(cache["k"][layer], k.astype(dt), cache_index)
        v_cache = _write_cache(cache["v"][layer], v.astype(dt), cache_index)
        cache["k"] = cache["k"].at[layer].set(k_cache)
        cache["v"] = cache["v"].at[layer].set(v_cache)
        s = k_cache.shape[1]
        # query i (absolute pos = positions[:, i]) attends keys j <= pos_i
        kj = jnp.arange(s)[None, None, None, :]
        mask = kj <= positions[:, None, :, None]  # [B,1,T,S]
        k_full, v_full = k_cache, v_cache
        kv_lens = None  # causal mask already hides the uninitialized tail
    else:
        s = t
        mask = L.causal_mask(t)
        if pad_mask is not None:
            mask = mask & pad_mask[:, None, None, :]
        k_full, v_full = k, v
        # right-padded batches → per-row valid lengths for the kernel
        kv_lens = pad_mask.sum(axis=1).astype(jnp.int32) if pad_mask is not None else None

    k_full = L.repeat_kv(k_full, h // hkv)
    v_full = L.repeat_kv(v_full, h // hkv)
    if use_kernel:
        out = attn_fn(q, k_full, v_full, kv_lens).reshape(b, t, d)
    else:
        out = L.attention(q, k_full, v_full, mask, dt).reshape(b, t, d)
    return L.dense(lp["wo"], out, dt), cache


def _mlp(lp: dict, cfg: LlamaConfig, x: Array) -> Array:
    dt = cfg.jdtype
    gate = jax.nn.silu(L.dense(lp["w_gate"], x, dt))
    up = L.dense(lp["w_up"], x, dt)
    return L.dense(lp["w_down"], gate * up, dt)


def block_forward(
    lp: dict,
    cfg: LlamaConfig,
    x: Array,
    positions: Array,
    cos: Array,
    sin: Array,
    pad_mask: Optional[Array] = None,
    attn_fn=None,
) -> Array:
    """One pre-norm transformer block on activations x [B, T, D] — the
    cache-free (training / scoring) path, factored out so the pipeline-parallel
    executor (parallel/pipeline.py) can scan it over a stage's layer stack."""
    attn_out, _ = _attn(
        lp["attn"], cfg, L.rmsnorm(lp["attn_norm"], x, cfg.norm_eps),
        positions, cos, sin, 0, None, 0, pad_mask, attn_fn,
    )
    x = x + attn_out
    return x + _mlp(lp["mlp"], cfg, L.rmsnorm(lp["mlp_norm"], x, cfg.norm_eps))


def stack_layer_params(params: dict, cfg: LlamaConfig) -> dict:
    """Rearrange per-layer subtrees ``layers_i`` into one stacked pytree with
    a leading layer dim: {"embed_tokens", "lm_head", "final_norm", "layers"}
    where every leaf of ``layers`` is [n_layers, ...]. The stacked form is
    what ``lax.scan`` consumes (one compiled block for L layers) and what the
    pipeline executor shards over the ``pp`` mesh axis (leading dim = stage)."""
    per_layer = [params[f"layers_{i}"] for i in range(cfg.n_layers)]
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_layer)
    return {
        "embed_tokens": params["embed_tokens"],
        "lm_head": params["lm_head"],
        "final_norm": params["final_norm"],
        "layers": stacked,
    }


def unstack_layer_params(stacked: dict, cfg: LlamaConfig) -> dict:
    """Inverse of :func:`stack_layer_params`."""
    params = {
        "embed_tokens": stacked["embed_tokens"],
        "lm_head": stacked["lm_head"],
        "final_norm": stacked["final_norm"],
    }
    for i in range(cfg.n_layers):
        params[f"layers_{i}"] = jax.tree.map(lambda leaf: leaf[i], stacked["layers"])
    return params


def llama_forward(
    params: dict,
    cfg: LlamaConfig,
    ids: Array,
    positions: Optional[Array] = None,
    cache: Optional[Cache] = None,
    cache_index: Array | int = 0,
    pad_mask: Optional[Array] = None,
    attn_fn=None,
) -> tuple[Array, Optional[Cache]]:
    """ids [B, T] → logits [B, T, vocab] (float32) and the updated cache.

    * Training / scoring: ``cache=None`` → causal attention over T.
    * Prefill: pass a fresh cache, ``positions = arange(T)``, index 0.
    * Decode: T == 1, ``positions = [[cur]]``, ``cache_index = cur``; with a
      ragged batch, ``positions = lens[:, None]`` and ``cache_index = lens``
      ([B] vector) so each row writes/reads at its own offset.
    * ``attn_fn`` (see sentio_tpu.kernels): flash/ring kernel used for the
      multi-token causal paths (training + prefill); decode stays XLA.
    """
    dt = cfg.jdtype
    b, t = ids.shape
    if cache is not None:
        cache = dict(cache)  # never mutate the caller's pytree
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    rope_len = cache["k"].shape[2] if cache is not None else max(t, cfg.max_len)
    cos, sin = L.rope_frequencies(cfg.head_dim, rope_len, cfg.rope_theta)

    x = L.embed(params["embed_tokens"], ids, dt)
    for i in range(cfg.n_layers):
        lp = params[f"layers_{i}"]
        if cache is None:
            x = block_forward(lp, cfg, x, positions, cos, sin, pad_mask, attn_fn)
            continue
        attn_out, cache = _attn(
            lp["attn"], cfg, L.rmsnorm(lp["attn_norm"], x, cfg.norm_eps),
            positions, cos, sin, i, cache, cache_index, pad_mask, attn_fn,
        )
        x = x + attn_out
        x = x + _mlp(lp["mlp"], cfg, L.rmsnorm(lp["mlp_norm"], x, cfg.norm_eps))
    x = L.rmsnorm(params["final_norm"], x, cfg.norm_eps)
    logits = L.dense(params["lm_head"], x, dt)
    return logits.astype(jnp.float32), cache


@jit_family("llama.loss", static_argnames=("cfg",))
def llama_loss(params: dict, cfg: LlamaConfig, ids: Array, mask: Array) -> Array:
    """Mean next-token cross-entropy over unpadded positions — the training
    objective for fine-tuning and for the multi-chip dry-run train step."""
    logits, _ = llama_forward(params, cfg, ids[:, :-1], pad_mask=mask[:, :-1])
    targets = ids[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[:, :, None], axis=-1)[..., 0]
    weights = mask[:, 1:].astype(jnp.float32)
    return (nll * weights).sum() / jnp.maximum(weights.sum(), 1.0)
