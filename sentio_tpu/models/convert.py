"""HuggingFace checkpoint → framework param-tree conversion.

SURVEY.md §7 lists "weight sourcing/conversion for the three models into
Flax checkpoints" as a hard part: the reference outsources all model compute
to hosted APIs (jina.py:33, jina_reranker.py:120, openai.py:117 under
/root/reference/src/core/), so it never touches weights. Here the three
model families (decoder LM for generate+verify, bi-encoder embedder,
cross-encoder reranker) run in-process, and this module maps the public
torch checkpoints onto our explicit pytrees:

* Llama-family ``*ForCausalLM`` → :func:`convert_llama` (rotate-half RoPE,
  GQA, SwiGLU — conventions match ``models/llama.py`` exactly).
* BERT / XLM-RoBERTa encoders → :func:`convert_encoder` (post-LN blocks,
  learned positions + token types — ``models/transformer.py``). XLM-R's
  2-slot position offset (padding_idx+1) is folded in here so runtime code
  uses plain 0-based positions.
* bge-reranker-class ``*ForSequenceClassification`` → :func:`convert_cross_encoder`.

Everything is host-side numpy: torch tensors are detached to np.float32 and
the resulting tree is device_put by the caller (optionally through
``parallel.sharding.shard_params`` for the TP layout). Layout rule: HF
``nn.Linear`` stores ``weight[out, in]``; our ``layers.dense`` computes
``x @ kernel`` with ``kernel[in, out]`` → every linear transposes once at
conversion time and never again at runtime.

No network: loaders accept a local directory only (``local_files_only``),
mirroring the zero-egress deployment posture.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Mapping

import numpy as np

from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.models.transformer import EncoderConfig


class ConversionError(Exception):
    pass


def _np(t: Any) -> np.ndarray:
    """torch.Tensor | np.ndarray → numpy (host). Numpy arrays keep their
    dtype (safetensors bf16 arrives as ml_dtypes.bfloat16 and stays that
    way — no 2x f32 blow-up for 8B-class checkpoints); torch tensors go
    through f32 per-tensor (transient)."""
    if isinstance(t, np.ndarray):
        return t
    try:  # torch tensor without importing torch at module scope
        return t.detach().to("cpu").to(dtype=_torch().float32).numpy()
    except AttributeError as e:
        raise ConversionError(f"cannot convert tensor of type {type(t)!r}") from e


def cast_tree(params: dict, dtype: str) -> dict:
    """Cast every floating leaf to ``dtype`` (bf16 via ml_dtypes on numpy).
    Param storage dtype is a deployment choice: f32 masters for fine-tuning,
    bf16 for serving 8B-class models at half the HBM/disk."""
    want = np.dtype(dtype) if dtype != "bfloat16" else _bf16()

    def cast(leaf):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) or str(arr.dtype) == "bfloat16":
            return arr.astype(want) if arr.dtype != want else arr
        return arr

    return _tree_map(cast, params)


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _tree_map(fn, tree):
    if isinstance(tree, dict):
        return {k: _tree_map(fn, v) for k, v in tree.items()}
    return fn(tree)


def _torch():
    import torch

    return torch


def _linear(sd: Mapping[str, Any], prefix: str, with_bias: bool = True) -> dict:
    out = {"kernel": _np(sd[f"{prefix}.weight"]).T.copy()}
    if with_bias and f"{prefix}.bias" in sd:
        out["bias"] = _np(sd[f"{prefix}.bias"])
    return out


# ---------------------------------------------------------------- Llama LM


def _decoder_kwargs_from_hf(hf_cfg: Any, dtype: str) -> dict:
    """Field mappings shared by every HF decoder family (llama, mixtral)."""
    return dict(
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=getattr(hf_cfg, "num_key_value_heads", hf_cfg.num_attention_heads),
        mlp_dim=hf_cfg.intermediate_size,
        max_len=getattr(hf_cfg, "max_position_embeddings", 8192),
        rope_theta=getattr(hf_cfg, "rope_theta", 10_000.0),
        dtype=dtype,
        norm_eps=getattr(hf_cfg, "rms_norm_eps", 1e-5),
    )


def llama_config_from_hf(hf_cfg: Any, dtype: str = "bfloat16") -> LlamaConfig:
    """transformers.LlamaConfig (or compatible) → LlamaConfig."""
    return LlamaConfig(**_decoder_kwargs_from_hf(hf_cfg, dtype))


def _attn_block(sd: Mapping[str, Any], p: str) -> dict:
    """Per-layer attention projections shared by every HF decoder family."""
    return {
        "wq": _linear(sd, f"{p}.self_attn.q_proj", with_bias=False),
        "wk": _linear(sd, f"{p}.self_attn.k_proj", with_bias=False),
        "wv": _linear(sd, f"{p}.self_attn.v_proj", with_bias=False),
        "wo": _linear(sd, f"{p}.self_attn.o_proj", with_bias=False),
    }


def convert_llama(state_dict: Mapping[str, Any], cfg: LlamaConfig) -> dict:
    """``LlamaForCausalLM.state_dict()`` → params for ``llama_forward``.

    Handles tied lm_head (falls back to embed weights when the checkpoint
    omits ``lm_head.weight``, as Llama-3.2-class models do).
    """
    sd = state_dict
    embed = _np(sd["model.embed_tokens.weight"])
    if "lm_head.weight" in sd:
        lm_head = _np(sd["lm_head.weight"]).T.copy()
    else:  # tied embeddings
        lm_head = embed.T.copy()
    params: dict = {
        "embed_tokens": {"embedding": embed},
        "lm_head": {"kernel": lm_head},
        "final_norm": {"scale": _np(sd["model.norm.weight"])},
    }
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}"
        params[f"layers_{i}"] = {
            "attn_norm": {"scale": _np(sd[f"{p}.input_layernorm.weight"])},
            "attn": _attn_block(sd, p),
            "mlp_norm": {"scale": _np(sd[f"{p}.post_attention_layernorm.weight"])},
            "mlp": {
                "w_gate": _linear(sd, f"{p}.mlp.gate_proj", with_bias=False),
                "w_up": _linear(sd, f"{p}.mlp.up_proj", with_bias=False),
                "w_down": _linear(sd, f"{p}.mlp.down_proj", with_bias=False),
            },
        }
    _check_shapes_llama(params, cfg)
    return params


def _check_shapes_llama(params: dict, cfg: LlamaConfig) -> None:
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    want = {
        ("embed_tokens", "embedding"): (cfg.vocab_size, cfg.dim),
        ("lm_head", "kernel"): (cfg.dim, cfg.vocab_size),
    }
    for path, shape in want.items():
        got = params[path[0]][path[1]].shape
        if tuple(got) != shape:
            raise ConversionError(f"{'.'.join(path)}: shape {got}, expected {shape}")
    wk = params["layers_0"]["attn"]["wk"]["kernel"].shape
    if wk != (cfg.dim, kv_dim):
        raise ConversionError(f"layers_0.attn.wk: shape {wk}, expected {(cfg.dim, kv_dim)}")


# ------------------------------------------------------------ Mixtral (MoE)


def moe_config_from_hf(hf_cfg: Any, dtype: str = "bfloat16",
                       capacity_factor: float | None = None) -> "MoeConfig":
    """transformers.MixtralConfig (or compatible) → MoeConfig.

    HF Mixtral routes top-k with NO capacity limit, so the default here is
    the no-drop capacity ``n_experts / experts_per_token`` — any expert can
    absorb every routed token even under fully imbalanced routing. A finite
    ``capacity_factor`` (e.g. 1.25 for training efficiency) may be passed
    explicitly, accepting dropped tokens and divergence from HF logits.
    """
    from sentio_tpu.models.moe import MoeConfig

    n_experts = getattr(hf_cfg, "num_local_experts", 8)
    experts_per_token = getattr(hf_cfg, "num_experts_per_tok", 2)
    if capacity_factor is None:
        capacity_factor = n_experts / experts_per_token
    return MoeConfig(
        **_decoder_kwargs_from_hf(hf_cfg, dtype),
        n_experts=n_experts,
        experts_per_token=experts_per_token,
        capacity_factor=capacity_factor,
    )


def convert_moe(state_dict: Mapping[str, Any], cfg: "MoeConfig") -> dict:
    """``MixtralForCausalLM.state_dict()`` → params for ``moe_forward``.

    HF stores each expert's SwiGLU as w1 (gate, [f, d]), w3 (up, [f, d]),
    w2 (down, [d, f]) and the router as ``block_sparse_moe.gate`` ([E, d]);
    here experts stack on a leading dim ([E, in, out], the ``ep`` sharding
    axis) and all matmuls are input-major, so every tensor transposes.
    """
    sd = state_dict
    embed = _np(sd["model.embed_tokens.weight"])
    if "lm_head.weight" in sd:
        lm_head = _np(sd["lm_head.weight"]).T.copy()
    else:  # tied embeddings
        lm_head = embed.T.copy()
    params: dict = {
        "embed_tokens": {"embedding": embed},
        "lm_head": {"kernel": lm_head},
        "final_norm": {"scale": _np(sd["model.norm.weight"])},
    }
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}"
        moe_p = f"{p}.block_sparse_moe"
        params[f"layers_{i}"] = {
            "attn_norm": {"scale": _np(sd[f"{p}.input_layernorm.weight"])},
            "attn": _attn_block(sd, p),
            "mlp_norm": {"scale": _np(sd[f"{p}.post_attention_layernorm.weight"])},
            "moe": {
                "router": {"kernel": _np(sd[f"{moe_p}.gate.weight"]).T.copy()},
                "w_gate": np.stack([
                    _np(sd[f"{moe_p}.experts.{e}.w1.weight"]).T
                    for e in range(cfg.n_experts)
                ]),
                "w_up": np.stack([
                    _np(sd[f"{moe_p}.experts.{e}.w3.weight"]).T
                    for e in range(cfg.n_experts)
                ]),
                "w_down": np.stack([
                    _np(sd[f"{moe_p}.experts.{e}.w2.weight"]).T
                    for e in range(cfg.n_experts)
                ]),
            },
        }
    _check_shapes_moe(params, cfg)
    return params


def _check_shapes_moe(params: dict, cfg: "MoeConfig") -> None:
    want = {
        ("embed_tokens", "embedding"): (cfg.vocab_size, cfg.dim),
        ("lm_head", "kernel"): (cfg.dim, cfg.vocab_size),
    }
    for path, shape in want.items():
        got = params[path[0]][path[1]].shape
        if tuple(got) != shape:
            raise ConversionError(f"{'.'.join(path)}: shape {got}, expected {shape}")
    moe = params["layers_0"]["moe"]
    if moe["router"]["kernel"].shape != (cfg.dim, cfg.n_experts):
        raise ConversionError(
            f"layers_0.moe.router: shape {moe['router']['kernel'].shape}, "
            f"expected {(cfg.dim, cfg.n_experts)}"
        )
    if moe["w_gate"].shape != (cfg.n_experts, cfg.dim, cfg.mlp_dim):
        raise ConversionError(
            f"layers_0.moe.w_gate: shape {moe['w_gate'].shape}, "
            f"expected {(cfg.n_experts, cfg.dim, cfg.mlp_dim)}"
        )
    if moe["w_down"].shape != (cfg.n_experts, cfg.mlp_dim, cfg.dim):
        raise ConversionError(
            f"layers_0.moe.w_down: shape {moe['w_down'].shape}, "
            f"expected {(cfg.n_experts, cfg.mlp_dim, cfg.dim)}"
        )


def load_moe_dir(model_dir: str | Path, dtype: str = "bfloat16"):
    """Local Mixtral-family checkpoint directory → (params, config)."""
    from transformers import AutoConfig

    hf_cfg = AutoConfig.from_pretrained(str(model_dir), local_files_only=True)
    cfg = moe_config_from_hf(hf_cfg, dtype=dtype)
    params = cast_tree(convert_moe(load_state_dict(model_dir), cfg), dtype)
    return params, cfg


# ------------------------------------------------------- BERT/XLM-R encoder


def encoder_config_from_hf(hf_cfg: Any, dtype: str = "bfloat16") -> EncoderConfig:
    # XLM-R reserves two position slots (pad + offset); expose the usable span
    offset = _position_offset(hf_cfg)
    return EncoderConfig(
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        mlp_dim=hf_cfg.intermediate_size,
        max_len=hf_cfg.max_position_embeddings - offset,
        n_types=max(getattr(hf_cfg, "type_vocab_size", 1), 1),
        dtype=dtype,
    )


def _position_offset(hf_cfg: Any) -> int:
    """RoBERTa-family checkpoints index positions from padding_idx+1 = 2;
    BERT from 0. Folding the offset into the converted table lets runtime
    code use arange(T) everywhere."""
    model_type = getattr(hf_cfg, "model_type", "")
    if model_type in ("roberta", "xlm-roberta", "camembert"):
        return getattr(hf_cfg, "pad_token_id", 1) + 1 if getattr(hf_cfg, "pad_token_id", 1) is not None else 2
    return 0


def convert_encoder(
    state_dict: Mapping[str, Any], cfg: EncoderConfig, position_offset: int = 0
) -> dict:
    """BERT/XLM-R ``*Model.state_dict()`` → params for ``encoder_forward``.

    Accepts both bare (``embeddings.…``) and prefixed (``bert.embeddings.…``/
    ``roberta.…``) key layouts so task-head checkpoints convert unchanged.
    """
    sd = _strip_encoder_prefix(state_dict)
    pos = _np(sd["embeddings.position_embeddings.weight"])
    if position_offset:
        pos = pos[position_offset:]
    if "embeddings.token_type_embeddings.weight" in sd:
        types = _np(sd["embeddings.token_type_embeddings.weight"])
    else:  # RoBERTa variants ship a single (or no) type row
        types = np.zeros((cfg.n_types, cfg.dim), np.float32)
    if types.shape[0] < cfg.n_types:  # pad missing type rows with zeros
        types = np.concatenate(
            [types, np.zeros((cfg.n_types - types.shape[0], cfg.dim), np.float32)]
        )
    params: dict = {
        "embed_tokens": {"embedding": _np(sd["embeddings.word_embeddings.weight"])},
        "embed_positions": {"embedding": pos.copy()},
        "embed_types": {"embedding": types},
        "embed_norm": {
            "scale": _np(sd["embeddings.LayerNorm.weight"]),
            "bias": _np(sd["embeddings.LayerNorm.bias"]),
        },
    }
    for i in range(cfg.n_layers):
        p = f"encoder.layer.{i}"
        params[f"layers_{i}"] = {
            "attn": {
                "wq": _linear(sd, f"{p}.attention.self.query"),
                "wk": _linear(sd, f"{p}.attention.self.key"),
                "wv": _linear(sd, f"{p}.attention.self.value"),
                "wo": _linear(sd, f"{p}.attention.output.dense"),
            },
            "attn_norm": {
                "scale": _np(sd[f"{p}.attention.output.LayerNorm.weight"]),
                "bias": _np(sd[f"{p}.attention.output.LayerNorm.bias"]),
            },
            "mlp": {
                "w_in": _linear(sd, f"{p}.intermediate.dense"),
                "w_out": _linear(sd, f"{p}.output.dense"),
            },
            "mlp_norm": {
                "scale": _np(sd[f"{p}.output.LayerNorm.weight"]),
                "bias": _np(sd[f"{p}.output.LayerNorm.bias"]),
            },
        }
    return params


def _strip_encoder_prefix(sd: Mapping[str, Any]) -> dict:
    for prefix in ("bert.", "roberta.", "model."):
        if any(k.startswith(prefix + "embeddings.") for k in sd):
            plen = len(prefix)
            return {k[plen:]: v for k, v in sd.items() if k.startswith(prefix)}
    return dict(sd)


def convert_cross_encoder(
    state_dict: Mapping[str, Any], cfg: EncoderConfig, position_offset: int = 0
) -> dict:
    """``*ForSequenceClassification`` (bge-reranker-class, 1 label) →
    params for ``cross_encoder_scores``: encoder tree + optional pooler +
    scalar head.

    RoBERTa/bge heads are two-stage — ``classifier.dense`` (+tanh) then
    ``classifier.out_proj`` — which maps onto the cross-encoder's optional
    ``pooler`` stage; BERT heads are ``bert.pooler.dense`` (+tanh) then
    ``classifier``. Both convert exactly.
    """
    encoder = convert_encoder(state_dict, cfg, position_offset)
    sd = state_dict
    params: dict = {"encoder": encoder}
    if "classifier.out_proj.weight" in sd:  # RoBERTa-family head
        params["pooler"] = _linear(sd, "classifier.dense")
        params["head"] = _linear(sd, "classifier.out_proj")
    elif "classifier.weight" in sd:  # BERT-family head over the pooler
        for pfx in ("bert.pooler.dense", "pooler.dense"):
            if f"{pfx}.weight" in sd:
                params["pooler"] = _linear(sd, pfx)
                break
        params["head"] = _linear(sd, "classifier")
    else:
        raise ConversionError("no classifier head found in state dict")
    if params["head"]["kernel"].shape[1] != 1:
        raise ConversionError(
            f"cross-encoder head must be scalar, got {params['head']['kernel'].shape[1]} labels"
        )
    return params


# ---------------------------------------------------------------- loaders


def load_state_dict(model_dir: str | Path) -> dict:
    """Load a checkpoint directory's tensors (safetensors preferred, torch
    ``pytorch_model.bin`` fallback) without instantiating an HF model."""
    model_dir = Path(model_dir)
    st_files = sorted(model_dir.glob("*.safetensors"))
    if st_files:
        try:
            from safetensors import safe_open
        except ImportError as e:  # pragma: no cover - safetensors ships with transformers
            raise ConversionError("safetensors not available") from e
        sd: dict = {}
        for f in st_files:
            with safe_open(str(f), framework="np") as fh:
                for k in fh.keys():
                    # native dtype preserved (bf16 → ml_dtypes.bfloat16):
                    # an 8B bf16 checkpoint loads at 16 GB, not 32
                    sd[k] = np.asarray(fh.get_tensor(k))
        return sd
    bins = sorted(model_dir.glob("pytorch_model*.bin"))
    if not bins:
        raise ConversionError(f"no safetensors or torch .bin files under {model_dir}")
    torch = _torch()
    sd = {}
    for f in bins:
        sd.update(torch.load(str(f), map_location="cpu", weights_only=True))
    return sd


def load_llama_dir(model_dir: str | Path, dtype: str = "bfloat16") -> tuple[dict, LlamaConfig]:
    """Local Llama checkpoint directory → (params, config)."""
    from transformers import AutoConfig

    hf_cfg = AutoConfig.from_pretrained(str(model_dir), local_files_only=True)
    cfg = llama_config_from_hf(hf_cfg, dtype=dtype)
    params = cast_tree(convert_llama(load_state_dict(model_dir), cfg), dtype)
    return params, cfg


def load_encoder_dir(
    model_dir: str | Path, dtype: str = "bfloat16", cross_encoder: bool = False
) -> tuple[dict, EncoderConfig]:
    """Local BERT/XLM-R checkpoint directory → (params, config)."""
    from transformers import AutoConfig

    hf_cfg = AutoConfig.from_pretrained(str(model_dir), local_files_only=True)
    cfg = encoder_config_from_hf(hf_cfg, dtype=dtype)
    offset = _position_offset(hf_cfg)
    sd = load_state_dict(model_dir)
    params = convert_cross_encoder(sd, cfg, offset) if cross_encoder else convert_encoder(sd, cfg, offset)
    return cast_tree(params, dtype), cfg
