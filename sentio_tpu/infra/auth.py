"""Auth: JWT access/refresh tokens, API keys, sessions, RBAC, lockout, audit.

Parity with /root/reference/src/utils/auth.py:30-482 — scopes {read, write,
admin, embed, chat, delete, metrics} mapped onto roles, HS256 JWTs, password
policy with failure lockout, security-event audit log — implemented on
stdlib ``hmac``/``hashlib`` (python-jose/passlib are not in this image;
HS256 and PBKDF2 need neither).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from sentio_tpu.config import AuthConfig, get_settings
from sentio_tpu.infra.exceptions import AuthError, ErrorCode, ForbiddenError

logger = logging.getLogger(__name__)
audit_logger = logging.getLogger("sentio_tpu.audit")

SCOPES = ("read", "write", "admin", "embed", "chat", "delete", "metrics")

ROLE_SCOPES: dict[str, tuple[str, ...]] = {
    "admin": SCOPES,
    "service": ("read", "write", "embed", "chat", "metrics"),
    "user": ("read", "chat", "embed"),
    "readonly": ("read",),
}


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(text: str) -> bytes:
    pad = "=" * (-len(text) % 4)
    return base64.urlsafe_b64decode(text + pad)


def hash_password(password: str, salt: Optional[bytes] = None) -> str:
    salt = salt or secrets.token_bytes(16)
    digest = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 200_000)
    return f"pbkdf2${_b64url(salt)}${_b64url(digest)}"


def verify_password(password: str, stored: str) -> bool:
    try:
        _, salt_b64, digest_b64 = stored.split("$")
        salt = _b64url_decode(salt_b64)
        expected = _b64url_decode(digest_b64)
    except ValueError:
        return False
    candidate = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 200_000)
    return hmac.compare_digest(candidate, expected)


class JWT:
    """Minimal HS256 JWT encode/verify (header.payload.signature)."""

    def __init__(self, secret: str) -> None:
        if not secret:
            raise ValueError("JWT secret must be non-empty")
        self._key = secret.encode()

    def encode(self, payload: dict[str, Any]) -> str:
        header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        body = _b64url(json.dumps(payload, separators=(",", ":")).encode())
        signing_input = f"{header}.{body}".encode()
        sig = _b64url(hmac.new(self._key, signing_input, hashlib.sha256).digest())
        return f"{header}.{body}.{sig}"

    def decode(self, token: str) -> dict[str, Any]:
        try:
            header_b64, body_b64, sig_b64 = token.split(".")
        except ValueError as exc:
            raise AuthError("malformed token") from exc
        signing_input = f"{header_b64}.{body_b64}".encode()
        expected = hmac.new(self._key, signing_input, hashlib.sha256).digest()
        if not hmac.compare_digest(expected, _b64url_decode(sig_b64)):
            raise AuthError("invalid token signature")
        try:
            header = json.loads(_b64url_decode(header_b64))
            payload = json.loads(_b64url_decode(body_b64))
        except (ValueError, json.JSONDecodeError) as exc:
            raise AuthError("malformed token payload") from exc
        if header.get("alg") != "HS256":
            raise AuthError("unsupported token algorithm")
        exp = payload.get("exp")
        if exp is not None and time.time() > float(exp):  # wall-clock: JWT exp is epoch
            raise AuthError("token expired", code=ErrorCode.TOKEN_EXPIRED)
        return payload


@dataclass
class User:
    username: str
    password_hash: str
    role: str = "user"
    disabled: bool = False
    failed_attempts: int = 0
    locked_until: float = 0.0


@dataclass
class Session:
    session_id: str
    username: str
    created_at: float
    last_seen: float


class AuthManager:
    def __init__(self, config: Optional[AuthConfig] = None) -> None:
        self.config = config or get_settings().auth
        secret = self.config.jwt_secret or secrets.token_urlsafe(32)
        self.jwt = JWT(secret)
        self._users: dict[str, User] = {}
        self._api_keys: dict[str, str] = {}  # key-hash -> username
        self._sessions: dict[str, Session] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ users

    def create_user(self, username: str, password: str, role: str = "user") -> User:
        self._check_password_policy(password)
        if role not in ROLE_SCOPES:
            raise ValueError(f"unknown role {role!r}")
        with self._lock:
            if username in self._users:
                raise ValueError(f"user {username!r} exists")
            user = User(username=username, password_hash=hash_password(password), role=role)
            self._users[username] = user
        self.log_security_event("user_created", username=username, role=role)
        return user

    def _check_password_policy(self, password: str) -> None:
        if len(password) < self.config.min_password_len:
            raise ValueError(f"password must be >= {self.config.min_password_len} chars")
        checks = [
            any(c.islower() for c in password),
            any(c.isupper() for c in password),
            any(c.isdigit() for c in password),
        ]
        if not all(checks):
            raise ValueError("password needs lower, upper, and digit characters")

    def authenticate(self, username: str, password: str) -> dict[str, str]:
        with self._lock:
            user = self._users.get(username)
        if user is None or user.disabled:
            self.log_security_event("login_failed", username=username, reason="unknown/disabled")
            raise AuthError("invalid credentials")
        now = time.time()  # wall-clock: lockout epoch, seconds granularity
        if user.locked_until > now:
            self.log_security_event("login_locked", username=username)
            raise AuthError("account locked", code=ErrorCode.ACCOUNT_LOCKED)
        if not verify_password(password, user.password_hash):
            with self._lock:
                user.failed_attempts += 1
                if user.failed_attempts >= self.config.max_failed_attempts:
                    user.locked_until = now + self.config.lockout_s
                    user.failed_attempts = 0
                    self.log_security_event("account_locked", username=username)
            raise AuthError("invalid credentials")
        with self._lock:
            user.failed_attempts = 0
        self.log_security_event("login_ok", username=username)
        return self.issue_tokens(user)

    # ----------------------------------------------------------------- tokens

    def issue_tokens(self, user: User) -> dict[str, str]:
        now = time.time()  # wall-clock: JWT iat/exp are epoch
        base = {"sub": user.username, "role": user.role, "scopes": list(ROLE_SCOPES[user.role])}
        access = self.jwt.encode({**base, "type": "access", "iat": now,
                                  "exp": now + self.config.access_ttl_s})
        refresh = self.jwt.encode({"sub": user.username, "type": "refresh", "iat": now,
                                   "exp": now + self.config.refresh_ttl_s})
        return {"access_token": access, "refresh_token": refresh, "token_type": "bearer"}

    def refresh(self, refresh_token: str) -> dict[str, str]:
        payload = self.jwt.decode(refresh_token)
        if payload.get("type") != "refresh":
            raise AuthError("not a refresh token")
        with self._lock:
            user = self._users.get(payload.get("sub", ""))
        if user is None or user.disabled:
            raise AuthError("unknown user")
        return self.issue_tokens(user)

    def verify_token(self, token: str) -> dict[str, Any]:
        payload = self.jwt.decode(token)
        if payload.get("type") != "access":
            raise AuthError("not an access token")
        return payload

    # --------------------------------------------------------------- API keys

    def create_api_key(self, username: str) -> str:
        key = f"stk_{secrets.token_urlsafe(32)}"
        digest = hashlib.sha256(key.encode()).hexdigest()
        with self._lock:
            self._api_keys[digest] = username
        self.log_security_event("api_key_created", username=username)
        return key

    def verify_api_key(self, key: str) -> dict[str, Any]:
        digest = hashlib.sha256(key.encode()).hexdigest()
        with self._lock:
            username = self._api_keys.get(digest)
            user = self._users.get(username) if username else None
        if user is None or user.disabled:
            raise AuthError("invalid API key")
        return {"sub": user.username, "role": user.role, "scopes": list(ROLE_SCOPES[user.role])}

    def revoke_api_key(self, key: str) -> bool:
        digest = hashlib.sha256(key.encode()).hexdigest()
        with self._lock:
            return self._api_keys.pop(digest, None) is not None

    # ---------------------------------------------------------------- sessions

    def create_session(self, username: str) -> Session:
        session = Session(
            session_id=secrets.token_urlsafe(24),
            username=username,
            created_at=time.time(),  # wall-clock: session metadata is user-visible
            last_seen=time.time(),  # wall-clock: session metadata is user-visible
        )
        with self._lock:
            self._sessions[session.session_id] = session
        return session

    def get_session(self, session_id: str) -> Optional[Session]:
        with self._lock:
            session = self._sessions.get(session_id)
            if session is not None:
                session.last_seen = time.time()  # wall-clock: session metadata is user-visible
            return session

    def end_session(self, session_id: str) -> bool:
        with self._lock:
            return self._sessions.pop(session_id, None) is not None

    # -------------------------------------------------------------------- rbac

    @staticmethod
    def require_scopes(payload: dict[str, Any], *needed: str) -> None:
        have = set(payload.get("scopes", []))
        missing = [s for s in needed if s not in have]
        if missing:
            raise ForbiddenError(f"missing scopes: {missing}")

    @staticmethod
    def require_role(payload: dict[str, Any], *roles: str) -> None:
        if payload.get("role") not in roles:
            raise ForbiddenError(f"requires role in {roles}")

    # -------------------------------------------------------------------- audit

    @staticmethod
    def log_security_event(event: str, **fields: Any) -> None:
        audit_logger.info(json.dumps({"event": event, "at": time.time(), **fields}))  # wall-clock: audit log epoch
